"""``vft-gc``: the storage lifecycle plane — chaos-proven deletion.

Every durable plane the fleet writes — the content-addressed feature
cache (cache.py), the fleet compile store (compile_cache.py), the serve
spool's ``done/``/``expired/`` responses (serve.py), the gateway
``inbox/`` uploads (gateway.py), incident bundles
(telemetry/alerts.py) and the append-only journals — grows without
bound, and a full disk is a fleet-wide FATAL outage (utils/faults.py
classifies ENOSPC as one fast failure, no retry burn). This module
treats the disk as a resource like the chip: **usage accounting**
(per-plane and per-tenant byte attribution), **safe eviction** (every
delete either recoverable or provably unreferenced) and **failure
discipline** (every delete journaled BEFORE it happens — the journal is
the state, exactly the queue/spool discipline, so a SIGKILLed GC leaves
a tree that still audits PASS and a re-run converges).

    vft-gc /shared/out                        # account + sweep once
    vft-gc /shared/out --dry-run              # plan only, delete nothing
    vft-gc /shared/out --watch                # daemon on gc_interval_s
    vft-gc /shared/out --quota-gb 50          # LRU-evict cache to quota

Safety rules, per plane (the audit invariants in audit.py check_gc):

  - **cache**: eviction is always a recoverable miss — entries are
    re-derivable from (video, config, weights) — so the only policy is
    last-hit LRU (cache.py bumps the entry mtime on every VERIFIED hit)
    under the byte quota, plus optional age retention;
  - **compile store**: entries whose environment fingerprint differs
    from this host's are unreachable executables — pruned past
    retention; THIS process's attached entry is pinned regardless;
  - **spool**: a ``done/``/``expired/`` response is deleted only past
    retention AND when its request is no longer claimable (no
    ``requests/`` or ``claimed/*/`` file with that rid) — a serve host
    that still holds the claim must always find its terminal marker;
  - **inbox**: an upload blob is deleted only past retention AND when
    no spool request (pending or claimed) references it — dedup means
    one blob serves many requests, so reference-counting is by scan;
  - **incidents**: bundles expire past retention unless the operator
    dropped a ``pinned`` marker file into the bundle;
  - **quarantine**: ``_queue/quarantined/`` items expire past retention
    (the POISON journal record is the durable evidence, queue.py);
  - **staging**: ``_queue/.staging/`` remnants are the QUEUE's to
    recover (parallel/queue.py sweeps them back to pending on the
    configured retention); GC deletes only remnants whose item already
    has a ``done/`` marker — completed work abandoned mid-steal.

Every deletion appends one record to ``_gc_{host}.jsonl`` *before* the
unlink. A record without a matching deletion (the process died in
between — inject site ``gc.evict``, fault ``drop``/``kill``) is
recoverable: the path still satisfies its planner, so the next run
re-journals and completes it. ``vft-audit`` treats journaled-but-present
as a note and deleted-but-still-referenced as a violation.

Config surface (validated by :func:`validate_gc_args` via
config.sanity_check): ``gc=true`` plus ``gc_quota_gb``,
``gc_*_retention_s`` and ``gc_interval_s``. With ``gc=false`` (the
default) no accounting runs, no artifact or telemetry byte changes —
the zero-footprint off-path. Usage is published as the heartbeat ``gc``
section + ``vft_gc_*`` metrics (telemetry/names.py), sampled into the
retained history (telemetry/history.py) where the ``disk_pressure``
burn-rate alert rule (telemetry/alerts.py) projects time-to-full.

See docs/storage.md for the planes table, the failure matrix and the
worked disk-pressure drill.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .telemetry.jsonl import append_jsonl, read_jsonl

GC_JOURNAL_PREFIX = "_gc_"
GC_JOURNAL_GLOB = GC_JOURNAL_PREFIX + "*.jsonl"
GC_JOURNAL_SCHEMA = "vft.gc_event/1"

#: the accounted planes, in eviction-priority order (recoverable first)
PLANES = ("cache", "compile", "spool", "inbox", "incidents",
          "quarantine", "staging", "journals")

#: journal filenames accounted under the "journals" plane (never
#: deleted by GC — each is an append-only state channel with its own
#: retention story; history compacts itself, the rest are the evidence)
_JOURNAL_GLOBS = ("_telemetry.jsonl", "_history_*.jsonl",
                  "_gateway_*.jsonl", "_failures.jsonl", "_health.jsonl",
                  "_alerts.jsonl", "_gc_*.jsonl")

_RETENTION_KEYS = ("gc_cache_retention_s", "gc_compile_retention_s",
                   "gc_spool_retention_s", "gc_inbox_retention_s",
                   "gc_incident_retention_s", "gc_quarantine_retention_s",
                   "gc_staging_retention_s")


def journal_filename(host_id: str) -> str:
    import re
    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", str(host_id))
    return f"{GC_JOURNAL_PREFIX}{safe}.jsonl"


# -- config ------------------------------------------------------------------

class GcConfig:
    """Resolved knobs: quota in bytes, one retention per plane (None =
    that plane is account-only), the watch cadence."""

    def __init__(self, *, quota_gb: Optional[float] = None,
                 cache_retention_s: Optional[float] = None,
                 compile_retention_s: Optional[float] = None,
                 spool_retention_s: Optional[float] = None,
                 inbox_retention_s: Optional[float] = None,
                 incident_retention_s: Optional[float] = None,
                 quarantine_retention_s: Optional[float] = None,
                 staging_retention_s: Optional[float] = None,
                 interval_s: float = 300.0) -> None:
        self.quota_bytes = (int(float(quota_gb) * 1e9)
                            if quota_gb is not None else None)
        self.cache_retention_s = cache_retention_s
        self.compile_retention_s = compile_retention_s
        self.spool_retention_s = spool_retention_s
        self.inbox_retention_s = inbox_retention_s
        self.incident_retention_s = incident_retention_s
        self.quarantine_retention_s = quarantine_retention_s
        self.staging_retention_s = staging_retention_s
        self.interval_s = float(interval_s)

    @classmethod
    def from_args(cls, args: Dict[str, Any]) -> "GcConfig":
        def opt(key: str) -> Optional[float]:
            v = args.get(key)
            return float(v) if v is not None else None

        return cls(quota_gb=opt("gc_quota_gb"),
                   cache_retention_s=opt("gc_cache_retention_s"),
                   compile_retention_s=opt("gc_compile_retention_s"),
                   spool_retention_s=opt("gc_spool_retention_s"),
                   inbox_retention_s=opt("gc_inbox_retention_s"),
                   incident_retention_s=opt("gc_incident_retention_s"),
                   quarantine_retention_s=opt("gc_quarantine_retention_s"),
                   staging_retention_s=opt("gc_staging_retention_s"),
                   interval_s=opt("gc_interval_s") or 300.0)


def validate_gc_args(args: Dict[str, Any]) -> None:
    """Launch-time validation of every ``gc``/``gc_*`` key — called by
    config.sanity_check whenever any is present, so vft-gc and a CLI run
    carrying them fail a typo identically (never a silently-ignored
    quota)."""
    g = args.get("gc", False)
    if not isinstance(g, bool):
        raise ValueError(f"gc={g!r}: expected true or false (the storage "
                         "lifecycle plane, gc.py; docs/storage.md)")
    q = args.get("gc_quota_gb")
    if q is not None:
        try:
            qf = float(q)
        except (TypeError, ValueError):
            qf = -1.0
        if qf <= 0:
            raise ValueError(f"gc_quota_gb={q!r}: need a float > 0 in GB "
                             "(total accounted bytes before LRU eviction), "
                             "or null for accounting without a quota")
    for key in _RETENTION_KEYS:
        v = args.get(key)
        if v is None:
            continue
        try:
            vf = float(v)
        except (TypeError, ValueError):
            vf = -1.0
        if vf <= 0:
            raise ValueError(f"{key}={v!r}: need a float > 0 in seconds "
                             "(age before expiry), or null to keep that "
                             "plane account-only (docs/storage.md)")
    iv = args.get("gc_interval_s")
    if iv is not None and float(iv) <= 0:
        raise ValueError(f"gc_interval_s={iv!r}: need a float > 0 (the "
                         "--watch sweep cadence in seconds)")


# -- usage accounting ---------------------------------------------------------

def _tree_bytes(path: str) -> Tuple[int, int]:
    """(files, bytes) under ``path`` — missing dirs count zero."""
    n = b = 0
    for dirpath, _dirs, files in os.walk(path):
        for fn in files:
            try:
                b += os.path.getsize(os.path.join(dirpath, fn))
                n += 1
            except OSError:
                pass
    return n, b


def usage(root: str, *, cache_dir: Optional[str] = None,
          compile_dir: Optional[str] = None) -> Dict[str, Any]:
    """Per-plane (and, where recorded, per-tenant) byte attribution.

    ``root`` is the shared out_root/spool; the cache and compile stores
    default to their process-wide locations (cache.default_cache_dir,
    compile_cache.default_root) and may be pointed elsewhere. Tenant
    attribution comes from the gateway admission journal — upload events
    carry ``(tenant, sha256, bytes)``, accepted events ``(tenant, id)``
    — which is what makes it free: no second bookkeeping channel.
    """
    from .cache import default_cache_dir
    from .compile_cache import default_root as compile_default_root

    root = str(root)
    cache_dir = cache_dir or default_cache_dir()
    compile_dir = compile_dir or compile_default_root()
    planes: Dict[str, Dict[str, int]] = {}

    def plane(name: str, files: int, nbytes: int) -> None:
        planes[name] = {"files": int(files), "bytes": int(nbytes)}

    plane("cache", *_tree_bytes(cache_dir))
    plane("compile", *_tree_bytes(compile_dir))
    n = b = 0
    for sub in ("requests", "claimed", "done", "expired"):
        dn, db = _tree_bytes(os.path.join(root, sub))
        n, b = n + dn, b + db
    plane("spool", n, b)
    plane("inbox", *_tree_bytes(os.path.join(root, "inbox")))
    plane("incidents", *_tree_bytes(os.path.join(root, "_incidents")))
    plane("quarantine",
          *_tree_bytes(os.path.join(root, "_queue", "quarantined")))
    plane("staging", *_tree_bytes(os.path.join(root, "_queue", ".staging")))
    n = b = 0
    for pat in _JOURNAL_GLOBS:
        for p in Path(root).glob(pat):
            try:
                b += p.stat().st_size
                n += 1
            except OSError:
                pass
    plane("journals", n, b)

    # per-tenant attribution off the admission journal: stored upload
    # bytes + accepted request counts per tenant (rid -> tenant also
    # feeds the spool response attribution)
    tenants: Dict[str, Dict[str, int]] = {}
    rid_tenant: Dict[str, str] = {}
    for jp in sorted(Path(root).glob("_gateway_*.jsonl")):
        for rec in read_jsonl(jp):
            t = rec.get("tenant")
            if not t:
                continue
            tt = tenants.setdefault(str(t), {"upload_bytes": 0,
                                             "accepted": 0,
                                             "spool_bytes": 0})
            ev = rec.get("event")
            if ev == "upload" and not rec.get("dedup"):
                tt["upload_bytes"] += int(rec.get("bytes") or 0)
            elif ev == "accepted":
                tt["accepted"] += 1
                rid_tenant[str(rec.get("id"))] = str(t)
    if rid_tenant:
        for sub in ("done", "expired"):
            d = os.path.join(root, sub)
            if not os.path.isdir(d):
                continue
            for fn in os.listdir(d):
                t = rid_tenant.get(fn[:-len(".json")]) \
                    if fn.endswith(".json") else None
                if t is None:
                    continue
                try:
                    tenants[t]["spool_bytes"] += os.path.getsize(
                        os.path.join(d, fn))
                except OSError:
                    pass

    total = sum(p["bytes"] for p in planes.values())
    return {"root": root, "cache_dir": cache_dir,
            "compile_dir": compile_dir, "time": round(time.time(), 3),
            "planes": planes, "tenants": tenants, "total_bytes": total}


# -- eviction planning --------------------------------------------------------

class Deletion:
    """One planned delete: where, why, and how many bytes come back."""

    __slots__ = ("plane", "path", "bytes", "reason", "is_dir")

    def __init__(self, plane: str, path: str, nbytes: int, reason: str,
                 is_dir: bool = False) -> None:
        self.plane = plane
        self.path = str(path)
        self.bytes = int(nbytes)
        self.reason = str(reason)
        self.is_dir = bool(is_dir)

    def __repr__(self) -> str:
        return f"Deletion({self.plane}: {self.path} [{self.reason}])"


def _cache_entries(cache_dir: str) -> List[Tuple[float, int, str]]:
    """Every cache entry as ``(last_hit_mtime, bytes, path)`` — mtime is
    the LRU signal (cache.py bumps it on every verified hit)."""
    out = []
    for dirpath, _dirs, files in os.walk(cache_dir):
        for fn in files:
            if not fn.endswith(".pkl"):
                continue
            p = os.path.join(dirpath, fn)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, p))
    return out


def plan_cache(cache_dir: str, cfg: GcConfig, now: float,
               over_quota_bytes: int) -> List[Deletion]:
    """Last-hit LRU over the content-addressed store: expire entries
    past retention, then evict coldest-first until ``over_quota_bytes``
    is recovered. Always safe — an evicted entry is a recoverable miss
    (the next run recomputes bit-identically from the video)."""
    entries = sorted(_cache_entries(cache_dir))
    out: List[Deletion] = []
    recovered = 0
    for mtime, size, path in entries:
        age = now - mtime
        if cfg.cache_retention_s is not None and \
                age > cfg.cache_retention_s:
            out.append(Deletion("cache", path, size,
                                f"last hit {age:.0f}s ago > retention "
                                f"{cfg.cache_retention_s:.0f}s"))
            recovered += size
        elif recovered < over_quota_bytes:
            out.append(Deletion("cache", path, size,
                                f"LRU eviction under quota (last hit "
                                f"{age:.0f}s ago)"))
            recovered += size
    return out


def plan_compile(compile_dir: str, cfg: GcConfig, now: float
                 ) -> List[Deletion]:
    """Prune compile-store entries whose environment fingerprint is not
    this host's (unreachable executables here) once past retention. The
    entry THIS process attached (compile_cache.active) is pinned."""
    from .compile_cache import MANIFEST_NAME, active, env_fingerprint
    if cfg.compile_retention_s is None or not os.path.isdir(compile_dir):
        return []
    _env, env_fp = env_fingerprint()
    pinned_key = None
    act = active()
    if act is not None:
        pinned_key = act.key
    out: List[Deletion] = []
    for man_path in Path(compile_dir).glob(
            os.path.join("*", "*", "*", MANIFEST_NAME)):
        entry_dir = man_path.parent
        try:
            man = json.loads(man_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        if man.get("env_fp") == env_fp or entry_dir.name == pinned_key:
            continue
        try:
            age = now - entry_dir.stat().st_mtime
        except OSError:
            continue
        if age <= cfg.compile_retention_s:
            continue
        _n, b = _tree_bytes(str(entry_dir))
        out.append(Deletion(
            "compile", str(entry_dir), b,
            f"env_fp {str(man.get('env_fp'))[:12]} != active "
            f"{env_fp[:12]}, idle {age:.0f}s", is_dir=True))
    return out


def _claimable_rids(root: str) -> set:
    """rids with a live ``requests/`` or ``claimed/*/`` file — the spool
    ground truth a response deletion must never contradict."""
    rids = set()
    rq = os.path.join(root, "requests")
    if os.path.isdir(rq):
        for fn in os.listdir(rq):
            if fn.endswith(".json"):
                rids.add(fn[:-len(".json")])
    cl = os.path.join(root, "claimed")
    if os.path.isdir(cl):
        for host in os.listdir(cl):
            hd = os.path.join(cl, host)
            if not os.path.isdir(hd):
                continue
            for fn in os.listdir(hd):
                if fn.endswith(".json"):
                    rids.add(fn[:-len(".json")])
    return rids


def _referenced_inbox_blobs(root: str) -> set:
    """Inbox blob basenames referenced by any live spool request
    (pending or claimed) — never deletable while a request might still
    be served off them."""
    refs = set()
    dirs = [os.path.join(root, "requests")]
    cl = os.path.join(root, "claimed")
    if os.path.isdir(cl):
        dirs += [os.path.join(cl, h) for h in os.listdir(cl)]
    for d in dirs:
        if not os.path.isdir(d):
            continue
        for fn in os.listdir(d):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, fn), encoding="utf-8") as f:
                    req = json.load(f)
            except (OSError, ValueError):
                continue
            for v in req.get("video_paths") or []:
                refs.add(os.path.basename(str(v)))
    return refs


def plan_spool(root: str, cfg: GcConfig, now: float) -> List[Deletion]:
    """Expire terminal responses: ``done/``/``expired/`` files past
    retention whose request is NOT still claimable."""
    if cfg.spool_retention_s is None:
        return []
    live = _claimable_rids(root)
    out: List[Deletion] = []
    for sub in ("done", "expired"):
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            continue
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".json"):
                continue
            rid = fn[:-len(".json")]
            p = os.path.join(d, fn)
            try:
                st = os.stat(p)
            except OSError:
                continue
            age = now - st.st_mtime
            if age <= cfg.spool_retention_s or rid in live:
                continue
            out.append(Deletion("spool", p, st.st_size,
                                f"{sub} response {age:.0f}s old, request "
                                "no longer claimable"))
    return out


def plan_inbox(root: str, cfg: GcConfig, now: float) -> List[Deletion]:
    """Expire upload blobs past retention that no live request
    references (dedup blobs are shared — reference check by scan)."""
    if cfg.inbox_retention_s is None:
        return []
    inbox = os.path.join(root, "inbox")
    if not os.path.isdir(inbox):
        return []
    refs = _referenced_inbox_blobs(root)
    out: List[Deletion] = []
    for fn in sorted(os.listdir(inbox)):
        p = os.path.join(inbox, fn)
        try:
            st = os.stat(p)
        except OSError:
            continue
        age = now - st.st_mtime
        if age <= cfg.inbox_retention_s or fn in refs:
            continue
        out.append(Deletion("inbox", p, st.st_size,
                            f"upload {age:.0f}s old, unreferenced"))
    return out


def plan_incidents(root: str, cfg: GcConfig, now: float) -> List[Deletion]:
    """Expire flight-recorder bundles past retention, honoring the
    operator's ``pinned`` marker file."""
    if cfg.incident_retention_s is None:
        return []
    inc = os.path.join(root, "_incidents")
    if not os.path.isdir(inc):
        return []
    out: List[Deletion] = []
    for name in sorted(os.listdir(inc)):
        bundle = os.path.join(inc, name)
        if not os.path.isdir(bundle):
            continue
        if os.path.exists(os.path.join(bundle, "pinned")):
            continue
        try:
            age = now - os.stat(bundle).st_mtime
        except OSError:
            continue
        if age <= cfg.incident_retention_s:
            continue
        _n, b = _tree_bytes(bundle)
        out.append(Deletion("incidents", bundle, b,
                            f"bundle {age:.0f}s old, not pinned",
                            is_dir=True))
    return out


def plan_quarantine(root: str, cfg: GcConfig, now: float) -> List[Deletion]:
    """Expire quarantined queue items past retention — the POISON
    journal record (parallel/queue.py) is the durable evidence; the
    marker file only blocks re-seeding, which expiry re-allows on
    purpose (a later run may retry content that was poison here)."""
    if cfg.quarantine_retention_s is None:
        return []
    q = os.path.join(root, "_queue", "quarantined")
    if not os.path.isdir(q):
        return []
    out: List[Deletion] = []
    for fn in sorted(os.listdir(q)):
        if not fn.endswith(".json"):
            continue
        p = os.path.join(q, fn)
        try:
            st = os.stat(p)
        except OSError:
            continue
        age = now - st.st_mtime
        if age <= cfg.quarantine_retention_s:
            continue
        out.append(Deletion("quarantine", p, st.st_size,
                            f"quarantined {age:.0f}s ago"))
    return out


def plan_staging(root: str, cfg: GcConfig, now: float) -> List[Deletion]:
    """Delete ``.staging/`` remnants whose item already has a done
    marker — completed work abandoned mid-steal. Remnants WITHOUT a done
    marker are never GC'd: they are unfinished work the queue's own
    sweep (parallel/queue.py, staging_retention_s) recovers to pending.
    """
    if cfg.staging_retention_s is None:
        return []
    st_dir = os.path.join(root, "_queue", ".staging")
    done_dir = os.path.join(root, "_queue", "done")
    if not os.path.isdir(st_dir):
        return []
    out: List[Deletion] = []
    for fn in sorted(os.listdir(st_dir)):
        if not fn.endswith(".json"):
            continue
        p = os.path.join(st_dir, fn)
        try:
            with open(p, encoding="utf-8") as f:
                iid = str(json.load(f).get("id"))
        except (OSError, ValueError):
            continue
        if not os.path.exists(os.path.join(done_dir, f"{iid}.json")):
            continue
        try:
            st = os.stat(p)
        except OSError:
            continue
        age = now - st.st_mtime
        if age <= cfg.staging_retention_s:
            continue
        out.append(Deletion("staging", p, st.st_size,
                            f"staged remnant of done item {iid}, "
                            f"{age:.0f}s old"))
    return out


def plan(root: str, cfg: GcConfig, *, cache_dir: Optional[str] = None,
         compile_dir: Optional[str] = None,
         now: Optional[float] = None,
         use: Optional[Dict[str, Any]] = None) -> List[Deletion]:
    """The full sweep plan across every plane. Quota pressure is
    resolved against the recoverable planes only (cache LRU): the
    retention-governed planes have correctness rules a byte target must
    never override."""
    now = time.time() if now is None else float(now)
    use = use or usage(root, cache_dir=cache_dir, compile_dir=compile_dir)
    cache_dir = use["cache_dir"]
    compile_dir = use["compile_dir"]
    over = 0
    if cfg.quota_bytes is not None and \
            use["total_bytes"] > cfg.quota_bytes:
        over = use["total_bytes"] - cfg.quota_bytes
    deletions: List[Deletion] = []
    deletions += plan_cache(cache_dir, cfg, now, over)
    deletions += plan_compile(compile_dir, cfg, now)
    deletions += plan_spool(root, cfg, now)
    deletions += plan_inbox(root, cfg, now)
    deletions += plan_incidents(root, cfg, now)
    deletions += plan_quarantine(root, cfg, now)
    deletions += plan_staging(root, cfg, now)
    return deletions


# -- journaled execution ------------------------------------------------------

def _journal_record(d: Deletion, root: str, host_id: str) -> dict:
    try:
        rel = os.path.relpath(d.path, root)
    except ValueError:
        rel = d.path
    return {"schema": GC_JOURNAL_SCHEMA, "event": "evict",
            "time": round(time.time(), 3), "host_id": host_id,
            "plane": d.plane, "path": d.path, "rel": rel,
            "bytes": d.bytes, "reason": d.reason}


def execute(root: str, deletions: List[Deletion],
            host_id: Optional[str] = None) -> Dict[str, Any]:
    """Run the plan: journal each delete to ``_gc_{host}.jsonl``, THEN
    unlink. Dying in between (``gc.evict`` drop/kill) is recoverable by
    construction — the journaled path still satisfies its planner, so
    the next run re-journals and completes. Returns per-plane tallies.
    """
    from .telemetry import inc
    from .utils import inject

    host_id = host_id or f"{socket.gethostname()}-{os.getpid()}"
    jpath = os.path.join(str(root), journal_filename(host_id))
    tally = {p: {"deleted": 0, "bytes": 0, "errors": 0} for p in PLANES}
    for d in deletions:
        append_jsonl(jpath, _journal_record(d, str(root), host_id))
        try:
            fault = inject.fire("gc.evict", plane=d.plane,
                                path=os.path.basename(d.path))
            if fault is not None and fault.kind == "drop":
                # the injected crash window: journaled, never unlinked —
                # exactly what a SIGKILL between the two lines leaves
                continue
            if d.is_dir:
                shutil.rmtree(d.path, ignore_errors=False)
            else:
                os.unlink(d.path)
        except FileNotFoundError:
            pass  # a sibling GC or the owner got there first: converged
        except OSError as e:
            # a failed unlink (or injected eio/enospc at the site) is a
            # journaled-but-present remnant: counted, named, and
            # re-planned by the next run — never a crashed sweep
            tally[d.plane]["errors"] += 1
            inc("vft_gc_sweep_errors_total", plane=d.plane)
            print(f"vft-gc: cannot delete {d.path}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            continue
        tally[d.plane]["deleted"] += 1
        tally[d.plane]["bytes"] += d.bytes
        inc("vft_gc_evicted_total", plane=d.plane)
        inc("vft_gc_evicted_bytes_total", d.bytes, plane=d.plane)
    return {p: t for p, t in tally.items()
            if t["deleted"] or t["errors"]}


def sweep(root: str, cfg: GcConfig, *, cache_dir: Optional[str] = None,
          compile_dir: Optional[str] = None,
          host_id: Optional[str] = None,
          dry_run: bool = False) -> Dict[str, Any]:
    """One full accounting + eviction pass; the unit ``vft-gc`` runs
    once, ``--watch`` runs on a cadence, and chaos kills mid-flight
    (inject site ``gc.sweep``)."""
    from .telemetry import inc
    from .utils import inject

    fault = inject.fire("gc.sweep", root=str(root))
    if fault is not None and fault.kind == "stall":
        time.sleep(0.25)  # a slow disk mid-sweep; the plan stays valid
    use = usage(root, cache_dir=cache_dir, compile_dir=compile_dir)
    deletions = plan(root, cfg, cache_dir=cache_dir,
                     compile_dir=compile_dir, use=use)
    planned_bytes = sum(d.bytes for d in deletions)
    executed: Dict[str, Any] = {}
    if deletions and not dry_run:
        executed = execute(root, deletions, host_id=host_id)
    inc("vft_gc_sweeps_total")
    inc("vft_gc_retained_total",
        sum(p["files"] for p in use["planes"].values())
        - sum(t["deleted"] for t in executed.values()))
    return {"usage": use, "planned": len(deletions),
            "planned_bytes": planned_bytes, "executed": executed,
            "dry_run": bool(dry_run),
            "quota_bytes": cfg.quota_bytes}


# -- heartbeat / metrics publication ------------------------------------------

class GcMonitor:
    """The accounting half wired into a run's heartbeat: registers the
    ``gc`` extra section on a recorder and refreshes the (walk-heavy)
    usage snapshot at most once per ``cfg.interval_s`` — between
    refreshes the section republishes the cached numbers, so the
    heartbeat cadence never pays a tree walk."""

    def __init__(self, root: str, cfg: GcConfig, *,
                 cache_dir: Optional[str] = None,
                 compile_dir: Optional[str] = None,
                 clock=time.time) -> None:
        self.root = str(root)
        self.cfg = cfg
        self.cache_dir = cache_dir
        self.compile_dir = compile_dir
        self.clock = clock
        self._last: Optional[Dict[str, Any]] = None
        self._last_t = 0.0
        self._recorder = None

    def snapshot(self) -> Dict[str, Any]:
        now = self.clock()
        if self._last is None or now - self._last_t >= self.cfg.interval_s:
            self._last = usage(self.root, cache_dir=self.cache_dir,
                               compile_dir=self.compile_dir)
            self._last_t = now
            self._publish(self._last)
        return self._last

    def _publish(self, use: Dict[str, Any]) -> None:
        r = self._recorder
        if r is None:
            return
        r.registry.gauge("vft_gc_used_bytes").set(use["total_bytes"])
        if self.cfg.quota_bytes is not None:
            r.registry.gauge("vft_gc_quota_bytes").set(self.cfg.quota_bytes)
        for plane_name, p in use["planes"].items():
            r.registry.gauge("vft_gc_plane_bytes",
                             plane=plane_name).set(p["bytes"])
        for tenant, t in use["tenants"].items():
            r.registry.gauge("vft_gc_tenant_bytes", tenant=tenant).set(
                t["upload_bytes"] + t["spool_bytes"])

    def section(self) -> Dict[str, Any]:
        use = self.snapshot()
        out: Dict[str, Any] = {
            "used_bytes": use["total_bytes"],
            "quota_bytes": self.cfg.quota_bytes,
            "planes": {p: v["bytes"] for p, v in use["planes"].items()},
        }
        if use["tenants"]:
            out["tenants"] = {
                t: v["upload_bytes"] + v["spool_bytes"]
                for t, v in use["tenants"].items()}
        return out

    def attach(self, recorder) -> "GcMonitor":
        self._recorder = recorder
        recorder.extra_sections["gc"] = self.section
        return self


# -- CLI ----------------------------------------------------------------------

def _fmt_bytes(b: Optional[int]) -> str:
    if b is None:
        return "-"
    v = float(b)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if v < 1000 or unit == "TB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1000.0
    return f"{v:.1f}TB"


def render_report(result: Dict[str, Any]) -> List[str]:
    use = result["usage"]
    quota = result.get("quota_bytes")
    lines = [f"vft-gc: {use['root']}",
             f"== usage ==  total={_fmt_bytes(use['total_bytes'])}"
             + (f"  quota={_fmt_bytes(quota)}" if quota else "")]
    for plane_name in PLANES:
        p = use["planes"].get(plane_name) or {}
        if not p.get("files"):
            continue
        lines.append(f"  {plane_name:<11} {p['files']:>6} file(s)  "
                     f"{_fmt_bytes(p['bytes'])}")
    for t, v in sorted((use.get("tenants") or {}).items()):
        lines.append(f"  tenant {t:<10} uploads="
                     f"{_fmt_bytes(v['upload_bytes'])}  responses="
                     f"{_fmt_bytes(v['spool_bytes'])}  "
                     f"accepted={v['accepted']}")
    verb = "planned (dry run)" if result["dry_run"] else "planned"
    lines.append(f"== sweep ==  {result['planned']} deletion(s) {verb}, "
                 f"{_fmt_bytes(result['planned_bytes'])}")
    for plane_name, t in sorted((result.get("executed") or {}).items()):
        lines.append(f"  {plane_name:<11} deleted={t['deleted']}  "
                     f"{_fmt_bytes(t['bytes'])}"
                     + (f"  errors={t['errors']}" if t["errors"] else ""))
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="storage lifecycle plane: account + journaled "
                    "eviction over the fleet's durable artifacts")
    ap.add_argument("root", help="the shared out_root / spool dir")
    ap.add_argument("--cache-dir", default=None,
                    help="feature-cache store (default VFT_CACHE_DIR)")
    ap.add_argument("--compile-dir", default=None,
                    help="compile store (default VFT_COMPILE_CACHE_DIR)")
    ap.add_argument("--quota-gb", type=float, default=None,
                    help="total byte quota; excess is LRU-evicted from "
                         "the recoverable planes (= gc_quota_gb)")
    for key in _RETENTION_KEYS:
        flag = "--" + key[len("gc_"):].replace("_", "-")
        ap.add_argument(flag, type=float, default=None, dest=key,
                        help=f"= {key} (seconds; unset = account-only)")
    ap.add_argument("--watch", action="store_true",
                    help="sweep on a cadence until interrupted")
    ap.add_argument("--every", type=float, default=None,
                    help="--watch cadence in seconds (= gc_interval_s, "
                         "default 300)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="--watch passes before exiting (0 = forever)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the plan, delete nothing")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"error: {args.root} is not a directory", file=sys.stderr)
        return 2
    # the config-surface path and the CLI flags validate identically
    cfg_args: Dict[str, Any] = {"gc": True}
    if args.quota_gb is not None:
        cfg_args["gc_quota_gb"] = args.quota_gb
    for key in _RETENTION_KEYS:
        if getattr(args, key) is not None:
            cfg_args[key] = getattr(args, key)
    if args.every is not None:
        cfg_args["gc_interval_s"] = args.every
    validate_gc_args(cfg_args)
    cfg = GcConfig.from_args(cfg_args)
    passes = 0
    while True:
        result = sweep(args.root, cfg, cache_dir=args.cache_dir,
                       compile_dir=args.compile_dir, dry_run=args.dry_run)
        if args.json:
            print(json.dumps(result, sort_keys=True))
        else:
            print("\n".join(render_report(result)))
        passes += 1
        if not args.watch or (args.iterations
                              and passes >= args.iterations):
            break
        try:
            time.sleep(max(0.05, cfg.interval_s))
        except KeyboardInterrupt:
            break
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
