"""``vft-serve``: a warm, long-lived extraction server over a file spool.

The batch CLI treats every invocation as a cold job: import jax, compile
(or at best re-load the persistent XLA cache), fault the params onto the
device, drain a list, exit. At serving scale that cold tax dominates
small requests — tens of seconds of compile against milliseconds of
forward. ``vft-serve`` keeps ONE process alive with:

  - the **compilation cache** enabled once (cli.py
    ``_enable_compilation_cache``) and every executable warm after its
    first use — request latency after request 1 contains no compile
    (the run manifest's ``compile_cache`` hit/miss counters prove it);
  - **params resident**: each family's extractor is constructed once,
    its weights committed to device memory for the process lifetime
    (the NamedSharding/commit discipline of parallel/mesh.py);
  - **cross-request clip packing**: with ``cross_video_batching=true``
    the extractor's one :class:`~.parallel.packer.ClipPacker` outlives
    requests, so clips from concurrently-processed requests fill the
    same fixed-shape device groups (the packer already packs across
    *videos*; the server merely feeds it videos from more than one
    request at a time) with the same poison-exact failure containment —
    a failed group fails exactly its member videos, each reported in
    its own request's response;
  - the **content-addressed feature cache** (cache.py): with
    ``cache=true`` repeat content short-circuits before any decoder is
    built, which at fleet scale is the dominant request outcome.

**Spool protocol** (filesystem-coordinated; no new daemon protocol —
docs/serving.md has the full contract):

  ======================  ==================================================
  ``{spool}/requests/``   clients atomically rename request JSON in
  ``{spool}/claimed/{host_id}/``  server claims by ``os.rename`` into its
                          OWN subdir (atomic; a losing racer just sees
                          ENOENT) — the dir name ties every claim to its
                          owner's heartbeat, so a crashed server's claims
                          are reclaimable (below), never orphaned
  ``{spool}/done/``       one response JSON per request (atomic replace)
  ``{spool}/_heartbeat_{host_id}.json``  liveness AND readiness: the
                          normal telemetry heartbeat (run_id-stamped,
                          PR 5 staleness semantics) plus a ``serve``
                          section — state, queue depths, request tallies
  ======================  ==================================================

**Claim reclamation** (the fleet queue's lease discipline,
parallel/queue.py, applied to the spool): a server that died mid-request
used to strand its claims in ``claimed/`` forever. Now every live server
periodically sweeps the other claim dirs; when an owner's heartbeat is
missing, final, or silent past the stall window, its claimed requests are
renamed back into ``requests/`` (first sweeper wins the rename) and
served by whoever claims them next — unless the dead server already
wrote the response, in which case the stale claim is simply dropped.
Flat ``claimed/*.json`` files (a pre-reclamation server version crashed)
have no identifiable owner and are reclaimed unconditionally.

A request is ``{"id": ..., "video_paths": [...]}``; the response carries
per-video statuses, artifact locations (the server's configured
``output_path``), wait/latency seconds, and the request's compile-cache
delta. **Admission control**: a backlog beyond ``serve_max_pending``
rejects new requests immediately (an explicit ``rejected`` response —
at saturation, fast refusal beats unbounded queueing), and claiming is
throttled while the shared-decode fan-out gauges
(``vft_fanout_queue_depth`` / ``put_blocked`` — PR 4) report
backpressure, so admission follows the pipeline's own signals rather
than a guess.

**Request-scoped correlation** (telemetry/context.py): each claimed
request's videos run under ``use_request(id)``, so every span record,
health digest, failure-journal entry and ``video_attempt`` trace span
they produce carries the request id — one id retrieves everything a
request touched, on any host (``vft-fleet --request <id>``).

**SLOs**: queue-wait (submit -> claim) and service (claim -> response)
land in the fixed-bucket latency histograms
(``vft_serve_queue_wait_seconds`` / ``vft_serve_service_seconds``,
telemetry/metrics.py), and with ``serve_slo_s=`` set, a request whose
wait+service exceeds it bumps ``vft_serve_slo_violations_total``. The
heartbeat ``serve`` section publishes p50/p95/p99 of both splits plus
attainment %, so SLO state is readable live off the spool (and
fleet-wide via ``vft-fleet``) — no unbounded in-memory latency list, no
scrape endpoint. ``trace=true`` additionally runs the Chrome-trace
recorder homed on the spool, so ``serve.request`` windows land on the
timeline ``vft-fleet --stitch`` merges across hosts.

**End-to-end deadlines & tenants** (the gateway arc, gateway.py): a
request may carry an absolute ``deadline``; the server refuses to START
it past the deadline (claim-time wasted-work guard — zero decode/device
time burned), stops BETWEEN videos when it expires mid-request (partial
results kept), and writes a terminal ``expired/{id}.json`` record with
status ``deadline_exceeded`` — never a ``done/`` response (vft-audit
holds the two mutually exclusive). Gateway-minted ids
(``{tenant}-{rid}``) additionally land every answered/rejected/expired
request in per-tenant tallies (heartbeat ``serve.tenants``; labelled
``vft_tenant_*_total`` counters) so SLO attainment is per-tenant for
free.

Run it: ``vft-serve feature_type=resnet spool_dir=/srv/vft ...`` (or
``python main.py serve ...``). All family config keys apply; the
serve-specific keys are ``spool_dir`` (required), ``serve_workers``,
``serve_max_pending``, ``serve_poll_interval_s``, ``serve_slo_s``,
``serve_idle_exit_s`` and ``serve_max_requests`` (the latter two bound
a session — tests, benches, canaries). SIGTERM finishes in-flight work,
writes a final heartbeat and exits 143 (the CLI's preemption contract).
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

REQUESTS_DIR = "requests"
CLAIMED_DIR = "claimed"
DONE_DIR = "done"
#: terminal ``deadline_exceeded`` records live HERE, never in ``done/``:
#: a request that expired has no response — it has an expiry record, and
#: vft-audit holds the two directories mutually exclusive per request id
EXPIRED_DIR = "expired"

#: request/response schema identifiers
REQUEST_SCHEMA = "vft.serve_request/1"
RESPONSE_SCHEMA = "vft.serve_response/1"


def tenant_of_request_id(request_id: Optional[str]) -> Optional[str]:
    """``{tenant}-{rid}`` gateway-minted ids -> tenant; plain spool ids
    -> None (delegates to telemetry/context.py, the single parser)."""
    from .telemetry.context import tenant_of
    return tenant_of(request_id)


# -- client side -------------------------------------------------------------

def spool_paths(spool_dir: str) -> Dict[str, str]:
    root = str(spool_dir)
    return {name: os.path.join(root, name)
            for name in (REQUESTS_DIR, CLAIMED_DIR, DONE_DIR, EXPIRED_DIR)}


def ensure_spool(spool_dir: str) -> None:
    for p in spool_paths(spool_dir).values():
        os.makedirs(p, exist_ok=True)


def submit_request(spool_dir: str, video_paths: List[str],
                   request_id: Optional[str] = None,
                   deadline: Optional[float] = None) -> str:
    """Drop one request into the spool (atomic: temp + rename INTO
    ``requests/``, so the server can never claim a half-written file);
    returns the request id.

    ``deadline`` is an absolute unix time past which the request is
    worthless to its caller: the server refuses to START it past the
    deadline (claim-time check), stops BETWEEN videos when it passes
    mid-request, and writes a terminal ``expired/`` record either way —
    the end-to-end deadline contract the gateway stamps from the
    client's ``timeout_s`` (gateway.py; docs/serving.md)."""
    ensure_spool(spool_dir)
    rid = request_id or uuid.uuid4().hex[:12]
    req = {"schema": REQUEST_SCHEMA, "id": rid,
           "video_paths": [str(v) for v in video_paths],
           "time": round(time.time(), 3)}
    if deadline is not None:
        req["deadline"] = round(float(deadline), 3)
    final = os.path.join(spool_dir, REQUESTS_DIR, f"{rid}.json")
    tmp = os.path.join(spool_dir, f".{rid}.json.tmp")
    try:
        # vft-lint: disable=VFT004 — this IS the temp+fsync+os.replace discipline, open-coded because the tmp name doubles as the spool claim-protocol dotfile
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(req, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    except BaseException:
        # unlink-on-failure, the sink discipline (utils/sinks.py): a raise
        # between the temp write and the rename (ENOSPC at fsync, a dying
        # client) must not litter the spool with .tmp files forever —
        # vft-audit's no-tmp-litter invariant covers spools too
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return rid


def read_response(spool_dir: str, request_id: str) -> Optional[dict]:
    path = os.path.join(spool_dir, DONE_DIR, f"{request_id}.json")
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_terminal(spool_dir: str, request_id: str) -> Optional[dict]:
    """The request's terminal record, whichever directory holds it: the
    ``done/`` response, or the ``expired/`` deadline record (status
    ``deadline_exceeded``). None while the request is still open."""
    resp = read_response(spool_dir, request_id)
    if resp is not None:
        return resp
    path = os.path.join(spool_dir, EXPIRED_DIR, f"{request_id}.json")
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def wait_response(spool_dir: str, request_id: str,
                  timeout_s: float = 300.0,
                  poll_s: float = 0.1) -> dict:
    """Block until the terminal record for ``request_id`` lands (or
    raise TimeoutError) — a ``done/`` response, or the ``expired/``
    deadline record for a request whose deadline passed. Polling a
    local/shared filesystem is the protocol — clients need nothing but
    the spool mount."""
    deadline = time.monotonic() + float(timeout_s)
    while True:
        resp = read_terminal(spool_dir, request_id)
        if resp is not None:
            return resp
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"no response for request {request_id} within {timeout_s}s")
        time.sleep(poll_s)


def server_state(spool_dir: str) -> Dict[str, Any]:
    """Client-side readiness probe: the freshest matching heartbeat's
    ``serve`` section (+ liveness verdict), or ``{"state": "absent"}``.
    Readiness == a fresh heartbeat whose serve state is ``ready``."""
    import glob
    from .telemetry.heartbeat import HEARTBEAT_GLOB, STALL_INTERVALS
    best: Optional[dict] = None
    for p in glob.glob(os.path.join(spool_dir, HEARTBEAT_GLOB)):
        try:
            with open(p, encoding="utf-8") as f:
                hb = json.load(f)
        except (OSError, ValueError):
            continue
        if "serve" not in hb:
            # the gateway heartbeats on the same spool (gateway.py) but
            # carries no serve section — readiness is about SERVERS, so
            # its liveness must never masquerade as a backend verdict
            continue
        if best is None or float(hb.get("time", 0)) > \
                float(best.get("time", 0)):
            best = hb
    if best is None:
        return {"state": "absent"}
    age = max(0.0, time.time() - float(best.get("time", 0)))
    interval = float(best.get("interval_s", 30.0)) or 30.0
    serve = dict(best.get("serve") or {})
    if best.get("final"):
        serve["state"] = "exited"
    elif age > STALL_INTERVALS * interval:
        serve["state"] = "stalled"
    serve.setdefault("state", "unknown")
    serve["heartbeat_age_s"] = round(age, 3)
    serve["run_id"] = best.get("run_id")
    return serve


# -- server side -------------------------------------------------------------

class ServeLoop:
    """The warm server: construct once, :meth:`run` until bounded out or
    signalled. Separated from :func:`main` so tests/benches can drive it
    in-process (a thread) with injected bounds."""

    def __init__(self, args, per_family=None,
                 out_root: Optional[str] = None) -> None:
        self.args = args
        self.per_family = per_family  # multi-family: {family: Config}
        self.spool_dir = str(args.spool_dir)
        self.paths = spool_paths(self.spool_dir)
        ensure_spool(self.spool_dir)
        self.poll_s = float(args.get("serve_poll_interval_s") or 0.25)
        self.max_pending = int(args.get("serve_max_pending") or 64)
        self.idle_exit_s = args.get("serve_idle_exit_s")
        self.max_requests = args.get("serve_max_requests")
        workers = args.get("serve_workers") or args.get("video_workers") or 1
        if workers == "auto":
            workers = max(1, min(8, (os.cpu_count() or 1) // 2))
        self.workers = max(1, int(workers))
        self._stop = threading.Event()
        self._state = "warming"
        self._state_lock = threading.Lock()
        self._tallies = {"done": 0, "partial": 0, "failed": 0,
                         "rejected": 0, "deadline_exceeded": 0}
        # per-tenant request/violation/reject tallies (gateway-minted
        # ids carry a tenant prefix, telemetry/context.py tenant_of):
        # published in the heartbeat serve section and rolled fleet-wide
        # by vft-fleet --prom as vft_tenant_*_total{tenant}
        self._tenants: Dict[str, Dict[str, int]] = {}
        self._inflight = 0
        self._inflight_rids: set = set()
        # SLO accounting: the latency *distributions* live in the
        # recorder registry's fixed-bucket histograms (bounded by
        # construction); this deque only keeps a small recent window for
        # the heartbeat's last/mean lines. The unbounded per-request
        # list this replaces grew for the life of the server.
        import collections
        self._recent = collections.deque(maxlen=32)
        slo = args.get("serve_slo_s")
        self.slo_s = float(slo) if slo is not None else None
        self._answered = 0
        self._slo_violations = 0

        # fleet-shared compile cache (compile_cache.py): attach BEFORE
        # the warm construction below so its init-time compiles land in
        # the entry — a restarted server re-attaches warm and its first
        # request after a crash or deploy contains no compile, which is
        # the whole point of the serve mode; sealed when run() exits
        from . import compile_cache
        self.compile_cache_entry = (
            compile_cache.attach_for_multi_args(per_family)
            if per_family is not None
            else compile_cache.attach_for_args(args.feature_type, args))

        # -- warm construction: params resident for the process lifetime --
        if per_family is not None:
            from .extractors.multi import MultiExtractor
            self.multi = MultiExtractor(per_family)
            self.extractor = None
        else:
            from .registry import get_extractor_cls
            from .utils.faults import FailureJournal, RetryPolicy
            self.multi = None
            self.extractor = get_extractor_cls(args.feature_type)(args)
            self.policy = RetryPolicy.from_config(args)
            self.journal = (FailureJournal(args.output_path)
                            if args.get("on_extraction") != "print"
                            else None)
        self.out_root = str(out_root if out_root is not None
                            else args.output_path)

        # telemetry recorder is NOT optional in serve mode: its heartbeat
        # in the SPOOL dir is the liveness/readiness protocol (clients
        # read it with server_state); run telemetry still lands in the
        # output dir via spans_path/manifest_path overrides below? No —
        # one recorder, homed on the spool, is the single source of truth
        import socket
        from .config import _plain
        from .telemetry.recorder import TelemetryRecorder
        host_id = socket.gethostname()
        try:
            import jax
            host_id = f"p{jax.process_index()}-{host_id}"
        except Exception:
            pass
        # pid-qualify: servers sharing one machine (and one spool) need
        # distinct claim dirs + heartbeat files, and the claim-dir name
        # must map 1:1 onto a heartbeat so sweepers can judge the owner
        host_id = f"{host_id}-{os.getpid()}"
        from .parallel.queue import _safe
        self.claim_dirname = _safe(host_id)
        self.claim_dir = os.path.join(self.paths[CLAIMED_DIR],
                                      self.claim_dirname)
        os.makedirs(self.claim_dir, exist_ok=True)
        self._last_reclaim_sweep = 0.0
        families = (list(per_family) if per_family is not None
                    else [args.feature_type])
        self.families = families
        run_config = (_plain(args) if per_family is None else
                      {"feature_type": ",".join(families),
                       "families": {f: _plain(a)
                                    for f, a in per_family.items()}})
        self.recorder = TelemetryRecorder(
            self.spool_dir, run_config=run_config,
            feature_type=",".join(families),
            interval_s=float(args.get("metrics_interval_s") or 5.0),
            host_id=host_id)
        self.recorder.extra_sections["serve"] = self._serve_section

        # retained history + alerting, homed on the spool like the
        # heartbeat (telemetry/history.py, telemetry/alerts.py): the SLO
        # burn-rate rule diffs this server's own retained
        # requests/violations counters on every tick, so a burn pages
        # without any external watcher. Registered before run() calls
        # recorder.start() — the t=0 heartbeat seeds the windows.
        self.alert_engine = None
        if bool(args.get("history", False)) or bool(args.get("alerts",
                                                             False)):
            from .telemetry.history import HistoryWriter
            HistoryWriter(self.spool_dir, host_id).attach(self.recorder)
        if bool(args.get("alerts", False)):
            from .telemetry.alerts import AlertEngine
            self.alert_engine = AlertEngine(
                self.spool_dir,
                run_id=self.recorder.run_id).attach(self.recorder)

        # pipeline tracing (trace=true): the Chrome-trace recorder homed
        # on the SPOOL dir like the heartbeat, so `serve.request` /
        # `video_attempt` windows (each stamped with its request id) land
        # on the timeline vft-fleet --stitch merges across hosts. Same
        # lifecycle as the batch CLI's: armed here, drained at exit.
        self.tracer = None
        if bool(args.get("trace", False)):
            from .telemetry.trace import TraceRecorder
            # per-host filename: sibling servers share one spool, and
            # each must leave its own stitchable timeline behind
            self.tracer = TraceRecorder(self.spool_dir,
                                        host_id=host_id).start()

    # -- heartbeat serve section ------------------------------------------
    def _serve_section(self) -> dict:
        from .telemetry.metrics import LATENCY_BUCKETS, histogram_quantiles
        with self._state_lock:
            lat = list(self._recent)
            answered = self._answered
            violations = self._slo_violations
            section = {
                "state": self._state,
                "pending": self._pending_count(),
                "inflight": self._inflight,
                "active_requests": sorted(self._inflight_rids),
                "workers": self.workers,
                "max_pending": self.max_pending,
                "requests": dict(self._tallies),
            }
            if self._tenants:
                section["tenants"] = {t: dict(v) for t, v
                                      in sorted(self._tenants.items())}
        if lat:
            section["last_latency_s"] = round(lat[-1], 3)
            section["mean_latency_s"] = round(sum(lat) / len(lat), 3)
        # SLO block: percentiles straight off the registry histograms —
        # a pure function of bounded state, so a scraper (or vft-fleet)
        # reads p50/p95/p99 + attainment from the heartbeat file alone
        reg = self.recorder.registry
        section["slo"] = {
            "slo_s": self.slo_s,
            "requests": answered,
            "violations": violations,
            "attainment_pct": (round(100.0 * (answered - violations)
                                     / answered, 2) if answered else None),
            "queue_wait": histogram_quantiles(reg.histogram(
                "vft_serve_queue_wait_seconds",
                buckets=LATENCY_BUCKETS).snapshot()),
            "service": histogram_quantiles(reg.histogram(
                "vft_serve_service_seconds",
                buckets=LATENCY_BUCKETS).snapshot()),
        }
        return section

    def _tenant_bump(self, tenant: Optional[str], key: str) -> None:
        """One per-tenant tally + its labelled registry counter; a
        no-op for untenanted (spool-direct) request ids."""
        if not tenant:
            return
        with self._state_lock:
            t = self._tenants.setdefault(
                tenant, {"requests": 0, "violations": 0, "rejects": 0})
            t[key] += 1
        name = {"requests": "vft_tenant_requests_total",
                "violations": "vft_tenant_slo_violations_total",
                "rejects": "vft_tenant_rejects_total"}[key]
        self.recorder.registry.counter(name, tenant=tenant).inc()

    def _account_request(self, wait_s: float, service_s: float,
                         tenant: Optional[str] = None) -> bool:
        """Fold one answered request into the SLO state: both splits into
        their histograms, the recent window, and — when ``serve_slo_s``
        is set — the violation counter when wait+service exceeds it.
        Gateway-minted ids additionally land in the per-tenant tallies.
        Returns True when this request violated the SLO."""
        from .telemetry.metrics import LATENCY_BUCKETS
        reg = self.recorder.registry
        reg.histogram("vft_serve_queue_wait_seconds",
                      buckets=LATENCY_BUCKETS).observe(wait_s)
        reg.histogram("vft_serve_service_seconds",
                      buckets=LATENCY_BUCKETS).observe(service_s)
        violated = (self.slo_s is not None
                    and wait_s + service_s > self.slo_s)
        with self._state_lock:
            self._recent.append(service_s)
            self._answered += 1
            if violated:
                self._slo_violations += 1
        if violated:
            reg.counter("vft_serve_slo_violations_total").inc()
        self._tenant_bump(tenant, "requests")
        if violated:
            self._tenant_bump(tenant, "violations")
        return violated

    def _pending_count(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.paths[REQUESTS_DIR])
                       if n.endswith(".json"))
        except OSError:
            return 0

    def _set_state(self, state: str) -> None:
        with self._state_lock:
            self._state = state
        # readiness must be visible promptly, not at the next interval
        try:
            self.recorder.write_heartbeat()
        except Exception:
            pass

    # -- request processing ------------------------------------------------
    def _respond(self, rid: str, payload: dict) -> bool:
        """Write the ``done/`` response atomically; returns False when
        the write was LOST (the injected ``spool.respond`` drop — a
        crashed NFS write, a dying server). Callers must treat False as
        \"the requester will never hear us\": requeue the claim so a
        later pass (or sibling) answers, instead of silently swallowing
        the request."""
        from .telemetry import jsonl
        from .utils import inject
        fault = inject.fire("spool.respond", request=rid)
        if fault is not None and fault.kind == "drop":
            return False
        payload = {"schema": RESPONSE_SCHEMA, "id": rid,
                   "time": round(time.time(), 3), **payload}
        jsonl.write_json_atomic(
            os.path.join(self.paths[DONE_DIR], f"{rid}.json"), payload)
        return True

    def _expire(self, rid: str, req: dict, claimed_path: str,
                statuses: Dict[str, Dict[str, str]], where: str) -> None:
        """Terminal ``deadline_exceeded``: write the ``expired/`` record
        (NEVER a ``done/`` response — vft-audit holds the two mutually
        exclusive), count it, and release the claim. ``statuses`` holds
        whatever videos finished before the deadline passed
        (``where="claim"`` means none — the wasted-work guard fired
        before any decode/device time burned)."""
        from .telemetry import jsonl
        tenant = tenant_of_request_id(rid)
        rec = {"schema": RESPONSE_SCHEMA, "id": rid,
               "status": "deadline_exceeded",
               "time": round(time.time(), 3),
               "deadline": req.get("deadline"),
               "expired_at": where,
               "videos": statuses,
               "processed": len(statuses)}
        if tenant:
            rec["tenant"] = tenant
        jsonl.write_json_atomic(
            os.path.join(self.paths[EXPIRED_DIR], f"{rid}.json"), rec)
        from . import telemetry
        telemetry.inc("vft_serve_deadline_exceeded_total")
        with self._state_lock:
            self._tallies["deadline_exceeded"] += 1
            # an expired request IS an answered-and-violated request for
            # attainment purposes: without these, deadline-heavy load makes
            # attainment_pct overstate health (the fleet-wide block would
            # only ever see the requests that finished in time)
            self._answered += 1
            self._slo_violations += 1
        self.recorder.registry.counter(
            "vft_serve_slo_violations_total").inc()
        self._tenant_bump(tenant, "requests")
        self._tenant_bump(tenant, "violations")
        try:
            os.unlink(claimed_path)
        except OSError:
            pass
        print(f"vft-serve: request {rid} deadline exceeded at {where} "
              f"({len(statuses)} video(s) finished before expiry)",
              file=sys.stderr)

    def _run_one_video(self, video_path: str) -> Dict[str, str]:
        """One video through the warm extractor(s); returns
        {family: status} with safe_extract's vocabulary."""
        from .utils.sinks import safe_extract
        if self.multi is not None:
            return self.multi.run_video(video_path, recorder=self.recorder)
        with self.recorder.video_span(video_path) as span:
            status = safe_extract(self.extractor._extract, video_path,
                                  policy=self.policy, journal=self.journal,
                                  decode_mode=self.extractor.video_decode)
            span.annotate(status=status)
        return {self.args.feature_type: status}

    def _process(self, claimed_path: str) -> None:
        from .telemetry import trace
        rid = os.path.basename(claimed_path)[:-len(".json")]
        t0 = time.perf_counter()
        from .telemetry.recorder import _mon_snapshot, compile_cache_summary
        mon_before = _mon_snapshot()
        try:
            with open(claimed_path, encoding="utf-8") as f:
                req = json.load(f)
            videos = [str(v) for v in req.get("video_paths") or []]
        except (OSError, ValueError) as e:
            self._respond(rid, {"status": "failed",
                                "error": f"unreadable request: {e}"})
            with self._state_lock:
                self._tallies["failed"] += 1
            os.unlink(claimed_path)
            return
        wait_s = max(0.0, time.time() - float(req.get("time") or time.time()))
        deadline = req.get("deadline")
        deadline = float(deadline) if deadline is not None else None
        if deadline is not None and time.time() >= deadline:
            # wasted-work guard: the request expired while QUEUED — the
            # caller stopped waiting, so cancel at claim time, before any
            # decode/device second burns (vft-audit pins zero spans for
            # claim-expired requests)
            self._expire(rid, req, claimed_path, {}, "claim")
            return
        statuses: Dict[str, Dict[str, str]] = {}
        expired = False
        from .telemetry.context import use_request
        with self._state_lock:
            self._inflight_rids.add(rid)
        try:
            # request-scoped correlation: every span/health/journal/trace
            # record the videos below produce carries this request's id
            # (telemetry/context.py) — thread-local, so concurrent
            # requests on sibling workers never cross-stamp
            with use_request(rid), \
                    trace.span("serve.request", id=rid, videos=len(videos)):
                # videos of ONE request run on this request's worker
                # thread sequentially; concurrency comes from multiple
                # claimed requests in flight, which is exactly what packs
                # their clips into shared device groups
                # (parallel/packer.py)
                for v in videos:
                    # deadline re-check BETWEEN videos: expiry mid-request
                    # stops before the next decode, keeping whatever
                    # partial results already landed
                    if deadline is not None and time.time() >= deadline:
                        expired = True
                        break
                    if self._stop.is_set():
                        statuses[v] = {f: "dropped" for f in self.families}
                        continue
                    try:
                        statuses[v] = self._run_one_video(v)
                    except Exception as e:  # safe_extract contains
                        # per-video failures; this guards the serve loop
                        statuses[v] = {f: "error" for f in self.families}
                        print(f"serve: request {rid} video {v} escaped: "
                              f"{type(e).__name__}: {e}", file=sys.stderr)
        finally:
            with self._state_lock:
                self._inflight_rids.discard(rid)
        # the deadline also gates the RESPONSE: a request that finished
        # its last video past the deadline still expires — the caller is
        # gone, and done/ vs expired/ stay mutually exclusive
        if deadline is not None and time.time() >= deadline:
            expired = True
        if expired:
            self._expire(rid, req, claimed_path, statuses,
                         "mid_request" if statuses else "claim")
            return
        flat = [s for per in statuses.values() for s in per.values()]
        ok = all(s in ("done", "skipped") for s in flat) and flat
        latency = time.perf_counter() - t0
        payload = {
            "status": "done" if ok else "partial",
            "videos": statuses,
            "output_path": self.out_root,
            "wait_s": round(wait_s, 3),
            "latency_s": round(latency, 3),
            # flat after request 1 == no recompilation (the acceptance
            # signal; misses here mean a new (family, shape) executable)
            "compile_cache": compile_cache_summary(mon_before),
        }
        if self.slo_s is not None:
            payload["slo_violated"] = bool(wait_s + latency > self.slo_s)
        if not self._respond(rid, payload):
            # the response write was LOST (injected spool.respond drop /
            # a dying store): requeue the claim so a later pass answers —
            # idempotent re-serving is cheap (sink skip-if-exists + the
            # content-addressed cache), and accounting happens only on
            # the pass whose response actually lands
            try:
                os.rename(claimed_path, os.path.join(
                    self.paths[REQUESTS_DIR], f"{rid}.json"))
            except OSError:
                pass
            print(f"vft-serve: response write for {rid} lost — requeued",
                  file=sys.stderr)
            return
        self._account_request(wait_s, latency,
                              tenant=tenant_of_request_id(rid))
        with self._state_lock:
            self._tallies["done" if ok else "partial"] += 1
        try:
            os.unlink(claimed_path)
        except OSError:
            pass

    def _claim_next(self) -> Optional[str]:
        """Claim the oldest pending request by atomic rename; None when
        the spool is empty (or every candidate was raced away)."""
        req_dir = self.paths[REQUESTS_DIR]
        try:
            names = [n for n in os.listdir(req_dir) if n.endswith(".json")]
        except OSError:
            return None
        from .utils import inject
        for name in sorted(
                names,
                key=lambda n: self._mtime(os.path.join(req_dir, n))):
            src = os.path.join(req_dir, name)
            dst = os.path.join(self.claim_dir, name)
            try:
                # chaos hook (utils/inject.py `spool.claim`): a failed
                # claim rename looks exactly like a lost race — the
                # request stays spooled for the next pass/server
                inject.fire("spool.claim", request=name[:-len(".json")])
                os.rename(src, dst)
                return dst
            except OSError:
                continue  # another server (or a withdrawal) won the race
        return None

    def _reclaim_orphans(self) -> int:
        """Release a dead server's spool claims (the fleet queue's
        lease-expiry discipline): claims whose owner's heartbeat is
        missing, final, or stale go back to ``requests/``; claims whose
        response already landed are dropped. Returns requeued count."""
        from .telemetry.heartbeat import STALL_INTERVALS, heartbeat_filename
        root = self.paths[CLAIMED_DIR]
        try:
            entries = os.listdir(root)
        except OSError:
            return 0
        requeued = 0
        now = time.time()
        for entry in entries:
            p = os.path.join(root, entry)
            if entry.endswith(".json") and os.path.isfile(p):
                # flat claim: a pre-reclamation server crashed holding it;
                # no owner dir means no heartbeat to wait out
                requeued += self._release_claim(p)
                continue
            if entry == self.claim_dirname or not os.path.isdir(p):
                continue
            hb = None
            try:
                with open(os.path.join(self.spool_dir,
                                       heartbeat_filename(entry)),
                          encoding="utf-8") as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                pass
            if hb is not None and not hb.get("final"):
                interval = float(hb.get("interval_s", 30.0) or 30.0)
                if now - float(hb.get("time", 0)) <= \
                        STALL_INTERVALS * interval:
                    continue  # owner is alive; its claims are its own
            try:
                names = [n for n in os.listdir(p) if n.endswith(".json")]
            except OSError:
                continue
            for name in names:
                requeued += self._release_claim(os.path.join(p, name))
        return requeued

    def _release_claim(self, path: str) -> int:
        """Move one orphaned claim back to ``requests/`` (atomic rename;
        a racing sweeper loses with ENOENT) — or drop it when its
        response already exists (the owner died between respond and
        cleanup; re-serving would only repeat finished work)."""
        from . import telemetry
        name = os.path.basename(path)
        rid = name[:-len(".json")]
        if os.path.exists(os.path.join(self.paths[DONE_DIR], name)):
            try:
                os.unlink(path)
            except OSError:
                pass
            return 0
        try:
            os.rename(path, os.path.join(self.paths[REQUESTS_DIR], name))
        except OSError:
            return 0  # a racing sweeper (or the resurrected owner) won
        telemetry.inc("vft_serve_reclaimed_total")
        print(f"vft-serve: reclaimed orphaned claim {rid} from a dead "
              "server", file=sys.stderr)
        return 1

    @staticmethod
    def _mtime(path: str) -> float:
        try:
            return os.path.getmtime(path)
        except OSError:
            return float("inf")

    def _reject_overflow(self) -> None:
        """Admission control: beyond ``serve_max_pending`` queued
        requests, refuse NEWEST arrivals immediately — a bounded queue
        with a fast no is kinder to callers (they can retry elsewhere)
        than an unbounded one that times them all out."""
        req_dir = self.paths[REQUESTS_DIR]
        try:
            names = sorted(
                (n for n in os.listdir(req_dir) if n.endswith(".json")),
                key=lambda n: self._mtime(os.path.join(req_dir, n)))
        except OSError:
            return
        for name in names[self.max_pending:][::-1]:
            src = os.path.join(req_dir, name)
            dst = os.path.join(self.claim_dir, name)
            try:
                os.rename(src, dst)
            except OSError:
                continue
            rid = name[:-len(".json")]
            if not self._respond(rid, {
                    "status": "rejected",
                    "error": f"server backlog over serve_max_pending="
                             f"{self.max_pending}; retry later"}):
                # lost rejection write: put the request back — a silent
                # drop would strand the caller with no terminal record
                try:
                    os.rename(dst, src)
                except OSError:
                    pass
                continue
            with self._state_lock:
                self._tallies["rejected"] += 1
            self._tenant_bump(tenant_of_request_id(rid), "rejects")
            try:
                os.unlink(dst)
            except OSError:
                pass

    def _backpressured(self) -> bool:
        """Defer claiming while the pipeline's own gauges say the decode
        fan-out is saturated (PR 4's vft_fanout_queue_depth): admitting
        more work would only grow in-process queues, not throughput."""
        snap = self.recorder.fanout_snapshot()
        depths = snap.get("queue_depth") or {}
        if not depths:
            return False
        depth_cap = float(getattr(self, "_fanout_depth_cap", 0) or 0)
        if depth_cap <= 0:
            from .parallel import fanout
            first = (next(iter(self.per_family.values()))
                     if self.per_family else self.args)
            self._fanout_depth_cap = depth_cap = float(
                first.get("fanout_depth") or fanout.DEFAULT_DEPTH)
        return max(depths.values()) >= depth_cap

    # -- main loop ---------------------------------------------------------
    def run(self) -> int:
        self.recorder.start()
        self._set_state("ready")
        print(f"vft-serve: ready — spool={self.spool_dir} "
              f"families={','.join(self.families)} workers={self.workers} "
              f"(heartbeat {self.recorder.heartbeat_path})")
        from concurrent.futures import ThreadPoolExecutor
        served = 0
        idle_since = time.monotonic()
        futures = set()
        try:
            with ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="vft-serve") as pool:
                while not self._stop.is_set():
                    futures = {f for f in futures if not f.done()}
                    with self._state_lock:
                        self._inflight = len(futures)
                    # lease-expiry sweep on the heartbeat cadence: a dead
                    # sibling's stall window is measured in its own
                    # interval_s, so sweeping faster buys nothing
                    if time.monotonic() - self._last_reclaim_sweep >= \
                            min(self.recorder.interval_s, 5.0):
                        self._last_reclaim_sweep = time.monotonic()
                        self._reclaim_orphans()
                    self._reject_overflow()
                    claimed = None
                    if len(futures) < self.workers \
                            and not self._backpressured():
                        claimed = self._claim_next()
                    if claimed is not None:
                        served += 1
                        idle_since = time.monotonic()
                        futures.add(pool.submit(self._process, claimed))
                        if self.max_requests is not None \
                                and served >= int(self.max_requests):
                            break
                        continue  # drain the spool before sleeping
                    if not futures:
                        if self.idle_exit_s is not None and \
                                time.monotonic() - idle_since \
                                >= float(self.idle_exit_s):
                            print("vft-serve: idle past "
                                  f"serve_idle_exit_s={self.idle_exit_s} — "
                                  "exiting")
                            break
                    self._stop.wait(self.poll_s)
                # bounded exit or stop: wait for in-flight requests (their
                # responses must land; atomic sinks make partial work safe)
                self._set_state("draining")
                for f in list(futures):
                    f.result()
        finally:
            with self._state_lock:
                self._inflight = 0
                self._state = "exited"
            self.recorder.close(tally=None, wall_s=None)
            if self.tracer is not None:
                # atomic temp+rename at close — an aborted server still
                # leaves a complete, stitchable trace behind
                self.tracer.close()
            # seal the compile-cache entry: the restarted server (or any
            # fleet sibling with the same fingerprint) attaches warm
            from . import compile_cache
            compile_cache.seal_active()
        return 143 if self._stop.is_set() else 0

    def stop(self) -> None:
        self._stop.set()


def serve_main(argv: Optional[List[str]] = None) -> None:
    """Entry point: ``vft-serve key=value ...`` (or
    ``python main.py serve ...``)."""
    from .config import (load_config, load_multi_config, parse_dotlist,
                         sanity_check, sanity_check_multi)
    from .registry import parse_feature_types
    argv = list(sys.argv[1:] if argv is None else argv)
    cli_args = parse_dotlist(argv)
    if "feature_type" not in cli_args or "spool_dir" not in cli_args:
        raise SystemExit(
            "Usage: vft-serve feature_type=<family>[,...] spool_dir=<dir> "
            "[key=value ...]   (docs/serving.md)")
    families = parse_feature_types(cli_args.feature_type)
    # file sinks only: responses point at artifacts, and the idempotent
    # skip + journals need per-family output dirs (print has neither)
    if cli_args.get("on_extraction", "save_numpy") == "print":
        raise SystemExit("vft-serve needs a file sink "
                         "(on_extraction=save_numpy or save_pickle): "
                         "responses reference artifact files")
    cli_args.setdefault("on_extraction", "save_numpy")
    from .cli import _enable_compilation_cache, _maybe_init_distributed
    if len(families) > 1:
        per_family = load_multi_config(families, cli_args)
        args = per_family[families[0]]
        # the user-level output root, captured BEFORE sanity_check
        # namespaces each family's path beneath it (cli.py does the same)
        out_root = str(args.output_path)
        _maybe_init_distributed(args)
        # no launch-time corpus: videos arrive per request
        sanity_check_multi(per_family, require_videos=False)
    else:
        per_family = None
        args = load_config(cli_args.feature_type, cli_args)
        _maybe_init_distributed(args)
        sanity_check(args, require_videos=False)
        out_root = str(args.output_path)
    _enable_compilation_cache(args)

    # fault-injection plan (utils/inject.py): armed for the server's
    # lifetime; VFT_INJECT overrides the config key (chaos harnesses
    # launch real server processes with the env var)
    from .utils import inject
    inject_plan = inject.arm_for_run(args.get("inject"))

    loop = ServeLoop(args, per_family=per_family, out_root=out_root)
    # SIGTERM/SIGINT: finish in-flight requests, final heartbeat, exit 143
    if threading.current_thread() is threading.main_thread():
        def _on_term(signo, frame):
            print("vft-serve: SIGTERM — draining in-flight requests")
            loop.stop()
        signal.signal(signal.SIGTERM, _on_term)
    try:
        rc = loop.run()
    finally:
        if inject_plan is not None:
            print(inject_plan.summary())
        inject.disarm()
    if rc:
        raise SystemExit(rc)


def main(argv: Optional[List[str]] = None) -> None:
    serve_main(argv)


if __name__ == "__main__":
    main()
