"""torch checkpoint -> Flax parameter transplant utilities.

The reference loads `.pt/.pth` torch state dicts (or fetches them from
torchvision / torch.hub / the OpenAI CDN — reference
models/_base/base_flow_extractor.py:118-137, models/r21d/extract_r21d.py:105-113,
models/clip/clip_src/clip.py:32-74). This module holds the generic layout
rules for converting those tensors into our NHWC/HWIO JAX trees; each model
file contributes its own key-mapping function built on these helpers.

Layout rules:
  - conv2d   OIHW  -> HWIO
  - conv3d   OIDHW -> DHWIO
  - linear   (out, in) -> (in, out)
  - batchnorm weight/bias/running_mean/running_var -> scale/bias/mean/var

torch is imported lazily: it is only needed when converting checkpoints (or in
parity tests), never on the TPU serving path.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np


def to_np(t) -> np.ndarray:
    """torch tensor -> float32/original-dtype numpy array (detached, CPU)."""
    arr = t.detach().cpu().numpy()
    return arr


def conv2d_kernel(t) -> np.ndarray:
    """OIHW -> HWIO."""
    return np.transpose(to_np(t), (2, 3, 1, 0))


def conv3d_kernel(t) -> np.ndarray:
    """OIDHW -> DHWIO."""
    return np.transpose(to_np(t), (2, 3, 4, 1, 0))


def linear_kernel(t) -> np.ndarray:
    """(out, in) -> (in, out)."""
    return np.transpose(to_np(t), (1, 0))


def bn_params(state_dict: Mapping[str, Any], prefix: str) -> Dict[str, np.ndarray]:
    """Map a torch BatchNorm{1,2,3}d at ``prefix`` to our inference-BN tree."""
    return {
        "scale": to_np(state_dict[f"{prefix}.weight"]),
        "bias": to_np(state_dict[f"{prefix}.bias"]),
        "mean": to_np(state_dict[f"{prefix}.running_mean"]),
        "var": to_np(state_dict[f"{prefix}.running_var"]),
    }


def set_in(tree: Dict[str, Any], path: str, value: np.ndarray) -> None:
    """Insert ``value`` at slash-separated ``path`` in a nested dict."""
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def strip_module_prefix(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Undo torch DataParallel's 'module.' prefix (reference utils/utils.py:232-238)."""
    out = {}
    for k, v in state_dict.items():
        out[k[len("module."):] if k.startswith("module.") else k] = v
    return out


def load_torch_state_dict(path: str) -> Dict[str, Any]:
    """Load a torch checkpoint file to CPU and unwrap common containers.

    Handles both plain pickled state_dicts and TorchScript archives — the
    OpenAI CLIP CDN ships JIT archives, which the reference unwraps the same
    way (reference models/clip/clip_src/clip.py:128-139: try jit.load, fall
    back to torch.load)."""
    import torch

    try:
        obj = torch.jit.load(path, map_location="cpu").state_dict()
    except RuntimeError:
        obj = torch.load(path, map_location="cpu", weights_only=False)
    if isinstance(obj, dict):
        for key in ("state_dict", "model_state_dict", "model"):
            if key in obj and isinstance(obj[key], dict):
                obj = obj[key]
                break
    return strip_module_prefix(obj)
