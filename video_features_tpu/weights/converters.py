"""Model-key -> (init_fn, convert_fn) registry for weight transplant.

One place that knows how to build the random template tree and map a torch
state_dict onto it, for every checkpoint family the framework loads
(SURVEY §2.5's transplant targets). Used by ``scripts/convert_weights.py``
for ahead-of-time ``.pth -> .msgpack`` conversion and by anything else that
needs a converter without constructing a full extractor.

The reference loads weights lazily per extractor from four different kinds
of source (local .pt/.pth, torchvision/torch.hub downloads, OpenAI CDN
TorchScript archives, GitHub releases — reference extract_r21d.py:105-113,
clip_src/clip.py:32-74, vggish_slim.py:122-127). Here every source funnels
through ``weights.store`` and these converters.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Tuple

Converter = Tuple[Callable[[], Any], Callable[[Dict[str, Any]], Any]]


def _clip_key_to_name() -> Dict[str, str]:
    from ..extractors.clip import model_key
    from ..models.clip import CONFIGS
    return {model_key(name): name for name in CONFIGS}


def registry() -> Dict[str, Converter]:
    """All convertible model keys (see store.HUB_FILENAMES for the accepted
    source checkpoint filenames). ``vggish_pca`` is intentionally absent: its
    params are two plain arrays loaded directly (models/vggish.py
    load_pca_params), not a flax tree."""
    from ..models import (clip as clip_m, i3d as i3d_m, pwc as pwc_m,
                          r21d as r21d_m, raft as raft_m, resnet as resnet_m,
                          s3d as s3d_m, vggish as vggish_m)

    reg: Dict[str, Converter] = {}
    for variant in resnet_m.VARIANTS:
        reg[variant] = (partial(resnet_m.init_params, variant),
                        resnet_m.params_from_torch)
    for variant in r21d_m.VARIANTS:
        reg[variant] = (partial(r21d_m.init_params, variant),
                        r21d_m.params_from_torch)
    for key in ("raft_sintel", "raft_kitti"):
        reg[key] = (raft_m.init_params, raft_m.params_from_torch)
    for modality in ("rgb", "flow"):
        reg[f"i3d_{modality}"] = (partial(i3d_m.init_params, modality),
                                  i3d_m.params_from_torch)
    reg["s3d_kinetics400"] = (s3d_m.init_params, s3d_m.params_from_torch)
    reg["pwc_sintel"] = (pwc_m.init_params, pwc_m.params_from_torch)
    reg["vggish"] = (vggish_m.init_params, vggish_m.params_from_torch)
    for key, name in _clip_key_to_name().items():
        reg[key] = (partial(clip_m.init_params, name),
                    clip_m.params_from_torch)
    return reg
