"""Weight resolution: find, convert and cache parameters for a model key.

The reference gets weights from four places (SURVEY §2.5): local ``.pt/.pth``
files in the repo, torchvision/torch.hub downloads, the OpenAI CDN (CLIP) and
GitHub releases (VGGish). This environment has no network egress, so the
story is:

  1. an explicit ``weights_path`` in the config — a torch checkpoint (``.pt``,
     ``.pth``) converted on the fly, or an already-converted ``.msgpack``;
  2. the ``VFT_WEIGHTS_DIR`` directory (default
     ``~/.cache/video_features_tpu``): ``{model_key}.msgpack`` converted
     previously, or ``{model_key}.pt[h]`` torch blobs dropped there;
  3. the torch hub cache (``$TORCH_HOME/hub/checkpoints``) for known
     torchvision/hub filenames;
  4. on a NETWORKED host, ``VFT_FETCH_WEIGHTS=1`` enables an in-process
     download from the same upstream sources the reference uses (OpenAI CDN
     with full SHA-256 pinning, reference models/clip/clip_src/clip.py:32-74;
     torchvggish GitHub releases, vggish_slim.py:122-127; torch-hub /
     torchvision CDN, extract_r21d.py:105-113), refusing on digest mismatch;
  5. random initialization — only if ``allow_random_weights`` is set (tests,
     dry runs, benchmarks that only measure throughput).
"""
from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Callable, Dict, Optional

# known torch-hub / CDN filenames per model key, for cache probing
HUB_FILENAMES: Dict[str, tuple] = {
    "resnet18": ("resnet18-f37072fd.pth", "resnet18-5c106cde.pth"),
    "resnet34": ("resnet34-b627a593.pth", "resnet34-333f7ec4.pth"),
    "resnet50": ("resnet50-0676ba61.pth", "resnet50-19c8e357.pth"),
    "resnet101": ("resnet101-63fe2227.pth", "resnet101-5d3b4d8f.pth"),
    "resnet152": ("resnet152-394f9c45.pth", "resnet152-b121ed2d.pth"),
    "r2plus1d_18_16_kinetics": ("r2plus1d_18-91a641e6.pth",),
    "r2plus1d_34_32_ig65m_ft_kinetics": ("r2plus1d_34_clip32_ig65m_from_scratch-449a7af9.pth",),
    "r2plus1d_34_8_ig65m_ft_kinetics": ("r2plus1d_34_clip8_ig65m_from_scratch-9bae36ae.pth",),
    # repo-local checkpoints in the reference (SURVEY §2.5); same filenames
    # accepted if dropped into VFT_WEIGHTS_DIR
    "raft_sintel": ("raft-sintel.pth",),
    "raft_kitti": ("raft-kitti.pth",),
    "i3d_rgb": ("i3d_rgb.pt",),
    "i3d_flow": ("i3d_flow.pt",),
    "s3d_kinetics400": ("S3D_kinetics400_torchified.pt",),
    "pwc_sintel": ("pwc_net_sintel.pt",),
    # torchvggish GitHub release filenames (reference vggish_slim.py:122-127)
    "vggish": ("vggish-10086976.pth",),
    "vggish_pca": ("vggish_pca_params-970ea276.pth", "vggish_pca_params.npz"),
    # OpenAI CDN filenames (reference clip_src/clip.py:32-42); TorchScript
    # archives are unwrapped by torch_import.load_torch_state_dict
    "clip_RN50": ("RN50.pt",),
    "clip_RN101": ("RN101.pt",),
    "clip_RN50x4": ("RN50x4.pt",),
    "clip_RN50x16": ("RN50x16.pt",),
    "clip_RN50x64": ("RN50x64.pt",),
    "clip_ViT-B-32": ("ViT-B-32.pt",),
    "clip_ViT-B-16": ("ViT-B-16.pt",),
    "clip_ViT-L-14": ("ViT-L-14.pt",),
    "clip_ViT-L-14-336px": ("ViT-L-14-336px.pt",),
}

#: full published SHA-256 digests: the OpenAI CDN embeds them in the
#: download URL path and the reference's _download() verifies exactly this
#: digest (reference models/clip/clip_src/clip.py:32-42,61-73)
CLIP_SHA256: Dict[str, str] = {
    "RN50.pt": "afeb0e10f9e5a86da6080e35cf09123aca3b358a0c3e3b6c78a7b63bc04b6762",
    "RN101.pt": "8fa8567bab74a42d41c5915025a8e4538c3bdbe8804a470a72f30b0d94fab599",
    "RN50x4.pt": "7e526bd135e493cef0776de27d5f42653e6b4c8bf9e0f653bb11773263205fdd",
    "RN50x16.pt": "52378b407f34354e150460fe41077663dd5b39c54cd0bfd2b27167a4a06ec9aa",
    "RN50x64.pt": "be1cfb55d75a9666199fb2206c106743da0f6468c9d327f3e0d0a543a9919d9c",
    "ViT-B-32.pt": "40d365715913c9da98579312b702a82c18be219cc2a73407c4526f58eba950af",
    "ViT-B-16.pt": "5806e77cd80f8b59890b7e101eabd078d9fb84e6937f9e85e4ecb61988df416f",
    "ViT-L-14.pt": "b8cca3fd41ae0c99ba7e8951adf17d267cdb84cd88be6f7c2e0eca1737a03836",
    "ViT-L-14-336px.pt": "3035c92b350959924f9f00213499208652fc7ea050643e8b385c2dac08641f02",
}

_TORCH_CDN = "https://download.pytorch.org/models/"
_IG65M = "https://github.com/moabitcoin/ig65m-pytorch/releases/download/v1.0.0/"
_VGGISH = "https://github.com/harritaylor/torchvggish/releases/download/v0.1/"
#: the reference vendors these blobs inside its own git tree
#: (.MISSING_LARGE_BLOBS); raw-file URLs are the only public source.
#: These are PICKLED torch checkpoints with no published digest, so a
#: mutable branch ref is an arbitrary-code-execution hazard: a moved or
#: compromised branch swaps the bytes under the same URL. Downloads
#: therefore require an immutable commit pin (``VFT_REF_COMMIT=<sha>``,
#: resolved at import so the URLs themselves are immutable); without one
#: the fetcher REFUSES these files unless ``VFT_ALLOW_MUTABLE_REF=1``
#: explicitly accepts the old master-ref behavior. Either way the first
#: successful fetch records the file's SHA-256 into
#: ``{weights_dir}/ref_digests.json`` and every later fetch verifies
#: against it (trust-on-first-use), so a silently-moved blob can never
#: replace an already-trusted one.
_REF_COMMIT = os.environ.get("VFT_REF_COMMIT", "")
_REF_RAW = ("https://github.com/habakan/video_features/raw/"
            f"{_REF_COMMIT or 'master'}/")
#: upstream filenames served from the reference repo's git tree (the
#: unpinned-pickle set the mutable-ref refusal above applies to)
REF_FILES = frozenset({
    "raft-sintel.pth", "raft-kitti.pth", "i3d_rgb.pt", "i3d_flow.pt",
    "S3D_kinetics400_torchified.pt", "pwc_net_sintel.pt",
})

#: upstream URL per filename — the same sources the reference downloads
#: from (or, for repo-local blobs, vendors)
WEIGHT_URLS: Dict[str, str] = {
    **{f: _TORCH_CDN + f for key in ("resnet18", "resnet34", "resnet50",
                                     "resnet101", "resnet152",
                                     "r2plus1d_18_16_kinetics")
       for f in HUB_FILENAMES[key]},
    **{f: _IG65M + f for key in ("r2plus1d_34_32_ig65m_ft_kinetics",
                                 "r2plus1d_34_8_ig65m_ft_kinetics")
       for f in HUB_FILENAMES[key]},
    "vggish-10086976.pth": _VGGISH + "vggish-10086976.pth",
    "vggish_pca_params-970ea276.pth": _VGGISH + "vggish_pca_params-970ea276.pth",
    **{f: f"https://openaipublic.azureedge.net/clip/models/{sha}/{f}"
       for f, sha in CLIP_SHA256.items()},
    "raft-sintel.pth": _REF_RAW + "models/raft/checkpoints/raft-sintel.pth",
    "raft-kitti.pth": _REF_RAW + "models/raft/checkpoints/raft-kitti.pth",
    "i3d_rgb.pt": _REF_RAW + "models/i3d/checkpoints/i3d_rgb.pt",
    "i3d_flow.pt": _REF_RAW + "models/i3d/checkpoints/i3d_flow.pt",
    "S3D_kinetics400_torchified.pt":
        _REF_RAW + "models/s3d/checkpoint/S3D_kinetics400_torchified.pt",
    "pwc_net_sintel.pt": _REF_RAW + "models/pwc/checkpoints/pwc_net_sintel.pt",
}


def expected_digest(fname: str):
    """``(kind, digest)`` for an upstream filename: ``'sha256'`` (full,
    CLIP CDN), ``'sha256-prefix'`` (the 8-hex tail torch-hub release names
    embed, e.g. ``resnet18-f37072fd.pth``), or ``(None, None)`` for the
    reference's repo-local blobs, which publish no digest."""
    if fname in CLIP_SHA256:
        return "sha256", CLIP_SHA256[fname]
    stem = Path(fname).stem
    if "-" in stem:
        tail = stem.rsplit("-", 1)[1]
        if len(tail) == 8 and all(c in "0123456789abcdef" for c in tail):
            return "sha256-prefix", tail
    return None, None


def _digest_registry_path() -> Path:
    return weights_dir() / "ref_digests.json"


def recorded_digest(fname: str) -> Optional[str]:
    """SHA-256 recorded for ``fname`` on a previous fetch (the
    trust-on-first-use registry for files with no published digest)."""
    import json
    try:
        with open(_digest_registry_path()) as f:
            return json.load(f).get(fname)
    except (OSError, ValueError):
        return None


def record_digest(fname: str, sha256: str) -> None:
    import json
    path = _digest_registry_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    data[fname] = sha256
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".json.tmp")
    # vft-lint: disable=VFT004 — temp+os.replace in place; the TOFU digest registry is advisory provenance, a lost record re-records on next fetch
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


#: a ``.part`` download older than this is litter from a fetcher that
#: died mid-stream (SIGKILL skips every unlink-on-failure handler) —
#: no live download runs this long, so the next fetch sweeps it
PART_STALE_S = 3600.0


def sweep_stale_parts(wd: Path, *, now: Optional[float] = None,
                      stale_s: float = PART_STALE_S) -> int:
    """Delete ``*.part`` temp files older than ``stale_s``. Young parts
    are left alone — a concurrent fetcher may still be streaming into
    them (the mkstemp names are per-process unique, so deleting someone
    else's LIVE part would fail their promote). Returns the count."""
    now = time.time() if now is None else float(now)
    swept = 0
    try:
        parts = sorted(Path(wd).glob("*.part"))
    except OSError:
        return 0
    for p in parts:
        try:
            if now - p.stat().st_mtime < stale_s:
                continue
            p.unlink()
            swept += 1
            print(f"weights: swept stale download litter {p.name}")
        except OSError:
            pass  # a sibling sweeper won the race, or perms: both fine
    return swept


def fetch_checkpoint(model_key: str) -> Optional[Path]:
    """Download ``model_key``'s upstream checkpoint into ``weights_dir()``,
    verifying the published SHA-256 while streaming. Mirrors the
    reference's behavior (clip.py:61-73): a digest mismatch deletes the
    file and raises — a truncated or tampered download is never usable.
    Files with no published digest (the reference's repo-local blobs)
    download with a provenance warning, matching the trust level of the
    reference's own git-hosted copies.

    Only called when ``VFT_FETCH_WEIGHTS=1`` (find_checkpoint); offline
    behavior is unchanged without the flag.
    """
    import hashlib
    import urllib.request
    wd = weights_dir()
    if wd.is_dir():
        sweep_stale_parts(wd)
    for fname in HUB_FILENAMES.get(model_key, ()):
        url = WEIGHT_URLS.get(fname)
        if url is None:
            continue
        dest = wd / fname
        kind, digest = expected_digest(fname)
        recorded = None
        if kind is None:
            if (fname in REF_FILES and not _REF_COMMIT
                    and os.environ.get("VFT_ALLOW_MUTABLE_REF") != "1"):
                raise RuntimeError(
                    f"{fname}: refusing to download a pickled checkpoint "
                    "from the MUTABLE 'master' ref of the reference repo "
                    "(torch.load is pickle — a moved or compromised branch "
                    "means arbitrary code execution). Pin an immutable "
                    "commit with VFT_REF_COMMIT=<sha>, or set "
                    "VFT_ALLOW_MUTABLE_REF=1 to accept the risk, or drop "
                    f"the file into {wd} yourself.")
            recorded = recorded_digest(fname)
            if recorded:
                print(f"{fname}: verifying against the SHA-256 recorded on "
                      f"first fetch ({_digest_registry_path()})")
            else:
                print(f"WARNING: no published digest for {fname}; "
                      f"downloading unverified from {url} (its SHA-256 "
                      "will be recorded for future fetches)")
        wd.mkdir(parents=True, exist_ok=True)
        # per-process unique temp name: concurrent fetchers sharing a
        # weights dir (multi-host launch) must never interleave writes
        # into one .part file and promote a co-written blob
        import tempfile
        fd, part_name = tempfile.mkstemp(prefix=fname + ".", suffix=".part",
                                         dir=wd)
        part = Path(part_name)
        h = hashlib.sha256()
        # wrap the fd BEFORE touching the network: if urlopen raises, the
        # with-statement still closes `out` (bare fd would leak per retry)
        # vft-lint: disable=VFT004 — verify-then-promote: the .part download is sha256-checked before the rename, a torn stream can never be promoted
        out = os.fdopen(fd, "wb")
        try:
            # socket-level timeout also bounds mid-stream read stalls — a
            # blackholed route must fail the fetch, not hang the run
            with out, urllib.request.urlopen(url, timeout=60) as src:
                while True:
                    chunk = src.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
                    out.write(chunk)
        except OSError as e:  # URLError subclasses OSError
            part.unlink(missing_ok=True)
            raise RuntimeError(
                f"VFT_FETCH_WEIGHTS=1: download of {url} failed ({e}). "
                "On an offline host, unset the flag and drop the file into "
                f"{wd} instead.") from e
        except Exception:
            part.unlink(missing_ok=True)
            raise
        got = h.hexdigest()
        ok = ((kind is None and (recorded is None or got == recorded)) or
              (kind == "sha256" and got == digest) or
              (kind == "sha256-prefix" and got.startswith(digest)))
        if not ok:
            part.unlink(missing_ok=True)
            which = (f"recorded digest (sha256:{recorded})" if kind is None
                     else f"published digest ({kind}:{digest})")
            raise RuntimeError(
                f"{fname}: downloaded file's SHA-256 {got[:16]}... does not "
                f"match the {which}; refusing to use it")
        os.replace(part, dest)  # atomic: never a torn final file
        if kind is None and recorded is None:
            # trust-on-first-use: later fetches verify against this
            record_digest(fname, got)
        verdict = (f" [{kind} verified]" if kind
                   else " [recorded sha256 verified]" if recorded
                   else f" [UNVERIFIED; sha256 {got[:16]}... recorded]")
        print(f"fetched {fname} -> {dest}{verdict}")
        return dest
    return None


def weights_dir() -> Path:
    return Path(os.environ.get(
        "VFT_WEIGHTS_DIR", os.path.expanduser("~/.cache/video_features_tpu")))


# -- weights-identity capture (cache.py feature-cache keying) ----------------
# resolve_params records WHAT it loaded (model key + file sha256, or the
# random-init sentinel) into the thread's active capture list, installed by
# BaseExtractor.__init__ right before the subclass resolves its params. The
# feature cache folds the capture into its key, so a swapped/re-converted
# checkpoint can never serve another checkpoint's cached features.

import threading as _threading

_capture_tls = _threading.local()


def start_weights_capture() -> list:
    """Begin a fresh capture on this thread; returns the (live) list that
    subsequent ``resolve_params`` calls on this thread append to. Each
    call replaces the active list, so sequentially-constructed extractors
    (multi-family runs) each keep only their own resolutions."""
    cap: list = []
    _capture_tls.capture = cap
    return cap


def _record_resolution(rec: dict) -> None:
    cap = getattr(_capture_tls, "capture", None)
    if cap is not None:
        cap.append(rec)


def _file_fingerprint(path: Path) -> str:
    """Streamed sha256 of the resolved checkpoint (memoized through
    cache.file_sha256 so repeated constructions don't re-hash)."""
    from ..cache import file_sha256
    return file_sha256(str(path))


def find_checkpoint(model_key: str,
                    explicit_path: Optional[str] = None) -> Optional[Path]:
    """Locate a weight file for ``model_key`` (msgpack preferred, else torch)."""
    if explicit_path:
        p = Path(explicit_path)
        if not p.exists():
            raise FileNotFoundError(f"weights_path does not exist: {p}")
        return p
    wd = weights_dir()
    for ext in (".msgpack", ".pt", ".pth"):
        p = wd / f"{model_key}{ext}"
        if p.exists():
            return p
    torch_home = Path(os.environ.get("TORCH_HOME",
                                     os.path.expanduser("~/.cache/torch")))
    for fname in HUB_FILENAMES.get(model_key, ()):
        # original upstream filenames are accepted both in the torch hub
        # cache and dropped directly into VFT_WEIGHTS_DIR
        for p in (torch_home / "hub" / "checkpoints" / fname, wd / fname):
            if p.exists():
                return p
    if os.environ.get("VFT_FETCH_WEIGHTS") == "1":
        return fetch_checkpoint(model_key)
    return None


def save_msgpack(params: Any, path: Path) -> None:
    from flax import serialization
    from ..utils.sinks import _write_bytes_atomic
    # a converted checkpoint is a durable artifact other runs will load
    # and fingerprint: a torn write must never be promotable
    _write_bytes_atomic(str(path), serialization.to_bytes(params))


def load_msgpack(template: Any, path: Path) -> Any:
    from flax import serialization
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def resolve_params(model_key: str,
                   init_fn: Callable[[], Any],
                   convert_fn: Callable[[Dict[str, Any]], Any],
                   weights_path: Optional[str] = None,
                   allow_random: bool = False,
                   cache_converted: bool = True) -> Any:
    """Return a parameter tree for ``model_key``.

    ``init_fn``: builds a randomly-initialized tree (also the msgpack
    template). ``convert_fn``: maps a torch state_dict onto that tree.
    """
    ckpt = find_checkpoint(model_key, weights_path)
    if ckpt is None:
        if allow_random:
            print(f"WARNING: no weights found for {model_key!r}; using RANDOM "
                  "init (allow_random_weights=true). Features will be "
                  "meaningless — for tests/benchmarks only.")
            # seeded init is deterministic: the sentinel keys cache entries
            # for random-weight runs (tests/benches) without a file to hash
            _record_resolution({"model_key": model_key, "random": True})
            return init_fn()
        raise FileNotFoundError(
            f"No weights for {model_key!r}. Provide `weights_path=...`, drop "
            f"a checkpoint into {weights_dir()}, or set "
            "`allow_random_weights=true` for throughput-only runs. Known "
            f"source filenames: {HUB_FILENAMES.get(model_key, '(model-specific)')}")
    try:
        _record_resolution({"model_key": model_key, "path": str(ckpt),
                            "sha256": _file_fingerprint(ckpt)})
    except OSError:
        # capture is keying metadata, not a load requirement; an unreadable
        # stat/hash surfaces as the load failure below if it matters
        pass
    if ckpt.suffix == ".msgpack":
        return load_msgpack(init_fn(), ckpt)
    from .torch_import import load_torch_state_dict
    params = convert_fn(load_torch_state_dict(str(ckpt)))
    if weights_path:
        # an explicit (possibly fine-tuned) checkpoint must not poison the
        # generic {model_key}.msgpack cache used by weights_path-less runs
        cache_converted = False
    if cache_converted:
        out = weights_dir() / f"{model_key}.msgpack"
        try:
            save_msgpack(params, out)
        except OSError:
            pass
    return params
