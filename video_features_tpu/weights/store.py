"""Weight resolution: find, convert and cache parameters for a model key.

The reference gets weights from four places (SURVEY §2.5): local ``.pt/.pth``
files in the repo, torchvision/torch.hub downloads, the OpenAI CDN (CLIP) and
GitHub releases (VGGish). This environment has no network egress, so the
story is:

  1. an explicit ``weights_path`` in the config — a torch checkpoint (``.pt``,
     ``.pth``) converted on the fly, or an already-converted ``.msgpack``;
  2. the ``VFT_WEIGHTS_DIR`` directory (default
     ``~/.cache/video_features_tpu``): ``{model_key}.msgpack`` converted
     previously, or ``{model_key}.pt[h]`` torch blobs dropped there;
  3. the torch hub cache (``$TORCH_HOME/hub/checkpoints``) for known
     torchvision/hub filenames;
  4. random initialization — only if ``allow_random_weights`` is set (tests,
     dry runs, benchmarks that only measure throughput).
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Callable, Dict, Optional

# known torch-hub / CDN filenames per model key, for cache probing
HUB_FILENAMES: Dict[str, tuple] = {
    "resnet18": ("resnet18-f37072fd.pth", "resnet18-5c106cde.pth"),
    "resnet34": ("resnet34-b627a593.pth", "resnet34-333f7ec4.pth"),
    "resnet50": ("resnet50-0676ba61.pth", "resnet50-19c8e357.pth"),
    "resnet101": ("resnet101-63fe2227.pth", "resnet101-5d3b4d8f.pth"),
    "resnet152": ("resnet152-394f9c45.pth", "resnet152-b121ed2d.pth"),
    "r2plus1d_18_16_kinetics": ("r2plus1d_18-91a641e6.pth",),
    "r2plus1d_34_32_ig65m_ft_kinetics": ("r2plus1d_34_clip32_ig65m_from_scratch-449a7af9.pth",),
    "r2plus1d_34_8_ig65m_ft_kinetics": ("r2plus1d_34_clip8_ig65m_from_scratch-9bae36ae.pth",),
    # repo-local checkpoints in the reference (SURVEY §2.5); same filenames
    # accepted if dropped into VFT_WEIGHTS_DIR
    "raft_sintel": ("raft-sintel.pth",),
    "raft_kitti": ("raft-kitti.pth",),
    "i3d_rgb": ("i3d_rgb.pt",),
    "i3d_flow": ("i3d_flow.pt",),
    "s3d_kinetics400": ("S3D_kinetics400_torchified.pt",),
    "pwc_sintel": ("pwc_net_sintel.pt",),
    # torchvggish GitHub release filenames (reference vggish_slim.py:122-127)
    "vggish": ("vggish-10086976.pth",),
    "vggish_pca": ("vggish_pca_params-970ea276.pth", "vggish_pca_params.npz"),
    # OpenAI CDN filenames (reference clip_src/clip.py:32-42); TorchScript
    # archives are unwrapped by torch_import.load_torch_state_dict
    "clip_RN50": ("RN50.pt",),
    "clip_RN101": ("RN101.pt",),
    "clip_RN50x4": ("RN50x4.pt",),
    "clip_RN50x16": ("RN50x16.pt",),
    "clip_RN50x64": ("RN50x64.pt",),
    "clip_ViT-B-32": ("ViT-B-32.pt",),
    "clip_ViT-B-16": ("ViT-B-16.pt",),
    "clip_ViT-L-14": ("ViT-L-14.pt",),
    "clip_ViT-L-14-336px": ("ViT-L-14-336px.pt",),
}


def weights_dir() -> Path:
    return Path(os.environ.get(
        "VFT_WEIGHTS_DIR", os.path.expanduser("~/.cache/video_features_tpu")))


def find_checkpoint(model_key: str,
                    explicit_path: Optional[str] = None) -> Optional[Path]:
    """Locate a weight file for ``model_key`` (msgpack preferred, else torch)."""
    if explicit_path:
        p = Path(explicit_path)
        if not p.exists():
            raise FileNotFoundError(f"weights_path does not exist: {p}")
        return p
    wd = weights_dir()
    for ext in (".msgpack", ".pt", ".pth"):
        p = wd / f"{model_key}{ext}"
        if p.exists():
            return p
    torch_home = Path(os.environ.get("TORCH_HOME",
                                     os.path.expanduser("~/.cache/torch")))
    for fname in HUB_FILENAMES.get(model_key, ()):
        # original upstream filenames are accepted both in the torch hub
        # cache and dropped directly into VFT_WEIGHTS_DIR
        for p in (torch_home / "hub" / "checkpoints" / fname, wd / fname):
            if p.exists():
                return p
    return None


def save_msgpack(params: Any, path: Path) -> None:
    from flax import serialization
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(params))


def load_msgpack(template: Any, path: Path) -> Any:
    from flax import serialization
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def resolve_params(model_key: str,
                   init_fn: Callable[[], Any],
                   convert_fn: Callable[[Dict[str, Any]], Any],
                   weights_path: Optional[str] = None,
                   allow_random: bool = False,
                   cache_converted: bool = True) -> Any:
    """Return a parameter tree for ``model_key``.

    ``init_fn``: builds a randomly-initialized tree (also the msgpack
    template). ``convert_fn``: maps a torch state_dict onto that tree.
    """
    ckpt = find_checkpoint(model_key, weights_path)
    if ckpt is None:
        if allow_random:
            print(f"WARNING: no weights found for {model_key!r}; using RANDOM "
                  "init (allow_random_weights=true). Features will be "
                  "meaningless — for tests/benchmarks only.")
            return init_fn()
        raise FileNotFoundError(
            f"No weights for {model_key!r}. Provide `weights_path=...`, drop "
            f"a checkpoint into {weights_dir()}, or set "
            "`allow_random_weights=true` for throughput-only runs. Known "
            f"source filenames: {HUB_FILENAMES.get(model_key, '(model-specific)')}")
    if ckpt.suffix == ".msgpack":
        return load_msgpack(init_fn(), ckpt)
    from .torch_import import load_torch_state_dict
    params = convert_fn(load_torch_state_dict(str(ckpt)))
    if weights_path:
        # an explicit (possibly fine-tuned) checkpoint must not poison the
        # generic {model_key}.msgpack cache used by weights_path-less runs
        cache_converted = False
    if cache_converted:
        out = weights_dir() / f"{model_key}.msgpack"
        try:
            save_msgpack(params, out)
        except OSError:
            pass
    return params
