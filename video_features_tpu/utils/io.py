"""Host-side video decode: streaming cv2 reader with in-process fps resampling.

Re-design of the reference's `VideoLoader` + ffmpeg re-encoding
(reference utils/io.py:14-176). Behavioral contract kept:

  - iterator yields ``(batch, timestamps_ms, indices)`` where ``batch`` is a
    list of per-frame transformed arrays, ``timestamps_ms[i] = idx/fps*1000``
    (reference utils/io.py:132), frames are RGB;
  - ``fps=N`` resamples to N fps; ``total=N`` targets a fixed number of frames
    by computing ``new_fps = total*src_fps/num_frames`` (reference
    utils/io.py:83-89); the two are mutually exclusive;
  - first batch has ``batch_size`` frames, later batches carry ``overlap``
    frames over from the previous batch (reference utils/io.py:120-152), the
    last batch may be short;
  - cv2's occasionally-missing frame #0 is worked around (reference
    utils/io.py:99-106).

Deliberate divergence: the reference shells out to
``ffmpeg -filter:v fps=N`` writing a *re-encoded* (lossy x264) temp file and
then decodes that (reference utils/io.py:14-36). Here the DEFAULT
(``fps_mode='select'``) is pure frame selection/duplication on the decoded
stream — the same frame-timing rule as ffmpeg's fps filter (round=near), but
with bit-exact source pixels, no temp files, no subprocess, and no double
decode. This is strictly more accurate and keeps the single host core free to
feed the TPU.

``fps_mode='reencode'`` opts back into the reference's exact provenance for
golden/parity runs: the committed golden refs were computed from *re-encoded*
pixels, so value-level comparison of fps-resampled variants must decode the
same lossy intermediate (VERDICT r4 missing #2). With an ffmpeg binary on
PATH it reproduces the reference command byte for byte; otherwise a cv2
``VideoWriter`` (mp4v) fallback writes the same frame selection through a
lossy codec so the decode-path feature delta stays measurable on
ffmpeg-less hosts (docs/performance.md records the measured numbers).
"""
from __future__ import annotations

import threading
from pathlib import Path
from typing import Callable, Iterator, List, Optional, Tuple, Union

import cv2
import numpy as np

from .faults import DeadlineExceeded


def get_video_props(path: Union[str, Path]) -> dict:
    """fps / num_frames / height / width via cv2 (reference utils/io.py:167-176)."""
    cap = cv2.VideoCapture(str(path))
    try:
        props = dict(
            fps=cap.get(cv2.CAP_PROP_FPS),
            num_frames=int(cap.get(cv2.CAP_PROP_FRAME_COUNT)),
            height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
            width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
        )
    finally:
        cap.release()
    if not props["fps"] or props["fps"] <= 0:
        raise ValueError(f"Cannot determine fps of {path}")
    return props


def count_frames_by_decode(path: Union[str, Path]) -> int:
    """Exact frame count by decoding the whole stream once.

    Fallback for containers where CAP_PROP_FRAME_COUNT is 0/garbage; only used
    on the resampling path, where a wrong count would silently truncate the
    output (and the idempotent skip would then make the loss permanent)."""
    cap = cv2.VideoCapture(str(path))
    n = 0
    try:
        while True:
            ok, _ = cap.read()
            if not ok:
                break
            n += 1
    finally:
        cap.release()
    return n


def fps_filter_map(num_frames: int, src_fps: float, dst_fps: float) -> np.ndarray:
    """Output->source frame-index map of ffmpeg's ``fps=dst_fps`` filter.

    ffmpeg's fps filter (round=near) assigns each input frame i (pts i/src_fps)
    the output slot ``round(i * dst_fps / src_fps)`` and fills every output
    slot with the latest input frame whose slot <= it (duplicating to fill
    gaps, dropping when several inputs collapse onto one slot). The stream
    ends at the EOF timestamp ``num_frames / src_fps`` (last pts + frame
    duration), so the filter emits exactly
    ``round(num_frames * dst_fps / src_fps)`` frames — a final input frame
    whose own slot lands past that cutoff is dropped, and on upsampling the
    last frame duplicates up to it. Verified against outputs recorded from
    the real binary: the golden refs pin 54 frames at fps=3 and 18 at fps=1
    for the 355-frame 19.62-fps sample (tests/test_golden.py), where the
    naive ``last slot + 1`` rule would emit one extra frame.

    Returns an int array `m` of length n_out with out[k] = src[m[k]];
    m is monotonic.
    """
    if num_frames <= 0:
        return np.zeros((0,), dtype=np.int64)
    r = dst_fps / src_fps
    i = np.arange(num_frames, dtype=np.float64)
    # half-away-from-zero rounding (ffmpeg AV_ROUND_NEAR_INF), NOT np.round's
    # banker's rounding: at an exact 2x downsample the two differ and banker's
    # rounding would select temporally non-uniform frames
    slots = np.floor(i * r + 0.5).astype(np.int64)
    # one guarded frame minimum: a video short enough to round to zero output
    # frames would otherwise produce an empty stream downstream
    n_out = max(int(np.floor(num_frames * r + 0.5)), 1)
    mapping = np.zeros((n_out,), dtype=np.int64)
    # latest input frame per slot wins; forward-fill gaps; slots at or past
    # the EOF cutoff are dropped with their frames
    last = 0
    src_of_slot = {}
    for idx, s in enumerate(slots):
        src_of_slot[int(s)] = idx
    for k in range(n_out):
        if k in src_of_slot:
            last = src_of_slot[k]
        mapping[k] = last
    return mapping


def plan_frame_selection(src_fps: float, src_num_frames: int,
                         fps: Optional[float] = None,
                         total: Optional[int] = None,
                         total_cap: Optional[int] = None,
                         ) -> Tuple[float, Optional[np.ndarray], int]:
    """Resolve one consumer's ``fps``/``total`` request against a source
    stream: ``(out_fps, index_map_or_None, num_frames)``.

    This is the frame-selection walk every decoded-stream consumer agrees
    on — :class:`VideoSource` applies it serially, and the multi-family
    shared-decode bus (parallel/fanout.py) computes each subscriber's plan
    with the SAME function so the union decode pass is provably
    bit-identical to N independent serial passes. ``index_map=None``
    means native delivery (every source frame, out index == src index);
    ``total_cap`` reproduces the reencode+total stop-early contract
    (reference utils/io.py:117-119) for VideoSource's temp-file path.
    Callers must resolve a lying ``src_num_frames <= 0`` (see
    :func:`count_frames_by_decode`) before requesting a resampling plan.
    """
    if total is not None:
        # reference utils/io.py:83-89: derive the fps that yields ~total
        fps = total * src_fps / max(src_num_frames, 1)
    if fps is not None:
        index_map = fps_filter_map(src_num_frames, src_fps, float(fps))
        if total is not None:
            index_map = index_map[:total]
        return float(fps), index_map, len(index_map)
    num_frames = src_num_frames
    if total_cap is not None:
        num_frames = min(num_frames, total_cap) if num_frames > 0 \
            else total_cap
    return float(src_fps), None, num_frames


def reencode_video_with_diff_fps(video_path: Union[str, Path],
                                 tmp_path: Union[str, Path],
                                 extraction_fps: float,
                                 backend: str = "auto") -> str:
    """Write a lossy re-encoded copy of ``video_path`` resampled to
    ``extraction_fps`` into ``tmp_path``; return its path.

    ``backend='ffmpeg'`` reproduces the reference's command exactly
    (``ffmpeg -hide_banner -loglevel panic -y -i <in> -filter:v
    fps=fps=<fps> <out>``, reference utils/io.py:14-36) including the
    ``{stem}_new_fps.mp4`` temp naming. ``backend='cv2'`` decodes the
    source, applies the SAME frame selection (fps_filter_map — verified
    against the real filter) and writes through cv2's mp4v encoder: the
    frame timing is identical, the pixels go through a different lossy
    codec (MPEG-4 pt.2 vs x264). ``'auto'`` prefers ffmpeg when on PATH.
    """
    import shutil as _shutil
    video_path, tmp_path = str(video_path), str(tmp_path)
    if backend == "auto":
        backend = "ffmpeg" if _shutil.which("ffmpeg") else "cv2"
    Path(tmp_path).mkdir(parents=True, exist_ok=True)
    new_path = str(Path(tmp_path) / f"{Path(video_path).stem}_new_fps.mp4")

    if backend == "ffmpeg":
        import subprocess
        cmd = [_shutil.which("ffmpeg"), "-hide_banner", "-loglevel",
               "panic", "-y", "-i", video_path,
               "-filter:v", f"fps=fps={extraction_fps}", new_path]
        subprocess.run(cmd, check=True)
        return new_path
    if backend != "cv2":
        raise ValueError(f"unknown reencode backend {backend!r}")

    props = get_video_props(video_path)
    n = props["num_frames"]
    if n <= 0:
        n = count_frames_by_decode(video_path)
        if n == 0:
            raise ValueError(f"No decodable frames in {video_path}")
    mapping = fps_filter_map(n, props["fps"], float(extraction_fps))
    writer = cv2.VideoWriter(
        new_path, cv2.VideoWriter_fourcc(*"mp4v"), float(extraction_fps),
        (props["width"], props["height"]))
    if not writer.isOpened():
        raise RuntimeError(
            f"cv2 VideoWriter cannot open {new_path} (mp4v); install "
            "ffmpeg for fps_mode=reencode on this host")
    stream = _FrameStream(video_path, channel_order="bgr")
    try:
        src_idx = -1
        current = None
        for want in mapping:
            while src_idx < want:
                current = stream.read()
                if current is None:
                    break
                src_idx += 1
            if current is None:
                break
            writer.write(current)
    finally:
        stream.release()
        writer.release()
    return new_path


#: channel orders a decoded stream can deliver: 'rgb' (converted), 'bgr'
#: (decoder-native, conversion deferred/skipped), 'i420' (packed
#: (H*3/2, W) YUV 4:2:0 planes at 1.5 B/px — the raw-YUV ingest wire,
#: colorspace conversion fused on device via ops/colorspace.py)
CHANNEL_ORDERS = ("rgb", "bgr", "i420")


def convert_decoded(frame_bgr: np.ndarray, channel_order: str) -> np.ndarray:
    """Decoder-native BGR frame -> the requested delivery format (the one
    shared conversion point of the serial, segment-worker and fan-out
    decode paths, so they cannot drift)."""
    if channel_order == "bgr":
        return frame_bgr
    if channel_order == "i420":
        from ..ops.colorspace import bgr_to_yuv420_frame
        return bgr_to_yuv420_frame(frame_bgr)
    return cv2.cvtColor(frame_bgr, cv2.COLOR_BGR2RGB)


class _FrameStream:
    """Sequential decoder with the missing-frame-0 workaround.

    ``channel_order='bgr'`` skips the per-frame ``cv2.cvtColor`` and yields
    the decoder's native BGR buffer. Transforms whose ops are all
    channel-independent (float conversion, resize, crop) can defer the
    RGB reorder to their smallest intermediate — a cheap slice on a
    112px crop instead of a full-resolution conversion pass per frame —
    with bit-identical results (channel reorder commutes with per-channel
    ops). The r21d/s3d host transforms use this.

    ``channel_order='i420'`` yields packed YUV 4:2:0 planes in cv2's
    (H*3/2, W) layout: ONE ``BGR2YUV_I420`` conversion replaces the
    BGR->RGB reorder and every downstream buffer carries 1.5 bytes/pixel
    instead of 3 — the raw-YUV ingest wire (``ingest=yuv420`` with
    ``resize=device``), converted back to RGB on device
    (ops/colorspace.py). Requires even frame dimensions (I420 chroma
    subsampling).
    """

    def __init__(self, path: str, channel_order: str = "rgb"):
        assert channel_order in CHANNEL_ORDERS, channel_order
        self.cap = cv2.VideoCapture(path)
        self._first = True
        self._order = channel_order
        self._path = str(path)
        # chaos hook (utils/inject.py `decode.read`): the armed plan is
        # captured once per stream so the per-frame cost when injection
        # is off stays one attribute read — every decode path (serial,
        # segment workers, the shared FrameBus) reads through here
        from . import inject
        self._inject = inject.active()

    def read(self) -> Optional[np.ndarray]:
        if self._inject is not None:
            self._inject.check("decode.read", {"video": self._path})
        # local ref: a concurrent release() (deadline watchdog) nulls
        # self.cap; going through the local keeps this thread's call
        # coherent and the next loop iteration observes the None
        cap = self.cap
        if cap is None:
            return None
        ok, frame = cap.read()
        if not ok and self._first:
            # cv2 sometimes fails on frame #0 only (reference utils/io.py:99-106)
            print("Detect missing frame")
            ok, frame = cap.read()
        self._first = False
        if not ok:
            return None
        return convert_decoded(frame, self._order)

    def skip(self) -> bool:
        """Advance one frame WITHOUT materializing it: ``grab()`` demuxes
        and decodes (inter-frame dependencies need that) but skips
        ``retrieve()``'s YUV->BGR conversion + frame copy. At
        extraction_fps=1 from a ~20 fps source, ~95% of frames are dropped
        by the fps filter — they pay decode only, never conversion.
        Same frame-0 retry as :meth:`read` (the missing-frame-0 workaround
        shifts indices identically on both paths)."""
        cap = self.cap
        if cap is None:
            return False
        ok = cap.grab()
        if not ok and self._first:
            print("Detect missing frame")
            ok = cap.grab()
        self._first = False
        return ok

    def release(self):
        # swap-then-release: idempotent and callable from the watchdog
        # thread while the decode thread is inside read()/skip() — cv2
        # fails the in-flight call instead of blocking forever
        cap, self.cap = self.cap, None
        if cap is not None:
            cap.release()


class VideoSource:
    """Streaming batched frame source.

    Yields ``(batch, timestamps_ms, indices)`` like the reference VideoLoader.
    ``fps``/``total`` resampling happens in-process (see module docstring).
    """

    def __init__(self,
                 path: Union[str, Path],
                 batch_size: int = 1,
                 fps: Optional[float] = None,
                 total: Optional[int] = None,
                 transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 overlap: int = 0,
                 channel_order: str = "rgb",
                 fps_mode: str = "select",
                 tmp_path: Optional[Union[str, Path]] = None,
                 keep_tmp: bool = False):
        assert isinstance(batch_size, int) and batch_size > 0
        assert isinstance(overlap, int) and 0 <= overlap < batch_size
        # eager: _FrameStream re-checks lazily at first decode, but that
        # fires inside a worker thread as a per-video failure, far from the
        # misconfigured call site
        assert channel_order in CHANNEL_ORDERS, channel_order
        if fps is not None and total is not None:
            raise ValueError("'fps' and 'total' are mutually exclusive")
        if fps_mode not in ("select", "reencode"):
            raise ValueError(
                f"fps_mode={fps_mode!r}: expected 'select' or 'reencode'")
        self.path = str(path)
        self.batch_size = batch_size
        self.transform = transform
        self.overlap = overlap
        #: 'bgr' defers the RGB reorder into the transform (see _FrameStream)
        self.channel_order = channel_order

        # deadline-watchdog support (utils/faults.py FaultContext):
        # cancel() is thread-safe and kills the in-flight decode
        self._cancelled = False
        self._cancel_reason = ""
        self._active_stream: Optional[_FrameStream] = None
        self._state_lock = threading.Lock()

        self._tmp_file: Optional[str] = None
        self._keep_tmp = keep_tmp
        self._total_cap: Optional[int] = None
        if fps_mode == "reencode" and (fps is not None or total is not None):
            # reference-provenance path: decode a lossy re-encoded temp
            # file at the target rate (reference utils/io.py:75-89 does
            # this for BOTH fps and total) and iterate it natively
            if tmp_path is None:
                raise ValueError("fps_mode='reencode' requires tmp_path")
            src_props = get_video_props(self.path)
            n0 = src_props["num_frames"]
            if total is not None and n0 <= 0:
                n0 = count_frames_by_decode(self.path)
            eff_fps = (fps if fps is not None
                       else total * src_props["fps"] / max(n0, 1))
            self._tmp_file = reencode_video_with_diff_fps(
                self.path, tmp_path, eff_fps)
            self.path = self._tmp_file
            self._total_cap = total
            fps = total = None

        props = get_video_props(self.path)
        self.src_fps = props["fps"]
        self.src_num_frames = props["num_frames"]
        self.height, self.width = props["height"], props["width"]

        if (fps is not None or total is not None) and self.src_num_frames <= 0:
            # metadata lied; resampling needs a real count (see
            # count_frames_by_decode) or the output would be truncated
            self.src_num_frames = count_frames_by_decode(self.path)
            if self.src_num_frames == 0:
                raise ValueError(f"No decodable frames in {self.path}")
        self.fps, self.index_map, self.num_frames = plan_frame_selection(
            self.src_fps, self.src_num_frames, fps=fps, total=total,
            total_cap=self._total_cap)

    def __len__(self):
        return self.num_frames

    def cancel(self, reason: str = "cancelled") -> None:
        """Thread-safe kill of the in-flight decode (deadline watchdog).

        Marks the source cancelled and releases the active
        ``_FrameStream`` so a read blocked inside cv2 fails promptly; the
        iterating thread then raises :class:`DeadlineExceeded` instead of
        emitting a silently-truncated stream."""
        with self._state_lock:
            self._cancelled = True
            self._cancel_reason = reason or "cancelled"
            stream = self._active_stream
        if stream is not None:
            stream.release()

    def release(self) -> None:
        """Thread-safe teardown (same surface as ProcessVideoSource /
        ParallelVideoSource): cancels any in-flight iteration and drops
        the re-encoded temp file if one exists."""
        self.cancel("released")
        self._cleanup_tmp()

    def _raise_if_cancelled(self) -> None:
        if self._cancelled:
            raise DeadlineExceeded(
                f"{self.path}: {self._cancel_reason}")

    def _cleanup_tmp(self) -> None:
        tmp, self._tmp_file = self._tmp_file, None
        if tmp and not self._keep_tmp:
            self._tmp_deleted = True
            try:
                Path(tmp).unlink(missing_ok=True)
            except OSError:
                pass

    def frames(self) -> Iterator[Tuple[np.ndarray, float, int]]:
        """Yield (frame, timestamp_ms, out_index) sequentially.

        Frames have ``self.transform`` applied (when set), exactly like the
        batched ``__iter__`` path — the two views must agree or per-frame
        resize/crop would silently be skipped for one of them.
        """
        from .profiling import profiler
        if getattr(self, "_tmp_deleted", False):
            # cv2 on a missing path fails SILENTLY (read() -> None): a
            # second pass over a consumed reencode-mode source would yield
            # an empty stream, not an error — fail loudly instead
            raise RuntimeError(
                f"reencode-mode VideoSource for {self.path} is single-"
                "pass: its re-encoded temp file was already deleted "
                "(construct a new source, or pass keep_tmp=True)")
        stream = _FrameStream(self.path, self.channel_order)
        with self._state_lock:
            self._active_stream = stream
        # checked AFTER registering: a cancel() landing between flag-set
        # and registration is caught here instead of being lost
        try:
            self._raise_if_cancelled()
        except DeadlineExceeded:
            with self._state_lock:
                self._active_stream = None
            stream.release()
            raise
        tf = self.transform

        def emit(rgb, out_idx):
            with profiler.stage("decode"):
                x = tf(rgb) if tf is not None else rgb
            return x, out_idx / self.fps * 1000.0, out_idx

        def timed_read():
            with profiler.stage("decode"):
                return stream.read()

        try:
            if self.index_map is None:
                out_idx = 0
                while self._total_cap is None or out_idx < self._total_cap:
                    self._raise_if_cancelled()
                    rgb = timed_read()
                    if rgb is None:
                        # a watchdog-released stream ends exactly like a
                        # normal EOF — distinguish them or a killed decode
                        # would write truncated features as a success
                        self._raise_if_cancelled()
                        return
                    yield emit(rgb, out_idx)
                    out_idx += 1
            else:
                src_idx = -1
                current = None
                for out_idx, want in enumerate(self.index_map):
                    self._raise_if_cancelled()
                    while src_idx < want:
                        if src_idx < want - 1:
                            # this source frame is dropped by the fps
                            # filter: grab()-skip it (no conversion/copy,
                            # see _FrameStream.skip)
                            with profiler.stage("decode"):
                                ok = stream.skip()
                            nxt = True if ok else None
                        else:
                            nxt = timed_read()
                            current = nxt
                        if nxt is None:
                            self._raise_if_cancelled()
                            # container metadata overstated the frame count;
                            # reaching stream end inside this loop always
                            # means the resampled output is short
                            print(f"Warning: {self.path} ended after "
                                  f"{src_idx + 1} frames (metadata said "
                                  f"{self.src_num_frames}); emitted "
                                  f"{out_idx}/{len(self.index_map)} "
                                  "resampled frames.")
                            return
                        src_idx += 1
                    yield emit(current, out_idx)
        finally:
            with self._state_lock:
                self._active_stream = None
            stream.release()
            self._cleanup_tmp()

    def __iter__(self) -> Iterator[Tuple[List, List[float], List[int]]]:
        return _batched(self.frames(), self.batch_size, self.overlap)

    def __del__(self):  # abandoned before/inside iteration: drop the
        try:            # re-encoded temp file (reference utils/io.py:160-164)
            self._cleanup_tmp()
        except Exception:
            pass


def _batched(frames: Iterator[Tuple[np.ndarray, float, int]],
             batch_size: int, overlap: int
             ) -> Iterator[Tuple[List, List[float], List[int]]]:
    """Batch a ``frames()`` stream (shared by VideoSource and
    ProcessVideoSource, whose frame iteration differs but whose batching
    contract must not)."""
    batch: List = []
    times: List[float] = []
    indices: List[int] = []
    fresh = 0  # frames added since the last yield (excludes carried overlap)
    for x, ts, idx in frames:  # frames() already applies transform
        batch.append(x)
        times.append(ts)
        indices.append(idx)
        fresh += 1
        if len(batch) == batch_size:
            yield batch, times, indices
            keep = overlap
            batch = batch[len(batch) - keep:] if keep else []
            times = times[len(times) - keep:] if keep else []
            indices = indices[len(indices) - keep:] if keep else []
            fresh = 0
    # the last batch may be short, but a batch of only carried-over
    # overlap frames is never emitted (reference utils/io.py:109-146)
    if fresh > 0:
        yield batch, times, indices


def _decode_worker(q, path: str, kwargs: dict) -> None:
    """ProcessVideoSource child body: decode + transform only.

    Runs in a SPAWNED interpreter whose imports stay light (numpy / cv2 /
    PIL via ops.host_transforms) — jax must never initialize here: on
    hosts whose sitecustomize injects an accelerator platform into every
    process, a jax op in a child could claim the single TPU chip out from
    under the parent."""
    try:
        src = VideoSource(path, **kwargs)
        q.put(("props", {"fps": src.fps, "src_fps": src.src_fps,
                         "num_frames": src.num_frames,
                         "src_num_frames": src.src_num_frames,
                         "height": src.height, "width": src.width}))
        for item in src.frames():
            q.put(("frame", item))
        q.put(("done", None))
    except BaseException as e:
        try:
            q.put(("error", f"{type(e).__name__}: {e}"))
        except Exception:
            pass


class ProcessVideoSource:
    """``VideoSource`` twin whose decode + transform run in a spawned
    worker process (``video_decode=process``).

    Threads (`video_workers`) overlap cv2 decode with device compute, but
    the numpy/PIL *transform* work still serializes on the parent's GIL;
    on multi-core hosts a decode PROCESS per in-flight video removes that
    ceiling. The spawned child imports only the light decode stack and
    ships transformed frames (already resized/cropped — tens of KB each,
    not raw full-resolution) through a bounded queue; the parent keeps all
    device work. Spawn + import costs ~1-2 s per video, so this pays off
    for long videos and multi-core CPU-bound pipelines — it is opt-in
    (docs/performance.md).

    Same observable surface as VideoSource: ``fps``/``num_frames``/
    ``height``/``width`` props, ``frames()``, batched ``__iter__``,
    transform applied child-side. Requires a PICKLABLE transform
    (ops/host_transforms.py — every built-in family's is).
    """

    def __init__(self, path: Union[str, Path], batch_size: int = 1,
                 fps: Optional[float] = None, total: Optional[int] = None,
                 transform: Optional[Callable] = None, overlap: int = 0,
                 channel_order: str = "rgb", depth: int = 16,
                 start_timeout_s: float = 120.0, fps_mode: str = "select",
                 tmp_path: Optional[Union[str, Path]] = None,
                 keep_tmp: bool = False):
        import multiprocessing as mp
        self.path = str(path)
        self.batch_size = batch_size
        self.overlap = overlap
        self._cancelled = False
        self._cancel_reason = ""
        ctx = mp.get_context("spawn")  # never fork a process holding jax
        self._q = ctx.Queue(maxsize=max(int(depth), 2))
        self._proc = ctx.Process(
            target=_decode_worker,
            args=(self._q, self.path,
                  dict(batch_size=1, fps=fps, total=total,
                       transform=transform, overlap=0,
                       channel_order=channel_order, fps_mode=fps_mode,
                       tmp_path=None if tmp_path is None else str(tmp_path),
                       keep_tmp=keep_tmp)),
            daemon=True)
        self._proc.start()
        try:
            tag, payload = self._q.get(timeout=start_timeout_s)
        except BaseException:
            self.release()  # don't leak the just-spawned process
            raise
        if tag == "error":
            self.release()
            raise RuntimeError(
                f"decode worker failed for {self.path}: {payload}")
        assert tag == "props", tag
        self.fps = payload["fps"]
        self.src_fps = payload["src_fps"]
        self.num_frames = payload["num_frames"]
        self.src_num_frames = payload["src_num_frames"]
        self.height = payload["height"]
        self.width = payload["width"]

    def __len__(self):
        return self.num_frames

    def _raise_if_cancelled(self) -> None:
        if self._cancelled:
            raise DeadlineExceeded(f"{self.path}: {self._cancel_reason}")

    def frames(self) -> Iterator[Tuple[np.ndarray, float, int]]:
        import queue as _queue
        try:
            while True:
                self._raise_if_cancelled()
                try:
                    # 1s poll (not one long get): bounds how stale the
                    # cancellation/liveness checks above can be
                    tag, payload = self._q.get(timeout=1.0)
                except _queue.Empty:
                    # a worker killed without running its except handler
                    # (OOM SIGKILL) can never enqueue 'error'/'done' — fail
                    # the video instead of hanging the extraction thread
                    proc = self._proc
                    if proc is not None and proc.is_alive():
                        continue
                    self._raise_if_cancelled()  # watchdog terminated it
                    # the worker may have flushed its tail (frames + 'done')
                    # and exited in the instant between the timeout and the
                    # liveness check: drain before declaring it dead
                    try:
                        tag, payload = self._q.get_nowait()
                        # fall through to the normal tag handling below
                    except _queue.Empty:
                        raise RuntimeError(
                            f"decode worker for {self.path} died without a "
                            "result (killed? exitcode="
                            f"{getattr(proc, 'exitcode', None)})"
                        ) from None
                if tag == "frame":
                    yield payload
                elif tag == "done":
                    return
                else:
                    raise RuntimeError(
                        f"decode worker failed for {self.path}: {payload}")
        finally:
            self.release()

    def __iter__(self) -> Iterator[Tuple[List, List[float], List[int]]]:
        return _batched(self.frames(), self.batch_size, self.overlap)

    def cancel(self, reason: str = "cancelled") -> None:
        """Thread-safe kill (deadline watchdog): terminate the decode
        child; the consuming thread raises DeadlineExceeded on its next
        poll instead of misreporting a dead-worker RuntimeError."""
        self._cancel_reason = reason or "cancelled"
        self._cancelled = True
        self.release()

    def release(self) -> None:
        proc, self._proc = self._proc, None
        if proc is None:
            return
        if proc.is_alive():
            proc.terminate()
        # join even a cleanly-exited worker: without it the child stays a
        # zombie until multiprocessing's lazy reaping, one per video
        proc.join(timeout=10)

    def __del__(self):  # abandoned mid-video (per-video error isolation)
        try:
            self.release()
        except Exception:
            pass


def _segment_decode_worker(q, path: str, seg: dict) -> None:
    """Decode one contiguous OUTPUT-index segment of a video and ship
    transformed frames. Runs in a spawned process (ParallelVideoSource).

    ``seg``: src_indices (np.int64 array, the fps_filter_map slice this
    segment must emit, monotonic), out_start, fps, transform,
    channel_order. Protocol: ('frame', (x, ts_ms, out_idx))* then
    ('done', n_emitted) — or ('error', msg).
    """
    try:
        transform = seg["transform"]
        fps = seg["fps"]
        src_indices = seg["src_indices"]
        out_start = seg["out_start"]
        cap = cv2.VideoCapture(path)
        try:
            src_pos = int(src_indices[0])
            if src_pos > 0:
                # bit-exact on OpenCV's ffmpeg backend: it decodes forward
                # from the previous keyframe (validated in test_io.py
                # parallel-vs-serial equality)
                cap.set(cv2.CAP_PROP_POS_FRAMES, src_pos)
                got = cap.get(cv2.CAP_PROP_POS_FRAMES)
                if int(round(got)) != src_pos:
                    # VFR streams / some codecs seek only approximately;
                    # a mis-seek would silently break the bit-identical-
                    # to-serial contract. Degrade THIS video to serial:
                    # re-open and grab()-skip forward from frame 0
                    # (correct, one-GOP-cheaper seek benefit lost).
                    # Constraint documented in docs/performance.md.
                    print(f"WARNING: seek verification failed for {path} "
                          f"(wanted frame {src_pos}, CAP_PROP_POS_FRAMES="
                          f"{got}); decoding this segment serially from "
                          "frame 0 (video_decode=parallel assumes CFR "
                          "seekable input)")
                    cap.release()
                    cap = cv2.VideoCapture(path)
                    src_pos = 0
            emitted = 0
            current = None
            cur_idx = src_pos - 1
            for k, want in enumerate(src_indices):
                want = int(want)
                while cur_idx < want:
                    if cur_idx < want - 1:
                        ok = cap.grab()
                        if not ok and cur_idx == -1:
                            print("Detect missing frame")
                            ok = cap.grab()
                    else:
                        ok, frame = cap.read()
                        if not ok and cur_idx == -1:
                            # the cv2 missing-frame-0 quirk, as in
                            # _FrameStream.read
                            print("Detect missing frame")
                            ok, frame = cap.read()
                        if ok:
                            current = convert_decoded(
                                frame, seg["channel_order"])
                    if not ok:
                        q.put(("done", emitted))
                        return
                    cur_idx += 1
                out_idx = out_start + k
                x = transform(current) if transform is not None else current
                q.put(("frame", (x, out_idx / fps * 1000.0, out_idx)))
                emitted += 1
            q.put(("done", emitted))
        finally:
            cap.release()
    except BaseException as e:
        try:
            q.put(("error", f"{type(e).__name__}: {e}"))
        except Exception:
            pass


class ParallelVideoSource:
    """Intra-video parallel decode: ONE video's output frame range split
    across ``decode_workers`` seek-aligned decoder processes.

    VERDICT r4 weak #4: a single long video was previously bound to one
    serial decoder no matter how many cores the host has. Here the output
    index range [0, M) is cut into ``decode_workers`` contiguous chunks;
    each worker opens its own ``cv2.VideoCapture``, seeks to its chunk's
    first source frame (frame-accurate on the ffmpeg backend — it decodes
    forward from the prior keyframe), and replays the SAME
    ``fps_filter_map`` walk the serial path uses (grab()-skip for filter-
    dropped frames, missing-frame-0 retry at source start). The parent
    concatenates chunks in order, so the merged stream is bit-identical to
    ``VideoSource`` — pinned by the equality test in test_io.py.

    Scaling model: decode throughput scales with min(workers, cores) until
    HBM-feed or transform cost dominates; each worker re-decodes from its
    segment's previous keyframe once (seek overhead ~ one GOP per worker,
    amortized over segment length — use segments >> GOP length, i.e. don't
    raise decode_workers so high that M/N approaches the keyframe
    interval). Same observable surface as VideoSource; transform must be
    picklable. EOF-before-metadata-count truncates at the first short
    segment exactly like the serial warning path.
    """

    def __init__(self, path: Union[str, Path], batch_size: int = 1,
                 fps: Optional[float] = None, total: Optional[int] = None,
                 transform: Optional[Callable] = None, overlap: int = 0,
                 channel_order: str = "rgb", decode_workers: int = 2,
                 depth: Optional[int] = None, fps_mode: str = "select",
                 tmp_path=None, keep_tmp: bool = False):
        import multiprocessing as mp
        if fps_mode != "select":
            raise NotImplementedError(
                "decode_workers > 1 requires fps_mode=select (the reencode "
                "path is a serial ffmpeg/cv2 re-encode; parallel-decoding "
                "its temp file would serialize on producing it anyway)")
        assert isinstance(decode_workers, int) and decode_workers >= 1
        self.path = str(path)
        self.batch_size = batch_size
        self.overlap = overlap

        probe = VideoSource(self.path, batch_size=batch_size, fps=fps,
                            total=total, transform=None, overlap=overlap,
                            channel_order=channel_order)
        self.fps = probe.fps
        self.src_fps = probe.src_fps
        self.num_frames = probe.num_frames
        self.src_num_frames = probe.src_num_frames
        self.height, self.width = probe.height, probe.width
        self._cancelled = False
        self._cancel_reason = ""
        if probe.index_map is None and probe.num_frames <= 0:
            # native-fps mode with lying container metadata: the resample
            # path recounts by decode (VideoSource.__init__); without the
            # same fallback here the index_map would be empty, zero
            # workers would spawn, and frames() would silently yield an
            # empty stream where serial decode reaches EOF (ADVICE medium)
            n = count_frames_by_decode(self.path)
            if n == 0:
                raise ValueError(f"No decodable frames in {self.path}")
            print(f"Warning: {self.path} metadata reported "
                  f"{probe.num_frames} frames; counted {n} by decode.")
            self.num_frames = self.src_num_frames = n
        index_map = (probe.index_map if probe.index_map is not None
                     else np.arange(self.num_frames, dtype=np.int64))

        m = len(index_map)
        n = max(1, min(decode_workers, m)) if m else 1
        bounds = [round(i * m / n) for i in range(n + 1)]
        ctx = mp.get_context("spawn")  # never fork a process holding jax
        self._queues = []
        self._procs = []
        self._expected = []
        for o0, o1 in zip(bounds, bounds[1:]):
            if o1 <= o0:
                continue
            # default: buffer the whole segment (+done marker) so every
            # worker decodes its full chunk concurrently instead of
            # stalling on a short queue until the parent reaches it —
            # but ONLY when a transform shrinks the frames. Untransformed
            # streams (resize=device ships raw full-resolution frames)
            # would buffer the whole video in host RAM, so they default
            # to a bounded 64/worker. `depth` overrides either way.
            if depth is not None:
                qsize = max(int(depth), 2)
            elif transform is not None:
                qsize = o1 - o0 + 1
            else:
                qsize = 64
            q = ctx.Queue(maxsize=qsize)
            seg = dict(src_indices=index_map[o0:o1], out_start=o0,
                       fps=self.fps, transform=transform,
                       channel_order=channel_order)
            p = ctx.Process(target=_segment_decode_worker,
                            args=(q, self.path, seg), daemon=True)
            p.start()
            self._queues.append(q)
            self._procs.append(p)
            self._expected.append(o1 - o0)

    def __len__(self):
        return self.num_frames

    def _raise_if_cancelled(self) -> None:
        if self._cancelled:
            raise DeadlineExceeded(f"{self.path}: {self._cancel_reason}")

    def frames(self) -> Iterator[Tuple[np.ndarray, float, int]]:
        import queue as _queue
        # local copies: cancel()/release() rebind the attributes to []
        # concurrently, but iteration order over the original lists stays
        # coherent for this thread
        segments = list(zip(self._queues, self._procs, self._expected))
        try:
            for q, proc, expected in segments:
                emitted = None
                while emitted is None:
                    self._raise_if_cancelled()
                    try:
                        # 1s poll bounds cancellation/liveness staleness
                        tag, payload = q.get(timeout=1.0)
                    except _queue.Empty:
                        if proc.is_alive():
                            continue
                        self._raise_if_cancelled()  # watchdog kill
                        try:
                            tag, payload = q.get_nowait()
                        except _queue.Empty:
                            raise RuntimeError(
                                f"decode worker for {self.path} died "
                                "without a result (killed? exitcode="
                                f"{proc.exitcode})") from None
                    if tag == "frame":
                        yield payload
                    elif tag == "done":
                        emitted = payload
                    else:
                        raise RuntimeError(
                            f"decode worker failed for {self.path}: "
                            f"{payload}")
                if emitted < expected:
                    # stream ended inside this segment: truncate here, like
                    # the serial path's metadata-overstated warning
                    print(f"Warning: {self.path} ended early; segment "
                          f"emitted {emitted}/{expected} frames — "
                          "truncating (metadata overstated the count).")
                    return
        finally:
            self.release()

    def __iter__(self) -> Iterator[Tuple[List, List[float], List[int]]]:
        return _batched(self.frames(), self.batch_size, self.overlap)

    def cancel(self, reason: str = "cancelled") -> None:
        """Thread-safe kill (deadline watchdog): terminate every segment
        worker; the consuming thread raises DeadlineExceeded on its next
        poll."""
        self._cancel_reason = reason or "cancelled"
        self._cancelled = True
        self.release()

    def release(self) -> None:
        procs, self._procs = self._procs, []
        for p in procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)
        self._queues = []

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class Prefetcher:
    """Decode-ahead iterator: runs ``iterable`` on a background thread into a
    bounded queue so host-side decode overlaps device compute.

    The reference pipeline is strictly serial — decode a batch, forward it,
    decode the next (reference models/_base/base_framewise_extractor.py:
    47-88). cv2 releases the GIL during decode, so one producer thread gives
    true overlap; ``depth`` bounds memory. Producer exceptions are re-raised
    in the consumer; an abandoned consumer unblocks the producer via the stop
    flag (checked on every bounded put).
    """

    _DONE = object()

    def __init__(self, iterable, depth: int = 2):
        self.iterable = iterable
        self.depth = depth
        # capture the constructing thread's telemetry span (if any): the
        # producer thread re-installs it so its decode-stage timings still
        # attribute to the right video's span (telemetry/spans.py)
        from ..telemetry import current_span
        self._span = current_span()

    def __iter__(self):
        import queue as _queue
        import threading
        import time as _time

        q: "_queue.Queue" = _queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def put_until_stopped(item) -> bool:
            """Bounded put that gives up when the consumer is gone."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def produce():
            from ..telemetry import trace as _trace
            from ..telemetry import use_span
            try:
                with use_span(self._span):
                    it = iter(self.iterable)
                    while True:
                        # tracing (no-op when off): `prefetch.next` spans
                        # bracket this thread's decode+transform of one
                        # batch; a blocked put means the CONSUMER fell
                        # behind (device-bound), the dual of starved-get
                        tr = _trace.active()
                        t0 = _time.perf_counter() if tr is not None else 0.0
                        try:
                            item = next(it)
                        except StopIteration:
                            break
                        if tr is not None:
                            tr.complete("prefetch.next", t0,
                                        _time.perf_counter() - t0)
                        t1 = _time.perf_counter()
                        if not put_until_stopped(item):
                            return
                        if tr is not None:
                            blocked = _time.perf_counter() - t1
                            if blocked >= _trace.STALL_MIN_S:
                                tr.complete("prefetch.put_blocked", t1,
                                            blocked)
                put_until_stopped(self._DONE)
            except BaseException as e:  # re-raised consumer-side
                put_until_stopped(e)

        t = threading.Thread(target=produce, name="vft-prefetch",
                             daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()


def read_video_frames(path: Union[str, Path],
                      fps: Optional[float] = None,
                      total: Optional[int] = None) -> Tuple[np.ndarray, float]:
    """Decode a whole video into an (T, H, W, 3) uint8 RGB array.

    Equivalent of the reference's torchvision ``read_video`` whole-video path
    used by R(2+1)D / S3D (reference models/r21d/extract_r21d.py:75), with the
    same optional fps resampling. Returns (frames, fps).
    """
    src = VideoSource(path, batch_size=1, fps=fps, total=total)
    frames = [rgb for rgb, _, _ in src.frames()]
    if not frames:
        return np.zeros((0, src.height, src.width, 3), dtype=np.uint8), src.fps
    return np.stack(frames), src.fps


def which_ffmpeg() -> str:
    """Path to the ffmpeg binary, or '' (reference utils/utils.py:170-183)."""
    import shutil
    return shutil.which("ffmpeg") or ""


def extract_wav_from_mp4(video_path: Union[str, Path],
                         tmp_path: Union[str, Path]) -> Tuple[str, str]:
    """mp4 -> .aac (codec copy) -> .wav via two ffmpeg calls, written into
    ``tmp_path`` (reference utils/utils.py:186-215: mp4 cannot be converted
    to wav directly with ``-acodec copy``, hence the two-step).

    Video decode in this framework is ffmpeg-free (cv2), but there is no
    in-process AAC decoder available, so the audio rip keeps the reference's
    ffmpeg dependency and fails with a clear message when the binary is
    absent.
    """
    import subprocess

    ffmpeg = which_ffmpeg()
    if not ffmpeg:
        raise RuntimeError(
            "ffmpeg is required to rip audio from .mp4 (reference "
            "utils/utils.py:197); install it or pass a .wav file directly")
    video_path = str(video_path)
    if not video_path.endswith(".mp4"):
        raise ValueError(f"expected an .mp4 file, got {video_path}")
    tmp = Path(tmp_path)
    tmp.mkdir(parents=True, exist_ok=True)
    stem = Path(video_path).stem
    aac = str(tmp / f"{stem}.aac")
    wav = str(tmp / f"{stem}.wav")
    from ..telemetry import trace
    with trace.span("wav_rip", video=video_path):
        for cmd in (
            [ffmpeg, "-hide_banner", "-loglevel", "panic", "-y", "-i",
             video_path, "-acodec", "copy", aac],
            [ffmpeg, "-hide_banner", "-loglevel", "panic", "-y", "-i", aac,
             wav],
        ):
            subprocess.run(cmd, check=True)
    return wav, aac
