"""Per-stage timing + XLA trace capture.

The reference has no observability beyond tqdm and print (SURVEY §5:
"Tracing / profiling: absent", reference main.py:14-18 "TODO: logging").
Here profiling is a first-class subsystem:

  - :data:`profiler` — a process-global stage timer. Pipelines wrap their
    hot phases in ``with profiler.stage("decode")`` etc.; when disabled the
    context manager is a no-op (two attribute reads), so instrumentation
    stays in place permanently. Stages used by the built-in pipelines:
    ``decode`` (cv2 read + host transform), ``forward``, ``write`` (sink
    IO). Under the synchronous path ``forward`` is true H2D + forward + D2H
    wall time; under async dispatch (FeatureStream, the default) it is the
    host's *stall* time materializing results — near-zero ``forward`` means
    the chip is fully hidden behind decode (see docs/performance.md).
  - ``profile=true`` on the CLI prints the aggregate per-stage breakdown at
    the end of the run — the decode-vs-forward-vs-write split that tells
    you whether the chip or the host is the bottleneck.
  - ``profile_trace_dir=/path`` additionally captures a ``jax.profiler``
    trace (one per run) viewable in TensorBoard/Perfetto, with device-side
    op timelines.
  - ``telemetry=true`` (telemetry/) rides the SAME ``profiler.stage`` call
    sites: the recorder installs :meth:`StageProfiler.set_hook`, which
    feeds latency histograms and per-video spans without new code in the
    hot loops. Stages are timed whenever either consumer (aggregate
    printing or the hook) is active.
"""
from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, Optional, Tuple


class StageProfiler:
    """Accumulates wall time and call counts per named stage."""

    def __init__(self) -> None:
        import threading
        self.enabled = False
        self._lock = threading.Lock()  # decode runs in the Prefetcher thread
        self._times: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self._hook: Optional[Callable[[str, float], None]] = None
        self._trace_hook: Optional[Callable[[str, float, float],
                                            None]] = None

    def set_hook(self, hook: Optional[Callable[[str, float], None]]) -> None:
        """Install (or clear, with None) a per-observation callback
        ``hook(stage_name, seconds)`` — the telemetry recorder's feed.
        Timing happens whenever ``enabled`` OR a hook is present."""
        self._hook = hook

    def set_trace_hook(self, hook: Optional[Callable[[str, float, float],
                                                     None]]) -> None:
        """Install (or clear) ``hook(stage_name, t0_perf, seconds)`` —
        the trace recorder's feed (telemetry/trace.py). Unlike the
        aggregate hook it receives the START time too, so each stage
        call becomes one complete timeline event."""
        self._trace_hook = hook

    @contextmanager
    def stage(self, name: str) -> Iterator[None]:
        hook = self._hook
        trace_hook = self._trace_hook
        if not self.enabled and hook is None and trace_hook is None:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if self.enabled:
                with self._lock:
                    self._times[name] += dt
                    self._counts[name] += 1
            if hook is not None:
                try:
                    hook(name, dt)
                except Exception:
                    pass  # observability must never fail the pipeline
            if trace_hook is not None:
                try:
                    trace_hook(name, t0, dt)
                except Exception:
                    pass

    def add(self, name: str, dt: float, n: int = 1) -> None:
        """Accumulate an externally-timed observation (the telemetry
        recorder's delta/total accumulators use this; ``enabled`` gates
        only the context-manager path)."""
        with self._lock:
            self._times[name] += dt
            self._counts[name] += n

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        with self._lock:
            return {k: (self._times[k], self._counts[k])
                    for k in self._times}

    def reset(self) -> None:
        with self._lock:
            self._times.clear()
            self._counts.clear()

    def drain(self) -> Dict[str, Tuple[float, int]]:
        """Atomic snapshot+reset under ONE lock acquisition.

        The old ``snapshot()``-then-``reset()`` pair could lose a stage
        update landing between the two calls (each took the lock
        independently); flushers that turn accumulated stage time into
        per-interval deltas (telemetry/recorder.py heartbeats) must use
        this instead."""
        with self._lock:
            out = {k: (self._times[k], self._counts[k])
                   for k in self._times}
            self._times.clear()
            self._counts.clear()
            return out

    def summary(self, title: str = "profile") -> str:
        """Stages can overlap in wall time (decode runs in the Prefetcher
        thread while forward runs on the main thread), so the accounted
        total can exceed wall clock — that overlap is the pipeline working
        as designed."""
        snap = self.snapshot()
        if not snap:
            return f"[{title}] no stages recorded"
        total = sum(t for t, _ in snap.values())
        lines = [f"[{title}] total accounted: {total:.3f}s"]
        for name, (t, n) in sorted(snap.items(), key=lambda kv: -kv[1][0]):
            lines.append(
                f"  {name:<10} {t:8.3f}s  {100 * t / total:5.1f}%  "
                f"{n:6d} calls  {1e3 * t / max(n, 1):8.3f} ms/call")
        return "\n".join(lines)


profiler = StageProfiler()


class TraceCapture:
    """``jax.profiler`` trace over a region, no-op when dir is None."""

    def __init__(self, trace_dir: Optional[str]) -> None:
        self.trace_dir = trace_dir
        self._active = False

    def __enter__(self):
        if self.trace_dir:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        return self

    def __exit__(self, *exc):
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
        return False
