"""Deterministic fault injection: prove the durability contracts.

PRs 1-8 accumulated a stack of crash-safety contracts — atomic-rename
sinks, verify-before-trust cache entries, exactly-once fleet done
markers, lease-steal reclamation, POISON quarantine, torn-tail-healing
journals — but each was exercised only by a bespoke hand-built failure
in one test. This module makes faults a *first-class, seeded, replayable
input*: a run armed with an injection plan fires the same faults at the
same sites in the same order every time, so a chaos matrix can sweep
seeds and any failing seed replays exactly from its recorded plan
(tests/test_chaos.py; docs/chaos.md).

**Sites** (:data:`SITES`) are named chokepoints threaded through the
durability surface — decode reads, the three legs of the atomic sink
write, cache store/lookup, queue claim/steal, the serve spool claim and
response write, the gateway's client-body read and spool submit
(gateway.py), the heartbeat tick, and a kill-self site in the per-video
attempt loop. A
site costs ONE module-global read when injection is off (the
telemetry/trace.py discipline): ``fire(site)`` returns ``None``
immediately, and per-frame call sites additionally hold the active plan
in a local so even the call can be skipped.

**Plans** are compact strings, validated by ``sanity_check`` at launch::

    inject="seed=7;sink.fsync=enospc@n1;decode.read=eio@p0.05"

``seed=<int>`` seeds every probabilistic trigger (per-site independent
streams, so adding a rule never perturbs another site's draws). Each
rule is ``<site>=<fault>@<trigger>``:

  ==========  ==============================================================
  fault       behavior when the trigger matches
  ==========  ==============================================================
  ``eio``     raise ``OSError(EIO)`` — a transient disk/NFS error
  ``enospc``  raise ``OSError(ENOSPC)`` — disk full (FATAL taxonomy)
  ``edquot``  raise ``OSError(EDQUOT)`` — quota exceeded (FATAL)
  ``erofs``   raise ``OSError(EROFS)`` — read-only filesystem (FATAL)
  ``error``   raise ``RuntimeError`` — a generic software fault
  ``torn``    ``sink.tmp_write``: write a truncated prefix, then raise
              EIO; ``cache.lookup``: truncate the stored entry so
              verify-before-trust must catch it; ``gateway.read``: the
              client connection dies mid-body (short read)
  ``drop``    rename/steal/submit/respond sites: the operation is lost
              (site-specific — a dropped spool submit or response is a
              silently lost write the deadline/requeue machinery must
              absorb)
  ``skew``    ``queue.claim``: stamp an already-expired lease deadline
  ``freeze``  ``heartbeat.tick``: silently skip the tick (host looks dead)
  ``stall``   ``gateway.read``: a slow client — the body read pauses
              mid-stream (the call site sleeps, then continues)
  ``kill``    ``os.kill(getpid(), SIGKILL)`` — no drain, no final heartbeat
  ==========  ==============================================================

Triggers: ``n<int>`` (exactly the Nth hit of that site, 1-based),
``first`` (= ``n1``), ``every<int>`` (every Nth hit), ``after<int>``
(every hit past the Nth), ``p<float>`` (each hit independently with
probability p, drawn from the seeded per-site stream).

**Arming**: cli.py / serve.py arm the plan from the ``inject=`` config
key at run start and disarm in their ``finally``; the ``VFT_INJECT``
environment variable *overrides* the config key and also arms
subprocess workers (decode worker processes, fleet-queue workers) at
import time — they never run the CLI prologue.

Every fired fault bumps ``vft_inject_fired_total{site=...}`` (when
telemetry is live) and the plan's own tally, so a chaos run records
exactly what it injected; ``scripts/audit_run.py`` (vft-audit) then
verifies the cross-subsystem invariants the fault was supposed to be
unable to break.
"""
from __future__ import annotations

import errno
import os
import random
import signal
import threading
import time
from typing import Any, Dict, Optional, Tuple

#: every named injection site, and the module that hosts its hook
SITES = (
    "decode.read",          # utils/io.py _FrameStream.read (all decode paths
                            # incl. the shared FrameBus, parallel/fanout.py)
    "sink.tmp_write",       # utils/sinks.py _write_bytes_atomic, pre-write
    "sink.fsync",           # utils/sinks.py _write_bytes_atomic, pre-fsync
    "sink.rename",          # utils/sinks.py _write_bytes_atomic, pre-replace
    "cache.store",          # cache.py FeatureCache.store
    "cache.lookup",         # cache.py FeatureCache.lookup
    "queue.claim",          # parallel/queue.py WorkQueue.claim_next
    "queue.steal_staging",  # parallel/queue.py WorkQueue._requeue, between
                            # the staging rename and the pending re-publish
    "spool.claim",          # serve.py ServeLoop._claim_next
    "spool.respond",        # serve.py ServeLoop._respond, pre-write
    "gateway.read",         # gateway.py _read_body (client upload/body)
    "gateway.spool_submit",  # gateway.py _submit_to_spool, pre-rename
    "heartbeat.tick",       # telemetry/heartbeat.py HeartbeatThread._run
    "worker.kill",          # utils/sinks.py safe_extract, per attempt
    "gc.evict",             # gc.py execute, between the journal append
                            # and the unlink
    "gc.sweep",             # gc.py sweep, per accounting+eviction pass
)

#: raise-kind faults -> the errno they raise with (None = RuntimeError)
_RAISE_ERRNO = {
    "eio": errno.EIO,
    "enospc": errno.ENOSPC,
    "edquot": errno.EDQUOT,
    "erofs": errno.EROFS,
    "error": None,
}

#: behavioral faults: ``fire`` returns them for the call site to apply
_BEHAVIORAL = ("torn", "drop", "skew", "freeze", "stall")

FAULT_KINDS = tuple(_RAISE_ERRNO) + _BEHAVIORAL + ("kill",)

#: which behavioral kinds make sense where — parse-time validation, so a
#: plan that asks for ``skew`` at a sink fails at launch, not mid-run
_BEHAVIORAL_SITES = {
    "torn": ("sink.tmp_write", "cache.lookup", "gateway.read"),
    "drop": ("sink.rename", "queue.steal_staging", "gateway.spool_submit",
             "spool.respond", "gc.evict"),
    "skew": ("queue.claim",),
    "freeze": ("heartbeat.tick",),
    "stall": ("gateway.read", "gc.sweep"),
}


class Fault:
    """One armed fault returned to (behavioral) call sites."""

    __slots__ = ("site", "kind", "hit")

    def __init__(self, site: str, kind: str, hit: int) -> None:
        self.site = site
        self.kind = kind
        self.hit = hit

    def __repr__(self) -> str:
        return f"Fault({self.site}={self.kind}@hit{self.hit})"


class _Rule:
    __slots__ = ("site", "kind", "trigger", "value", "rng")

    def __init__(self, site: str, kind: str, trigger: str, value: float,
                 seed: int) -> None:
        self.site = site
        self.kind = kind
        self.trigger = trigger
        self.value = value
        # per-site independent stream: adding/removing another site's
        # rule can never shift this one's draws between runs
        self.rng = random.Random(f"{seed}:{site}:{kind}")

    def should_fire(self, hit: int) -> bool:
        if self.trigger == "n":
            return hit == int(self.value)
        if self.trigger == "every":
            return hit % int(self.value) == 0
        if self.trigger == "after":
            return hit > int(self.value)
        # "p": one deterministic draw per hit, fire or not
        return self.rng.random() < self.value


class InjectionPlan:
    """A parsed, armed plan: per-site hit counters + fire decisions.

    Thread-safe: sites are hit from decode threads, the heartbeat
    flusher and fleet workers concurrently; the lock only exists while a
    plan is armed (chaos runs), never on the injection-off path.
    """

    def __init__(self, spec: str, seed: int,
                 rules: Dict[str, _Rule]) -> None:
        self.spec = spec
        self.seed = seed
        self.rules = rules
        self.hits: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    def check(self, site: str, ctx: Dict[str, Any]) -> Optional[Fault]:
        rule = self.rules.get(site)
        if rule is None:
            return None
        with self._lock:
            hit = self.hits.get(site, 0) + 1
            self.hits[site] = hit
            if not rule.should_fire(hit):
                return None
            self.fired[site] = self.fired.get(site, 0) + 1
        return self._apply(rule, site, hit, ctx)

    def _apply(self, rule: _Rule, site: str, hit: int,
               ctx: Dict[str, Any]) -> Optional[Fault]:
        detail = " ".join(f"{k}={v}" for k, v in ctx.items() if v is not None)
        print(f"INJECT: {site}={rule.kind} fired (hit {hit}, seed "
              f"{self.seed}{', ' + detail if detail else ''})")
        from .. import telemetry
        telemetry.inc("vft_inject_fired_total", site=site)
        if rule.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(30)  # SIGKILL is not synchronous; never fall through
        if rule.kind in _RAISE_ERRNO:
            eno = _RAISE_ERRNO[rule.kind]
            if eno is None:
                raise RuntimeError(
                    f"injected fault at {site} (hit {hit}, seed {self.seed})")
            raise OSError(eno, f"injected {rule.kind.upper()} at {site} "
                               f"(hit {hit}, seed {self.seed})")
        return Fault(site, rule.kind, hit)

    def summary(self) -> str:
        with self._lock:
            fired = dict(self.fired)
            hits = dict(self.hits)
        parts = [f"{s}:{fired.get(s, 0)}/{hits[s]}" for s in sorted(hits)]
        return (f"inject: seed={self.seed} fired/hits "
                f"{{{', '.join(parts) or 'no sites hit'}}} "
                f"(plan {self.spec!r})")


def parse_plan(spec: str) -> InjectionPlan:
    """Parse (and validate) an ``inject=`` plan string; raises
    ``ValueError`` with the offending clause on any malformed piece, so
    ``sanity_check`` fails a typo'd plan at launch."""
    if not isinstance(spec, str) or not spec.strip():
        raise ValueError(f"inject={spec!r}: expected a non-empty plan "
                         "string like 'seed=1;sink.fsync=enospc@n1'")
    seed = 0
    rules: Dict[str, _Rule] = {}
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(f"inject: clause {clause!r} is not key=value")
        key, val = (p.strip() for p in clause.split("=", 1))
        if key == "seed":
            try:
                seed = int(val)
            except ValueError:
                raise ValueError(f"inject: seed={val!r} is not an int")
            continue
        if key not in SITES:
            raise ValueError(f"inject: unknown site {key!r} "
                             f"(sites: {', '.join(SITES)})")
        kind, trigger, value = _parse_fault(key, val)
        rules[key] = _Rule(key, kind, trigger, value, seed)
    # rules built before the seed clause would carry the default seed:
    # rebuild so clause order never matters
    rules = {s: _Rule(s, r.kind, r.trigger, r.value, seed)
             for s, r in rules.items()}
    if not rules:
        raise ValueError(f"inject={spec!r}: plan has no site rules")
    return InjectionPlan(spec, seed, rules)


def _parse_fault(site: str, val: str) -> Tuple[str, str, float]:
    kind, sep, trig = val.partition("@")
    kind = kind.strip()
    if kind not in FAULT_KINDS:
        raise ValueError(f"inject: {site}: unknown fault {kind!r} "
                         f"(faults: {', '.join(FAULT_KINDS)})")
    if kind in _BEHAVIORAL and site not in _BEHAVIORAL_SITES[kind]:
        raise ValueError(
            f"inject: fault {kind!r} only applies at "
            f"{'/'.join(_BEHAVIORAL_SITES[kind])}, not {site!r}")
    trig = (trig.strip() or "first") if sep else "first"
    if trig == "first":
        return kind, "n", 1.0
    for prefix in ("every", "after"):  # before 'n'/'p': longest first
        if trig.startswith(prefix):
            try:
                n = int(trig[len(prefix):])
            except ValueError:
                n = 0
            if n < 1:
                raise ValueError(f"inject: {site}: trigger {trig!r} needs "
                                 f"a positive int after '{prefix}'")
            return kind, prefix, float(n)
    if trig.startswith("n"):
        try:
            n = int(trig[1:])
        except ValueError:
            n = 0
        if n < 1:
            raise ValueError(f"inject: {site}: trigger {trig!r} needs a "
                             "positive int after 'n'")
        return kind, "n", float(n)
    if trig.startswith("p"):
        try:
            p = float(trig[1:])
        except ValueError:
            p = -1.0
        if not 0.0 < p <= 1.0:
            raise ValueError(f"inject: {site}: trigger {trig!r} needs a "
                             "probability in (0, 1] after 'p'")
        return kind, "p", p
    raise ValueError(f"inject: {site}: unknown trigger {trig!r} "
                     "(use n<int>, first, every<int>, after<int>, p<float>)")


# -- the armed plan (one module global; None = injection off) ----------------

_active: Optional[InjectionPlan] = None


def _set_active(plan: Optional[InjectionPlan]) -> None:
    global _active
    _active = plan


def active() -> Optional[InjectionPlan]:
    """The armed plan, if any (one global read — hot call sites hold the
    result in a local and skip the per-hit work entirely when None)."""
    return _active


def fire(site: str, **ctx: Any) -> Optional[Fault]:
    """The injection hook. Off (no plan): one global read, return None.
    Armed: count the hit; when the site's trigger matches, raise-kind
    faults raise here, ``kill`` SIGKILLs the process, and behavioral
    faults (torn/drop/skew/freeze) are returned for the call site to
    apply. Returns None when nothing fires."""
    plan = _active
    if plan is None:
        return None
    return plan.check(site, ctx)


def arm_for_run(config_spec: Optional[str]) -> Optional[InjectionPlan]:
    """Arm the plan for one CLI/serve run: ``VFT_INJECT`` (the
    subprocess-worker override) wins over the ``inject=`` config key.
    Returns the armed plan (or None — which also DISARMS any plan a
    previous in-process run left behind)."""
    spec = os.environ.get("VFT_INJECT") or config_spec
    plan = parse_plan(spec) if spec else None
    _set_active(plan)
    return plan


def disarm() -> None:
    """Back to the import-time baseline: the ``VFT_INJECT`` env plan if
    set (spawned workers must stay armed for their whole life), else
    off."""
    spec = os.environ.get("VFT_INJECT")
    _set_active(parse_plan(spec) if spec else None)


# subprocess workers (decode worker processes, fleet-queue/serve workers
# launched with VFT_INJECT in their environment) arm at import time —
# they never run the CLI prologue that calls arm_for_run
if os.environ.get("VFT_INJECT"):
    _active = parse_plan(os.environ["VFT_INJECT"])
