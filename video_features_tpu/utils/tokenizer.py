"""Byte-level BPE tokenizer for CLIP text inputs.

Behavioral parity with the reference's SimpleTokenizer + ``tokenize``
(reference models/clip/clip_src/simple_tokenizer.py:62-132, clip.py:200-239):
GPT-2-style reversible byte<->unicode mapping, lowercased regex pre-split,
merge ranks from the 48894 merge rules in ``bpe_simple_vocab_16e6.txt.gz``,
vocab = 256 bytes + 256 ``</w>``-suffixed bytes + merges + the two specials
(49408 total), and fixed-length (context_length,) int sequences
``[sot] + bpe(text) + [eot]`` zero-padded on the right.

The vocab file is DATA the reference vendors in its tree; in this framework
it is resolved like model weights (``VFT_WEIGHTS_DIR``) or via an explicit
``bpe_path``. ``ftfy`` mojibake fixing (basic_clean, simple_tokenizer.py:50-53)
is applied when the library is available; for the ASCII zero-shot prompts
("a photo of {label}") it is an identity either way.
"""
from __future__ import annotations

import gzip
import html
from functools import lru_cache
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import regex

CONTEXT_LENGTH = 77
VOCAB_SIZE = 49408
SOT = "<|startoftext|>"
EOT = "<|endoftext|>"

# pre-split pattern (simple_tokenizer.py:81): contractions, letter runs,
# single digits, punctuation runs
_PAT = regex.compile(
    r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"""
    r"""|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""",
    regex.IGNORECASE)


@lru_cache()
def byte_to_unicode() -> Dict[int, str]:
    """Reversible byte -> printable-unicode map (simple_tokenizer.py:15-36).

    Printable latin bytes map to themselves; the rest are shifted into the
    256+ plane so no vocab entry is whitespace or a control character.
    """
    keep = (list(range(ord("!"), ord("~") + 1)) +
            list(range(ord("¡"), ord("¬") + 1)) +
            list(range(ord("®"), ord("ÿ") + 1)))
    # insertion order (printable bytes first, shifted ones after) defines
    # the first 256 vocab indices — must match the reference exactly
    mapping = {b: chr(b) for b in keep}
    shifted = 0
    for b in range(256):
        if b not in mapping:
            mapping[b] = chr(256 + shifted)
            shifted += 1
    return mapping


def find_bpe_vocab(explicit_path: Optional[str] = None) -> Path:
    from ..weights.store import weights_dir
    if explicit_path:
        p = Path(explicit_path)
        if not p.exists():
            raise FileNotFoundError(f"bpe_path does not exist: {p}")
        return p
    p = weights_dir() / "bpe_simple_vocab_16e6.txt.gz"
    if p.exists():
        return p
    raise FileNotFoundError(
        "CLIP BPE vocab not found. Drop `bpe_simple_vocab_16e6.txt.gz` (the "
        f"OpenAI CLIP vocab file) into {weights_dir()} or pass `bpe_path=...`.")


def _clean(text: str) -> str:
    try:
        import ftfy
        text = ftfy.fix_text(text)
    except ImportError:
        pass
    text = html.unescape(html.unescape(text)).strip()
    return regex.sub(r"\s+", " ", text).strip()


class ClipTokenizer:

    def __init__(self, bpe_path: Optional[str] = None) -> None:
        raw = gzip.open(str(find_bpe_vocab(bpe_path))).read().decode("utf-8")
        # first line is a version header; only the first 48894 merges are
        # part of the 49152-token vocab (simple_tokenizer.py:66-67)
        merge_lines = raw.split("\n")[1:VOCAB_SIZE - 256 - 2 + 1 - 256]
        merges: List[Tuple[str, str]] = []
        for line in merge_lines:
            a, b = line.split()
            merges.append((a, b))
        base = list(byte_to_unicode().values())
        vocab = base + [c + "</w>" for c in base]
        vocab += ["".join(m) for m in merges]
        vocab += [SOT, EOT]
        self.encoder: Dict[str, int] = {tok: i for i, tok in enumerate(vocab)}
        self.decoder = {i: tok for tok, i in self.encoder.items()}
        self.rank: Dict[Tuple[str, str], int] = {
            m: i for i, m in enumerate(merges)}
        self.sot_token = self.encoder[SOT]
        self.eot_token = self.encoder[EOT]
        self._byte_enc = byte_to_unicode()
        self._byte_dec = {v: k for k, v in self._byte_enc.items()}
        self._cache: Dict[str, str] = {SOT: SOT, EOT: EOT}

    def _bpe(self, token: str) -> str:
        """Greedily apply the lowest-ranked merge until none applies."""
        if token in self._cache:
            return self._cache[token]
        word: Tuple[str, ...] = tuple(token[:-1]) + (token[-1] + "</w>",)
        if len(word) == 1:
            return token + "</w>"
        while len(word) > 1:
            pairs = set(zip(word[:-1], word[1:]))
            best = min(pairs, key=lambda p: self.rank.get(p, float("inf")))
            if best not in self.rank:
                break
            first, second = best
            merged: List[str] = []
            i = 0
            while i < len(word):
                if (word[i] == first and i + 1 < len(word)
                        and word[i + 1] == second):
                    merged.append(first + second)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        out = " ".join(word)
        self._cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for token in _PAT.findall(_clean(text).lower()):
            mapped = "".join(self._byte_enc[b] for b in token.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(mapped).split(" "))
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(self.decoder[i] for i in ids)
        data = bytearray(self._byte_dec[c] for c in text)
        return data.decode("utf-8", errors="replace").replace("</w>", " ")

    def tokenize(self, texts: Union[str, Sequence[str]],
                 context_length: int = CONTEXT_LENGTH,
                 truncate: bool = False) -> np.ndarray:
        """Texts -> (N, context_length) int32, [sot] + bpe + [eot], 0-padded
        (clip.py:200-239)."""
        if isinstance(texts, str):
            texts = [texts]
        out = np.zeros((len(texts), context_length), dtype=np.int32)
        for i, text in enumerate(texts):
            ids = [self.sot_token] + self.encode(text) + [self.eot_token]
            if len(ids) > context_length:
                if not truncate:
                    raise RuntimeError(
                        f"Input {texts[i]} is too long for context length "
                        f"{context_length}")
                ids = ids[:context_length]
                ids[-1] = self.eot_token
            out[i, :len(ids)] = ids
        return out
