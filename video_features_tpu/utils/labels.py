"""show_pred support: top-5 class printout against label maps.

Equivalent of reference utils/utils.py:20-51 (`show_predictions_on_dataset`),
numpy/JAX instead of torch. Label maps (Kinetics-400, ImageNet-1k class name
lists) ship as package data.
"""
from __future__ import annotations

from pathlib import Path
from typing import List, Sequence, Union

import numpy as np

_DATA_DIR = Path(__file__).resolve().parent

KINETICS_CLASS_PATH = _DATA_DIR / "K400_label_map.txt"
IMAGENET_CLASS_PATH = _DATA_DIR / "IN_label_map.txt"


def load_label_map(dataset: Union[str, Sequence[str]]) -> List[str]:
    if dataset == "kinetics":
        path = KINETICS_CLASS_PATH
    elif dataset == "imagenet":
        path = IMAGENET_CLASS_PATH
    elif isinstance(dataset, (list, tuple)):
        return list(dataset)
    else:
        raise NotImplementedError(f"dataset: {dataset}")
    with open(path) as f:
        return [x.strip() for x in f]


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def show_predictions_on_dataset(logits: np.ndarray,
                                dataset: Union[str, Sequence[str]],
                                k: int = 5) -> None:
    """Print per-row top-k ``logit | prob | label`` tables
    (same format as reference utils/utils.py:36-51)."""
    classes = load_label_map(dataset)
    logits = np.asarray(logits, dtype=np.float32)
    probs = softmax(logits)
    top_idx = np.argsort(-probs, axis=-1)[:, :k]
    for b in range(logits.shape[0]):
        print('  Logits | Prob. | Label ')
        for idx in top_idx[b]:
            print(f'{logits[b, idx]:8.3f} | {probs[b, idx]:.3f} | {classes[idx]}')
        print()
