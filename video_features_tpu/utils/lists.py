"""Work-list building and clip-window slicing (host-side, pure Python).

Covers the reference's `form_list_from_user_input` (utils/utils.py:128-167)
and `form_slices` (utils/utils.py:59-68).
"""
from __future__ import annotations

import random
from pathlib import Path
from typing import List, Optional, Sequence, Tuple, Union


def form_slices(size: int, stack_size: int, step_size: int) -> List[Tuple[int, int]]:
    """Windows [i*step, i*step+stack) fully inside [0, size).

    Matches reference utils/utils.py:59-68: the trailing partial stack is
    dropped — that drop is observable in feature counts and is part of the
    output contract.
    """
    full_stack_num = (size - stack_size) // step_size + 1
    return [(i * step_size, i * step_size + stack_size)
            for i in range(max(full_stack_num, 0))]


def form_list_from_user_input(
        video_paths: Union[str, Sequence[str], None] = None,
        file_with_video_paths: Optional[str] = None,
        to_shuffle: bool = True,
) -> List[str]:
    """Normalize user video specification into a list of paths.

    Same contract as reference utils/utils.py:128-167: either an inline
    str/list or a text file (one path per line, blank lines skipped); missing
    paths produce a warning, not an error; optional shuffle decorrelates
    independently-launched workers picking the same video first.
    """
    if file_with_video_paths is None:
        if video_paths is None:
            path_list: List[str] = []
        elif isinstance(video_paths, str):
            path_list = [video_paths]
        else:
            path_list = [str(p) for p in video_paths]
    else:
        with open(file_with_video_paths) as rfile:
            path_list = [line.strip("\n") for line in rfile.readlines()]
            path_list = [p for p in path_list if len(p) > 0]

    for path in path_list:
        if not Path(path).exists():
            print(f"The path does not exist: {path}")

    if to_shuffle:
        random.shuffle(path_list)
    return path_list
