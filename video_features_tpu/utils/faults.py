"""Fault-tolerance runtime: taxonomy, retry policy, deadlines, journal.

The reference's entire failure story is "print a traceback and move on"
(reference models/_base/base_extractor.py:40-53) — acceptable for a
workstation run, not for a preemptible TPU fleet where a long video costs
minutes of compute (RAFT, arXiv:2003.12039) and a single hung decode
stalls a worker thread forever. This module gives the extraction loop
four properties the ROADMAP north star needs:

  1. **Taxonomy** (:func:`classify`): every per-video failure is
     ``TRANSIENT`` (ffmpeg blip, OOM-killed decode worker, NFS hiccup —
     worth retrying), ``POISON`` (the input itself is bad — bounded
     retries, then quarantine) or ``FATAL`` (config/programming error —
     retrying cannot help; fail the video immediately, keep the run's
     per-video isolation).
  2. **Retry policy** (:class:`RetryPolicy`): bounded attempts with
     exponential backoff + jitter, configured by the ``retry_attempts=``
     / ``retry_backoff_s=`` config keys. Clock/sleep/rng are injectable
     so tier-1 tests never really sleep.
  3. **Per-video deadline** (:class:`FaultContext`): ``video_deadline_s=``
     arms a watchdog timer that cancels every registered in-flight video
     source (thread-safe ``cancel()`` on VideoSource /
     ProcessVideoSource / ParallelVideoSource, utils/io.py) so a hung
     decode fails ONLY that video — the worker thread comes back and the
     rest of the run proceeds.
  4. **Failure journal** (:class:`FailureJournal`):
     ``{output_path}/_failures.jsonl``, one atomically-appended record
     per terminal failure. A restarted worker consults it to skip
     known-POISON inputs instead of re-failing them (override with
     ``retry_failed=true``); the end-of-run summary tallies categories.

The **decode degradation ladder** also lives here (:data:`LADDER`,
:func:`demote`): when a video fails under ``video_decode=parallel`` or
``process``, the retry runs it with the next-simpler source
(``parallel -> process -> inline``) via the thread-local context's
``decode_override``, which ``BaseExtractor.video_source`` honors.
"""
from __future__ import annotations

import errno
import json
import os
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Union

# -- taxonomy ---------------------------------------------------------------

TRANSIENT = "TRANSIENT"  # environment blip: retry with backoff
POISON = "POISON"        # the input is bad: bounded retries, then quarantine
FATAL = "FATAL"          # config/programming error: retrying cannot help

CATEGORIES = (TRANSIENT, POISON, FATAL)


class DeadlineExceeded(Exception):
    """Raised (by a cancelled video source) when the per-video wall-clock
    deadline kills an in-flight decode. Classified TRANSIENT: a hung
    decode is usually an NFS/network stall, and the retry additionally
    walks the decode ladder toward simpler sources."""


class PoisonError(Exception):
    """Explicitly mark an input-is-bad failure (classify -> POISON)."""


class FatalError(Exception):
    """Explicitly mark a do-not-retry failure (classify -> FATAL)."""


#: substrings of worker-forwarded error strings (the decode subprocess
#: protocol ships ``f"{type(e).__name__}: {e}"``, utils/io.py) that mark
#: the CHILD's exception as input-shaped
_POISON_MARKERS = ("ValueError", "PoisonError", "NonFiniteFeatureError",
                   "No decodable frames", "Cannot determine fps")

#: OSError errnos that mean the ENVIRONMENT cannot take writes at all —
#: full disk, exceeded quota, read-only remount. Retrying burns the whole
#: retry budget plus backoff wall-clock per video and every video fails
#: the same way, turning one full disk into a slow fleet-wide hang; fail
#: the video immediately so the operator sees N fast FATALs, not a crawl
_FATAL_ERRNOS = frozenset({
    getattr(errno, name) for name in ("ENOSPC", "EDQUOT", "EROFS")
    if hasattr(errno, name)
})

#: the same verdict for worker-FORWARDED errors: the decode subprocess
#: protocol ships strings, and str(OSError) keeps the strerror
_FATAL_MARKERS = ("ENOSPC", "EDQUOT", "EROFS", "No space left on device",
                  "Disk quota exceeded", "Read-only file system")


def classify(exc: BaseException) -> str:
    """Map an exception to TRANSIENT / POISON / FATAL.

    Unknown exceptions default to TRANSIENT: a wrong TRANSIENT costs a few
    bounded retries; a wrong POISON quarantines a healthy video and a
    wrong FATAL skips retries that might have worked.
    """
    if isinstance(exc, DeadlineExceeded):
        return TRANSIENT
    if isinstance(exc, FatalError):
        return FATAL
    if isinstance(exc, PoisonError):
        return POISON
    from ..telemetry.health import NonFiniteFeatureError
    if isinstance(exc, NonFiniteFeatureError):
        # the output-health gate (telemetry/health.py, health=true) found
        # NaN/Inf in a computed feature: quarantine over silent write —
        # retries rarely fix a numerically-poisoned (input, model) pair
        return POISON
    if isinstance(exc, (NotImplementedError, AssertionError, TypeError,
                        AttributeError, NameError, ImportError)):
        # config/programming errors: these would fail every retry (and
        # likely every other video) identically
        return FATAL
    if isinstance(exc, (ValueError, KeyError, IndexError)):
        # cv2-can't-open / no-frames / bad-fps all surface as ValueError
        # (utils/io.py get_video_props, count_frames_by_decode)
        return POISON
    if type(exc).__module__ == "cv2":
        return POISON  # codec/container rejection of this input
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        if "died without a result" in msg:
            return TRANSIENT  # OOM-SIGKILLed decode worker (utils/io.py)
        if any(m in msg for m in _POISON_MARKERS):
            return POISON  # worker-forwarded child exception, by name
        if any(m in msg for m in _FATAL_MARKERS):
            return FATAL  # forwarded full-disk/quota/read-only verdicts
        return TRANSIENT  # spawn failures, queue breakage, ffmpeg blips
    if isinstance(exc, OSError):
        if exc.errno in _FATAL_ERRNOS:
            # full disk / quota / read-only: retrying cannot help and every
            # other video fails identically — fail fast, keep isolation
            return FATAL
        return TRANSIENT  # NFS hiccup / EIO blip / URLError
    if isinstance(exc, MemoryError):
        return TRANSIENT  # host memory pressure may clear
    return TRANSIENT


# -- decode degradation ladder ---------------------------------------------

#: most- to least-parallel decode source; demotion walks rightward
LADDER = ("parallel", "process", "inline")


def demote(mode: Optional[str]) -> Optional[str]:
    """Next-simpler decode mode, or None when already at (or past)
    ``inline``."""
    if mode not in LADDER:
        return None
    i = LADDER.index(mode)
    return LADDER[i + 1] if i + 1 < len(LADDER) else None


# -- retry policy -----------------------------------------------------------

@dataclass
class RetryPolicy:
    """Bounded-retry parameters plus injectable time sources.

    ``attempts`` counts TOTAL tries per video (1 = the reference's
    single-shot behavior). ``backoff_delay(k)`` is the sleep AFTER failed
    attempt ``k`` (1-based): ``backoff_s * 2**(k-1)``, capped, with
    uniform jitter in ``[0, jitter * base]`` so a restarted fleet does
    not retry in lockstep against the same NFS server.
    """
    attempts: int = 1
    backoff_s: float = 0.5
    backoff_cap_s: float = 30.0
    jitter: float = 0.1
    deadline_s: Optional[float] = None
    ladder: bool = True  # demote video_decode on retries
    retry_failed: bool = False  # re-run journal-quarantined inputs
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    rng: random.Random = field(default_factory=random.Random)

    def __post_init__(self):
        if int(self.attempts) < 1:
            raise ValueError(f"retry_attempts={self.attempts}: need >= 1")
        if float(self.backoff_s) < 0:
            raise ValueError(f"retry_backoff_s={self.backoff_s}: need >= 0")
        if self.deadline_s is not None and float(self.deadline_s) <= 0:
            raise ValueError(
                f"video_deadline_s={self.deadline_s}: need > 0 (or null)")
        self.attempts = int(self.attempts)

    @classmethod
    def from_config(cls, args) -> "RetryPolicy":
        """Build from the ``retry_attempts`` / ``retry_backoff_s`` /
        ``video_deadline_s`` / ``retry_failed`` config keys (all 8
        ``configs/*.yml`` carry them)."""
        attempts = args.get("retry_attempts")
        backoff = args.get("retry_backoff_s")
        deadline = args.get("video_deadline_s")
        return cls(
            attempts=1 if attempts is None else int(attempts),
            backoff_s=0.5 if backoff is None else float(backoff),
            deadline_s=None if deadline is None else float(deadline),
            retry_failed=bool(args.get("retry_failed", False)),
        )

    def backoff_delay(self, failed_attempt: int) -> float:
        base = min(float(self.backoff_s) * (2.0 ** (failed_attempt - 1)),
                   float(self.backoff_cap_s))
        return base * (1.0 + float(self.jitter) * self.rng.random())


# -- per-video fault context (deadline watchdog + ladder override) ----------

_tls = threading.local()


def current_context() -> Optional["FaultContext"]:
    """The FaultContext of the video attempt running on THIS thread, if
    any (``BaseExtractor.video_source`` registers its sources here)."""
    return getattr(_tls, "ctx", None)


class FaultContext:
    """One extraction attempt of one video: deadline watchdog + the
    decode-ladder override, installed thread-locally for the duration.

    The watchdog is a daemon :class:`threading.Timer`; at
    ``deadline_s`` it calls ``cancel()`` on every registered source.
    Cancellation is cooperative-but-forceful: sources release their
    underlying capture/worker processes (unblocking a stuck ``read()``)
    and raise :class:`DeadlineExceeded` from their ``frames()`` loop, so
    only THIS video fails — the worker thread survives.
    """

    def __init__(self, video_path: str, deadline_s: Optional[float] = None,
                 decode_override: Optional[str] = None):
        self.video_path = str(video_path)
        self.deadline_s = deadline_s
        self.decode_override = decode_override
        self.deadline_expired = False
        self._sources: List = []
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._prev = None

    # -- source registry ----------------------------------------------------
    def register(self, source) -> None:
        """Track a live video source; cancelled immediately when the
        deadline already fired (a source constructed after expiry must
        not run to completion)."""
        with self._lock:
            expired = self.deadline_expired
            self._sources.append(source)
        if expired:
            self._cancel_source(source)

    def _cancel_source(self, source) -> None:
        try:
            source.cancel(
                f"video deadline ({self.deadline_s}s) exceeded for "
                f"{self.video_path}")
        except Exception:
            pass  # watchdog must never die on a half-torn-down source

    def _expire(self) -> None:
        with self._lock:
            self.deadline_expired = True
            sources = list(self._sources)
        print(f"WATCHDOG: {self.video_path} exceeded video_deadline_s="
              f"{self.deadline_s}; killing its in-flight decode "
              f"({len(sources)} source(s))")
        from .. import telemetry
        telemetry.inc("vft_deadline_expirations_total")
        for s in sources:
            self._cancel_source(s)

    # -- context manager ----------------------------------------------------
    def __enter__(self) -> "FaultContext":
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self
        if self.deadline_s is not None:
            self._timer = threading.Timer(float(self.deadline_s),
                                          self._expire)
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        _tls.ctx = self._prev
        with self._lock:
            self._sources.clear()


# -- persistent failure journal --------------------------------------------

class FailureJournal:
    """``{output_path}/_failures.jsonl`` — append-only verdicts.

    One JSON record per line: ``{video, category, attempts, error,
    elapsed_s, host, time}``. Appends are single ``os.write`` calls on an
    ``O_APPEND`` fd, so concurrent shard workers sharing the output dir
    never interleave partial lines (POSIX atomic-append for records well
    under PIPE_BUF would require <=4KiB; errors are truncated to keep
    records small). ``load()`` is last-record-wins per video, so a
    later ``RESOLVED`` record (written when ``retry_failed=true``
    succeeds) lifts a quarantine without rewriting history.
    """

    FILENAME = "_failures.jsonl"
    RESOLVED = "RESOLVED"

    def __init__(self, output_path: Union[str, Path]):
        self.path = os.path.join(str(output_path), self.FILENAME)
        self._cache: Optional[Dict[str, dict]] = None
        self._cache_stat: Optional[tuple] = None
        self._lock = threading.Lock()

    # -- writes -------------------------------------------------------------
    def record(self, video: str, category: str, attempts: int, error: str,
               elapsed_s: float) -> dict:
        rec = {
            "video": str(video),
            "category": str(category),
            "attempts": int(attempts),
            "error": str(error)[:1000],
            "elapsed_s": round(float(elapsed_s), 3),
            "host": socket.gethostname(),
            "time": time.time(),
        }
        # serve-mode correlation (telemetry/context.py): stamp the spool
        # request whose video failed; absent in batch runs, so existing
        # journal records and their consumers are untouched
        from ..telemetry.context import current_request_id
        rid = current_request_id()
        if rid is not None:
            rec["request_id"] = rid
        self._append(rec)
        from .. import telemetry
        telemetry.inc("vft_failures_total", category=str(category))
        return rec

    def resolve(self, video: str) -> None:
        """Lift a quarantine: a ``retry_failed=true`` run extracted this
        video successfully, so future runs must not skip it."""
        self._append({"video": str(video), "category": self.RESOLVED,
                      "host": socket.gethostname(), "time": time.time()})

    def _append(self, rec: dict) -> None:
        # single atomic O_APPEND write + torn-tail healing, shared with
        # _telemetry.jsonl (telemetry/jsonl.py — factored out of this
        # class so every JSONL artifact has identical crash semantics)
        from ..telemetry.jsonl import append_jsonl
        append_jsonl(self.path, rec)
        with self._lock:
            self._cache = None  # force re-read after our own write

    # -- reads --------------------------------------------------------------
    def load(self) -> Dict[str, dict]:
        """Per-video latest record. Cached on (mtime, size); corrupt
        lines (a torn append from a killed worker) are skipped, never
        fatal — the journal is an optimization, not a lock."""
        try:
            st = os.stat(self.path)
            stat_key = (st.st_mtime_ns, st.st_size)
        except OSError:
            return {}
        with self._lock:
            if self._cache is not None and self._cache_stat == stat_key:
                return self._cache
        out: Dict[str, dict] = {}
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                for raw in f:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        rec = json.loads(raw)
                    except (json.JSONDecodeError, ValueError):
                        continue
                    if isinstance(rec, dict) and "video" in rec:
                        out[str(rec["video"])] = rec
        except OSError:
            return {}
        with self._lock:
            self._cache, self._cache_stat = out, stat_key
        return out

    def poison_record(self, video: str) -> Optional[dict]:
        """This video's latest record iff it quarantines (category
        POISON); RESOLVED / TRANSIENT / FATAL records do not — transient
        and fatal terminal failures are re-attempted by a restarted
        worker (the environment or config may have changed)."""
        rec = self.load().get(str(video))
        if rec is not None and rec.get("category") == POISON:
            return rec
        return None

    def tally_by_category(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self.load().values():
            cat = rec.get("category", "?")
            if cat != self.RESOLVED:
                out[cat] = out.get(cat, 0) + 1
        return out
