from .lists import form_list_from_user_input, form_slices
from .sinks import (action_on_extraction, is_already_exist, load_numpy,
                    load_pickle, make_path, write_numpy, write_pickle)
