"""Optical-flow -> RGB rendering with the Middlebury color wheel.

Same visualization contract as reference utils/flow_viz.py:20-132 (the
standard public Middlebury scheme of Baker et al. / Dana's colorwheel):
hue encodes flow direction, saturation encodes magnitude normalized by the
per-image maximum radius.
"""
from __future__ import annotations

import numpy as np


def make_colorwheel() -> np.ndarray:
    """(55, 3) uint-valued color wheel spanning RY/YG/GC/CB/BM/MR arcs."""
    arcs = [("RY", 15, (255, 0, 0), (0, 255, 0)),
            ("YG", 6, (255, 255, 0), (-255, 0, 0)),
            ("GC", 4, (0, 255, 0), (0, 0, 255)),
            ("CB", 11, (0, 255, 255), (0, -255, 0)),
            ("BM", 13, (0, 0, 255), (255, 0, 0)),
            ("MR", 6, (255, 0, 255), (0, 0, -255))]
    rows = []
    for _, n, base, delta in arcs:
        t = np.arange(n, dtype=np.float64)[:, None] / n
        base = np.asarray(base, dtype=np.float64)
        delta = np.asarray(delta, dtype=np.float64)
        # the ramp term is floored BEFORE adding to the base (a descending
        # arc is 255 - floor(255*t), not floor(255 - 255*t) — off by one
        # LSB on fractional steps)
        rows.append(base + np.sign(delta) * np.floor(t * np.abs(delta)))
    return np.concatenate(rows, axis=0)


_WHEEL = make_colorwheel()


def flow_uv_to_colors(u: np.ndarray, v: np.ndarray,
                      convert_to_bgr: bool = False) -> np.ndarray:
    """Normalized (u, v) in [-1, 1] -> (H, W, 3) uint8 colors."""
    ncols = _WHEEL.shape[0]
    rad = np.sqrt(u ** 2 + v ** 2)
    a = np.arctan2(-v, -u) / np.pi           # [-1, 1]
    fk = (a + 1) / 2 * (ncols - 1)           # wheel position
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = fk - k0
    img = np.zeros(u.shape + (3,), dtype=np.uint8)
    for i in range(3):
        col0 = _WHEEL[k0, i] / 255.0
        col1 = _WHEEL[k1, i] / 255.0
        col = (1 - f) * col0 + f * col1
        # saturate toward white inside the unit radius, darken outside
        col = np.where(rad <= 1, 1 - rad * (1 - col), col * 0.75)
        ch = 2 - i if convert_to_bgr else i
        img[..., ch] = np.floor(255 * col)
    return img


def flow_to_image(flow_uv: np.ndarray, clip_flow: float = None,
                  convert_to_bgr: bool = False) -> np.ndarray:
    """(H, W, 2) flow (pixels) -> (H, W, 3) uint8 visualization.

    Magnitude is normalized by the image's own max radius (reference
    utils/flow_viz.py:110-132), so colors are comparable within one frame
    only.
    """
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, \
        "input flow must have shape (H, W, 2)"
    if clip_flow is not None:
        flow_uv = np.clip(flow_uv, 0, clip_flow)
    u = flow_uv[..., 0]
    v = flow_uv[..., 1]
    rad_max = max(float(np.sqrt(u ** 2 + v ** 2).max()), 0.0)
    eps = 1e-5
    return flow_uv_to_colors(u / (rad_max + eps), v / (rad_max + eps),
                             convert_to_bgr)
