"""Output sinks and the idempotent resume contract.

Implements the reference's output behavior (models/_base/base_extractor.py:55-127
and utils/utils.py:53-57,241-251):

  - file name contract: ``{video_stem}_{key}{ext}`` under the (already
    namespaced) output dir
  - sinks: 'print' (max/mean/min summary), 'save_numpy' (.npy),
    'save_pickle' (.pkl)
  - `is_already_exist`: every expected key file must exist AND load without
    error — loading doubles as corruption detection, which is what makes
    independently-launched (or preempted) workers resumable.

This idempotent-file contract is the framework's checkpoint format for
preemptible TPU workers, exactly as it is the reference's de-facto resume
mechanism.
"""
from __future__ import annotations

import os
import pickle
import traceback
from pathlib import Path
from typing import Dict, Sequence

import numpy as np

EXTS = {"save_numpy": ".npy", "save_pickle": ".pkl"}


def make_path(output_root: str, video_path: str, output_key: str, ext: str) -> str:
    """``{output_root}/{stem}_{key}{ext}`` (reference utils/utils.py:53-57)."""
    fname = f"{Path(video_path).stem}_{output_key}{ext}"
    return os.path.join(str(output_root), fname)


def load_numpy(fpath):
    return np.load(fpath)


def write_numpy(fpath, value):
    from .. import native
    # temp-file + fsync + atomic rename (native/vft_native.cpp): a preempted
    # worker can never leave a half-written feature file behind
    if native.write_npy_atomic(fpath, value):
        return
    return np.save(fpath, value)


def load_pickle(fpath):
    with open(fpath, "rb") as f:
        return pickle.load(f)


def write_pickle(fpath, value):
    with open(fpath, "wb") as f:
        pickle.dump(value, f)


def is_already_exist(on_extraction: str, output_path: str, video_path: str,
                     output_feat_keys: Sequence[str]) -> bool:
    """True iff every key file exists and loads cleanly.

    Mirrors reference base_extractor.py:95-127: for the 'print' sink nothing is
    persisted, so extraction always re-runs; for file sinks a file that exists
    but fails to load (partial write from a preempted worker) counts as absent.
    """
    if on_extraction == "print":
        return False
    if on_extraction not in EXTS:
        raise NotImplementedError(f"on_extraction: {on_extraction}")
    ext = EXTS[on_extraction]
    loader = load_numpy if on_extraction == "save_numpy" else load_pickle

    from .. import native

    how_many_files_should_exist = len(output_feat_keys)
    existing = 0
    for key in output_feat_keys:
        fpath = make_path(output_path, video_path, key, ext)
        if os.path.exists(fpath):
            # O(header) structural check (native/vft_native.cpp) instead of
            # loading the whole array; None = cannot judge -> full load
            verdict = (native.validate_npy(fpath)
                       if on_extraction == "save_numpy" else None)
            if verdict is True:
                existing += 1
            elif verdict is False:
                print(f"Failed to load: {fpath}. Will extract again.")
            else:
                try:
                    loader(fpath)
                    existing += 1
                except Exception:
                    print(f"Failed to load: {fpath}. Will extract again.")
    if existing == how_many_files_should_exist:
        print(f'Features for "{video_path}" already exist in "{output_path}" — skipping. '
              "Use a different `output_path` to extract again.")
        return True
    return False


def action_on_extraction(feats_dict: Dict[str, np.ndarray],
                         video_path: str,
                         output_path: str,
                         on_extraction: str) -> None:
    """Dispatch extracted features to the configured sink.

    Mirrors reference base_extractor.py:55-93 including the re-check before
    overwrite (another worker may have finished this video while we computed)
    and the empty-value warning.
    """
    if on_extraction == "print":
        print(f"\nFeatures for: {video_path}")
        for k, v in feats_dict.items():
            print(k)
            print(np.asarray(v))
            arr = np.asarray(v)
            if arr.dtype != object and arr.size > 0:
                print(f"max: {arr.max():.8f}; mean: {arr.mean():.8f}; min: {arr.min():.8f}")
            print()
        return
    if on_extraction not in EXTS:
        raise NotImplementedError(f"on_extraction: {on_extraction}")

    from .profiling import profiler

    os.makedirs(output_path, exist_ok=True)
    writer = write_numpy if on_extraction == "save_numpy" else write_pickle
    for key, value in feats_dict.items():
        fpath = make_path(output_path, video_path, key, EXTS[on_extraction])
        arr = np.asarray(value)
        if arr.size == 0:
            print("Warning: the value is empty for", key, "@", video_path)
        with profiler.stage("write"):
            writer(fpath, value)


def safe_extract(extract_fn, video_path: str) -> str:
    """Run one video; any failure prints a traceback and is non-fatal.

    The per-video error isolation of reference base_extractor.py:40-53
    (KeyboardInterrupt re-raised). Returns ``'done'``, ``'skipped'`` (the
    idempotent already-exists path returned without extracting), or
    ``'error'`` — the CLI's run summary tallies these.
    """
    try:
        result = extract_fn(video_path)
        return "done" if result is not None else "skipped"
    except KeyboardInterrupt:
        raise
    except Exception:
        print(f"An error occurred extracting features for: {video_path}")
        traceback.print_exc()
        return "error"
