"""Output sinks and the idempotent resume contract.

Implements the reference's output behavior (models/_base/base_extractor.py:55-127
and utils/utils.py:53-57,241-251):

  - file name contract: ``{video_stem}_{key}{ext}`` under the (already
    namespaced) output dir
  - sinks: 'print' (max/mean/min summary), 'save_numpy' (.npy),
    'save_pickle' (.pkl)
  - `is_already_exist`: every expected key file must exist AND load without
    error — loading doubles as corruption detection, which is what makes
    independently-launched (or preempted) workers resumable.

This idempotent-file contract is the framework's checkpoint format for
preemptible TPU workers, exactly as it is the reference's de-facto resume
mechanism.
"""
from __future__ import annotations

import errno
import hashlib
import io
import os
import pickle
import tempfile
import traceback
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from . import inject

EXTS = {"save_numpy": ".npy", "save_pickle": ".pkl"}


def make_path(output_root: str, video_path: str, output_key: str, ext: str) -> str:
    """``{output_root}/{stem}_{key}{ext}`` (reference utils/utils.py:53-57)."""
    fname = f"{Path(video_path).stem}_{output_key}{ext}"
    return os.path.join(str(output_root), fname)


def load_numpy(fpath):
    return np.load(fpath)


def _write_bytes_atomic(fpath, data: bytes) -> None:
    """Temp file in the target dir + flush + fsync + ``os.replace`` — the
    same contract as native write_npy_atomic, for already-serialized
    bytes (the hash-before-rename artifact-digest path).

    The unlink-on-failure is load-bearing, not defensive: a raise
    anywhere between mkstemp and ``os.replace`` (ENOSPC at fsync, a
    failed rename) must not leak the ``.tmp`` file into the output dir
    forever — ``vft-audit``'s no-tmp-litter invariant and the injected
    ``sink.*`` faults (utils/inject.py, tests/test_inject.py) pin it.
    """
    d = os.path.dirname(fpath) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d,
                               prefix=os.path.basename(fpath) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            fault = inject.fire("sink.tmp_write", path=str(fpath))
            if fault is not None and fault.kind == "torn":
                # a short write: the disk filled (or the process died)
                # mid-write — exactly what atomic rename must hide
                f.write(data[:max(1, len(data) // 2)])
                f.flush()
                raise OSError(errno.EIO,
                              f"injected torn write for {fpath}")
            f.write(data)
            f.flush()
            inject.fire("sink.fsync", path=str(fpath))
            os.fsync(f.fileno())
        fault = inject.fire("sink.rename", path=str(fpath))
        if fault is not None and fault.kind == "drop":
            raise OSError(errno.EIO,
                          f"injected rename drop for {fpath}")
        os.replace(tmp, fpath)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_numpy(fpath, value, want_digest: bool = False
                ) -> Optional[Tuple[int, str]]:
    """Atomic .npy write; with ``want_digest`` returns ``(bytes, sha256)``
    of EXACTLY what was renamed into place (serialized once in memory,
    hashed before the rename — so the digest can never describe a file a
    concurrent worker replaced underneath us)."""
    from .. import native
    if want_digest or inject.active() is not None:
        # the Python path is byte-identical to the native writer (pinned
        # by tests/test_sinks.py); chaos runs take it unconditionally so
        # the sink.{tmp_write,fsync,rename} injection sites cover every
        # .npy write, not just the digest-requesting ones
        buf = io.BytesIO()
        np.save(buf, np.asarray(value))
        data = buf.getvalue()
        _write_bytes_atomic(fpath, data)
        if want_digest:
            return len(data), hashlib.sha256(data).hexdigest()
        return None
    # temp-file + fsync + atomic rename (native/vft_native.cpp): a preempted
    # worker can never leave a half-written feature file behind
    if native.write_npy_atomic(fpath, value):
        return None
    # native writer unavailable (no compiler on this host): the Python
    # atomic path is byte-identical (pinned by tests/test_sinks.py) — a
    # raw np.save here was the one non-atomic .npy fallback left
    buf = io.BytesIO()
    np.save(buf, np.asarray(value))
    _write_bytes_atomic(fpath, buf.getvalue())
    return None


def load_pickle(fpath):
    with open(fpath, "rb") as f:
        return pickle.load(f)


def write_pickle(fpath, value, want_digest: bool = False
                 ) -> Optional[Tuple[int, str]]:
    # same temp-file + fsync + atomic-rename discipline as write_numpy: a
    # preempted worker must never leave a torn .pkl that load_pickle would
    # half-read (or that poisons is_already_exist's resume check forever)
    data = pickle.dumps(value)
    _write_bytes_atomic(fpath, data)
    if want_digest:
        return len(data), hashlib.sha256(data).hexdigest()
    return None


def is_already_exist(on_extraction: str, output_path: str, video_path: str,
                     output_feat_keys: Sequence[str]) -> bool:
    """True iff every key file exists and loads cleanly.

    Mirrors reference base_extractor.py:95-127: for the 'print' sink nothing is
    persisted, so extraction always re-runs; for file sinks a file that exists
    but fails to load (partial write from a preempted worker) counts as absent.
    """
    if on_extraction == "print":
        return False
    if on_extraction not in EXTS:
        raise NotImplementedError(f"on_extraction: {on_extraction}")
    ext = EXTS[on_extraction]
    loader = load_numpy if on_extraction == "save_numpy" else load_pickle

    from .. import native

    how_many_files_should_exist = len(output_feat_keys)
    existing = 0
    for key in output_feat_keys:
        fpath = make_path(output_path, video_path, key, ext)
        if os.path.exists(fpath):
            # O(header) structural check (native/vft_native.cpp) instead of
            # loading the whole array; None = cannot judge -> full load
            verdict = (native.validate_npy(fpath)
                       if on_extraction == "save_numpy" else None)
            if verdict is True:
                existing += 1
            elif verdict is False:
                print(f"Failed to load: {fpath}. Will extract again.")
            else:
                try:
                    loader(fpath)
                    existing += 1
                except Exception:
                    print(f"Failed to load: {fpath}. Will extract again.")
    if existing == how_many_files_should_exist:
        print(f'Features for "{video_path}" already exist in "{output_path}" — skipping. '
              "Use a different `output_path` to extract again.")
        return True
    return False


def action_on_extraction(feats_dict: Dict[str, np.ndarray],
                         video_path: str,
                         output_path: str,
                         on_extraction: str) -> None:
    """Dispatch extracted features to the configured sink.

    Mirrors reference base_extractor.py:55-93 including the re-check before
    overwrite (another worker may have finished this video while we computed)
    and the empty-value warning.
    """
    if on_extraction == "print":
        print(f"\nFeatures for: {video_path}")
        for k, v in feats_dict.items():
            print(k)
            print(np.asarray(v))
            arr = np.asarray(v)
            if arr.dtype != object and arr.size > 0:
                print(f"max: {arr.max():.8f}; mean: {arr.mean():.8f}; min: {arr.min():.8f}")
            print()
        return
    if on_extraction not in EXTS:
        raise NotImplementedError(f"on_extraction: {on_extraction}")

    from .profiling import profiler
    from .. import telemetry

    os.makedirs(output_path, exist_ok=True)
    writer = write_numpy if on_extraction == "save_numpy" else write_pickle
    # with a live span, each write also records what landed on disk
    # (byte size + sha256 of the renamed bytes) as an `artifact` span
    # event, so scripts/compare_runs.py can detect truncated or changed
    # outputs between runs without re-reading any feature file
    span = telemetry.current_span()
    for key, value in feats_dict.items():
        fpath = make_path(output_path, video_path, key, EXTS[on_extraction])
        arr = np.asarray(value)
        if arr.size == 0:
            print("Warning: the value is empty for", key, "@", video_path)
        with profiler.stage("write"):
            info = writer(fpath, value, want_digest=span is not None)
        if info is not None:
            span.event("artifact", key=key, file=os.path.basename(fpath),
                       bytes=info[0], sha256=info[1])


def safe_extract(extract_fn, video_path: str, policy=None, journal=None,
                 decode_mode: str = None, on_terminal_failure=None) -> str:
    """Run one video under the fault-tolerance runtime (utils/faults.py).

    Extends the per-video error isolation of reference
    base_extractor.py:40-53 (KeyboardInterrupt still re-raised) with:

      - **quarantine skip**: with a ``journal``, a video whose latest
        journal record is POISON is skipped up front (``'quarantined'``)
        unless ``policy.retry_failed`` — the restarted-worker resume path;
      - **categorized retries**: each failure is classified
        TRANSIENT/POISON/FATAL; TRANSIENT and POISON get up to
        ``policy.attempts`` total tries with exponential backoff +
        jitter; FATAL fails immediately (retrying a config error cannot
        help, and per-video isolation keeps the run going);
      - **decode degradation ladder**: when ``decode_mode`` is
        ``'parallel'``/``'process'``, each retry demotes one rung
        (``parallel -> process -> inline``) via the fault context that
        ``BaseExtractor.video_source`` consults;
      - **deadline watchdog**: ``policy.deadline_s`` arms a per-attempt
        timer that cancels the in-flight sources (DeadlineExceeded) so a
        hung decode fails only this video;
      - **journal record**: a terminal failure appends one
        ``_failures.jsonl`` record; ``on_terminal_failure`` (when given)
        receives it too, journal or not.

    Default arguments (``policy=None``) reproduce the old single-attempt
    behavior exactly. Returns ``'done'``, ``'skipped'`` (idempotent
    already-exists), ``'quarantined'`` (journal skip) or ``'error'``.
    """
    from . import faults
    from .. import telemetry
    from ..telemetry import trace

    if policy is None:
        policy = faults.RetryPolicy()  # single attempt, no deadline
    telemetry.annotate(decode_mode=decode_mode)
    if journal is not None and not policy.retry_failed:
        rec = journal.poison_record(video_path)
        if rec is not None:
            print(f'"{video_path}" is quarantined by {journal.path} '
                  f'(category={rec.get("category")}, '
                  f'attempts={rec.get("attempts")}) — skipping. '
                  "Pass retry_failed=true to re-run it.")
            telemetry.inc("vft_quarantine_skips_total")
            telemetry.event("quarantine_skip",
                            category=rec.get("category"))
            return "quarantined"

    t0 = policy.clock()
    category = None
    err_repr = ""
    attempts_made = 0
    mode = decode_mode if policy.ladder else None
    for attempt in range(1, policy.attempts + 1):
        attempts_made = attempt
        override = mode if (mode is not None and mode != decode_mode) \
            else None
        ctx = faults.FaultContext(video_path,
                                  deadline_s=policy.deadline_s,
                                  decode_override=override)
        # chaos hook (utils/inject.py): `worker.kill=kill@nK` SIGKILLs
        # this worker at the K-th video attempt fleet-wide — the
        # deterministic replay of test_chaos's scripted preemptions
        inject.fire("worker.kill", video=str(video_path), attempt=attempt)
        try:
            # one timeline span per attempt (trace=true; no-op otherwise):
            # the unit trace_report.py cuts the per-video critical path on,
            # recorded for failed attempts too. In serve mode the attempt
            # additionally names its spool request (telemetry/context.py),
            # so one request id finds its timeline windows across hosts.
            _rid = telemetry.current_request_id()
            with trace.span("video_attempt", video=str(video_path),
                            attempt=attempt,
                            **({"request": _rid} if _rid else {})):
                with ctx:
                    result = extract_fn(video_path)
            if attempt > 1:
                print(f'Recovered "{video_path}" on attempt '
                      f"{attempt}/{policy.attempts}"
                      + (f" (video_decode={mode})" if override else ""))
                telemetry.inc("vft_video_recoveries_total")
            telemetry.annotate(attempts=attempt)
            if journal is not None and policy.retry_failed \
                    and journal.poison_record(video_path) is not None:
                journal.resolve(video_path)  # lift the quarantine
            return "done" if result is not None else "skipped"
        except KeyboardInterrupt:
            raise
        except BaseException as e:
            if not isinstance(e, Exception):
                raise  # SystemExit/GeneratorExit are not video failures
            category = faults.classify(e)
            err_repr = f"{type(e).__name__}: {e}"
            print(f"An error occurred extracting features for: {video_path} "
                  f"(attempt {attempt}/{policy.attempts}, "
                  f"category={category})")
            traceback.print_exc()
            telemetry.event("attempt_failed", attempt=attempt,
                            category=category)
            if category == faults.FATAL:
                break  # retrying a config/programming error cannot help
            if attempt < policy.attempts:
                next_mode = faults.demote(mode)
                if next_mode is not None:
                    print(f"DECODE LADDER: retrying \"{video_path}\" with "
                          f"video_decode={next_mode} (was {mode})")
                    telemetry.event("ladder", to=next_mode)
                    telemetry.inc("vft_decode_demotions_total")
                    mode = next_mode
                delay = policy.backoff_delay(attempt)
                telemetry.inc("vft_video_retries_total")
                if delay > 0:
                    print(f"Retrying \"{video_path}\" in {delay:.2f}s ...")
                    with trace.span("retry_backoff", video=str(video_path),
                                    attempt=attempt,
                                    delay_s=round(delay, 3)):
                        policy.sleep(delay)

    elapsed = policy.clock() - t0
    telemetry.annotate(attempts=attempts_made, category=category,
                       error=err_repr)
    rec = {"video": str(video_path), "category": category,
           "attempts": attempts_made, "error": err_repr,
           "elapsed_s": round(float(elapsed), 3)}
    if journal is not None:
        rec = journal.record(video_path, category, attempts_made, err_repr,
                             elapsed)
    if on_terminal_failure is not None:
        try:
            on_terminal_failure(rec)
        except Exception:
            pass
    return "error"
