"""Content-addressed feature cache: never decode (or compute) twice.

At millions-of-users scale repeat content is the dominant pattern
(ROADMAP item 1): the same trailer, meme clip or re-uploaded video
arrives byte-identical thousands of times, and the cold CLI re-pays the
full decode -> transform -> device -> sink cost for every copy. With
``cache=true`` a finished extraction is stored once under a key that
captures everything that could change its value, and every later
request for the same (content, configuration, weights) triple is served
from the store without constructing a decoder at all:

  **content identity** — ``sha256`` of the input file's bytes (streamed,
  memoized per ``(path, size, mtime)`` so a corpus pass hashes each file
  once). Sources that cannot be byte-hashed (pipes, devices) fall back
  to the decode-plan identity: the probed stream properties plus the
  exact ``plan_frame_selection`` mapping the extraction would use — the
  same walk ``VideoSource`` and the shared-decode ``FrameBus`` agree on,
  so two sources that would decode identical frame streams key alike.

  **config fingerprint** — the sanity-checked config with every
  non-semantic key dropped (paths, worker counts, telemetry switches,
  retry policy: none of them change a feature value) and every
  value-bearing default RESOLVED: the extractor's own ``resize_mode`` /
  ``ingest`` resolutions replace the raw ``resize=auto`` / ``ingest=null``
  strings, so ``resize=auto`` and an explicit ``resize=device`` hash
  identically whenever they resolve the same (pinned by
  tests/test_cache.py).

  **weights fingerprint** — sha256 of every checkpoint file the
  extractor's ``weights/store.resolve_params`` actually loaded (captured
  at init via :func:`~.weights.store.start_weights_capture`), so a
  re-converted or fine-tuned checkpoint can never serve stale features.
  ``allow_random_weights`` runs key under a ``random:`` sentinel — the
  seeded init is deterministic, which is what the tests and benches rely
  on.

Serving is **verify-before-trust**: a stored entry carries the PR-5
quantization-tolerant content signature (telemetry/health.py) of every
feature tensor, recomputed on load; a mismatch (bit rot, torn write,
tampering) deletes the entry and reports a miss instead of serving bad
features. Writes go through the same atomic temp+fsync+rename
discipline as the sinks (utils/sinks.py ``_write_bytes_atomic``), so a
preempted worker can never leave a half-written entry that later
lookups would trust.

Telemetry: ``vft_cache_{hit,miss,bypass}_total{family=...}`` counters
(bypass = work avoided by the filename skip-if-exists check WITHOUT
consulting the cache — docs/performance.md documents the precedence:
cache hit > filename skip), a ``cache`` section in every heartbeat
(telemetry/recorder.py ``cache_snapshot``), and ``cache.lookup`` /
``cache.hit`` / ``cache.store`` timeline spans when ``trace=true``.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

#: schema identifier stamped into every entry; bump on breaking change
SCHEMA_VERSION = "vft.feature_cache/1"

#: config keys that can never change a feature VALUE — dropped from the
#: fingerprint so runs that differ only operationally share entries.
#: (feature_type/model_name stay IN: they select the network.)
NON_SEMANTIC_KEYS = frozenset({
    # where things land / come from
    "output_path", "tmp_path", "keep_tmp_files",
    "video_paths", "file_with_video_paths", "config",
    # how work is scheduled, observed and retried
    "video_workers", "decode_workers", "decode_depth", "video_decode",
    "fanout_depth", "cross_video_batching", "clip_batch_size",
    "batch_size", "flow_stack_batch", "model_parallel",
    "mesh_devices", "distributed",
    "telemetry", "metrics_interval_s", "trace", "health", "parity",
    "roofline", "history", "alerts",
    "profile", "profile_trace_dir", "compilation_cache_dir",
    "retry_attempts", "retry_backoff_s", "video_deadline_s",
    "retry_failed",
    # fleet scheduling (parallel/queue.py) moves work between hosts; it
    # cannot change what any (video, config, weights) triple computes
    "fleet", "fleet_lease_s", "fleet_max_reclaims", "fleet_canary",
    # the cache's own knobs must not key the cache; the compile cache's
    # knobs (compile_cache.py) likewise change where executables come
    # from, never what any program computes. cache_scope changes WHO may
    # observe an entry (a tenant salt in the key, below), never the
    # feature values — it must not perturb the config fingerprint
    "cache", "cache_dir", "cache_scope",
    "compile_cache", "compile_cache_dir",
    # chaos-injection plans perturb scheduling/IO, never feature values
    # (a fault either recovers bit-identically or fails the video)
    "inject",
    # serve-mode knobs (serve.py): spool plumbing, not feature values
    "spool_dir", "serve_max_pending", "serve_poll_interval_s",
    "serve_idle_exit_s", "serve_max_requests", "serve_workers",
    "serve_warmup_video", "serve_slo_s",
    # gateway knobs (gateway.py): ingress admission/deadline plumbing
    "gateway_tenants", "gateway_port", "gateway_host",
    "gateway_max_queued", "gateway_spool_bound", "gateway_max_body_mb",
    "gateway_poll_interval_s", "gateway_expire_grace_s",
    "gateway_default_timeout_s",
    # sink format changes the FILE, not the feature values; entries store
    # arrays and are written through whichever sink the run uses
    "on_extraction", "show_pred",
    # storage lifecycle knobs (gc.py): eviction is always a recoverable
    # miss — deleting an entry can change how long a run takes, never
    # what any (video, config, weights) triple computes
    "gc", "gc_quota_gb", "gc_cache_retention_s",
    "gc_compile_retention_s", "gc_spool_retention_s",
    "gc_inbox_retention_s", "gc_incident_retention_s",
    "gc_quarantine_retention_s", "gc_staging_retention_s",
    "gc_interval_s",
})

#: config keys that DO bear on feature values — they stay in the
#: fingerprint, and the choice is now explicit: ``vft-lint`` rule VFT001
#: fails the build when a key in any family YAML (or read by a
#: validator) is in neither set, which is exactly how every one of
#: PRs 9/11/13/14 almost re-introduced the cache-poisoning hazard this
#: pair of sets exists to prevent. When adding a config key, ask "can
#: two runs that differ only in this key produce different features?" —
#: yes -> here, no -> NON_SEMANTIC_KEYS above.
SEMANTIC_KEYS = frozenset({
    # what network, on which backend, at what precision
    "feature_type", "model_name", "device", "precision",
    "weights_path", "allow_random_weights",
    # which frames reach it
    "extraction_fps", "extraction_total", "fps_mode",
    # how pixels are prepared (resolved resize/ingest overlay included)
    "resize", "ingest", "side_size", "resize_to_smaller_edge",
    # clip windowing (value-bearing: changes the stacks the net sees)
    "stack_size", "step_size", "streams",
    # flow-family knobs (iteration counts and flow nets change outputs)
    "flow_type", "flow_iters", "flow_weights_path",
    "flow_model_weights_path", "iters", "finetuned_on",
    # kernel dispatch (implementations are near- but not bit-identical)
    "corr_lookup_impl", "fuse_convc1", "vision_attn",
    # CLIP text side + prediction rendering inputs
    "bpe_path", "pred_texts",
    # VGGish post-processing
    "frontend", "postprocess", "pca_weights_path",
})

_sha_lock = threading.Lock()
#: (abspath, size, mtime_ns) -> hex digest; bounded FIFO
_sha_memo: Dict[tuple, str] = {}
_SHA_MEMO_CAP = 4096


def file_sha256(path: str) -> str:
    """Streamed sha256 of a file, memoized on ``(path, size, mtime)`` so
    a two-pass corpus run hashes each input once (the memo is the cheap
    in-process analog of the content-addressed store itself)."""
    st = os.stat(path)
    key = (os.path.abspath(path), st.st_size, st.st_mtime_ns)
    with _sha_lock:
        hit = _sha_memo.get(key)
    if hit is not None:
        return hit
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    digest = h.hexdigest()
    with _sha_lock:
        if len(_sha_memo) >= _SHA_MEMO_CAP:
            _sha_memo.pop(next(iter(_sha_memo)), None)
        _sha_memo[key] = digest
    return digest


def plan_identity(video_path: str, fps: Optional[float],
                  total: Optional[int]) -> str:
    """Decode-plan-level identity for sources that cannot be byte-hashed:
    the probed stream properties plus the exact frame-selection mapping
    (utils/io.py ``plan_frame_selection`` — the walk every decoded-stream
    consumer agrees on). Weaker than a byte hash (two different encodes
    with identical probe properties would collide), so it is only the
    FALLBACK identity; the sha256 fast path wins whenever the bytes are
    readable."""
    from .utils.io import get_video_props, plan_frame_selection
    props = get_video_props(video_path)
    out_fps, index_map, num_frames = plan_frame_selection(
        props["fps"], props["num_frames"], fps=fps, total=total)
    h = hashlib.sha256()
    h.update(repr((os.path.basename(str(video_path)),
                   round(float(props["fps"]), 4),
                   int(props["num_frames"]),
                   int(props["width"]), int(props["height"]),
                   round(float(out_fps), 4), int(num_frames))).encode())
    if index_map is not None:
        h.update(np.asarray(index_map, np.int64).tobytes())
    return "plan:" + h.hexdigest()


def content_identity(video_path: str, fps: Optional[float] = None,
                     total: Optional[int] = None) -> str:
    """``sha256:<hex>`` of the file bytes (fast path), or the
    ``plan:<hex>`` decode-plan identity when the bytes are unreadable."""
    try:
        return "sha256:" + file_sha256(str(video_path))
    except OSError:
        return plan_identity(video_path, fps, total)


def canonical_config(args: Dict[str, Any],
                     resolved: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """The value-bearing view of a sanity-checked config: non-semantic
    keys dropped, ``resolved`` overlays (the extractor's own
    ``resize_mode``/``ingest`` resolutions) replacing their raw keys,
    and nested dicts flattened deterministically."""
    from .config import _plain
    plain = _plain(dict(args))
    out = {k: v for k, v in plain.items() if k not in NON_SEMANTIC_KEYS}
    for k, v in (resolved or {}).items():
        if v is not None:
            out[k] = v
    return out


def config_fingerprint(args: Dict[str, Any],
                       resolved: Optional[Dict[str, Any]] = None) -> str:
    """sha256 over the sorted canonical config repr — two configs that
    resolve to the same extraction semantics fingerprint identically."""
    canon = canonical_config(args, resolved)
    blob = repr(sorted(canon.items(), key=lambda kv: kv[0]))
    return hashlib.sha256(blob.encode()).hexdigest()


def weights_fingerprint(capture: Optional[List[dict]]) -> str:
    """sha256 over the (sorted) identities of every checkpoint the
    extractor resolved: ``{model_key, sha256}`` per resolution, or the
    ``random:{model_key}`` sentinel for seeded random init. An empty /
    missing capture (extractor resolved nothing — unlikely but legal)
    keys as ``'none'``."""
    if not capture:
        return "none"
    items = []
    for rec in capture:
        if rec.get("random"):
            items.append(f"random:{rec.get('model_key')}")
        else:
            items.append(f"{rec.get('model_key')}:{rec.get('sha256')}")
    blob = "\n".join(sorted(items))
    return hashlib.sha256(blob.encode()).hexdigest()


def entry_key(content_id: str, config_fp: str, weights_fp: str,
              tenant: Optional[str] = None) -> str:
    """The store key: one sha256 over the three identity components —
    plus, under ``cache_scope=tenant``, the requesting tenant's id as a
    fourth component, so one tenant's entries can never be observed by
    (or served to) another. The default ``shared`` scope omits it: at
    fleet scale cross-tenant dedup of repeat content is the dominant
    win, and byte-identical inputs hash to one entry for everyone."""
    salt = f"\ntenant:{tenant}" if tenant else ""
    return hashlib.sha256(
        f"{content_id}\n{config_fp}\n{weights_fp}{salt}".encode()
    ).hexdigest()


def default_cache_dir() -> str:
    return os.environ.get(
        "VFT_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "video_features_tpu", "feature_cache"))


class FeatureCache:
    """One extractor's handle on the content-addressed store.

    Entries live at ``{root}/{family}/{key[:2]}/{key}.pkl`` (two-level
    fan-out keeps directories small at corpus scale). The handle is
    cheap; all state is the filesystem plus the weights/config
    fingerprints computed once at attach time.
    """

    def __init__(self, root: str, family: str, config_fp: str,
                 weights_fp: str, *, fps: Optional[float] = None,
                 total: Optional[int] = None,
                 scope: str = "shared") -> None:
        self.root = str(root)
        self.family = str(family)
        self.config_fp = config_fp
        self.weights_fp = weights_fp
        self.scope = str(scope)
        self._fps = fps
        self._total = total

    # -- construction ------------------------------------------------------
    @classmethod
    def for_extractor(cls, ext) -> Optional["FeatureCache"]:
        """Build the handle from a constructed extractor, or None when
        ``cache=false``. Resolution happens HERE, after subclass init:
        the extractor's ``resize_mode``/``ingest`` attributes are the
        ground truth the raw ``resize=auto``/``ingest=null`` strings
        resolve to, which is what makes ``resize=auto`` and its resolved
        value share entries."""
        args = getattr(ext, "args", None)
        if args is None or not bool(args.get("cache", False)):
            return None
        root = args.get("cache_dir") or default_cache_dir()
        resolved = {}
        for attr, key in (("resize_mode", "resize"), ("ingest", "ingest")):
            val = getattr(ext, attr, None)
            if val is not None:
                resolved[key] = val
        config_fp = config_fingerprint(args, resolved)
        weights_fp = weights_fingerprint(
            getattr(ext, "_weights_capture", None))
        return cls(os.path.join(root, str(ext.feature_type)),
                   ext.feature_type, config_fp, weights_fp,
                   fps=args.get("extraction_fps"),
                   total=args.get("extraction_total"),
                   scope=args.get("cache_scope", "shared") or "shared")

    # -- keying ------------------------------------------------------------
    def key_for(self, video_path: str) -> str:
        cid = content_identity(video_path, self._fps, self._total)
        if self.scope == "tenant":
            # isolation semantics (docs/serving.md): the requesting
            # tenant (thread-local, minted into the request id by the
            # gateway) salts the key, so a hit can only ever be served
            # to the tenant whose extraction stored it. Untenanted work
            # (batch CLI, spool-direct) keys under its own sentinel.
            from .telemetry.context import current_tenant
            return entry_key(cid, self.config_fp, self.weights_fp,
                             tenant=current_tenant() or "_untenanted")
        return entry_key(cid, self.config_fp, self.weights_fp)

    def entry_path(self, key: str) -> str:
        return os.path.join(self.root, key[:2], key + ".pkl")

    # -- lookup / store ----------------------------------------------------
    def lookup(self, video_path: str,
               expected_keys: Optional[Sequence[str]] = None
               ) -> Optional[Dict[str, np.ndarray]]:
        """The stored features for ``video_path`` under this cache's
        fingerprints, or None (miss). A hit is re-verified against the
        stored quantization-tolerant signatures (telemetry/health.py)
        before being served; an entry that fails to load, fails the
        schema/keys check or fails signature verification is deleted and
        reported as a miss — corrupted bytes are never served."""
        from .telemetry import trace
        from .telemetry.health import content_signature
        from .utils import inject

        with trace.span("cache.lookup", video=str(video_path),
                        family=self.family):
            key = self.key_for(video_path)
            path = self.entry_path(key)
            if not os.path.exists(path):
                return None
            try:
                fault = inject.fire("cache.lookup", video=str(video_path),
                                    key=key[:12])
                if fault is not None and fault.kind == "torn":
                    # bit rot / a torn pre-atomic-writer entry: truncate
                    # the stored bytes so verify-before-trust must catch it
                    with open(path, "r+b") as f:
                        f.truncate(max(1, os.path.getsize(path) // 2))
                with open(path, "rb") as f:
                    entry = pickle.load(f)
                feats = entry["feats"]
                sigs = entry["sigs"]
                if entry.get("schema") != SCHEMA_VERSION:
                    raise ValueError(
                        f"schema {entry.get('schema')!r} != {SCHEMA_VERSION}")
                if expected_keys is not None and \
                        set(feats) != set(expected_keys):
                    raise ValueError(
                        f"entry keys {sorted(feats)} != expected "
                        f"{sorted(expected_keys)}")
                for k, arr in feats.items():
                    got = content_signature(np.asarray(arr))
                    if got != sigs.get(k):
                        raise ValueError(
                            f"content signature mismatch for key {k!r}")
            except Exception as e:
                # torn write / bit rot / stale schema: drop the entry so
                # the recompute below repopulates it, and never serve it
                print(f"cache: dropping corrupted entry {path} "
                      f"({type(e).__name__}: {e}) — treating as a miss")
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return None
            try:
                # last-hit signal for the LRU eviction plane (gc.py):
                # mtime bump on a VERIFIED hit only — no sidecar file, so
                # gc=false runs stay byte-identical in artifacts
                os.utime(path)
            except OSError:
                pass
            trace.instant("cache.hit", video=str(video_path),
                          family=self.family, key=key[:12])
            return feats

    def store(self, video_path: str, feats: Dict[str, Any]) -> str:
        """Write one entry atomically (temp + fsync + rename, the sink
        discipline) with per-key content signatures; returns the key."""
        from .telemetry import trace
        from .telemetry.health import content_signature
        from .utils import inject
        from .utils.sinks import _write_bytes_atomic

        with trace.span("cache.store", video=str(video_path),
                        family=self.family):
            inject.fire("cache.store", video=str(video_path),
                        family=self.family)
            key = self.key_for(video_path)
            arrays = {k: np.asarray(v) for k, v in feats.items()}
            entry = {
                "schema": SCHEMA_VERSION,
                "family": self.family,
                "video": os.path.basename(str(video_path)),
                "config_fp": self.config_fp,
                "weights_fp": self.weights_fp,
                "sigs": {k: content_signature(a)
                         for k, a in arrays.items()},
                "feats": arrays,
                "time": round(time.time(), 3),
            }
            _write_bytes_atomic(self.entry_path(key), pickle.dumps(entry))
            return key


# -- store maintenance -------------------------------------------------------

def cache_stats(root: Optional[str] = None) -> Dict[str, Any]:
    """Entry count + byte total per family under ``root`` (operator
    visibility; the serve heartbeat's counters are the live view)."""
    root = root or default_cache_dir()
    out: Dict[str, Any] = {"root": root, "families": {}, "entries": 0,
                           "bytes": 0}
    if not os.path.isdir(root):
        return out
    for family in sorted(os.listdir(root)):
        fam_dir = os.path.join(root, family)
        if not os.path.isdir(fam_dir):
            continue
        n = b = 0
        for dirpath, _dirnames, filenames in os.walk(fam_dir):
            for fn in filenames:
                if fn.endswith(".pkl"):
                    n += 1
                    try:
                        b += os.path.getsize(os.path.join(dirpath, fn))
                    except OSError:
                        pass
        out["families"][family] = {"entries": n, "bytes": b}
        out["entries"] += n
        out["bytes"] += b
    return out
