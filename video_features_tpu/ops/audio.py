"""Audio DSP frontend for VGGish: waveform -> log-mel examples.

Parity target: the reference's pure-numpy pipeline (reference
models/vggish/vggish_src/mel_features.py + vggish_input.py + vggish_params.py):

  - stride-tricks framing with no zero padding (mel_features.py:21-45),
  - *periodic* Hann window (mel_features.py:48-68),
  - rFFT magnitude STFT at fft_length = next pow2 of the 400-sample window
    (mel_features.py:71-92, log_mel_spectrogram:225-232),
  - HTK mel filterbank, 64 bins over 125-7500 Hz, DC bin zeroed
    (mel_features.py:114-189),
  - log(mel + 0.01) (vggish_params.py LOG_OFFSET),
  - 0.96 s / 96-frame examples with no overlap (vggish_input.py:60-71).

This is host-side preprocessing (like the PIL resizes of the vision
families): shapes depend on the waveform length, so it stays numpy and the
fixed-shape (B, 96, 64, 1) example batches go to the device. One deliberate
substitution: the reference resamples with ``resampy`` (vggish_input.py:50);
this build uses a polyphase Kaiser resampler (scipy.signal.resample_poly).
Both are windowed-sinc designs; outputs differ at the ~1e-3 level on real
audio, which only matters when the source is not already 16 kHz.

WAV reading uses the stdlib ``wave`` module (the reference uses soundfile,
vggish_input.py:91-94) and enforces the same 16-bit PCM / 32768.0 contract.
"""
from __future__ import annotations

import wave as wave_module
from fractions import Fraction
from typing import Tuple

import numpy as np

SAMPLE_RATE = 16000
STFT_WINDOW_LENGTH_SECONDS = 0.025
STFT_HOP_LENGTH_SECONDS = 0.010
NUM_MEL_BINS = 64
MEL_MIN_HZ = 125.0
MEL_MAX_HZ = 7500.0
LOG_OFFSET = 0.01
EXAMPLE_WINDOW_SECONDS = 0.96
EXAMPLE_HOP_SECONDS = 0.96

_MEL_BREAK_FREQUENCY_HERTZ = 700.0
_MEL_HIGH_FREQUENCY_Q = 1127.0


def frame(data: np.ndarray, window_length: int,
          hop_length: int) -> np.ndarray:
    """(num_samples, ...) -> (num_frames, window_length, ...) strided view;
    incomplete trailing frames are dropped (mel_features.py:21-45)."""
    num_samples = data.shape[0]
    num_frames = 1 + int(np.floor((num_samples - window_length) / hop_length))
    shape = (num_frames, window_length) + data.shape[1:]
    strides = (data.strides[0] * hop_length,) + data.strides
    return np.lib.stride_tricks.as_strided(data, shape=shape, strides=strides)


def periodic_hann(window_length: int) -> np.ndarray:
    """One full cycle of a period-N raised cosine (mel_features.py:48-68) —
    NOT np.hanning's symmetric period-(N-1) window."""
    return 0.5 - 0.5 * np.cos(
        2 * np.pi / window_length * np.arange(window_length))


def stft_magnitude(signal: np.ndarray, fft_length: int, hop_length: int,
                   window_length: int) -> np.ndarray:
    frames = frame(signal, window_length, hop_length)
    return np.abs(np.fft.rfft(frames * periodic_hann(window_length),
                              int(fft_length)))


def hertz_to_mel(frequencies_hertz) -> np.ndarray:
    """HTK mel scale (mel_features.py:100-112)."""
    return _MEL_HIGH_FREQUENCY_Q * np.log(
        1.0 + (frequencies_hertz / _MEL_BREAK_FREQUENCY_HERTZ))


def spectrogram_to_mel_matrix(num_mel_bins: int = 20,
                              num_spectrogram_bins: int = 129,
                              audio_sample_rate: float = 8000,
                              lower_edge_hertz: float = 125.0,
                              upper_edge_hertz: float = 3800.0) -> np.ndarray:
    """(num_spectrogram_bins, num_mel_bins) triangular-in-mel filterbank,
    DC row zeroed (mel_features.py:114-189)."""
    nyquist_hertz = audio_sample_rate / 2.0
    if lower_edge_hertz < 0.0:
        raise ValueError(f"lower_edge_hertz {lower_edge_hertz} must be >= 0")
    if lower_edge_hertz >= upper_edge_hertz:
        raise ValueError(f"lower_edge_hertz {lower_edge_hertz} >= "
                         f"upper_edge_hertz {upper_edge_hertz}")
    if upper_edge_hertz > nyquist_hertz:
        raise ValueError(f"upper_edge_hertz {upper_edge_hertz} is greater "
                         f"than Nyquist {nyquist_hertz}")
    spectrogram_bins_mel = hertz_to_mel(
        np.linspace(0.0, nyquist_hertz, num_spectrogram_bins))
    band_edges_mel = np.linspace(hertz_to_mel(lower_edge_hertz),
                                 hertz_to_mel(upper_edge_hertz),
                                 num_mel_bins + 2)
    weights = np.empty((num_spectrogram_bins, num_mel_bins))
    for i in range(num_mel_bins):
        lower, center, upper = band_edges_mel[i:i + 3]
        lower_slope = (spectrogram_bins_mel - lower) / (center - lower)
        upper_slope = (upper - spectrogram_bins_mel) / (upper - center)
        weights[:, i] = np.maximum(0.0, np.minimum(lower_slope, upper_slope))
    weights[0, :] = 0.0
    return weights


def log_mel_spectrogram(data: np.ndarray,
                        audio_sample_rate: float = 8000,
                        log_offset: float = 0.0,
                        window_length_secs: float = 0.025,
                        hop_length_secs: float = 0.010,
                        **kwargs) -> np.ndarray:
    """(num_frames, num_mel_bins) log-mel magnitudes
    (mel_features.py:192-232)."""
    window_length_samples = int(round(audio_sample_rate * window_length_secs))
    hop_length_samples = int(round(audio_sample_rate * hop_length_secs))
    fft_length = 2 ** int(
        np.ceil(np.log(window_length_samples) / np.log(2.0)))
    spectrogram = stft_magnitude(data, fft_length, hop_length_samples,
                                 window_length_samples)
    mel = np.dot(spectrogram, spectrogram_to_mel_matrix(
        num_spectrogram_bins=spectrogram.shape[1],
        audio_sample_rate=audio_sample_rate, **kwargs))
    return np.log(mel + log_offset)


def resample(data: np.ndarray, src_rate: int, dst_rate: int) -> np.ndarray:
    """Polyphase Kaiser resampling (substitutes the reference's resampy
    call, vggish_input.py:49-50 — see module docstring)."""
    from scipy.signal import resample_poly
    ratio = Fraction(int(dst_rate), int(src_rate))
    return resample_poly(data, ratio.numerator, ratio.denominator)


def waveform_to_examples(data: np.ndarray, sample_rate: int) -> np.ndarray:
    """Waveform -> (num_examples, 96, 64, 1) float32 NHWC log-mel patches
    (vggish_input.py:26-77; the reference emits NCHW (N, 1, 96, 64) — the
    flattening order inside the VGG is NHWC-compatible either way)."""
    if data.ndim > 1:
        data = np.mean(data, axis=1)  # mono mix
    if sample_rate != SAMPLE_RATE:
        data = resample(data, sample_rate, SAMPLE_RATE)
    log_mel = log_mel_spectrogram(
        data, audio_sample_rate=SAMPLE_RATE, log_offset=LOG_OFFSET,
        window_length_secs=STFT_WINDOW_LENGTH_SECONDS,
        hop_length_secs=STFT_HOP_LENGTH_SECONDS,
        num_mel_bins=NUM_MEL_BINS, lower_edge_hertz=MEL_MIN_HZ,
        upper_edge_hertz=MEL_MAX_HZ)
    features_sample_rate = 1.0 / STFT_HOP_LENGTH_SECONDS
    window = int(round(EXAMPLE_WINDOW_SECONDS * features_sample_rate))
    hop = int(round(EXAMPLE_HOP_SECONDS * features_sample_rate))
    examples = frame(log_mel, window_length=window, hop_length=hop)
    return np.ascontiguousarray(examples, dtype=np.float32)[..., None]


def read_wav(path: str) -> Tuple[np.ndarray, int]:
    """16-bit PCM WAV -> (samples in [-1, 1] float64 (n,) or (n, ch), rate).

    Same contract as the reference's ``sf.read(dtype='int16') / 32768.0``
    (vggish_input.py:91-94); non-16-bit files are rejected like the
    reference's dtype assert.
    """
    with wave_module.open(path, "rb") as w:
        n_channels = w.getnchannels()
        width = w.getsampwidth()
        rate = w.getframerate()
        raw = w.readframes(w.getnframes())
    if width != 2:
        raise ValueError(f"Bad sample type: {8 * width}-bit PCM in {path}; "
                         "expected 16-bit (vggish_input.py:92-93)")
    data = np.frombuffer(raw, dtype="<i2").astype(np.float64) / 32768.0
    if n_channels > 1:
        data = data.reshape(-1, n_channels)
    return data, rate


# --- device (jnp) frontend -------------------------------------------------
#
# The numpy pipeline above is the bit-parity twin of the reference's host DSP
# (mel_features.py). The device frontend below fuses the same math — framing,
# periodic-Hann STFT, HTK mel filterbank matmul, log — into the jitted VGG
# forward, so the (weak) extraction host only mono-mixes, resamples, and
# slices the waveform. Per-example chunking reproduces whole-waveform
# processing exactly: example i covers log-mel frames [96i, 96i+96), whose
# STFTs read samples [96i*160, 96i*160 + 95*160 + 400) — 15600 samples with
# hop 15360 (SURVEY §7 step 5: "jnp mel frontend").

EXAMPLE_CHUNK_SAMPLES = 95 * 160 + 400  # 15600
EXAMPLE_HOP_SAMPLES = 96 * 160          # 15360


def chunk_waveform(data: np.ndarray, sample_rate: int) -> np.ndarray:
    """Mono-mix + resample to 16 kHz + slice into per-example waveform
    chunks: -> (num_examples, 15600) float32. Host-side prep for
    :func:`logmel_examples_jnp`; for audio holding at least one complete
    example this yields the same example count as
    :func:`waveform_to_examples` (the nested STFT/example frame counts
    reduce to the same floor expression); sub-example audio yields (0, ...)
    rather than the host path's error on sub-window input."""
    if data.ndim > 1:
        data = np.mean(data, axis=1)
    if sample_rate != SAMPLE_RATE:
        data = resample(data, sample_rate, SAMPLE_RATE)
    data = np.asarray(data, dtype=np.float32)
    if len(data) < EXAMPLE_CHUNK_SAMPLES:
        return np.zeros((0, EXAMPLE_CHUNK_SAMPLES), dtype=np.float32)
    # zero-copy strided view; one contiguous copy for the device transfer
    return np.ascontiguousarray(
        frame(data, EXAMPLE_CHUNK_SAMPLES, EXAMPLE_HOP_SAMPLES))


def logmel_examples_jnp(chunks):
    """(B, 15600) float32 waveform chunks -> (B, 96, 64, 1) log-mel examples,
    jittable. Same constants as the numpy path (16 kHz, 25 ms/10 ms STFT,
    periodic Hann, 512-point rFFT, 64 HTK mel bins 125-7500 Hz, log+0.01)."""
    import jax.numpy as jnp
    win = int(round(SAMPLE_RATE * STFT_WINDOW_LENGTH_SECONDS))   # 400
    hop = int(round(SAMPLE_RATE * STFT_HOP_LENGTH_SECONDS))      # 160
    fft_length = 512
    starts = jnp.arange(96) * hop
    idx = starts[:, None] + jnp.arange(win)[None, :]             # (96, 400)
    frames = chunks[:, idx]                                      # (B, 96, 400)
    windowed = frames * jnp.asarray(periodic_hann(win), jnp.float32)
    mag = jnp.abs(jnp.fft.rfft(windowed, fft_length))            # (B, 96, 257)
    mel_mat = jnp.asarray(spectrogram_to_mel_matrix(
        num_mel_bins=NUM_MEL_BINS, num_spectrogram_bins=fft_length // 2 + 1,
        audio_sample_rate=SAMPLE_RATE, lower_edge_hertz=MEL_MIN_HZ,
        upper_edge_hertz=MEL_MAX_HZ), jnp.float32)
    mel = mag @ mel_mat                                          # (B, 96, 64)
    return jnp.log(mel + LOG_OFFSET)[..., None]
