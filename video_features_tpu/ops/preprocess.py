"""Host-side frame preprocessing (resize / crop), numpy in, numpy out.

The reference preprocesses with torchvision/PIL on CPU per frame
(reference models/resnet/extract_resnet.py:27-33, models/transforms.py). The
parity-critical part is interpolation: PIL resizes are *antialiased*, while
naive bilinear (torch F.interpolate / jax.image without antialias) is not.
We therefore keep resizes on the host using PIL exactly where the reference
does, and do the arithmetic-only steps (scale, normalize) inside the jitted
device function where XLA fuses them into the first conv.

Implements equivalents of:
  - torchvision Resize(size) smaller-edge semantics + CenterCrop
    (reference models/resnet/extract_resnet.py:27-33)
  - `resize`/`ResizeImproved` smaller/larger-edge switch
    (reference models/transforms.py:191-242)
  - tensor-video resize via non-antialiased bilinear for the I3D path
    (reference models/transforms.py:76-96 uses F.interpolate)
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np
from PIL import Image

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], dtype=np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], dtype=np.float32)

_PIL_MODES = {
    "bilinear": Image.BILINEAR,
    "bicubic": Image.BICUBIC,
    "nearest": Image.NEAREST,
}


def resize_edge_size(w: int, h: int, size: int,
                     to_smaller_edge: bool = True) -> Tuple[int, int]:
    """(out_w, out_h) matching PIL aspect-preserving resize.

    Same rounding as reference models/transforms.py:218-229: the non-matched
    edge is ``int(size * long/short)`` (truncation, not round).
    """
    if (w <= h and w == size) or (h <= w and h == size):
        return w, h
    if (w < h) == to_smaller_edge:
        return size, int(size * h / w)
    return int(size * w / h), size


def pil_resize(img: np.ndarray, size: Union[int, Tuple[int, int]],
               to_smaller_edge: bool = True,
               interpolation: str = "bilinear") -> np.ndarray:
    """Antialiased PIL resize of an HWC uint8 (or float-convertible) image.

    ``size`` int: aspect-preserving to the smaller (or larger) edge, as in
    reference models/transforms.py:191-231. ``size`` (h, w): exact.
    """
    pil = Image.fromarray(img)
    mode = _PIL_MODES[interpolation]
    if isinstance(size, int):
        w, h = pil.size
        ow, oh = resize_edge_size(w, h, size, to_smaller_edge)
        if (ow, oh) == (w, h):
            return np.asarray(pil)
        return np.asarray(pil.resize((ow, oh), mode))
    return np.asarray(pil.resize((size[1], size[0]), mode))


def center_crop_offsets(h: int, w: int, th: int, tw: int) -> Tuple[int, int]:
    """torchvision CenterCrop's window origin: ``round((H - th) / 2)`` with
    banker's rounding via int(round(.)) (reference extract_resnet.py:30).
    Shared by the host path (:func:`center_crop`) and the device-resize path
    so both crop identically."""
    return int(round((h - th) / 2.0)), int(round((w - tw) / 2.0))


def center_crop(img: np.ndarray, crop: Union[int, Tuple[int, int]]) -> np.ndarray:
    """Center crop of an HWC image (torchvision rounding)."""
    th, tw = (crop, crop) if isinstance(crop, int) else crop
    i, j = center_crop_offsets(img.shape[0], img.shape[1], th, tw)
    return img[i:i + th, j:j + tw]


def quantize_u8(x: np.ndarray) -> np.ndarray:
    """[0, 1] float -> uint8 wire format (round-to-nearest, clipped).

    Quantization noise is <=1/510 per channel — below bfloat16 input
    rounding — so the bf16 production pipeline ships 1 byte/pixel/channel
    to the device instead of 4 (H2D bandwidth is the pipeline bottleneck).
    """
    return np.clip(np.round(x * 255.0), 0, 255).astype(np.uint8)


def tensor_center_crop(img: np.ndarray, crop_size: int) -> np.ndarray:
    """Floor-division center crop (reference models/transforms.py:132-143).

    Used by the I3D path; differs from :func:`center_crop` by using ``//``
    instead of round, which shifts the window by one pixel on odd differences.
    """
    h, w = img.shape[:2]
    i = (h - crop_size) // 2
    j = (w - crop_size) // 2
    return img[i:i + crop_size, j:j + crop_size]


def bilinear_resize_no_antialias(img: np.ndarray,
                                 out_hw: Tuple[int, int]) -> np.ndarray:
    """Non-antialiased bilinear resize (align_corners=False).

    Matches torch ``F.interpolate(mode='bilinear', align_corners=False)`` as
    used for video tensors in reference models/transforms.py:76-96. cv2's
    INTER_LINEAR implements the same half-pixel sampling without antialias.
    """
    import cv2
    h, w = out_hw
    return cv2.resize(img.astype(np.float32), (w, h),
                      interpolation=cv2.INTER_LINEAR)


def _bilinear_axis_weights(n_out: int, n_in: int, scale: float):
    """Half-pixel bilinear gather indices/weights for one axis."""
    src = (np.arange(n_out, dtype=np.float64) + 0.5) / scale - 0.5
    src = np.clip(src, 0.0, n_in - 1)
    lo = np.floor(src).astype(np.int64)
    hi = np.minimum(lo + 1, n_in - 1)
    w_hi = (src - lo).astype(np.float32)
    return lo, hi, w_hi


def bilinear_resize_by_scale(img: np.ndarray, scale: float) -> np.ndarray:
    """torch ``F.interpolate(scale_factor=s, recompute_scale_factor=False)``.

    The reference's int-size Resize (models/transforms.py:86-96) passes a
    *scale factor*, and torch then maps coordinates with that exact scale —
    NOT with out_size/in_size as cv2 does — so the two differ by a sub-pixel
    drift that grows toward the image edge. This implements torch's mapping
    exactly: out size floor(in*s), src = (dst+0.5)/s - 0.5, clamped, no
    antialias.
    """
    h, w = img.shape[:2]
    oh, ow = int(h * scale), int(w * scale)
    ylo, yhi, wy = _bilinear_axis_weights(oh, h, scale)
    xlo, xhi, wx = _bilinear_axis_weights(ow, w, scale)
    im = img.astype(np.float32)
    rows_lo, rows_hi = im[ylo], im[yhi]
    top = rows_lo[:, xlo] * (1 - wx)[None, :, None] + \
        rows_lo[:, xhi] * wx[None, :, None]
    bot = rows_hi[:, xlo] * (1 - wx)[None, :, None] + \
        rows_hi[:, xhi] * wx[None, :, None]
    return top * (1 - wy)[:, None, None] + bot * wy[:, None, None]


def pil_resize_matrix(in_size: int, out_size: int,
                      interpolation: str = "bilinear") -> np.ndarray:
    """(out_size, in_size) row-stochastic matrix of PIL's separable resample
    coefficients for one axis (Pillow Resample.c precompute_coeffs, float
    path): triangle filter for bilinear (support 1), Catmull-Rom a=-0.5 for
    bicubic (support 2), both with support scaled by the downscale factor —
    PIL's antialiasing. A full PIL resize is then ``R @ img @ C.T`` per
    channel, which :func:`device_resize` runs as two MXU matmuls on device.
    """
    if interpolation == "bilinear":
        support0 = 1.0

        def filt(x):
            return np.maximum(0.0, 1.0 - np.abs(x))
    elif interpolation == "bicubic":
        support0, a = 2.0, -0.5

        def filt(x):
            x = np.abs(x)
            return np.where(
                x < 1, ((a + 2) * x - (a + 3)) * x * x + 1,
                np.where(x < 2, (((x - 5) * x + 8) * x - 4) * a, 0.0))
    else:
        raise NotImplementedError(interpolation)
    scale = in_size / out_size
    filterscale = max(scale, 1.0)
    support = support0 * filterscale
    m = np.zeros((out_size, in_size), dtype=np.float32)
    for i in range(out_size):
        center = (i + 0.5) * scale
        xmin = max(int(center - support + 0.5), 0)
        xmax = min(int(center + support + 0.5), in_size)
        w = filt((np.arange(xmin, xmax) - center + 0.5) / filterscale)
        m[i, xmin:xmax] = w / w.sum()
    return m


def device_resize(batch_u8, rmat, cmat):
    """Jittable PIL-parity resize: (B, H, W, C) uint8 -> (B, Ho, Wo, C)
    float32 in [0, 255].

    Two matmuls against :func:`pil_resize_matrix` coefficients — horizontal
    first with round+clamp to the uint8 range between passes, exactly the
    two-pass uint8 storage PIL uses (bicubic overshoots otherwise). Within
    2 LSB of PIL output over random images (tests/test_io.py). This moves
    the host pipeline's dominant cost (~1.3 ms/frame of PIL filtering vs
    ~0.34 ms of cv2 decode) onto the MXU.
    """
    import jax.numpy as jnp
    x = batch_u8.astype(jnp.float32)
    x = jnp.einsum("ow,bhwc->bhoc", cmat, x)  # horizontal pass
    x = jnp.clip(jnp.round(x), 0.0, 255.0)    # PIL's inter-pass uint8 store
    x = jnp.einsum("oh,bhwc->bowc", rmat, x)  # vertical pass
    return jnp.clip(jnp.round(x), 0.0, 255.0)


def make_device_resizer(in_h: int, in_w: int, oh: int, ow: int,
                        interpolation: str = "bilinear"):
    """Returns a jittable fn resizing (..., in_h, in_w, C) uint8 frames to
    (..., oh, ow, C) uint8 via :func:`device_resize` (any leading dims are
    flattened for the matmuls and restored). Output is uint8 — device_resize
    already rounds and clamps, so the cast is exact and matches PIL's uint8
    output byte for byte (within its 2-LSB envelope) while quartering the
    resident size of resized intermediates."""
    import jax.numpy as jnp
    rmat = pil_resize_matrix(in_h, oh, interpolation)
    cmat = pil_resize_matrix(in_w, ow, interpolation)

    def resize_frames(x_u8):
        lead, tail = x_u8.shape[:-3], x_u8.shape[-3:]
        out = device_resize(x_u8.reshape((-1,) + tail), rmat, cmat)
        return out.astype(jnp.uint8).reshape(lead + (oh, ow) + tail[-1:])

    return resize_frames
