"""Picklable host-transform callables, one per family shape.

The extractors' host transforms used to be closures over ``self`` — fine
in-process, but ``video_decode=process`` (utils/io.py ProcessVideoSource)
ships the transform to a spawned decode worker via pickle, and a closure
cannot cross that boundary. These classes are the same functions as plain
data + ``__call__``; the extractors now build instances of them, so the
in-process and process-decode paths run literally identical code.

Deliberately light imports (numpy / PIL / cv2 through ops.preprocess and
ops.colorspace): unpickling in a decode worker must not drag jax/flax in —
the worker only decodes and transforms, and on hosts whose sitecustomize
injects an accelerator platform into every process, an accidental jax op
in a child could claim the single TPU chip out from under the parent.
"""
from __future__ import annotations

import numpy as np

from . import colorspace
from . import preprocess as pp


def encode_wire(x01: np.ndarray, ingest: str) -> np.ndarray:
    """[0, 1] float HWC frame -> wire format (clip-stack families' tail)."""
    if ingest == "float32":
        return x01
    u8 = pp.quantize_u8(x01)
    if ingest == "uint8":
        return u8
    return colorspace.rgb_to_yuv420(u8)


def encode_wire_u8(u8: np.ndarray, ingest: str) -> np.ndarray:
    """uint8 HWC frame -> wire format (frame-wise families' tail)."""
    if ingest == "uint8":
        return u8
    return colorspace.rgb_to_yuv420(u8)


class R21DTransform:
    """Decoder-native BGR frame -> 112px wire clip frame (extractors/r21d).

    float/resize/crop are channel-independent, so the RGB reorder happens
    on the 112px crop — 6x fewer pixels than a full-resolution cvtColor,
    bit-identical result (frame_channel_order='bgr' contract)."""

    def __init__(self, ingest: str):
        self.ingest = ingest

    def __call__(self, bgr: np.ndarray) -> np.ndarray:
        x = bgr.astype(np.float32) / 255.0
        x = pp.bilinear_resize_no_antialias(x, (128, 171))
        x = np.ascontiguousarray(pp.center_crop(x, 112)[:, :, ::-1])
        return encode_wire(x, self.ingest)


class S3DTransform:
    """Decoder-native BGR frame -> 224px wire clip frame (extractors/s3d);
    same deferred-reorder contract as R21DTransform."""

    def __init__(self, ingest: str):
        self.ingest = ingest

    def __call__(self, bgr: np.ndarray) -> np.ndarray:
        x = bgr.astype(np.float32) / 255.0
        scale = 224.0 / min(x.shape[0], x.shape[1])
        x = pp.bilinear_resize_by_scale(x, scale)
        x = np.ascontiguousarray(pp.center_crop(x, 224)[:, :, ::-1])
        return encode_wire(x, self.ingest)


class ResizeCropTransform:
    """RGB frame -> PIL resize + center crop -> uint8 wire (resnet: 256->
    224 bilinear; clip: R->R bicubic)."""

    def __init__(self, size: int, crop: int, interpolation: str,
                 ingest: str):
        self.size = size
        self.crop = crop
        self.interpolation = interpolation
        self.ingest = ingest

    def __call__(self, rgb: np.ndarray) -> np.ndarray:
        out = pp.pil_resize(rgb, self.size,
                            interpolation=self.interpolation)
        return encode_wire_u8(pp.center_crop(out, self.crop), self.ingest)


class MinSideResize:
    """RGB frame -> smaller-edge PIL bilinear resize, kept uint8 (the i3d
    host path; reference extract_i3d.py:41-46)."""

    def __init__(self, min_side: int):
        self.min_side = min_side

    def __call__(self, rgb: np.ndarray) -> np.ndarray:
        return pp.pil_resize(rgb, self.min_side)
