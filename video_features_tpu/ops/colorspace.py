"""YUV 4:2:0 wire format: halve host->device bytes for the bf16 pipeline.

The end-to-end clip pipeline is H2D-bandwidth-bound on TPU hosts (the
backbone forward is 50x faster than the transfer of its input batch), so the
production ingest mode ships frames to the device as packed I420 planes —
1.5 bytes/pixel instead of 3 (uint8 RGB) or 12 (float32 RGB) — and performs
the colorspace conversion on device, fused by XLA into the normalization and
the first conv.

This mirrors what video codecs store natively: every mp4 the reference
decodes (reference utils/io.py:39-176 via cv2) is YUV 4:2:0 internally, and
cv2 upsamples to BGR on the host only to have the extractor quantize it
straight back down. Wire format:

  packed frame = [ Y (H*W) | U (H/2*W/2) | V (H/2*W/2) ]  uint8, C-order

Conversion matches cv2's I420 path bit-closely (max |diff| < 1 vs
``cv2.cvtColor(..., COLOR_YUV2RGB_I420)``): studio-swing BT.601 with
top-left 2x2 chroma subsampling on encode and nearest-neighbor chroma
upsampling on decode (verified empirically against cv2 5.0).
"""
from __future__ import annotations

import numpy as np

# studio-swing BT.601 (cv2 I420): Y in [16, 235], chroma in [16, 240]
_Y_SCALE = 1.164383
_V_TO_R = 1.596027
_U_TO_G = -0.391762
_V_TO_G = -0.812968
_U_TO_B = 2.017232


def packed_size(h: int, w: int) -> int:
    """Bytes per packed I420 frame; h and w must be even."""
    if h % 2 or w % 2:
        raise ValueError(f"I420 needs even dims, got {h}x{w}")
    return h * w * 3 // 2


def rgb_to_yuv420(frame_u8: np.ndarray) -> np.ndarray:
    """uint8 RGB (H, W, 3) -> packed I420 (H*W*3/2,) uint8, via cv2."""
    import cv2
    h, w = frame_u8.shape[:2]
    packed_size(h, w)  # validates evenness
    return cv2.cvtColor(frame_u8, cv2.COLOR_RGB2YUV_I420).reshape(-1)


def bgr_to_yuv420_frame(frame_bgr: np.ndarray) -> np.ndarray:
    """Decoder-native BGR uint8 (H, W, 3) -> cv2-layout packed I420
    (H*3/2, W) uint8 — the raw-YUV wire frame of ``ingest=yuv420`` under
    ``resize=device``.

    This is the ONE per-frame host conversion the raw-ingest decode path
    pays, replacing (not adding to) the BGR->RGB reorder: its output is
    1.5 bytes/pixel instead of 3, so every downstream copy — fan-out
    queue, prefetch queue, np.stack, and above all the H2D transfer —
    moves half the bytes of a raw uint8 RGB frame and an eighth of the
    float32 wire the reference shipped."""
    import cv2
    h, w = frame_bgr.shape[:2]
    packed_size(h, w)  # validates evenness
    return cv2.cvtColor(frame_bgr, cv2.COLOR_BGR2YUV_I420)


def yuv420_frame_to_rgb_u8(packed_2d, h: int, w: int):
    """cv2-layout packed I420 (..., H*3/2, W) uint8 -> (..., H, W, 3)
    uint8 RGB on device. Jittable.

    Rounds the BT.601 float conversion back onto the uint8 lattice so the
    downstream device resize (ops/preprocess.py device_resize) sees an
    integer-valued image exactly like the host decoder would have handed
    it — cv2's own BGR output differs from this reconstruction by < 1
    intensity level (see module docstring)."""
    import jax.numpy as jnp
    lead = packed_2d.shape[:-2]
    flat = packed_2d.reshape(*lead, h * w * 3 // 2)
    rgb = yuv420_packed_to_rgb(flat, h, w)
    return jnp.round(rgb).astype(jnp.uint8)


def yuv420_packed_to_rgb(packed, h: int, w: int):
    """Packed I420 uint8 (..., H*W*3/2) -> float32 RGB (..., H, W, 3) in
    [0, 255]. Jittable; shapes are static. Matches cv2 YUV2RGB_I420
    (nearest chroma upsample) to < 1 intensity level."""
    import jax.numpy as jnp
    n_y = h * w
    n_c = (h // 2) * (w // 2)
    lead = packed.shape[:-1]
    y = packed[..., :n_y].reshape(*lead, h, w).astype(jnp.float32)
    u = packed[..., n_y:n_y + n_c].reshape(*lead, h // 2, w // 2)
    v = packed[..., n_y + n_c:].reshape(*lead, h // 2, w // 2)
    # nearest-neighbor chroma upsample to full res
    u = jnp.repeat(jnp.repeat(u, 2, axis=-2), 2, axis=-1).astype(jnp.float32)
    v = jnp.repeat(jnp.repeat(v, 2, axis=-2), 2, axis=-1).astype(jnp.float32)
    yc = _Y_SCALE * (y - 16.0)
    u = u - 128.0
    v = v - 128.0
    rgb = jnp.stack([yc + _V_TO_R * v,
                     yc + _U_TO_G * u + _V_TO_G * v,
                     yc + _U_TO_B * u], axis=-1)
    return jnp.clip(rgb, 0.0, 255.0)
