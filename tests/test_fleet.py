"""Work-stealing fleet queue (parallel/queue.py, ISSUE 8): claim
atomicity under concurrent claimants, lease expiry + stealing, heartbeat
-driven reclamation, quarantine-after-N-reclaims, exactly-once completion
markers, and the telemetry_report fleet/straggler rendering.

Everything here is filesystem-state unit testing with an injected clock —
no sleeps, no subprocesses. The end-to-end twins are
scripts/check_fleet_smoke.py (real CLI workers) and tests/test_chaos.py
(worker kill + lease reclamation); bench.py bench_fleet measures the
makespan ratio the queue exists to win.
"""
import json
import os
import sys
import threading
import time
from pathlib import Path

import pytest

from video_features_tpu.parallel import queue as fq
from video_features_tpu.telemetry.jsonl import write_json_atomic

pytestmark = pytest.mark.quick


class Clock:
    """Injectable time source: tests advance leases, never sleep."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def _hb(root, host, *, now, age_s=0.0, interval_s=1.0, final=False):
    write_json_atomic(
        os.path.join(root, f"_heartbeat_{host}.json"),
        {"host_id": host, "time": now - age_s, "interval_s": interval_s,
         "final": final})


def _wq(root, host, clk, **kw):
    kw.setdefault("lease_s", 5.0)
    return fq.WorkQueue(str(root), host_id=host, run_id=f"run-{host}",
                        clock=clk, **kw)


def test_seed_idempotent_and_concurrent(tmp_path):
    clk = Clock()
    a, b = _wq(tmp_path, "A", clk), _wq(tmp_path, "B", clk)
    videos = [f"/data/v{i:02d}.mp4" for i in range(10)]
    assert a.seed(videos) == 10
    assert b.seed(videos) == 0  # every item already pending
    assert a.counts() == {"pending": 10, "claimed": 0, "done": 0,
                          "quarantined": 0}


def test_claim_atomicity_concurrent_claimants(tmp_path):
    """4 hosts x 2 threads hammer claim_next on one shared queue: no item
    claimed twice, no item lost — the os.rename claim is the lock."""
    clk = Clock()
    videos = [f"/data/v{i:03d}.mp4" for i in range(40)]
    hosts = [_wq(tmp_path, f"h{i}", clk) for i in range(4)]
    hosts[0].seed(videos)
    claimed, lock = [], threading.Lock()

    def worker(q):
        while True:
            rec = q.claim_next()
            if rec is None:
                return
            with lock:
                claimed.append(rec["video"])

    threads = [threading.Thread(target=worker, args=(q,))
               for q in hosts for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(claimed) == sorted(videos)  # exactly once each
    assert len(set(claimed)) == len(videos)
    c = hosts[0].counts()
    assert c["pending"] == 0 and c["claimed"] == len(videos)
    assert sum(q._tallies["claimed"] for q in hosts) == len(videos)


def test_lease_expiry_is_stolen_and_tallied(tmp_path):
    clk = Clock()
    a, b = _wq(tmp_path, "A", clk), _wq(tmp_path, "B", clk)
    # a live heartbeat with a long interval: only the LEASE decides here
    _hb(tmp_path, "A", now=clk.t, interval_s=60.0)
    a.seed(["/data/slow.mp4"])
    rec = a.claim_next()
    assert rec["deadline"] == pytest.approx(clk.t + 5.0)
    assert b.reclaim_expired() == 0  # lease still live
    clk.t += 6.0  # A stalled past its lease without renewing
    assert b.reclaim_expired() == 1
    stolen = b.claim_next()
    assert stolen["video"] == "/data/slow.mp4"
    assert stolen["reclaims"] == 1 and stolen["last_owner"] == "A"
    assert b._tallies["stolen"] == 1 and b._tallies["reclaimed"] == 1


def test_live_renewal_prevents_stealing(tmp_path):
    clk = Clock()
    a, b = _wq(tmp_path, "A", clk), _wq(tmp_path, "B", clk)
    _hb(tmp_path, "A", now=clk.t)
    a.seed(["/data/v.mp4"])
    a.claim_next()
    for _ in range(4):  # heartbeat ticks keep pushing the deadline
        clk.t += 3.0
        _hb(tmp_path, "A", now=clk.t)
        a.renew_leases()
        assert b.reclaim_expired() == 0
    assert a.counts()["claimed"] == 1


def test_stale_heartbeat_releases_unexpired_lease(tmp_path):
    """A SIGKILLed host stops renewing AND beating: siblings must not
    wait out a long lease when the heartbeat already proves death."""
    clk = Clock()
    a = _wq(tmp_path, "A", clk, lease_s=10_000.0)
    b = _wq(tmp_path, "B", clk, lease_s=10_000.0)
    a.seed(["/data/v.mp4"])
    a.claim_next()
    _hb(tmp_path, "A", now=clk.t, interval_s=1.0)
    assert b.reclaim_expired() == 0  # fresh heartbeat: A is alive
    clk.t += 10.0  # > STALL_INTERVALS * interval_s, lease NOT expired
    assert b.reclaim_expired() == 1
    assert b.claim_next()["last_owner"] == "A"


def test_final_heartbeat_releases_claims(tmp_path):
    clk = Clock()
    a = _wq(tmp_path, "A", clk, lease_s=10_000.0)
    b = _wq(tmp_path, "B", clk, lease_s=10_000.0)
    a.seed(["/data/v.mp4"])
    a.claim_next()
    _hb(tmp_path, "A", now=clk.t, final=True)  # clean exit, claim leaked
    assert b.reclaim_expired() == 1


def test_quarantine_after_max_reclaims(tmp_path):
    """An item that keeps outliving its workers is pathological: after
    max_reclaims lease reclaims it routes to quarantined/ + the failure
    journal as POISON instead of being re-dispatched forever."""
    class Journal:
        records = []

        def record(self, video, category, attempts, error, elapsed_s):
            self.records.append(
                dict(video=video, category=category, attempts=attempts,
                     error=error))

    clk = Clock()
    j = Journal()
    a = _wq(tmp_path, "A", clk, max_reclaims=2, journal=j)
    b = _wq(tmp_path, "B", clk, max_reclaims=2, journal=j)
    a.seed(["/data/poison.mp4"])
    # reclaim 1 and 2 re-dispatch; reclaim 3 (> max_reclaims=2) quarantines
    for stealer, victim in ((b, a), (a, b), (b, a)):
        victim.claim_next()
        clk.t += 6.0
        stealer.reclaim_expired()
    c = a.counts()
    assert c == {"pending": 0, "claimed": 0, "done": 0, "quarantined": 1}
    q = json.loads(
        (tmp_path / "_queue" / "quarantined" / os.listdir(
            tmp_path / "_queue" / "quarantined")[0]).read_text())
    assert q["reclaims"] == 3
    assert len(j.records) == 1
    assert j.records[0]["category"] == "POISON"
    assert j.records[0]["video"] == "/data/poison.mp4"
    assert "fleet_max_reclaims" in j.records[0]["error"]


def test_complete_first_writer_wins(tmp_path):
    """Reclaim race: two hosts legitimately end up extracting the same
    item (idempotent sinks make that safe); exactly one done marker
    exists and the loser books lease_lost, not done."""
    clk = Clock()
    a, b = _wq(tmp_path, "A", clk), _wq(tmp_path, "B", clk)
    a.seed(["/data/v.mp4"])
    rec_a = a.claim_next()
    clk.t += 6.0
    b.reclaim_expired()
    rec_b = b.claim_next()
    assert b.complete(rec_b, "done") is True
    assert a.complete(rec_a, "done") is False  # marker already exists
    done = list((tmp_path / "_queue" / "done").glob("*.json"))
    assert len(done) == 1
    assert json.loads(done[0].read_text())["by"] == "B"
    assert a._tallies["lease_lost"] == 1 and b._tallies["done"] == 1
    assert a.all_done() and b.all_done()


def test_done_item_never_reclaimed_or_reseeded(tmp_path):
    clk = Clock()
    a, b = _wq(tmp_path, "A", clk), _wq(tmp_path, "B", clk)
    a.seed(["/data/v.mp4"])
    a.complete(a.claim_next(), "done")
    assert b.seed(["/data/v.mp4"]) == 0  # done marker is ground truth
    # a raced re-seed (torn reclaimer) is discarded at claim time
    iid = fq.item_id("/data/v.mp4")
    (tmp_path / "_queue" / "pending" / f"{iid}.json").write_text(
        json.dumps({"schema": fq.ITEM_SCHEMA, "id": iid,
                    "video": "/data/v.mp4", "reclaims": 0}))
    assert b.claim_next() is None
    assert b._tallies["duplicate_discarded"] == 1
    assert b.all_done()


def test_release_returns_item_unbumped(tmp_path):
    clk = Clock()
    a, b = _wq(tmp_path, "A", clk), _wq(tmp_path, "B", clk)
    a.seed(["/data/v.mp4"])
    rec = a.claim_next()
    a.release(rec)  # graceful hand-back (SIGTERM drain): not a pathology
    assert a.counts()["pending"] == 1
    again = b.claim_next()
    assert again["reclaims"] == 0
    assert b._tallies["stolen"] == 0  # released, not stolen


def test_staging_orphan_recovered(tmp_path):
    """A stealer that died between the staging rename and the pending
    write must not lose the item: old staging entries are swept back."""
    clk = Clock()
    a = _wq(tmp_path, "A", clk, lease_s=5.0)
    staging = tmp_path / "_queue" / ".staging" / "dead.it-1234.json"
    staging.write_text(json.dumps(
        {"schema": fq.ITEM_SCHEMA, "id": "it-1234",
         "video": "/data/v.mp4", "reclaims": 1}))
    os.utime(staging, (clk.t - 30.0, clk.t - 30.0))  # > 4 lease periods
    assert a.reclaim_expired() == 1
    rec = a.claim_next()
    assert rec["id"] == "it-1234" and rec["reclaims"] == 1


def test_staging_retention_config_replaces_lease_heuristic(tmp_path):
    """gc_staging_retention_s governs orphan recovery when set: a long
    retention holds an entry the old 4-lease heuristic would already
    have swept; once the (fake) clock passes it, the sweep recovers."""
    clk = Clock()
    a = _wq(tmp_path, "A", clk, lease_s=5.0, staging_retention_s=100.0)
    staging = tmp_path / "_queue" / ".staging" / "dead.it-7.json"
    staging.write_text(json.dumps(
        {"schema": fq.ITEM_SCHEMA, "id": "it-7",
         "video": "/data/v.mp4", "reclaims": 0}))
    os.utime(staging, (clk.t - 30.0, clk.t - 30.0))  # > 4 leases (20s)
    assert a.reclaim_expired() == 0  # held: configured retention wins
    clk.t += 80.0                    # age 110s > retention 100s
    assert a.reclaim_expired() == 1
    assert a.claim_next()["id"] == "it-7"
    with pytest.raises(ValueError):
        _wq(tmp_path, "B", clk, staging_retention_s=0.0)


def test_drain_exactly_once_across_hosts(tmp_path):
    # real wall clock here: drain idle-waits on a real threading.Event
    videos = [f"/data/v{i:02d}.mp4" for i in range(12)]
    hosts = [fq.WorkQueue(str(tmp_path), host_id=f"h{i}", lease_s=60.0)
             for i in range(3)]
    for i in range(3):
        _hb(tmp_path, f"h{i}", now=time.time())
    for h in hosts:
        h.seed(videos)
    ran, lock = [], threading.Lock()

    def run_fn(video):
        with lock:
            ran.append(video)
        return "done"

    threads = [threading.Thread(
        target=lambda h=h: h.drain(run_fn, workers=2, poll_s=0.02))
        for h in hosts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(ran) == sorted(videos)  # every video exactly once
    assert hosts[0].all_done()
    done = list((tmp_path / "_queue" / "done").glob("*.json"))
    assert len(done) == len(videos)
    sections = [h.heartbeat_section() for h in hosts]
    assert sum(s["claimed"] for s in sections) == len(videos)
    assert all(s["mode"] == "queue" for s in sections)


def test_heartbeat_section_renews_leases(tmp_path):
    clk = Clock()
    a = _wq(tmp_path, "A", clk)
    a.seed(["/data/v.mp4"])
    rec = a.claim_next()
    first_deadline = rec["deadline"]
    clk.t += 3.0
    section = a.heartbeat_section()  # the heartbeat tick IS the renewal
    assert section["active_claims"] == 1
    assert section["oldest_active_claim_age_s"] == pytest.approx(3.0)
    stamped = json.loads(Path(a._claim_path(rec["id"])).read_text())
    assert stamped["deadline"] == pytest.approx(first_deadline + 3.0)


def test_canary_founding_member_passes(tmp_path):
    clk = Clock()
    a = _wq(tmp_path, "A", clk)
    ok, lines = a.canary_gate(lambda v, d: ("done", 0.1))
    assert ok and "founding member" in lines[0]
    assert a.heartbeat_section()["canary"] == "founding"


def test_telemetry_report_fleet_line_and_straggler(tmp_path):
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                           / "scripts"))
    import telemetry_report
    now = time.time()
    fleet_a = {"mode": "queue", "active_claims": 1, "claimed": 5,
               "done": 4, "stolen": 1, "reclaimed": 1, "requeued": 1,
               "oldest_active_claim_age_s": 42.0,
               "queue": {"pending": 0, "claimed": 1, "done": 10},
               "canary": "off"}
    fleet_b = dict(fleet_a, active_claims=0, claimed=6, done=6, stolen=0,
                   oldest_active_claim_age_s=0.0)
    for host, fl in (("hostA", fleet_a), ("hostB", fleet_b)):
        write_json_atomic(
            tmp_path / f"_heartbeat_{host}.json",
            {"host_id": host, "time": now, "interval_s": 30.0,
             "final": False, "videos_done": fl["done"], "fleet": fl})
    paths = [str(p) for p in tmp_path.glob("_heartbeat_*.json")]
    out = "\n".join(telemetry_report.render_heartbeats(paths, now))
    assert "fleet: claimed=5 done=4 stolen=1" in out
    assert "STRAGGLER" in out
    a_line = next(l for l in out.splitlines() if "claimed=5" in l)
    b_line = next(l for l in out.splitlines() if "claimed=6" in l)
    assert "STRAGGLER" in a_line and "STRAGGLER" not in b_line


# ---------------------------------------------------------------------------
# Canary gating: a joining host re-extracts a slice of done work and must
# pass compare_runs digest bands + bench_history timing bands first.
# ---------------------------------------------------------------------------

def _health_rec(video, *, mean=0.5, sig="sigA"):
    return {"schema": "vft.feature_health/1", "video": str(video),
            "feature_type": "resnet", "key": "resnet",
            "shape": [4, 512], "dtype": "float32", "elems": 2048,
            "nan": 0, "inf": 0, "min": 0.0, "max": 1.0, "mean": mean,
            "std": 0.1, "l2": 10.0, "sig": sig, "time": 1.0}


def test_canary_gate_digest_and_timing_bands(tmp_path):
    from video_features_tpu.telemetry.jsonl import append_jsonl
    clk = Clock()
    a = _wq(tmp_path, "A", clk)
    vids = []
    for i in range(2):
        v = tmp_path / f"v{i}.mp4"
        v.write_bytes(b"x")  # canary samples only EXISTING videos
        vids.append(str(v))
    a.seed(vids)
    for _ in range(2):
        a.complete(a.claim_next(), "done", elapsed_s=2.0)
    for v in vids:
        append_jsonl(tmp_path / "_health.jsonl", _health_rec(v))

    def extract(mean=0.5, sig="sigA", elapsed=1.5):
        def fn(video, out_dir):
            append_jsonl(Path(out_dir) / "_health.jsonl",
                         _health_rec(video, mean=mean, sig=sig))
            return "done", elapsed
        return fn

    ok, lines = _wq(tmp_path, "B", clk).canary_gate(extract())
    assert ok, lines
    assert any("PASS" in l for l in lines)

    # numeric drift past the stock atol=1e-2 band: gated out
    ok, lines = _wq(tmp_path, "C", clk).canary_gate(
        extract(mean=0.9, sig="sigZ"))
    assert not ok
    assert any("DIGEST DRIFT" in l for l in lines), lines

    # 15x slower than the fleet's 2.0s median: outside the 2x band
    ok, lines = _wq(tmp_path, "D", clk).canary_gate(extract(elapsed=30.0))
    assert not ok
    assert any("timing band" in l and "FAIL" in l for l in lines), lines

    verdicts = [json.loads(p.read_text()) for p in
                (tmp_path / "_queue" / "canary").glob("*.json")]
    assert sorted(v["ok"] for v in verdicts) == [False, False, True]


def test_cli_canary_join_passes_end_to_end(sample_video, tmp_path, capsys):
    """Worker 1 drains a 2-video queue with health digests; worker 2
    joins the finished run with fleet_canary=true — it must re-extract
    the done slice, pass both bands against the fleet's digests, write
    its verdict, and exit with nothing left to claim."""
    import shutil

    from video_features_tpu.cli import main as cli_main
    vids = []
    for i in range(2):
        dst = tmp_path / f"v_canary_{i}.mp4"
        shutil.copy(sample_video, dst)
        vids.append(str(dst))
    args = ["feature_type=resnet", "model_name=resnet18", "device=cpu",
            "allow_random_weights=true", "on_extraction=save_numpy",
            "extraction_total=4", "batch_size=8", "video_workers=1",
            "telemetry=true", "health=true", "metrics_interval_s=0.5",
            "fleet=queue", "fleet_lease_s=10",
            f"output_path={tmp_path / 'out'}",
            f"tmp_path={tmp_path / 'tmp'}",
            "video_paths=[" + ",".join(vids) + "]"]
    cli_main(args)
    capsys.readouterr()
    cli_main(args + ["fleet_canary=true"])
    out = capsys.readouterr().out
    assert "fleet canary" in out
    assert "0 extracted" in out  # the queue was already drained
    qdir = tmp_path / "out" / "resnet" / "resnet18" / "_queue"
    verdicts = [json.loads(p.read_text())
                for p in (qdir / "canary").glob("*.json")]
    assert len(verdicts) == 1 and verdicts[0]["ok"] is True, verdicts
    assert len(verdicts[0]["videos"]) == 2
    assert len(list((qdir / "done").glob("*.json"))) == 2


def test_canary_warm_tightens_timing_band_and_heartbeat(tmp_path):
    """ISSUE 11 warm fast path: a joining host whose compile-cache
    fingerprint fully hit has no cold compile for the generous timing
    band to absorb — the re-compile allowance is skipped (band tightens
    to WARM_CANARY_BAND) and canary_warm lands in the heartbeat fleet
    section + the verdict file."""
    from video_features_tpu.telemetry.jsonl import append_jsonl
    clk = Clock()
    a = _wq(tmp_path, "A", clk)
    v = tmp_path / "v0.mp4"
    v.write_bytes(b"x")
    a.seed([str(v)])
    a.complete(a.claim_next(), "done", elapsed_s=2.0)
    append_jsonl(tmp_path / "_health.jsonl", _health_rec(str(v)))

    def extract(video, out_dir):
        append_jsonl(Path(out_dir) / "_health.jsonl", _health_rec(video))
        return "done", 3.0  # 1.5x the fleet median

    # a COLD joiner passes: 1.5x sits inside the default 2x compile
    # allowance
    cold = _wq(tmp_path, "B", clk)
    ok, lines = cold.canary_gate(extract)
    assert ok, lines
    # default heartbeat section: not warm, idle counter present
    sect = cold.heartbeat_section()
    assert sect["canary_warm"] is False
    assert sect["idle_wait_s_total"] == 0.0

    # the SAME timing, warm: no compile to absorb, band tightens, FAIL
    warm = _wq(tmp_path, "C", clk)
    warm.canary_warm = True
    ok, lines = warm.canary_gate(extract)
    assert not ok
    assert any("compile cache warm" in l and "tightened" in l
               for l in lines), lines
    assert warm.heartbeat_section()["canary_warm"] is True
    verdict = json.loads(
        (tmp_path / "_queue" / "canary" / "C.json").read_text())
    assert verdict["canary_warm"] is True and verdict["ok"] is False


def test_drain_accumulates_idle_wait(tmp_path):
    """The capacity planner's stall-share signal: a host idling behind
    another host's live lease accumulates idle_wait_s_total in its
    heartbeat fleet section."""
    clk = Clock()
    _hb(tmp_path, "A", now=clk.t)
    _hb(tmp_path, "B", now=clk.t)
    a = _wq(tmp_path, "A", clk)
    b = _wq(tmp_path, "B", clk)
    a.seed(["only.mp4"])
    rec = a.claim_next()  # A holds the only item, unexpired
    assert rec is not None
    stop = threading.Event()

    def finish():
        time.sleep(0.12)
        a.complete(rec, "done")
    t = threading.Thread(target=finish)
    t.start()
    b.drain(lambda v: "done", workers=1, stop=stop, poll_s=0.02)
    t.join()
    assert b.heartbeat_section()["idle_wait_s_total"] > 0.0
