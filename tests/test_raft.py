"""RAFT: parity against the actual reference torch model (imported read-only
from /root/reference as the numerical oracle)."""
import os
import sys

import numpy as np
from pathlib import Path
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import raft as raft_model  # noqa: E402

if "/root/reference" not in sys.path:
    sys.path.insert(0, "/root/reference")


def _ref_raft():
    try:
        from models.raft.raft_src.raft import RAFT as RefRAFT
    except Exception as e:  # pragma: no cover
        pytest.skip(f"reference RAFT not importable: {e}")
    torch.manual_seed(0)
    m = RefRAFT().eval()
    # give the cnet BNs non-trivial running stats so converter bugs show
    g = torch.Generator().manual_seed(1)
    for mod in m.modules():
        if isinstance(mod, torch.nn.BatchNorm2d):
            mod.running_mean.copy_(
                torch.rand(mod.running_mean.shape, generator=g) - 0.5)
            mod.running_var.copy_(
                torch.rand(mod.running_var.shape, generator=g) + 0.5)
    return m


def test_flax_matches_reference_torch():
    oracle = _ref_raft()
    params = raft_model.params_from_torch(oracle.state_dict())
    model = raft_model.RAFT(iters=20)

    # >=128 px per side: the reference's bilinear_sampler divides by
    # (W-1) per pyramid level, so a 1x1 level (inputs < 128) NaNs even in
    # torch; 128x160 -> levels 16x20, 8x10, 4x5, 2x2
    rng = np.random.default_rng(2)
    img1 = rng.uniform(0, 255, size=(1, 128, 160, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, size=(1, 128, 160, 3)).astype(np.float32)
    t1 = torch.from_numpy(img1).permute(0, 3, 1, 2)
    t2 = torch.from_numpy(img2).permute(0, 3, 1, 2)
    with torch.no_grad():
        want = oracle(t1, t2).permute(0, 2, 3, 1).numpy()  # (B, H, W, 2)
    got = np.asarray(model.apply({"params": params}, jnp.asarray(img1),
                                 jnp.asarray(img2)))
    assert got.shape == want.shape == (1, 128, 160, 2)
    # 20 recurrent iterations amplify fp noise; flows here are O(1-10) px
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=1e-3)


def test_input_padder_pad_amounts():
    # pad to /8, sintel mode splits evenly (reference raft.py:30-40)
    x = np.zeros((1, 436, 1024, 3))
    (t, b), (l, r) = raft_model.pad_to_multiple(x)
    assert (t + b) == (440 - 436) and (l, r) == (0, 0)
    assert t == 2 and b == 2
    x = np.zeros((1, 48, 64, 3))
    assert raft_model.pad_to_multiple(x) == ((0, 0), (0, 0))


def test_corr_pyramid_and_lookup_match_torch():
    """Level shapes + the lookup itself vs the reference CorrBlock."""
    try:
        from models.raft.raft_src.corr import CorrBlock
    except ImportError:
        pytest.skip("reference RAFT source not available "
                    "(/root/reference mount absent on this host)")

    rng = np.random.default_rng(0)
    f1 = rng.standard_normal((1, 16, 20, 32)).astype(np.float32)
    f2 = rng.standard_normal((1, 16, 20, 32)).astype(np.float32)
    pyr = raft_model.build_corr_pyramid(jnp.asarray(f1), jnp.asarray(f2))
    assert [p.shape for p in pyr] == [
        (1, 320, 16, 20), (1, 320, 8, 10), (1, 320, 4, 5), (1, 320, 2, 2)]

    # fractional coords exercise the bilinear weights and border clipping
    gx, gy = np.meshgrid(np.arange(20.0), np.arange(16.0))
    coords = (np.stack([gx, gy], axis=-1)[None] +
              rng.uniform(-2, 2, size=(1, 16, 20, 2))).astype(np.float32)
    got = np.asarray(raft_model.corr_lookup(pyr, jnp.asarray(coords)))

    t1 = torch.from_numpy(f1).permute(0, 3, 1, 2)
    t2 = torch.from_numpy(f2).permute(0, 3, 1, 2)
    blk = CorrBlock(t1, t2)
    tc = torch.from_numpy(coords).permute(0, 3, 1, 2)
    want = blk(tc).permute(0, 2, 3, 1).numpy()
    assert got.shape == want.shape == (1, 16, 20, 4 * 81)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_end_to_end_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.raft import ExtractRAFT

    cfg = load_config("raft", {
        "video_paths": sample_video, "device": "cpu",
        "batch_size": 4, "extraction_fps": 1, "side_size": 128,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractRAFT(cfg)
    feats = ex._extract(sample_video)
    # ~18.1s @1fps = 19 frames -> 18 flow pairs; 240x320 -> min side 128
    # => 128x170, padded to /8 inside jit and unpadded back
    n, c, h, w = feats["raft"].shape
    assert (c, h, w) == (2, 128, 170) and n == len(feats["timestamps_ms"]) - 1
    assert (tmp_path / "out" / "raft" / f"{Path(sample_video).stem}_raft.npy").exists()


def test_flow_viz_matches_reference():
    import importlib.util
    if not os.path.exists("/root/reference/utils/flow_viz.py"):
        pytest.skip("reference flow_viz source not available "
                    "(/root/reference mount absent on this host)")
    spec = importlib.util.spec_from_file_location(
        "ref_flow_viz", "/root/reference/utils/flow_viz.py")
    ref = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ref)
    from video_features_tpu.utils import flow_viz

    np.testing.assert_array_equal(flow_viz.make_colorwheel(),
                                  ref.make_colorwheel())
    rng = np.random.default_rng(3)
    flow = rng.uniform(-12, 12, size=(32, 40, 2)).astype(np.float32)
    np.testing.assert_array_equal(flow_viz.flow_to_image(flow),
                                  ref.flow_to_image(flow))


@pytest.mark.slow  # ~44s; test_io device-resize + the i3d sibling cover the fused path
def test_raft_device_resize_matches_host(sample_video, tmp_path, monkeypatch):
    """resize=device with side_size: the fused MXU resize in front of the
    flow net must match the host-PIL path closely (flow endpoint error well
    under a pixel for 2-LSB input deltas)."""
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls

    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "weights"))

    def feats(resize):
        args = load_config("raft", parse_dotlist([
            "feature_type=raft", "device=cpu", "batch_size=4",
            "extraction_fps=1", "side_size=128", "allow_random_weights=true",
            f"resize={resize}", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}", f"video_paths={sample_video}"]))
        sanity_check(args)
        return get_extractor_cls("raft")(args).extract(sample_video)

    host = feats("host")
    dev = feats("device")
    np.testing.assert_array_equal(host["timestamps_ms"],
                                  dev["timestamps_ms"])
    a, b = host["raft"], dev["raft"]  # (N, 2, H, W)
    assert a.shape == b.shape and a.shape[1] == 2
    err = np.abs(a - b)
    assert np.median(err) < 0.1 and np.percentile(err, 99) < 1.0, \
        (np.median(err), np.percentile(err, 99))


def test_iters_config_knob(tmp_path):
    """`iters` (raft) / `flow_iters` (i3d) expose the GRU refinement count
    the reference hardcodes at 20 (raft.py:118); default stays 20."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.registry import get_extractor_cls

    def build(**patch):
        args = load_config("raft", dict(
            {"feature_type": "raft", "device": "cpu", "batch_size": 1,
             "allow_random_weights": True, "video_paths": "x.mp4",
             "output_path": str(tmp_path / "o"),
             "tmp_path": str(tmp_path / "t")}, **patch))
        sanity_check(args)
        return get_extractor_cls("raft")(args)

    assert build().model.iters == 20
    assert build(iters=2).model.iters == 2


def test_fused_convc1_path_matches_default(rng, monkeypatch):
    """The fused lookup+convc1 scan path (VFT_CORR_LOOKUP=pallas, the TPU
    default — interpret mode here) produces the same flow as the gather
    path, through the full model: same param tree (the _Convc1Params twin
    shares nn.Conv's path/shapes), same numerics up to matmul reorder."""
    from video_features_tpu.models import raft as rm

    params = rm.init_params(iters=4)
    assert params["update_block"]["encoder"]["convc1"]["kernel"].shape \
        == (1, 1, 324, 256)
    x1 = jnp.asarray(rng.integers(
        0, 255, size=(1, 64, 72, 3)).astype(np.float32))
    x2 = jnp.asarray(rng.integers(
        0, 255, size=(1, 64, 72, 3)).astype(np.float32))
    model = rm.RAFT(iters=4)
    want = np.asarray(model.apply({"params": params}, x1, x2))
    monkeypatch.setenv("VFT_CORR_LOOKUP", "pallas")
    monkeypatch.setenv("VFT_FUSE_CONVC1", "1")
    got = np.asarray(model.apply({"params": params}, x1, x2))
    np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-3)
    # and the explicitly-unfused pallas path still matches too
    monkeypatch.setenv("VFT_FUSE_CONVC1", "0")
    unfused = np.asarray(model.apply({"params": params}, x1, x2))
    np.testing.assert_allclose(unfused, want, atol=1e-3, rtol=1e-3)


def test_bfloat16_mode_close_to_f32(rng):
    """RAFT(dtype=bf16) + bf16 params: convs run MXU-native while pyramid/
    coords/norms stay f32 (models/raft.py RAFT docstring). Flow drift must
    stay well under the I3D flow stream's ToUInt8 quantization step."""
    import jax
    import jax.numpy as jnp
    from video_features_tpu.models import raft as rm
    from video_features_tpu.parallel.mesh import cast_floating

    params = rm.init_params(iters=4)
    x1 = jnp.asarray(rng.integers(0, 255, size=(1, 64, 72, 3)).astype(np.float32))
    x2 = jnp.asarray(rng.integers(0, 255, size=(1, 64, 72, 3)).astype(np.float32))
    f32 = np.asarray(jax.jit(lambda p, a, b: rm.RAFT(iters=4).apply(
        {"params": p}, a, b))(params, x1, x2))
    bf16 = np.asarray(jax.jit(lambda p, a, b: rm.RAFT(
        iters=4, dtype=jnp.bfloat16).apply({"params": p}, a, b))(
        cast_floating(params, jnp.bfloat16), x1, x2))
    d = np.abs(bf16 - f32)
    assert np.isfinite(bf16).all()
    # loose bound: random weights amplify bf16 noise vs trained ones
    assert np.median(d) < 0.1 and np.percentile(d, 99) < 1.0, \
        (np.median(d), np.percentile(d, 99))


def test_precision_bfloat16_wires_model_dtype(tmp_path, monkeypatch):
    """precision=bfloat16 must reach RAFT.dtype (and f32 stay default) —
    wiring only, no forward (bf16 CPU compiles are minutes-slow)."""
    import jax.numpy as jnp
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "w"))
    for precision, want in (("float32", jnp.float32),
                            ("bfloat16", jnp.bfloat16)):
        args = load_config("raft", parse_dotlist([
            "feature_type=raft", "device=cpu", f"precision={precision}",
            "allow_random_weights=true", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}", "video_paths=x.mp4"]))
        sanity_check(args)
        ex = get_extractor_cls("raft")(args)
        assert ex.model.dtype == want, precision
