"""Per-seam numerics observatory (telemetry/parity.py, ISSUE 19).

Contracts pinned here:
  - seam digests carry exactly PARITY_FIELDS and validate against the
    checked-in schema; the tolerance registry self-validates (known
    seams, numeric bounds, written justifications, '*' defaults);
  - ``parity=false`` is a true zero: byte-identical features, no
    ``_parity.jsonl`` anywhere, an empty heartbeat section, and the
    TransformTap/tap off paths are pure pass-throughs;
  - the journal is bit-stable (modulo wall-clock fields) across
    ``video_workers`` 1 vs 2 and across shared-decode (multi-family)
    vs private-decode (single-family) runs — the observatory must
    never report drift that is merely scheduling;
  - the certify A/B attributes an injected drift to exactly the
    perturbed seam (FAIL names the FIRST out-of-band seam), and its
    verdict document round-trips through the checked-in schema — the
    committed ``evidence/parity/*_bf16`` verdicts included.
"""
import contextlib
import io as _io
import json
import shutil
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.telemetry import parity
from video_features_tpu.telemetry.jsonl import read_jsonl

REPO_ROOT = Path(__file__).resolve().parent.parent


# -- units (quick tier) -----------------------------------------------------

@pytest.mark.quick
def test_digest_seam_fields_and_schema():
    arr = np.linspace(-2, 2, 60, dtype=np.float32).reshape(5, 12)
    for seam in parity.SEAMS:
        rec = parity.digest_seam(seam, "feat", arr, video="v.mp4",
                                 feature_type="resnet", index=3)
        assert tuple(rec) == parity.PARITY_FIELDS, seam
        assert rec["seam"] == seam and rec["index"] == 3
        assert rec["schema"] == parity.SCHEMA_VERSION
        assert parity.validate_parity(rec) == []


@pytest.mark.quick
def test_tolerance_registry_self_validates():
    assert parity.validate_tolerances() == []
    # every band resolves: family-specific where declared, '*' fallback
    for seam in parity.SEAMS:
        band = parity.tolerance_for("nosuchfamily", seam)
        assert band["max_abs"] > 0 and 0 < band["cos"] <= 1.0
    raft = parity.tolerance_for("raft", "backbone")
    assert raft["max_abs"] > parity.tolerance_for("*", "decode")["max_abs"]


@pytest.mark.quick
def test_tolerance_registry_rejects_corruption(monkeypatch):
    bad = dict(parity.TOLERANCES)
    bad[("x", "nosuchseam")] = {"max_abs": 1.0, "cos": 0.9,
                                "why": "long enough justification here"}
    bad[("raft", "backbone")] = {"max_abs": "big", "cos": 0.9, "why": "no"}
    monkeypatch.setattr(parity, "TOLERANCES", bad)
    errs = parity.validate_tolerances()
    assert any("unknown seam" in e for e in errs)
    assert any("is not a number" in e for e in errs)
    assert any("written justification" in e for e in errs)


@pytest.mark.quick
def test_normalize_flip_pins_reference_dtype():
    # dtype=bf16 pins f32 on the reference arm REGARDLESS of the (now
    # flipped) YAML default — a re-certify stays meaningful post-flip
    ref, cand = parity._normalize_flip("dtype=bf16")
    assert ref == {"precision": "float32"}
    assert cand == {"precision": "bfloat16"}
    ref, cand = parity._normalize_flip("precision=float32")
    assert cand == {"precision": "float32"}
    with pytest.raises(SystemExit):
        parity._normalize_flip("dtype=int8")


@pytest.mark.quick
def test_off_path_is_pure_passthrough():
    assert parity.active() is None
    assert parity.snapshot() == {}
    # tap() with no active observer: one global read, no effect
    parity.tap("decode", "frame", np.ones(3), video="v",
               feature_type="resnet")
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    # identity transform: the frame object passes through untouched
    assert parity.TransformTap(None, "v.mp4", "resnet")(x) is x
    t = parity.TransformTap(lambda f: f * 2.0, "v.mp4", "resnet")
    np.testing.assert_array_equal(t(x), x * 2.0)


@pytest.mark.quick
def test_compare_captures_names_first_drifted_seam():
    rng = np.random.default_rng(7)
    ref = {}
    for i, seam in enumerate(parity.SEAMS):
        ref[("v.mp4", seam, "frame", i)] = \
            rng.standard_normal((4, 6)).astype(np.float64)
    cand = {k: v.copy() for k, v in ref.items()}
    seams, first, verdict = parity.compare_captures(ref, cand, "resnet")
    assert (first, verdict) == (None, "PASS")
    assert all(seams[s]["ok"] and seams[s]["max_abs"] == 0.0
               for s in parity.SEAMS)

    # drift injected past the backbone band: FAIL must name backbone,
    # and the upstream seams must stay clean (that IS the attribution)
    band = parity.tolerance_for("resnet", "backbone")["max_abs"]
    k = ("v.mp4", "backbone", "frame", 2)
    cand[k] = cand[k] + 10 * band
    seams, first, verdict = parity.compare_captures(ref, cand, "resnet")
    assert (first, verdict) == ("backbone", "FAIL")
    assert not seams["backbone"]["ok"]
    assert seams["decode"]["ok"] and seams["transform"]["ok"]

    # a record-set mismatch (a seam silently losing taps) also fails
    del cand[("v.mp4", "decode", "frame", 0)]
    seams, first, verdict = parity.compare_captures(ref, cand, "resnet")
    assert first == "decode" and seams["decode"]["note"]


@pytest.mark.quick
def test_committed_evidence_verdicts_validate():
    """The checked-in bf16-flip evidence must stay schema-valid PASS —
    the configs/raft.yml + pwc.yml dtype defaults cite these files."""
    for fam in ("raft", "pwc"):
        p = (REPO_ROOT / "evidence" / "parity" / f"{fam}_bf16"
             / parity.VERDICT_FILENAME)
        doc = json.loads(p.read_text())
        assert parity.validate_verdict(doc) == [], p
        assert doc["family"] == fam and doc["verdict"] == "PASS"
        assert doc["flip"] == "dtype=bf16" and doc["first_drift"] is None
        assert set(doc["seams"]) == set(parity.SEAMS)
        # collect_verdicts must surface it (the alerts/fleet planes
        # consume verdicts exclusively through this walk)
        got = parity.collect_verdicts(str(p.parent))
        assert [d["family"] for d in got] == [fam]


# -- CLI end-to-end ---------------------------------------------------------

def _run(out, tmp, vids, *extra):
    from video_features_tpu.cli import main as cli_main
    with contextlib.redirect_stdout(_io.StringIO()):
        cli_main(["feature_type=resnet", "model_name=resnet18",
                  "device=cpu", "allow_random_weights=true",
                  "on_extraction=save_numpy", "batch_size=8",
                  "extraction_total=4", "retry_attempts=1",
                  f"output_path={out}", f"tmp_path={tmp}",
                  f"video_paths=[{','.join(vids)}]", *extra])


def _stripped(root):
    """Sorted journal records minus the wall-clock-dependent fields —
    the bit-stability comparison key."""
    recs = []
    for p in Path(root).rglob("_parity*.jsonl"):
        recs.extend(read_jsonl(p))
    assert all(parity.validate_parity(r) == [] for r in recs)
    out = []
    for r in recs:
        r = dict(r)
        r.pop("time", None)
        r.pop("request_id", None)
        out.append(json.dumps(r, sort_keys=True))
    return sorted(out)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory, sample_video):
    td = tmp_path_factory.mktemp("parity_corpus")
    vids = []
    for i in range(2):
        dst = td / f"v_par_{i}.mp4"
        shutil.copy(sample_video, dst)
        vids.append(str(dst))
    return td, vids


@pytest.fixture(scope="module")
def w1_run(corpus):
    """parity=true reference run (video_workers=1), shared per-module."""
    td, vids = corpus
    out = td / "w1"
    _run(out, td / "tmp", vids, "parity=true", "telemetry=true",
         "metrics_interval_s=60", "video_workers=1")
    return out


# the CLI E2E arms below each pay a full extraction run; tier-1's 870s
# budget can't absorb them, and the CI parity gate
# (scripts/check_parity_schema.py) already proves the zero-footprint /
# all-four-seams / identity-certify contracts on a real smoke every
# push — the full CI tier keeps these richer matrices honest
@pytest.mark.slow
def test_parity_false_zero_footprint_and_byte_identity(corpus, w1_run,
                                                       tmp_path):
    td, vids = corpus
    off = tmp_path / "off"
    _run(off, td / "tmp", vids, "telemetry=true", "metrics_interval_s=60")
    # zero footprint: no journal anywhere, empty heartbeat section
    assert not list(off.rglob("_parity*.jsonl"))
    hbs = list(off.rglob("_heartbeat*.json"))
    assert hbs and json.loads(hbs[0].read_text())["parity"] == {}
    # and the taps cost nothing observable: features byte-identical to
    # the parity=true run
    on_npy = sorted(p.relative_to(w1_run) for p in w1_run.rglob("*.npy"))
    off_npy = sorted(p.relative_to(off) for p in off.rglob("*.npy"))
    assert on_npy == off_npy and len(on_npy) == 6
    for rel in on_npy:
        assert (w1_run / rel).read_bytes() == (off / rel).read_bytes(), rel


@pytest.mark.slow
def test_journal_bit_stable_across_video_workers(corpus, w1_run, tmp_path):
    td, vids = corpus
    out = tmp_path / "w2"
    _run(out, td / "tmp", vids, "parity=true", "video_workers=2")
    ref = _stripped(w1_run)
    assert ref and {json.loads(r)["seam"] for r in ref} == set(parity.SEAMS)
    assert _stripped(out) == ref


# the r21d clip-stack arm makes this the file's slowest test; tier-1's
# 870s budget keeps it in the full CI tier (the single-family taps and
# the workers matrix above already run in tier 1)
@pytest.mark.slow
def test_journal_bit_stable_shared_vs_private_decode(corpus, w1_run,
                                                     tmp_path):
    """A multi-family shared-decode run's resnet records must equal the
    private-decode single-family run's — the TransformTap wraps the
    family transform BEFORE the shared-decode subscribe, so both paths
    tap the same tensors on the family's own thread."""
    from video_features_tpu.cli import main as cli_main
    td, vids = corpus
    out = tmp_path / "multi"
    with contextlib.redirect_stdout(_io.StringIO()):
        cli_main(["feature_type=resnet,r21d", "device=cpu",
                  "allow_random_weights=true", "on_extraction=save_numpy",
                  "retry_attempts=1", "parity=true",
                  "resnet.model_name=resnet18", "resnet.batch_size=8",
                  "resnet.extraction_total=4", "r21d.extraction_fps=1",
                  "r21d.stack_size=10", "r21d.step_size=10",
                  f"output_path={out}", f"tmp_path={td / 'tmp'}",
                  f"video_paths=[{','.join(vids)}]"])
    all_recs = [json.loads(r) for r in _stripped(out)]
    by_fam = {}
    for r in all_recs:
        by_fam.setdefault(r["feature_type"], []).append(
            json.dumps(r, sort_keys=True))
    # both families asked, both journaled — all four seams each
    for fam in ("resnet", "r21d"):
        assert {json.loads(r)["seam"] for r in by_fam[fam]} == \
            set(parity.SEAMS), fam
    want = [r for r in _stripped(w1_run)
            if json.loads(r)["feature_type"] == "resnet"]
    assert sorted(by_fam["resnet"]) == want


@pytest.mark.slow
def test_certify_attributes_injected_drift(corpus, tmp_path):
    """An eps injected at the transform tap must FAIL at exactly that
    seam — decode (upstream) clean, the verdict file schema-valid."""
    td, vids = corpus
    with contextlib.redirect_stdout(_io.StringIO()):
        doc = parity.certify("resnet", flip=None, videos=[vids[0]],
                             frames=4, out_dir=str(tmp_path),
                             perturb={"transform": 0.05})
    assert doc["verdict"] == "FAIL"
    assert doc["first_drift"] == "transform"
    assert doc["seams"]["decode"]["ok"]
    assert not doc["seams"]["transform"]["ok"]
    on_disk = json.loads(
        (tmp_path / parity.VERDICT_FILENAME).read_text())
    assert parity.validate_verdict(on_disk) == []
    assert on_disk["verdict"] == "FAIL"
    # the report/validate surface consumes it the same way
    assert parity.collect_verdicts(str(tmp_path))[0]["first_drift"] == \
        "transform"


# the CI quick gate (scripts/check_parity_schema.py check_certify) runs
# this same identity A/B on every push; tier 1 doesn't need to pay for
# it twice
@pytest.mark.slow
def test_certify_identity_is_bit_exact(corpus, tmp_path):
    """Two arms of the same seeded config are BIT-identical — the
    harness itself contributes zero error (this is what makes a PASS
    verdict evidence about the flip, not about the harness)."""
    td, vids = corpus
    with contextlib.redirect_stdout(_io.StringIO()):
        doc = parity.certify("resnet", flip=None, videos=[vids[0]],
                             frames=4, out_dir=str(tmp_path))
    assert doc["verdict"] == "PASS" and doc["first_drift"] is None
    for seam in parity.SEAMS:
        m = doc["seams"][seam]
        assert m["ok"] and m["max_abs"] == 0.0 and m["cos"] == 1.0, seam
