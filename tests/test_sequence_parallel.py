"""Ring / all-to-all sequence parallelism vs dense attention, 8-dev CPU mesh."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from video_features_tpu.parallel.sequence import (dense_attention,
                                                  ring_attention,
                                                  ulysses_attention)


def _qkv(rng, b=2, t=64, h=8, d=16):
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from jax.sharding import Mesh
    return Mesh(np.array(devs[:8]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(rng, seq_mesh, causal):
    q, k, v = _qkv(rng)
    ref = np.asarray(dense_attention(q, k, v, causal=causal))
    out = np.asarray(ring_attention(q, k, v, mesh=seq_mesh, causal=causal))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(rng, seq_mesh, causal):
    q, k, v = _qkv(rng)
    ref = np.asarray(dense_attention(q, k, v, causal=causal))
    out = np.asarray(ulysses_attention(q, k, v, mesh=seq_mesh, causal=causal))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_attention_long_sequence_memory_shape(rng, seq_mesh):
    """T=1024 over 8 devices: per-device block is 128 — the score matrix a
    device materializes is (128, 1024/8) per step, never (1024, 1024)."""
    q, k, v = _qkv(rng, b=1, t=1024, h=2, d=8)
    ref = np.asarray(dense_attention(q, k, v))
    out = np.asarray(ring_attention(q, k, v, mesh=seq_mesh))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
