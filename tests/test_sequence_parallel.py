"""Ring / all-to-all sequence parallelism vs dense attention, 8-dev CPU mesh."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from video_features_tpu.parallel.sequence import (dense_attention,
                                                  ring_attention,
                                                  ulysses_attention)


def _qkv(rng, b=2, t=64, h=8, d=16):
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from jax.sharding import Mesh
    return Mesh(np.array(devs[:8]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(rng, seq_mesh, causal):
    q, k, v = _qkv(rng)
    ref = np.asarray(dense_attention(q, k, v, causal=causal))
    out = np.asarray(ring_attention(q, k, v, mesh=seq_mesh, causal=causal))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(rng, seq_mesh, causal):
    q, k, v = _qkv(rng)
    ref = np.asarray(dense_attention(q, k, v, causal=causal))
    out = np.asarray(ulysses_attention(q, k, v, mesh=seq_mesh, causal=causal))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_attention_long_sequence_memory_shape(rng, seq_mesh):
    """T=1024 over 8 devices: per-device block is 128 — the score matrix a
    device materializes is (128, 1024/8) per step, never (1024, 1024)."""
    q, k, v = _qkv(rng, b=1, t=1024, h=2, d=8)
    ref = np.asarray(dense_attention(q, k, v))
    out = np.asarray(ring_attention(q, k, v, mesh=seq_mesh))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_blockwise_attention_matches_dense(rng):
    """Single-device FlashAttention-style recurrence: exact vs dense for
    causal and non-causal, block-divisible and ragged T, block >= T."""
    from video_features_tpu.parallel.sequence import (blockwise_attention,
                                                      dense_attention)
    for t, bs in ((32, 8), (37, 8), (16, 64)):
        q, k, v = (jnp.asarray(rng.normal(size=(2, t, 3, 8))
                               .astype(np.float32)) for _ in range(3))
        for causal in (False, True):
            got = blockwise_attention(q, k, v, block_size=bs, causal=causal)
            want = dense_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5,
                                       err_msg=f"t={t} bs={bs} causal={causal}")
