"""Mixed-geometry multi-video extraction in one CLI run.

The reference ships TWO sample videos with different geometry and timing
(v_GGSY1Qvo990: 355f @19.62fps 320x240; v_ZNVhz7ctTq0: 420f @30fps
480x360) but its tests only ever exercise the first. One run over both
pins the per-resolution behavior the single-video tests can't see:

  - the work-list loop carries state across videos of different shapes;
  - under ``resize=device`` the per-source-resolution runner cache
    (extractors/base.py _cached_resize_runner) must compile one executable
    per geometry and keep both live;
  - fps resampling derives from each video's own fps (30 vs 19.62);
  - outputs land under one dir with the {stem}_{key}.npy contract.

Skips when the second sample is absent (it has no synthesized stand-in:
the point is real mixed containers).
"""
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tests.conftest import REFERENCE_ROOT  # single mount-path definition

SAMPLE2 = os.path.join(REFERENCE_ROOT, "sample", "v_ZNVhz7ctTq0.mp4")


@pytest.mark.parametrize("resize", ["host", "device"])
def test_two_videos_two_geometries_one_run(resize, sample_video, tmp_path):
    if not os.path.exists(SAMPLE2):
        pytest.skip("second reference sample not available")
    out = tmp_path / "out"
    cmd = [sys.executable, "main.py", "feature_type=resnet",
           "model_name=resnet18", "device=cpu", "batch_size=16",
           "extraction_fps=2", "allow_random_weights=true",
           f"resize={resize}", "on_extraction=save_numpy",
           f"output_path={out}", f"tmp_path={tmp_path / 'tmp'}",
           f"video_paths=[{sample_video},{SAMPLE2}]"]
    res = subprocess.run(cmd, cwd=str(Path(__file__).resolve().parent.parent),
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]

    feat_dir = out / "resnet" / "resnet18"
    # fps rule (golden-pinned): round(n_frames * 2 / src_fps)
    expect = {Path(sample_video).stem: round(355 * 2 / 19.62),
              "v_ZNVhz7ctTq0": round(420 * 2 / 30.0)}
    for stem, n in expect.items():
        feats = np.load(feat_dir / f"{stem}_resnet.npy")
        ts = np.load(feat_dir / f"{stem}_timestamps_ms.npy")
        fps = np.load(feat_dir / f"{stem}_fps.npy")
        assert feats.shape == (n, 512), (stem, feats.shape)
        assert ts.shape == (n,)
        assert float(fps) == 2.0
        assert np.isfinite(feats).all()
