"""Parity tests for the Pallas TPU kernels (interpret mode on CPU).

Each kernel is checked against the framework's pure-XLA implementation of the
same op, which is itself golden-tested against the torch reference
(test_pwc.py, test_raft.py) — so agreement here chains to reference parity.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from video_features_tpu.kernels.cost_volume import cost_volume_xla
from video_features_tpu.kernels.corr_lookup import (corr_lookup_onehot,
                                                    corr_lookup_pallas)
from video_features_tpu.models.raft import (build_corr_pyramid,
                                             corr_lookup_gather)

pytestmark = pytest.mark.quick


@pytest.mark.parametrize("b,h,w,c", [(1, 7, 9, 3), (2, 5, 12, 16)])
def test_cost_volume_matches_reference_semantics(rng, b, h, w, c):
    """Pin the XLA cost volume to the reference CUDA kernel's contract
    (correlation.py:47-115): channel (dy+4)*9+(dx+4) = channel-mean of
    f1 * shift(f2, dy, dx), zero padding — via an explicit numpy loop.
    (The Pallas twin was measured tied with XLA on v5e and deleted in
    round 5; see kernels/cost_volume.py docstring.)"""
    r = 4
    f1 = rng.normal(size=(b, h, w, c)).astype(np.float32)
    f2 = rng.normal(size=(b, h, w, c)).astype(np.float32)
    got = np.asarray(cost_volume_xla(jnp.asarray(f1), jnp.asarray(f2), r))
    assert got.shape == (b, h, w, (2 * r + 1) ** 2)
    f2p = np.pad(f2, ((0, 0), (r, r), (r, r), (0, 0)))
    for dy in (-r, 0, 1, r):
        for dx in (-r, -1, 0, r):
            win = f2p[:, r + dy:r + dy + h, r + dx:r + dx + w]
            want = (f1 * win).mean(axis=-1)
            ch = (dy + r) * (2 * r + 1) + (dx + r)
            np.testing.assert_allclose(got[..., ch], want,
                                       atol=1e-5, rtol=1e-5)


def test_cost_volume_bf16_accumulates_f32(rng):
    """bf16 inputs must not accumulate the 196-term channel sum in bf16:
    the result must track the f32 computation to bf16-rounding, not to
    bf16-accumulation (which would be ~1% off)."""
    f1 = rng.normal(size=(1, 6, 8, 196)).astype(np.float32)
    f2 = rng.normal(size=(1, 6, 8, 196)).astype(np.float32)
    exact = np.asarray(cost_volume_xla(jnp.asarray(f1), jnp.asarray(f2)))
    bf = np.asarray(cost_volume_xla(
        jnp.asarray(f1).astype(jnp.bfloat16),
        jnp.asarray(f2).astype(jnp.bfloat16)), dtype=np.float32)
    # input rounding to bf16 costs ~0.4% on a mean of 196 unit-normal
    # products; bf16 ACCUMULATION would cost several times that
    np.testing.assert_allclose(bf, exact, atol=2e-2)


def _pyramid_and_coords(rng, b=1, h8=12, w8=10, c=64):
    f1 = rng.normal(size=(b, h8, w8, c)).astype(np.float32)
    f2 = rng.normal(size=(b, h8, w8, c)).astype(np.float32)
    pyramid = build_corr_pyramid(jnp.asarray(f1), jnp.asarray(f2))
    # coords spread across (and slightly beyond) the image so both in-range
    # bilinear blending and the zeros-padding boundary path are exercised
    coords = rng.uniform(-6.0, max(h8, w8) + 6.0,
                         size=(b, h8, w8, 2)).astype(np.float32)
    return pyramid, jnp.asarray(coords), (h8, w8)


def test_corr_lookup_onehot_matches_gather(rng):
    pyramid, coords, _ = _pyramid_and_coords(rng)
    ref = np.asarray(corr_lookup_gather(pyramid, coords))
    ours = np.asarray(corr_lookup_onehot(pyramid, coords))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_corr_lookup_onehot_integer_coords(rng):
    """Integer coords hit the fx=fy=0 degenerate corner weights."""
    pyramid, _, (h8, w8) = _pyramid_and_coords(rng)
    b = pyramid[0].shape[0]
    gx, gy = np.meshgrid(np.arange(w8, dtype=np.float32),
                         np.arange(h8, dtype=np.float32))
    coords = jnp.asarray(np.broadcast_to(
        np.stack([gx, gy], -1), (b, h8, w8, 2)))
    ref = np.asarray(corr_lookup_gather(pyramid, coords))
    ours = np.asarray(corr_lookup_onehot(pyramid, coords))
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_corr_lookup_pallas_matches_gather(rng):
    pyramid, coords, _ = _pyramid_and_coords(rng)
    ref = np.asarray(corr_lookup_gather(pyramid, coords))
    ours = np.asarray(corr_lookup_pallas(pyramid, coords, interpret=True))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_corr_lookup_packed_matches_gather(rng):
    """The lane-dense packed fused kernel (VFT_CORR_LOOKUP=packed, the
    measured negative-result alternative) keeps exact lookup semantics."""
    from video_features_tpu.kernels.corr_lookup import (corr_lookup_packed,
                                                        pack_pyramid)
    pyramid, coords, _ = _pyramid_and_coords(rng)
    packed, metas = pack_pyramid(pyramid)
    ref = np.asarray(corr_lookup_gather(pyramid, coords))
    ours = np.asarray(corr_lookup_packed(packed, metas, coords,
                                         interpret=True))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_corr_lookup_packed_degenerate_pyramid(rng):
    """Tiny inputs pool down to 1x1 and then 0x0 levels; the packed kernel
    must reproduce the gather's all-zeros semantics for both (the fused
    kernel stores an explicit zero placeholder plane, corr_lookup.py
    _plan_level)."""
    from video_features_tpu.kernels.corr_lookup import (corr_lookup_packed,
                                                        pack_pyramid)
    pyramid, coords, _ = _pyramid_and_coords(rng, h8=6, w8=5, c=16)
    shapes = [tuple(c.shape[2:]) for c in pyramid]
    assert (1, 1) in shapes and (0, 0) in shapes, shapes
    packed, metas = pack_pyramid(pyramid)
    ref = np.asarray(corr_lookup_gather(pyramid, coords))
    ours = np.asarray(corr_lookup_packed(packed, metas, coords,
                                         interpret=True))
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def _proj_weight(rng, c_out=24):
    w = rng.normal(size=(4 * 81, c_out)).astype(np.float32) * 0.1
    b = rng.normal(size=(c_out,)).astype(np.float32)
    return jnp.asarray(w), jnp.asarray(b)


def test_corr_lookup_proj_matches_composition(rng):
    """The fused lookup+convc1 kernel (round-4 TPU default inside the RAFT
    scan) equals the unfused composition relu(lookup @ W + b)."""
    from video_features_tpu.kernels.corr_lookup import (
        corr_lookup_proj, corr_lookup_proj_ref, proj_lookup_supported,
        stack_aligned_pyramid)
    pyramid, coords, _ = _pyramid_and_coords(rng)
    assert proj_lookup_supported(pyramid)
    wgt, bias = _proj_weight(rng)
    stacked, metas = stack_aligned_pyramid(pyramid)
    ref = np.asarray(corr_lookup_proj_ref(pyramid, coords, wgt, bias))
    ours = np.asarray(corr_lookup_proj(stacked, metas, coords, wgt, bias,
                                       interpret=True))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_corr_lookup_proj_integer_and_oob_coords(rng):
    """fx=fy=0 degenerate bilinear weights (the hat selector's exact-1 peak)
    and fully out-of-range windows (zeros rule -> relu(bias))."""
    from video_features_tpu.kernels.corr_lookup import (
        corr_lookup_proj, corr_lookup_proj_ref, stack_aligned_pyramid)
    pyramid, _, (h8, w8) = _pyramid_and_coords(rng)
    b = pyramid[0].shape[0]
    gx, gy = np.meshgrid(np.arange(w8, dtype=np.float32),
                         np.arange(h8, dtype=np.float32))
    coords = np.broadcast_to(np.stack([gx, gy], -1),
                             (b, h8, w8, 2)).copy()
    coords[:, 0, :, :] = -50.0  # first row: windows fully out of range
    coords = jnp.asarray(coords)
    wgt, bias = _proj_weight(rng)
    stacked, metas = stack_aligned_pyramid(pyramid)
    ref = np.asarray(corr_lookup_proj_ref(pyramid, coords, wgt, bias))
    ours = np.asarray(corr_lookup_proj(stacked, metas, coords, wgt, bias,
                                       interpret=True))
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)
    want_oob = np.broadcast_to(np.maximum(np.asarray(bias), 0.0),
                               ours[:, 0].shape)
    np.testing.assert_allclose(ours[:, 0], want_oob, atol=1e-6)


def test_corr_lookup_proj_degenerate_pyramid(rng):
    """Tiny inputs pool down to 1x1 and 0x0 levels; the fused kernel skips
    the empty level (its taps are all in the zeros-padding region)."""
    from video_features_tpu.kernels.corr_lookup import (
        corr_lookup_proj, corr_lookup_proj_ref, stack_aligned_pyramid)
    pyramid, coords, _ = _pyramid_and_coords(rng, h8=6, w8=5, c=16)
    shapes = [tuple(c.shape[2:]) for c in pyramid]
    assert (1, 1) in shapes and (0, 0) in shapes, shapes
    wgt, bias = _proj_weight(rng)
    stacked, metas = stack_aligned_pyramid(pyramid)
    assert metas[-1].hlp == 0
    ref = np.asarray(corr_lookup_proj_ref(pyramid, coords, wgt, bias))
    ours = np.asarray(corr_lookup_proj(stacked, metas, coords, wgt, bias,
                                       interpret=True))
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_pack_pyramid_geometry(rng):
    """The lane-dense packing stays dense: one 128-lane line carries
    multiple narrow image rows, all levels' row-groups share ONE fused
    lane plane, and zero fill covers phantom rows + lane tails (the
    zeros-padding rule)."""
    from video_features_tpu.kernels.corr_lookup import pack_pyramid
    pyramid, _, _ = _pyramid_and_coords(rng, b=2, h8=28, w8=28, c=16)
    packed, metas = pack_pyramid(pyramid)
    # RAFT-224 finest level: 4 rows of 28 cols per 128-lane line, 7 groups
    m0 = metas[0]
    assert (m0.j, m0.g, m0.k, m0.off) == (4, 7, 128, 0)
    b, p = pyramid[0].shape[:2]
    assert packed.shape == (b * p, sum(m.g * m.k for m in metas))
    assert metas[1].off == 7 * 128
    # spot value: query (b=1, p=5), image row 9 col 3 -> group 2, sub-row 1
    want = float(pyramid[0][1, 5, 9, 3])
    got = float(packed[p + 5, 2 * 128 + 1 * 28 + 3])
    assert got == want
    # level-0 lane tail beyond j*wl is zero fill in every group
    for g in range(7):
        tail = packed[:, g * 128 + 112:(g + 1) * 128]
        assert float(jnp.abs(tail).max()) == 0.0
