"""Roofline observatory (telemetry/roofline.py, ISSUE 12).

Pins the MFU-accounting contracts: cost-card capture at the
DataParallelApply dispatch seam for a jitted toy program, the
peak-registry / cached-microbench fallback chain, all four verdict
classifications on synthetic timings, the ``_roofline.json`` schema
round-trip, the bench-history direction-of-goodness of the new
efficiency series, and the zero-footprint byte-identity of
``roofline=false``.
"""
from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.parallel.mesh import DataParallelApply, get_mesh
from video_features_tpu.telemetry import roofline

pytestmark = pytest.mark.quick


@pytest.fixture
def observer(tmp_path, monkeypatch):
    """A started observer with a pinned peak, always closed (and the
    process-global slot cleared) even when the test fails."""
    monkeypatch.setenv("VFT_ROOFLINE_PEAK", "0.05,10")
    obs = roofline.RooflineObserver(str(tmp_path), default_family="toy",
                                    run_id="test")
    assert obs.start() is obs
    yield obs
    obs.close(write=False)
    assert roofline.active() is None


def _toy_runner(n: int = 16) -> DataParallelApply:
    return DataParallelApply(lambda p, x: x @ p,
                             np.eye(n, dtype=np.float32),
                             mesh=get_mesh(n_devices=1))


# -- cost cards ---------------------------------------------------------------

def test_cost_card_capture_toy_program(observer, tmp_path):
    runner = _toy_runner()
    batch = np.ones((4, 16), np.float32)
    for _ in range(3):
        runner(batch)
    doc = observer.close()
    fam = doc["families"]["toy"]
    assert fam["dispatches"] == 3
    assert len(fam["programs"]) == 1
    card = fam["programs"][0]
    assert card["shape"] == [4, 16] and card["dispatches"] == 3
    # the card's numbers ARE XLA's own cost model for this program
    direct = roofline.program_cost(runner._fn, runner.params, batch)
    assert card["flops"] == direct["flops"] > 0
    assert card["bytes"] == direct["bytes"] > 0
    assert fam["flops_total"] == pytest.approx(3 * direct["flops"])
    # forward stage seconds joined in (the profiler-hook chain)
    assert fam["forward_calls"] == 3 and fam["forward_s"] > 0
    assert fam["effective_tflops"] is not None
    assert fam["mfu"] == pytest.approx(
        fam["effective_tflops"] / 0.05, rel=1e-6)
    # file landed atomically under the observer's home
    assert (tmp_path / roofline.ROOFLINE_FILENAME).exists()


def test_distinct_shapes_get_distinct_cards(observer):
    runner = _toy_runner()
    runner(np.ones((2, 16), np.float32))
    runner(np.ones((4, 16), np.float32))
    runner(np.ones((4, 16), np.float32))
    doc = observer.summary()
    cards = doc["families"]["toy"]["programs"]
    assert sorted(tuple(c["shape"]) for c in cards) == [(2, 16), (4, 16)]
    by_shape = {tuple(c["shape"]): c["dispatches"] for c in cards}
    assert by_shape == {(2, 16): 1, (4, 16): 2}


def test_observe_dispatch_is_noop_when_off():
    # no active observer: the mesh hook is one global read, never raises
    assert roofline.active() is None
    runner = _toy_runner()
    out = runner(np.ones((4, 16), np.float32))
    assert out.shape == (4, 16)


# -- peak registry + microbench fallback --------------------------------------

def test_peak_registry_known_kinds(monkeypatch):
    monkeypatch.delenv("VFT_ROOFLINE_PEAK", raising=False)
    # the v5e calibration from docs/performance.md: practical 127 of
    # nominal 197, HBM 819 — matched under both spellings
    for kind in ("TPU v5 lite", "TPU v5e"):
        peak = roofline.peak_for_device(device_kind=kind, platform="tpu",
                                        measure=False)
        assert peak["peak_tflops"] == 127.0
        assert peak["nominal_tflops"] == 197.0
        assert peak["peak_gbps"] == 819.0
        assert peak["source"] == "registry"
    assert roofline.registry_peak("weird accelerator 9000") is None


def test_peak_microbench_fallback_and_cache(tmp_path, monkeypatch):
    monkeypatch.delenv("VFT_ROOFLINE_PEAK", raising=False)
    monkeypatch.setenv("VFT_ROOFLINE_CACHE_DIR", str(tmp_path))
    calls = []

    def fake_measure():
        calls.append(1)
        return {"peak_tflops": 0.123, "peak_gbps": 4.56}

    peak = roofline.peak_for_device(device_kind="FPGA mystery",
                                    platform="cpu",
                                    measure_fn=fake_measure)
    assert peak["peak_tflops"] == 0.123 and peak["source"] == "microbench"
    assert len(calls) == 1
    assert list(Path(tmp_path).glob("peak_*.json"))

    def exploding_measure():  # second resolve must hit the cache
        raise AssertionError("microbench re-ran despite a cached peak")

    cached = roofline.peak_for_device(device_kind="FPGA mystery",
                                      platform="cpu",
                                      measure_fn=exploding_measure)
    assert cached["peak_tflops"] == 0.123
    assert cached["source"] == "microbench (cached)"
    # measure=False never blocks on a matmul (the heartbeat contract)
    assert roofline.peak_for_device(device_kind="other unknown",
                                    platform="cpu", measure=False) is None


def test_peak_env_override(monkeypatch):
    monkeypatch.setenv("VFT_ROOFLINE_PEAK", "127,819")
    peak = roofline.peak_for_device(device_kind="anything")
    assert peak["peak_tflops"] == 127.0 and peak["peak_gbps"] == 819.0
    assert peak["source"] == "env"
    monkeypatch.setenv("VFT_ROOFLINE_PEAK", "bogus")
    with pytest.raises(ValueError, match="VFT_ROOFLINE_PEAK"):
        roofline.peak_for_device(device_kind="anything")


def test_measure_peak_small_probe():
    # a tiny real probe: the numbers must be positive and finite (the
    # 2048^3 default is the production calibration; n=128 keeps CI fast)
    m = roofline.measure_peak(n=128, band_elems=1 << 16, calls=2, trials=1)
    assert m["peak_tflops"] > 0 and np.isfinite(m["peak_tflops"])
    assert m["peak_gbps"] > 0 and np.isfinite(m["peak_gbps"])


# -- the four verdicts --------------------------------------------------------

def test_classify_all_four_verdicts():
    peak_tf, peak_gb = 100.0, 1000.0  # ridge at 100 FLOP/byte
    # device idle most of the wall: sandbagged by the host, whatever the
    # program's intensity
    assert roofline.classify(1e15, 1e12, forward_s=1.0, wall_s=10.0,
                             peak_tflops=peak_tf,
                             peak_gbps=peak_gb) == "host-bound"
    # device window explained by FLOPs at peak: saturated
    assert roofline.classify(8e14, 1e11, forward_s=10.0, wall_s=10.0,
                             peak_tflops=peak_tf,
                             peak_gbps=peak_gb) == "compute-bound"
    # below the ridge, window explained by bytes at peak bandwidth
    assert roofline.classify(1e12, 8e12, forward_s=10.0, wall_s=10.0,
                             peak_tflops=peak_tf,
                             peak_gbps=peak_gb) == "bandwidth-bound"
    # neither FLOPs nor bytes explain the window: fixed per-dispatch cost
    assert roofline.classify(1e12, 1e11, forward_s=10.0, wall_s=10.0,
                             peak_tflops=peak_tf,
                             peak_gbps=peak_gb) == "launch-overhead-bound"
    # undecidable inputs yield None, never a fabricated verdict
    assert roofline.classify(0.0, 0.0, 1.0, 1.0, peak_tf, peak_gb) is None
    assert roofline.classify(1e12, 1e11, 10.0, 10.0, None, None) is None


# -- schema round-trip --------------------------------------------------------

def test_roofline_json_schema_roundtrip(observer, tmp_path):
    runner = _toy_runner()
    runner(np.ones((4, 16), np.float32))
    doc = observer.close()
    path = tmp_path / roofline.ROOFLINE_FILENAME
    reloaded = json.loads(path.read_text())
    assert reloaded == json.loads(json.dumps(doc))  # atomic, complete
    assert roofline.validate_roofline(reloaded) == []
    assert set(reloaded) == set(roofline.ROOFLINE_FIELDS)
    fam = reloaded["families"]["toy"]
    assert set(fam) == set(roofline.FAMILY_FIELDS)
    assert set(fam["programs"][0]) == set(roofline.CARD_FIELDS)
    assert fam["verdict"] in roofline.VERDICTS + (None,)
    # the aggregator reads the same artifact back for vft-roofline
    agg = roofline.aggregate_rooflines(str(tmp_path))
    assert agg["families"]["toy"]["dispatches"] == 1
    assert any("toy" in ln for ln in roofline.render_table(agg))


# -- bench-history direction of goodness --------------------------------------

def test_bench_history_efficiency_series():
    import sys
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                           / "scripts"))
    import bench_history
    # mfu/effective_tflops are higher-is-better EVEN on overhead-named
    # parent rows (the series unit is the field name)
    assert not bench_history.lower_is_better("x [mfu]", "mfu")
    assert not bench_history.lower_is_better(
        "roofline accounting overhead (...) [effective_tflops]",
        "effective_tflops")
    assert bench_history.lower_is_better(
        "roofline accounting overhead (...)", "x wall-clock")
    rec = {"headline": {"metric": "r21d", "value": 1500.0,
                        "unit": "clips/sec/chip", "mfu": 0.61,
                        "effective_tflops": 78.0},
           "metrics": [{"metric": "s3d row", "value": 160.0,
                        "unit": "stacks/sec/chip", "mfu": 0.4}]}
    rows = bench_history._rows(rec)
    names = {r["metric"]: r for r in rows}
    assert names["r21d [mfu]"]["value"] == 0.61
    assert names["r21d [effective_tflops]"]["value"] == 78.0
    assert names["s3d row [mfu]"]["unit"] == "mfu"


# -- zero footprint when off --------------------------------------------------

@pytest.mark.parametrize("order", ["off_first"])
def test_roofline_off_zero_footprint_byte_identity(tmp_path, sample_video,
                                                   monkeypatch, order):
    """roofline=false leaves NO _roofline.json and the features are
    byte-identical to a roofline=true run — observation must never
    change what is computed."""
    from video_features_tpu.cli import main as cli_main
    monkeypatch.setenv("VFT_ROOFLINE_PEAK", "0.05,10")
    base = ["feature_type=resnet", "model_name=resnet18", "device=cpu",
            "allow_random_weights=true", "on_extraction=save_numpy",
            "batch_size=8", "extraction_total=6", "retry_attempts=1",
            f"video_paths=[{sample_video}]", f"tmp_path={tmp_path}/tmp"]
    cli_main(base + [f"output_path={tmp_path}/off", "roofline=false"])
    cli_main(base + [f"output_path={tmp_path}/on", "roofline=true"])
    off_dir = tmp_path / "off" / "resnet" / "resnet18"
    on_dir = tmp_path / "on" / "resnet" / "resnet18"
    assert not list((tmp_path / "off").rglob("_roofline*.json"))
    on_doc = json.loads(
        (on_dir / roofline.ROOFLINE_FILENAME).read_text())
    assert roofline.validate_roofline(on_doc) == []
    assert on_doc["families"]["resnet"]["verdict"] in roofline.VERDICTS
    off_npy = sorted(p.relative_to(off_dir) for p in off_dir.glob("*.npy"))
    on_npy = sorted(p.relative_to(on_dir) for p in on_dir.glob("*.npy"))
    assert off_npy == on_npy and off_npy
    for rel in off_npy:
        assert (off_dir / rel).read_bytes() == (on_dir / rel).read_bytes()
    # the off path left the process clean: no dangling observer
    assert roofline.active() is None
