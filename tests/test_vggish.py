"""VGGish: mel-frontend parity vs the reference numpy DSP, VGG net parity vs
a torch oracle, and E2E extraction from a synthesized wav."""
import importlib.util
import os
import wave

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import vggish as vggish_model  # noqa: E402
from video_features_tpu.ops import audio  # noqa: E402
from tests.torch_oracles import TorchVGGish  # noqa: E402

REF_MEL = "/root/reference/models/vggish/vggish_src/mel_features.py"


def _load_ref_mel():
    if not os.path.exists(REF_MEL):
        pytest.skip("reference mel_features not available")
    spec = importlib.util.spec_from_file_location("ref_mel", REF_MEL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_mel_frontend_matches_reference():
    ref = _load_ref_mel()
    rng = np.random.default_rng(0)
    wav = rng.normal(scale=0.1, size=48000)  # 3 s @ 16 kHz

    np.testing.assert_array_equal(audio.periodic_hann(400),
                                  ref.periodic_hann(400))
    np.testing.assert_array_equal(audio.frame(wav, 400, 160),
                                  ref.frame(wav, 400, 160))
    np.testing.assert_allclose(
        audio.stft_magnitude(wav, 512, 160, 400),
        ref.stft_magnitude(wav, fft_length=512, hop_length=160,
                           window_length=400), rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(
        audio.spectrogram_to_mel_matrix(64, 257, 16000, 125.0, 7500.0),
        ref.spectrogram_to_mel_matrix(
            num_mel_bins=64, num_spectrogram_bins=257,
            audio_sample_rate=16000, lower_edge_hertz=125.0,
            upper_edge_hertz=7500.0), rtol=1e-12, atol=1e-12)
    want_logmel = ref.log_mel_spectrogram(
        wav, audio_sample_rate=16000, log_offset=0.01,
        window_length_secs=0.025, hop_length_secs=0.010, num_mel_bins=64,
        lower_edge_hertz=125.0, upper_edge_hertz=7500.0)
    got_logmel = audio.log_mel_spectrogram(
        wav, audio_sample_rate=16000, log_offset=0.01,
        window_length_secs=0.025, hop_length_secs=0.010, num_mel_bins=64,
        lower_edge_hertz=125.0, upper_edge_hertz=7500.0)
    np.testing.assert_allclose(got_logmel, want_logmel, rtol=1e-12,
                               atol=1e-12)

    # example framing (vggish_input.py:60-71): 3 s -> 3 non-overlapping
    # 96-frame examples, NHWC with a trailing channel axis
    examples = audio.waveform_to_examples(wav, 16000)
    want = ref.frame(want_logmel, window_length=96, hop_length=96)
    assert examples.shape == (3, 96, 64, 1)
    np.testing.assert_allclose(examples[..., 0], want.astype(np.float32),
                               rtol=1e-6, atol=1e-6)

    # stereo mono-mix + resampling path: only shape/finite checks (the
    # reference's resampy is not installed; ours is scipy polyphase)
    stereo = rng.normal(scale=0.1, size=(44100 * 2, 2))
    ex2 = audio.waveform_to_examples(stereo, 44100)
    assert ex2.shape[1:] == (96, 64, 1) and np.isfinite(ex2).all()
    assert ex2.shape[0] == 2


def test_vggish_net_matches_torch_oracle():
    torch.manual_seed(0)
    oracle = TorchVGGish().eval()
    params = vggish_model.params_from_torch(oracle.state_dict())
    model = vggish_model.VGGish()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(3, 96, 64, 1)).astype(np.float32)
    with torch.no_grad():
        want = oracle(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    got = np.asarray(model.apply({"params": params}, jnp.asarray(x)))
    assert got.shape == want.shape == (3, 128)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_postprocess_matches_reference_math():
    rng = np.random.default_rng(2)
    emb = rng.normal(size=(5, 128)).astype(np.float32)
    vectors = rng.normal(size=(128, 128)).astype(np.float32)
    means = rng.normal(size=(128, 1)).astype(np.float32)
    # reference Postprocessor.postprocess (vggish_slim.py:63-92) in torch
    t = torch.mm(torch.from_numpy(vectors),
                 torch.from_numpy(emb).t() - torch.from_numpy(means)).t()
    t = torch.clamp(t, -2.0, 2.0)
    want = torch.squeeze(torch.round((t - (-2.0)) * (255.0 / 4.0))).numpy()
    got = vggish_model.postprocess(emb, vectors, means)
    np.testing.assert_allclose(got, want, atol=1e-5)


def _write_wav(path, data_i16, rate=16000, channels=1):
    with wave.open(str(path), "wb") as w:
        w.setnchannels(channels)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(data_i16.tobytes())


def test_read_wav_roundtrip(tmp_path):
    rng = np.random.default_rng(3)
    mono = (rng.uniform(-0.5, 0.5, 1600) * 32768).astype("<i2")
    p = tmp_path / "mono.wav"
    _write_wav(p, mono)
    data, rate = audio.read_wav(str(p))
    assert rate == 16000 and data.shape == (1600,)
    np.testing.assert_allclose(data, mono / 32768.0)

    stereo = (rng.uniform(-0.5, 0.5, (800, 2)) * 32768).astype("<i2")
    p2 = tmp_path / "stereo.wav"
    _write_wav(p2, stereo.reshape(-1), channels=2)
    data2, _ = audio.read_wav(str(p2))
    assert data2.shape == (800, 2)


def test_end_to_end_extraction_from_wav(tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.vggish import ExtractVGGish

    # 2.5 s of 440 Hz tone -> 2 full 0.96 s examples
    t = np.arange(int(16000 * 2.5)) / 16000.0
    tone = (0.4 * np.sin(2 * np.pi * 440 * t) * 32767).astype("<i2")
    wav_path = tmp_path / "tone.wav"
    _write_wav(wav_path, tone)

    cfg = load_config("vggish", {
        "video_paths": str(wav_path), "device": "cpu",
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractVGGish(cfg)
    feats = ex._extract(str(wav_path))
    assert ex.output_feat_keys == ["vggish"]
    assert feats["vggish"].shape == (2, 128)
    assert (tmp_path / "out" / "vggish" / "tone_vggish.npy").exists()


def test_device_frontend_matches_numpy_dsp():
    """logmel_examples_jnp (the frontend fused into the jitted forward under
    frontend=device) must reproduce the numpy/reference DSP."""
    import jax
    rng = np.random.default_rng(4)
    wav = rng.normal(scale=0.1, size=50000)
    want = audio.waveform_to_examples(wav, 16000)
    chunks = audio.chunk_waveform(wav, 16000)
    assert chunks.shape == (want.shape[0], audio.EXAMPLE_CHUNK_SAMPLES)
    got = np.asarray(jax.jit(audio.logmel_examples_jnp)(chunks))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)
    # short input: no complete example -> empty, consistent with the host path
    assert audio.chunk_waveform(wav[:10000], 16000).shape[0] == \
        audio.waveform_to_examples(wav[:10000], 16000).shape[0]


def test_end_to_end_device_frontend_matches_host(tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.vggish import ExtractVGGish

    rng = np.random.default_rng(5)
    noise = (0.3 * rng.standard_normal(int(16000 * 2.5)) * 32767) \
        .clip(-32768, 32767).astype("<i2")
    wav_path = tmp_path / "noise.wav"
    _write_wav(wav_path, noise)

    def run(frontend, sub):
        cfg = load_config("vggish", {
            "video_paths": str(wav_path), "device": "cpu",
            "frontend": frontend, "allow_random_weights": True,
            "output_path": str(tmp_path / sub / "o"),
            "tmp_path": str(tmp_path / sub / "t"),
        })
        sanity_check(cfg)
        return ExtractVGGish(cfg).extract(str(wav_path))["vggish"]

    host = run("host", "h")
    device = run("device", "d")
    assert host.shape == device.shape == (2, 128)
    np.testing.assert_allclose(device, host, atol=1e-3, rtol=1e-3)
