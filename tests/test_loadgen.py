"""The traffic-scenario observatory (loadgen.py / `vft-loadgen`,
ISSUE 17): deterministic replay, composed-stream independence, and the
recorded-drill verdict artifact.

Three layers, cheapest first:
  - pure units: spec validation fails loudly at load; the arrival-rate
    shapes; Zipf skew actually skews;
  - the replay contract: same YAML + same seed => bit-identical journal
    bytes across runs and across process restarts, per-scenario streams
    independent under composition (A's lines identical whether A runs
    alone or with B), every journal record valid against
    telemetry/loadgen_event.schema.json;
  - one end-to-end drill over real HTTP (GatewayServer + ServeLoop with
    the video step stubbed): _scenario.json validates against its
    schema, tallies reconcile with the journal, the attainment curve
    renders in vft-fleet, vft-audit stays green.

The PR's satellite contracts are pinned here too: expired requests
count against SLO attainment (serve.py), the 429 Retry-After includes
weighted-fair-share queue backlog on top of token refill (gateway.py),
and retained history samples carry per-tenant attainment (history.py).

The real-extraction CI twin is scripts/check_scenario_smoke.py.
"""
import json
import threading
import time
from collections import deque
from pathlib import Path

import pytest

from video_features_tpu import loadgen, serve
from video_features_tpu.gateway import GatewayServer
from video_features_tpu.loadgen import (DrillRunner, content_key,
                                        load_scenario, offered_events,
                                        synthesize_corpus,
                                        write_tenant_table)
from video_features_tpu.telemetry.jsonl import read_jsonl

pytestmark = pytest.mark.quick

REPO = Path(__file__).resolve().parent.parent

SCN_A = """
scenario: alpha_stream
seed: 101
duration_s: 12
clock: virtual
speedup: 40
# generous: at x40 a 0.02s wall poll tick is 0.8 VIRTUAL seconds, so
# queueing granularity alone costs whole virtual seconds of wait
slo_s: 60
curve_windows: 4
arrivals:
  process: constant
  rate_rps: 2.0
corpus:
  n_items: 5
  zipf_s: 1.1
tenants:
  alpha:
    key: alpha-k
    share: 1.0
    priority: high
    rate_rps: 10
    burst: 40
    max_inflight: 32
objectives:
  - min_admitted_pct: 90
  - min_attainment_pct: 80
"""

SCN_B = """
scenario: beta_stream
seed: 101
duration_s: 12
clock: virtual
speedup: 40
arrivals:
  process: burst
  rate_rps: 0.5
  burst:
    period_s: 6
    length_s: 2
    rate_rps: 4.0
corpus:
  n_items: 3
  zipf_s: 0.0
tenants:
  beta:
    key: beta-k
    share: 1.0
    priority: low
    rate_rps: 10
    burst: 40
    max_inflight: 32
"""


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def _schema(name):
    p = REPO / "video_features_tpu" / "telemetry" / name
    return json.loads(p.read_text())


# -- spec validation ----------------------------------------------------------

def test_load_scenario_rejects_malformed(tmp_path):
    cases = [
        ("scenario: Bad-Name\nseed: 1\ntenants:\n  a: {key: k}\n",
         "scenario"),
        ("scenario: ok\ntenants:\n  a: {key: k}\n", "seed"),
        ("scenario: ok\nseed: 1\n", "tenant"),
        ("scenario: ok\nseed: 1\ntenants:\n  a: {key: k}\n"
         "arrivals: {process: lumpy}\n", "process"),
        ("scenario: ok\nseed: 1\ntenants:\n  a: {key: k,"
         " priority: urgent}\n", "priority"),
        ("scenario: ok\nseed: 1\ntenants:\n  a: {key: k,"
         " timeout_s: [5, 1]}\n", "timeout_s"),
        ("scenario: ok\nseed: 1\ntenants:\n  a: {key: k}\n"
         "objectives:\n  - min_sparkle: 1\n", "unknown"),
        ("scenario: ok\nseed: 1\ntenants:\n  a: {key: k}\n"
         "objectives:\n  - tenant: ghost\n    min_expired: 1\n",
         "ghost"),
    ]
    for i, (text, needle) in enumerate(cases):
        p = _write(tmp_path, f"bad{i}.yml", text)
        with pytest.raises(ValueError, match=needle):
            load_scenario(p)


def test_rate_shapes(tmp_path):
    spec = load_scenario(_write(tmp_path, "b.yml", SCN_B))
    # floor between trains, floor+burst inside one
    assert loadgen._rate_at(spec, 3.0) == pytest.approx(0.5)
    assert loadgen._rate_at(spec, 1.0) == pytest.approx(4.5)
    assert loadgen._max_rate(spec) == pytest.approx(4.5)


def test_zipf_skew_concentrates_on_hot_ranks(tmp_path):
    spec = load_scenario(_write(tmp_path, "a.yml", SCN_A))
    events = [e for e in offered_events(spec) if e["event"] == "request"]
    hot = content_key(spec, 0)
    n_hot = sum(1 for e in events for v in e["videos"] if v == hot)
    total = sum(len(e["videos"]) for e in events)
    # zipf s=1.1 over 5 items: rank 0 carries ~44% of draws; uniform
    # would be 20% — assert clear concentration, not the exact share
    assert n_hot / total > 0.30


# -- the replay contract ------------------------------------------------------

def test_dry_run_journal_bit_identical(tmp_path):
    spec_path = _write(tmp_path, "a.yml", SCN_A)
    outs = []
    for d in ("r1", "r2"):
        rc = loadgen.loadgen_main([
            spec_path, "--spool", str(tmp_path / "spool"),
            "--out", str(tmp_path / d), "--host-id", "h", "--dry-run"])
        assert rc == 0
        outs.append((tmp_path / d / "_loadgen_h.jsonl").read_bytes())
    assert outs[0] == outs[1]
    assert outs[0]  # not vacuously identical


def test_journal_records_validate_against_schema(tmp_path):
    jsonschema = pytest.importorskip("jsonschema")
    spec = load_scenario(_write(tmp_path, "a.yml", SCN_A))
    schema = _schema("loadgen_event.schema.json")
    events = offered_events(spec)
    assert events[0]["event"] == "begin"
    assert events[-1]["event"] == "end"
    assert events[-1]["offered"] == len(events) - 2
    for ev in events:
        jsonschema.validate(ev, schema)
        assert set(ev) <= set(loadgen.LOADGEN_FIELDS)
    # ids and ranks are scenario-scoped and sequential
    reqs = [e for e in events if e["event"] == "request"]
    assert [e["id"] for e in reqs] == \
        [f"alpha_stream-{i + 1:05d}" for i in range(len(reqs))]


def test_composed_scenarios_leave_each_stream_untouched(tmp_path):
    """The independence half of the replay contract: scenario A's
    journal lines are byte-identical whether A runs alone or composed
    with B on the same timeline — every random draw comes from a
    scenario-scoped stream, so B cannot perturb A."""
    a = load_scenario(_write(tmp_path, "a.yml", SCN_A))
    b = load_scenario(_write(tmp_path, "b.yml", SCN_B))
    solo = [json.dumps(e, sort_keys=True) for e in offered_events(a)]
    composed = sorted(
        (e for s in (a, b) for e in offered_events(s)),
        key=lambda e: (e["t"], e["scenario"], e["seq"]))
    from_composed = [json.dumps(e, sort_keys=True) for e in composed
                     if e["scenario"] == "alpha_stream"]
    assert from_composed == solo
    # and B did contribute its own events to the composition
    assert any(e["scenario"] == "beta_stream" for e in composed)


def test_write_tenant_table_scales_rates_only(tmp_path):
    import yaml
    a = load_scenario(_write(tmp_path, "a.yml", SCN_A))
    out = tmp_path / "tenants.yml"
    write_tenant_table([a], str(out), 40.0)
    doc = yaml.safe_load(out.read_text())
    t = doc["tenants"]["alpha"]
    assert t["rate_rps"] == pytest.approx(400.0)  # 10 virtual x 40
    assert t["burst"] == 40 and t["max_inflight"] == 32  # counts pass
    assert t["key"] == "alpha-k" and t["priority"] == "high"


def test_synthesize_corpus_items_distinct_and_stable(tmp_path):
    a = load_scenario(_write(tmp_path, "a.yml", SCN_A))
    c1 = synthesize_corpus(str(tmp_path / "corpus"), [a])
    c2 = synthesize_corpus(str(tmp_path / "corpus"), [a])
    assert c1 == c2 and len(c1) == 5
    blobs = {Path(p).read_bytes() for p in c1.values()}
    assert len(blobs) == 5  # content-addressed planes see 5 items


# -- satellite: expired requests count against SLO attainment -----------------

def _make_loop(tmp_path, **over):
    from video_features_tpu.config import load_config, sanity_check
    spool = tmp_path / "spool"
    cfg = load_config("resnet", dict({
        "model_name": "resnet18", "device": "cpu",
        "allow_random_weights": True, "on_extraction": "save_numpy",
        "extraction_total": 6, "batch_size": 8, "cache": False,
        "spool_dir": str(spool), "serve_poll_interval_s": 0.05,
        "metrics_interval_s": 1, "serve_slo_s": 60.0,
        "output_path": str(tmp_path / "out"),
        "tmp_path": str(tmp_path / "tmp")}, **over))
    sanity_check(cfg, require_videos=False)
    return serve.ServeLoop(cfg, out_root=str(tmp_path / "out")), str(spool)


def test_expired_request_is_an_slo_violation(tmp_path):
    """Satellite 1: a deadline-expired request is answered-and-violated
    for attainment purposes — without this, deadline-heavy load makes
    the published attainment overstate health (only the survivors were
    being counted)."""
    import os
    loop, spool = _make_loop(tmp_path)
    loop._run_one_video = lambda v: {"resnet": "done"}
    rid = serve.submit_request(spool, ["/v.mp4"], request_id="t1-exp",
                              deadline=time.time() - 0.1)
    src = Path(spool) / "requests" / f"{rid}.json"
    dst = Path(loop.claim_dir) / f"{rid}.json"
    os.rename(src, dst)
    loop._process(str(dst))
    assert serve.read_terminal(spool, rid)["status"] == "deadline_exceeded"
    with loop._state_lock:
        assert loop._answered == 1
        assert loop._slo_violations == 1
    # the heartbeat block derives 0% attainment from one expiry
    hb_slo = loop._serve_section()["slo"]
    assert hb_slo["requests"] == 1 and hb_slo["violations"] == 1
    assert hb_slo["attainment_pct"] == 0.0
    loop.recorder.close()


# -- satellite: Retry-After includes fair-share queue backlog -----------------

TENANTS_YML = """
tenants:
  alpha:
    key: alpha-k
    rate_rps: 100
    burst: 100
    max_inflight: 8
    priority: high
  beta:
    key: beta-k
    rate_rps: 0.5
    burst: 1
    max_inflight: 2
    priority: low
"""


def test_retry_after_includes_queue_backlog(tmp_path):
    """Satellite 2: refill alone tells a client when it has a TOKEN,
    not when the edge queue has ROOM. Under backlog, the 429's
    Retry-After must grow by the class's weighted-fair-share drain
    estimate, or refill-timed retries thunder back into a full queue.
    (The empty-queue case — Retry-After == refill exactly — is pinned
    by tests/test_gateway.py.)"""
    (tmp_path / "tenants.yml").write_text(TENANTS_YML)
    g = GatewayServer({"spool_dir": str(tmp_path / "spool"),
                       "gateway_tenants": str(tmp_path / "tenants.yml"),
                       "gateway_poll_interval_s": 0.25,
                       "gateway_spool_bound": 64})
    try:
        beta = g.tenants["beta-k"]
        assert g._backlog_wait_s("low") == 0.0  # computed, not assumed
        code, _body, _h = g.admit(beta, ["/v.mp4"], None)
        assert code == 202  # burst=1 consumed; next is a rate-429

        # craft a backlog: 10 high + 128 more low queued at the edge
        # (plus the one just admitted — no pump is draining). low's
        # fair share of the 64-per-tick budget is 1/(4+1) -> 12.8, so
        # draining 129 takes ~10 ticks x 0.25s = ~2.5s
        with g._lock:
            g._queues.setdefault("high", deque()).extend(
                {"id": f"h{i}"} for i in range(10))
            g._queues.setdefault("low", deque()).extend(
                {"id": f"l{i}"} for i in range(128))
        assert g._backlog_wait_s("low") == pytest.approx(2.52, rel=0.05)

        code, body, hdrs = g.admit(beta, ["/v.mp4"], None)
        assert code == 429
        # refill-only would be ceil((1 - tokens)/0.5) <= 2; the backlog
        # term pushes past it
        assert int(hdrs["Retry-After"]) >= 4
        assert float(body["retry_after_s"]) >= 4
    finally:
        g.httpd.server_close()
        g.recorder.close()


# -- satellite: retained history carries per-tenant attainment ----------------

def test_history_sample_carries_tenant_attainment():
    from video_features_tpu.telemetry.history import sample_from_heartbeat
    hb = {"time": 123.0, "host_id": "h", "run_id": "r",
          "serve": {"tenants": {
              "alpha": {"requests": 20, "violations": 1, "rejects": 0},
              "beta": {"requests": 0, "violations": 0, "rejects": 2}}}}
    s = sample_from_heartbeat(hb)
    assert s["tenants"]["alpha"] == {"requests": 20, "violations": 1,
                                     "attainment_pct": 95.0}
    # zero-request tenants report None, not a fake 100%
    assert s["tenants"]["beta"]["attainment_pct"] is None


# -- the end-to-end drill -----------------------------------------------------

def test_drill_end_to_end_verdict_artifact_and_fleet_render(tmp_path):
    spec = load_scenario(_write(tmp_path, "a.yml", SCN_A))
    spool = tmp_path / "spool"
    write_tenant_table([spec], str(tmp_path / "tenants.yml"),
                       spec["speedup"])
    loop, _sp = _make_loop(tmp_path, serve_poll_interval_s=0.02)
    loop._run_one_video = lambda v: time.sleep(0.002) or {"resnet": "done"}
    t = threading.Thread(target=loop.run, daemon=True)
    t.start()
    gw = GatewayServer({"spool_dir": str(spool),
                        "gateway_tenants": str(tmp_path / "tenants.yml"),
                        "gateway_poll_interval_s": 0.05,
                        "metrics_interval_s": 1}).start()
    try:
        corpus = synthesize_corpus(str(tmp_path / "corpus"), [spec])
        runner = DrillRunner(
            [spec], str(spool), f"http://127.0.0.1:{gw.port}",
            corpus=corpus, audit_root=str(tmp_path), host_id="lg-e2e",
            drain_timeout_s=60.0)
        report = runner.run()
    finally:
        gw.stop()
        loop.stop()
        t.join(timeout=60)

    # the verdict artifact is on disk and validates against its schema
    art = json.loads((spool / "_scenario.json").read_text())
    jsonschema = pytest.importorskip("jsonschema")
    jsonschema.validate(art, _schema("scenario.schema.json"))
    assert art == report
    assert art["verdict"] == "PASS", art["objectives"]
    assert art["audit"]["pass"] is True

    # tallies reconcile with the deterministic journal
    journal = list(read_jsonl(spool / "_loadgen_lg-e2e.jsonl"))
    offered = sum(1 for r in journal if r.get("event") == "request")
    assert art["offered"] == offered > 0
    assert art["admitted"] + art["rejected"] + art["shed"] \
        + art["errors"] == offered
    assert art["admitted"] == art["completed"] + art["expired"]
    assert art["scenarios"][0]["offered"] == offered
    assert art["latency"]["wait"]["p95"] is not None

    # the curve covers the timeline and carries per-window attainment
    assert len(art["curve"]) == spec["curve_windows"]
    assert art["curve"][-1]["t1"] == spec["duration_s"]
    vals = [w["tenants"].get("alpha", {}).get("attainment_pct")
            for w in art["curve"]]
    assert any(v is not None for v in vals)

    # the drill renders in vft-fleet and exports vft_scenario_* gauges
    from video_features_tpu.fleet_report import (aggregate,
                                                 build_prom_dump, render)
    agg = aggregate(str(spool))
    assert any(s.get("scenario") == "alpha_stream"
               for s in agg["scenarios"])
    text = "\n".join(render(agg))
    assert "== scenarios ==" in text and "curve=" in text
    names = {s["name"] for s in build_prom_dump(agg)["series"]}
    assert {"vft_scenario_pass", "vft_scenario_offered",
            "vft_scenario_attainment_pct"} <= names

    # a fresh audit over the whole tree stays green (invariant 12
    # included: artifact/journal consistency)
    from video_features_tpu.audit import audit_run
    ok, violations, _notes = audit_run(str(tmp_path),
                                       expect_complete=True)
    assert ok, "\n".join(violations)


def test_audit_flags_inconsistent_scenario_artifact(tmp_path):
    """Invariant 12 bites: an artifact claiming traffic the journal
    doesn't record, or PASS over a failed audit, FAILS vft-audit."""
    from video_features_tpu.audit import audit_run
    from video_features_tpu.telemetry.jsonl import (append_jsonl,
                                                    write_json_atomic)
    spool = tmp_path / "spool"
    for d in ("requests", "claimed", "done", "expired", "inbox"):
        (spool / d).mkdir(parents=True)
    tb = {"offered": 2, "admitted": 1, "completed": 1, "expired": 0,
          "rejected": 1, "shed": 0, "errors": 0, "violations": 0,
          "attainment_pct": 100.0}
    art = {"schema": "vft.scenario/1", "time": 1.0, "scenario": "s",
           "scenarios": [{"name": "s", "seed": 1, "spec_sha": "x"}],
           "clock": "virtual", "speedup": 40.0, "duration_s": 10.0,
           "slo_s": None, "host_id": "h", "journal": "_loadgen_h.jsonl",
           "offered": 2, "admitted": 1, "completed": 1, "expired": 0,
           "rejected": 1, "shed": 0, "errors": 0, "tenants": {"a": tb},
           "latency": {"unit": "virtual_s",
                       "wait": {"p50": None, "p95": None, "p99": None},
                       "service": {"p50": None, "p95": None,
                                   "p99": None}},
           "curve": [], "history": None,
           "alerts": {"page": 0, "ticket": 0},
           "audit": {"pass": False, "violations": 3},
           "objectives": [], "verdict": "PASS"}
    write_json_atomic(spool / "_scenario.json", art)
    # journal records only ONE request event, not the claimed two
    append_jsonl(str(spool / "_loadgen_h.jsonl"),
                 {"schema": "vft.loadgen_event/1", "scenario": "s",
                  "seed": 1, "seq": 1, "t": 0.1, "event": "request",
                  "id": "s-00001", "tenant": "a", "klass": "high",
                  "videos": ["k"], "timeout_s": None, "slow_bps": None})
    ok, violations, _ = audit_run(str(tmp_path))
    assert not ok
    assert any("records 1 request event" in v for v in violations)
    assert any("PASS over a recorded audit failure" in v
               for v in violations)
