"""Stage profiler unit tests + CLI integration."""
import time

import numpy as np
import pytest

from video_features_tpu.utils.profiling import StageProfiler, TraceCapture


def test_disabled_profiler_records_nothing():
    p = StageProfiler()
    with p.stage("x"):
        pass
    assert p.snapshot() == {}
    assert "no stages" in p.summary()


def test_stage_accumulation_and_summary():
    p = StageProfiler()
    p.enabled = True
    for _ in range(3):
        with p.stage("decode"):
            time.sleep(0.01)
    with p.stage("forward"):
        time.sleep(0.03)
    snap = p.snapshot()
    assert snap["decode"][1] == 3
    assert snap["forward"][1] == 1
    assert snap["decode"][0] >= 0.03
    s = p.summary("t")
    assert "decode" in s and "forward" in s and "%" in s
    p.reset()
    assert p.snapshot() == {}


def test_stage_records_on_exception():
    p = StageProfiler()
    p.enabled = True
    with pytest.raises(ValueError):
        with p.stage("boom"):
            raise ValueError
    assert p.snapshot()["boom"][1] == 1


def test_stage_thread_safety():
    import threading
    p = StageProfiler()
    p.enabled = True

    def work():
        for _ in range(200):
            with p.stage("s"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert p.snapshot()["s"][1] == 800


def test_trace_capture_noop_without_dir():
    with TraceCapture(None):
        pass  # must not touch jax.profiler


def test_cli_profile_flag_prints_breakdown(tmp_path, sample_video, capsys):
    from video_features_tpu import cli
    from video_features_tpu.utils.profiling import profiler
    try:
        cli.main([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_fps=1", "allow_random_weights=true",
            "on_extraction=save_numpy", f"output_path={tmp_path}/out",
            f"tmp_path={tmp_path}/tmp", f"video_paths={sample_video}",
            "profile=true",
        ])
        out = capsys.readouterr().out
        assert "[profile: resnet" in out
        for stage in ("decode", "forward", "write"):
            assert stage in out, f"missing stage {stage} in breakdown"
    finally:
        profiler.enabled = False
        profiler.reset()
