"""Ahead-of-time weight conversion: registry coverage + .pth -> .msgpack
round trip through the scripts/convert_weights.py machinery."""
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from video_features_tpu.weights import store  # noqa: E402
from video_features_tpu.weights.converters import registry  # noqa: E402
from tests.torch_oracles import TorchResNet, randomize_bn_stats  # noqa: E402


def test_registry_covers_every_hub_key():
    reg = registry()
    missing = set(store.HUB_FILENAMES) - set(reg) - {"vggish_pca"}
    assert not missing, f"no converter for: {sorted(missing)}"


def test_convert_script_roundtrip(tmp_path, monkeypatch):
    oracle = TorchResNet(variant="resnet18").eval()
    randomize_bn_stats(oracle)
    ckpt = tmp_path / "resnet18-f37072fd.pth"
    torch.save(oracle.state_dict(), ckpt)

    env = {"VFT_WEIGHTS_DIR": str(tmp_path / "w"), "JAX_PLATFORMS": "cpu"}
    script = Path(__file__).resolve().parent.parent / "scripts" / \
        "convert_weights.py"
    out = subprocess.run(
        [sys.executable, str(script), "--model-key", "resnet18",
         "--ckpt", str(ckpt)],
        capture_output=True, text=True, env={**__import__("os").environ,
                                             **env})
    assert out.returncode == 0, out.stderr
    msgpack = tmp_path / "w" / "resnet18.msgpack"
    assert msgpack.exists()

    # the cached tree must round-trip bit-exactly vs direct conversion
    init_fn, convert_fn = registry()["resnet18"]
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "w"))
    loaded = store.load_msgpack(init_fn(), msgpack)
    direct = convert_fn(oracle.state_dict())
    want = direct["backbone"]["conv1"]["kernel"]
    got = loaded["backbone"]["conv1"]["kernel"]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
