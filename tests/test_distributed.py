"""Real two-process ``jax.distributed`` extraction (the only scale-out branch
tests could not cover in-unit).

Two actual OS processes connect to one coordinator, run the REAL CLI with
``distributed=true`` into ONE shared output directory, and exit. Asserts:

  - both processes see ``process_count() == 2`` (the distributed runtime
    actually formed, not two independent singletons);
  - the work list is split disjointly and completely: every video's features
    exist exactly once in the shared dir, and each worker's runtime-derived
    shard (``local_shard_of_list`` under the real ``jax.process_index()``)
    matches the deterministic expectation computed in-test;
  - each worker's own shard was fully written before it exited;
  - clean exits (rc 0), no output corruption (files load).

The CLI's distributed branch (cli.py: jax.distributed.initialize before any
backend touch) is entered by both workers; the test driver pre-initializes
with explicit coordinator/process args — the branch's already-initialized
guard must then no-op instead of raising.

Subprocess logs go to files, never PIPEs (an un-drained PIPE once deadlocked
a SIGTERM test on this host — see tests/test_multihost.py).
"""
import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.parallel.mesh import local_shard_of_list

N_VIDEOS = 6
TIMEOUT_S = 480


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_WORKER = textwrap.dedent("""
    import sys
    from pathlib import Path
    sys.path.insert(0, {repo!r})
    import jax
    # hard-pin cpu BEFORE distributed init: sitecustomize on some hosts
    # re-points jax at an accelerator plugin after env vars are read, and a
    # 2-process probe must never race for the real TPU chip
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address={coord!r},
                               num_processes=2, process_id={pid})
    assert jax.process_count() == 2, jax.process_count()
    from video_features_tpu.cli import main
    main([
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "distributed=true", "allow_random_weights=true", "batch_size=16",
        "extraction_fps=2", "on_extraction=save_numpy",
        "output_path={out}", "tmp_path={tmp}",
        "file_with_video_paths={listfile}",
    ])
    # report the shard the real runtime (process_index) assigned this worker,
    # and require its own outputs to already exist at exit
    from video_features_tpu.parallel.mesh import local_shard_of_list
    videos = Path({listfile!r}).read_text().split()
    mine = local_shard_of_list(videos)
    feat_dir = Path({out!r}) / "resnet" / "resnet18"
    for v in mine:
        f = feat_dir / (Path(v).stem + "_resnet.npy")
        assert f.exists(), f
    print("SHARD", {pid}, ",".join(sorted(Path(v).stem for v in mine)))
    print("WORKER_DONE", {pid}, jax.process_count())
""")


def test_two_process_distributed_extraction(sample_video, tmp_path):
    videos = []
    for i in range(N_VIDEOS):
        dst = tmp_path / f"v_dist_{i:03d}.mp4"
        dst.write_bytes(Path(sample_video).read_bytes())
        videos.append(str(dst))
    listfile = tmp_path / "videos.txt"
    listfile.write_text("\n".join(videos) + "\n")

    # expected deterministic split (the exact hashing the workers run)
    shards = [local_shard_of_list(videos, host_id=i, num_hosts=2)
              for i in range(2)]
    assert sorted(shards[0] + shards[1]) == sorted(videos)
    assert not (set(shards[0]) & set(shards[1]))
    # the fixed stem names make both shards non-empty; if this ever trips,
    # rename the copies rather than weakening the assert
    assert shards[0] and shards[1]

    out = tmp_path / "out"
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    procs, logs = [], []
    for pid in range(2):
        script = _WORKER.format(
            repo=str(Path(__file__).resolve().parent.parent),
            coord=coord, pid=pid, out=str(out),
            tmp=str(tmp_path / f"wtmp_{pid}"), listfile=str(listfile))
        log = open(tmp_path / f"worker_{pid}.log", "w")
        logs.append(log)
        procs.append(subprocess.Popen([sys.executable, "-c", script],
                                      stdout=log, stderr=subprocess.STDOUT,
                                      env=env))
    try:
        for p in procs:
            assert p.wait(timeout=TIMEOUT_S) == 0, _tail(tmp_path)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()

    # every video extracted exactly once into the shared dir, loadable
    feat_dir = out / "resnet" / "resnet18"
    for v in videos:
        stem = Path(v).stem
        f = feat_dir / f"{stem}_resnet.npy"
        assert f.exists(), f"missing features for {stem}: {_tail(tmp_path)}"
        arr = np.load(f)  # corruption check: must load
        assert arr.ndim == 2 and arr.shape[1] == 512

    # runtime-derived shards match the deterministic expectation
    for pid in range(2):
        logtext = (tmp_path / f"worker_{pid}.log").read_text()
        assert f"WORKER_DONE {pid} 2" in logtext, logtext[-2000:]
        want = ",".join(sorted(Path(v).stem for v in shards[pid]))
        assert f"SHARD {pid} {want}" in logtext, (want, logtext[-2000:])


def _tail(tmp_path):
    return "\n".join(
        f"--- worker {i} ---\n" +
        (tmp_path / f"worker_{i}.log").read_text()[-1500:]
        for i in range(2))
