# Regular package marker. Several test modules import the shared torch
# oracles as `tests.torch_oracles`; other modules put /root/reference on
# sys.path ahead of the repo root, whose own `tests/` directory would then
# shadow this one as a *namespace* package (no torch_oracles) depending on
# import order. A regular package always wins over namespace candidates, so
# this file pins the resolution regardless of path/import order.
