"""Output-health pillar: feature digests, the non-finite POISON gate,
run comparison, bench history, artifact sha events and the report's
fail-on-failures gate (ISSUE 5)."""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.telemetry import health
from video_features_tpu.telemetry import jsonl as tjsonl
from video_features_tpu.utils import faults, sinks

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parent.parent
SCRIPTS = REPO_ROOT / "scripts"


# -- digests ----------------------------------------------------------------

def test_digest_array_stats_and_counts():
    a = np.array([[1.0, -2.0], [3.0, 0.0]], dtype=np.float32)
    r = health.digest_array("k", a, video="v.mp4", feature_type="resnet")
    assert r["schema"] == health.SCHEMA_VERSION
    assert r["shape"] == [2, 2] and r["dtype"] == "float32"
    assert r["elems"] == 4 and r["nan"] == 0 and r["inf"] == 0
    assert r["min"] == -2.0 and r["max"] == 3.0
    assert r["mean"] == pytest.approx(0.5)
    assert r["l2"] == pytest.approx(np.sqrt(14.0))
    assert set(r) == set(health.HEALTH_FIELDS)
    assert health.validate_health(r) == []


def test_digest_nonfinite_counts_and_finite_stats():
    a = np.ones((3, 3), dtype=np.float32)
    a[0, 0] = np.nan
    a[1, 1] = np.inf
    a[2, 2] = -np.inf
    r = health.digest_array("k", a, video="v", feature_type="raft")
    assert r["nan"] == 1 and r["inf"] == 2
    # stats cover the finite values only — NaN must not poison them
    assert r["min"] == r["max"] == r["mean"] == 1.0
    assert health.validate_health(r) == []


def test_content_signature_quantization_tolerance():
    rng = np.random.default_rng(3)
    # bucket-center values: the signature's tolerance guarantee is
    # probabilistic (a value already straddling a SIG_GRID bucket edge
    # can flip on any jitter — compare_runs' stat bands are the
    # authoritative drift measure), so the deterministic test pins the
    # center-of-bucket case
    a = (rng.integers(-200, 200, (16, 64)) *
         health.SIG_GRID).astype(np.float32)
    sig = health.content_signature(a)
    # sub-tolerance jitter (bf16-noise scale) hashes identically
    assert health.content_signature(a + 1e-5) == sig
    # a shift past the value tier's atol=1e-2 changes it
    assert health.content_signature(a + 0.063) != sig
    # and so does a NaN
    b = a.copy()
    b[0, 0] = np.nan
    assert health.content_signature(b) != sig
    # shape participates: a reshape of identical bytes is a different sig
    assert health.content_signature(a.reshape(32, 32)) != sig


def test_digest_features_appends_jsonl(tmp_path):
    feats = {"feat": np.arange(6, dtype=np.float32),
             "logits": np.ones((2, 3), dtype=np.float32)}
    recs = health.digest_features(feats, "v.mp4", "s3d", str(tmp_path))
    assert len(recs) == 2
    on_disk = list(tjsonl.read_jsonl(tmp_path / health.HEALTH_FILENAME))
    assert [r["key"] for r in on_disk] == ["feat", "logits"]
    assert all(health.validate_health(r) == [] for r in on_disk)


# -- the non-finite gate routes through the faults taxonomy -----------------

def test_check_features_raises_poison_and_journals(tmp_path):
    bad = np.ones(4, dtype=np.float32)
    bad[2] = np.nan
    with pytest.raises(health.NonFiniteFeatureError) as ei:
        health.check_features({"feat": bad}, "v.mp4", "raft", str(tmp_path))
    assert faults.classify(ei.value) == faults.POISON
    # the digest of the bad tensor was journaled BEFORE the raise
    recs = list(tjsonl.read_jsonl(tmp_path / health.HEALTH_FILENAME))
    assert recs and recs[0]["nan"] == 1

    # end to end: safe_extract quarantines it via the journal
    journal = faults.FailureJournal(str(tmp_path))

    def extract(video_path):
        health.check_features({"feat": bad}, video_path, "raft",
                              str(tmp_path))
        return {"feat": bad}

    policy = faults.RetryPolicy(attempts=2, backoff_s=0.0,
                                sleep=lambda s: None)
    assert sinks.safe_extract(extract, "v.mp4", policy=policy,
                              journal=journal) == "error"
    assert journal.poison_record("v.mp4") is not None  # quarantined
    assert sinks.safe_extract(extract, "v.mp4", policy=policy,
                              journal=journal) == "quarantined"


def test_worker_forwarded_nonfinite_string_classifies_poison():
    # the decode-subprocess protocol ships f"{type}: {msg}" RuntimeErrors
    e = RuntimeError("NonFiniteFeatureError: non-finite feature values")
    assert faults.classify(e) == faults.POISON


# -- artifact digests (hash-before-rename) in sinks -------------------------

def test_writers_return_bytes_and_sha_on_request(tmp_path):
    arr = np.arange(12, dtype=np.float32)
    npy = str(tmp_path / "a_feat.npy")
    assert sinks.write_numpy(npy, arr) is None  # default: no digest work
    info = sinks.write_numpy(npy, arr, want_digest=True)
    assert info is not None and info[0] == os.path.getsize(npy)
    import hashlib
    assert info[1] == hashlib.sha256(open(npy, "rb").read()).hexdigest()
    np.testing.assert_array_equal(sinks.load_numpy(npy), arr)

    pkl = str(tmp_path / "a_feat.pkl")
    info = sinks.write_pickle(pkl, {"x": arr}, want_digest=True)
    assert info[0] == os.path.getsize(pkl)
    assert info[1] == hashlib.sha256(open(pkl, "rb").read()).hexdigest()
    assert [p.name for p in tmp_path.iterdir()] == \
        sorted(["a_feat.npy", "a_feat.pkl"])  # no temp junk


def test_action_on_extraction_emits_artifact_events(tmp_path):
    from video_features_tpu.telemetry.spans import VideoSpan
    feats = {"feat": np.ones((2, 4), dtype=np.float32)}
    with VideoSpan("v.mp4", feature_type="resnet") as span:
        sinks.action_on_extraction(feats, "v.mp4", str(tmp_path),
                                   "save_numpy")
        span.annotate(status="done")
    events = [e for e in span.record["events"] if e["kind"] == "artifact"]
    assert len(events) == 1
    ev = events[0]
    assert ev["file"] == "v_feat.npy"
    assert ev["bytes"] == os.path.getsize(tmp_path / "v_feat.npy")
    assert len(ev["sha256"]) == 64
    from video_features_tpu.telemetry import schema as tschema
    assert tschema.validate_span(span.record) == []


# -- compare_runs -----------------------------------------------------------

def test_compare_runs_selftest_fixture():
    sys.path.insert(0, str(SCRIPTS))
    try:
        import compare_runs
    finally:
        sys.path.pop(0)
    assert compare_runs.selftest() == 0


def test_compare_runs_stage_and_failure_deltas(tmp_path):
    sys.path.insert(0, str(SCRIPTS))
    try:
        import compare_runs
    finally:
        sys.path.pop(0)
    a, b = tmp_path / "a", tmp_path / "b"
    for d, decode_ms, fail in ((a, 10.0, False), (b, 30.0, True)):
        d.mkdir()
        tjsonl.write_json_atomic(d / "_run.json", {
            "stage_totals": {"decode": {"s": decode_ms, "calls": 1000}}})
        if fail:
            tjsonl.append_jsonl(d / "_failures.jsonl", {
                "video": "bad.mp4", "category": "POISON", "attempts": 3,
                "error": "x"})
    rc, lines = compare_runs.compare(str(a), str(b))
    text = "\n".join(lines)
    assert rc == 1
    assert "stage decode" in text and "beyond" in text
    assert "new failure in candidate: bad.mp4" in text
    # identity compare stays green
    rc, _ = compare_runs.compare(str(a), str(a))
    assert rc == 0


def test_compare_runs_detects_truncated_artifact(tmp_path):
    sys.path.insert(0, str(SCRIPTS))
    try:
        import compare_runs
    finally:
        sys.path.pop(0)
    from video_features_tpu.telemetry.spans import VideoSpan

    def run_dir(d, nbytes):
        d.mkdir()
        with VideoSpan("v.mp4", feature_type="resnet") as span:
            span.annotate(status="done")
            span.event("artifact", key="feat", file="v_feat.npy",
                       bytes=nbytes, sha256=f"sha-{nbytes}")
        tjsonl.append_jsonl(d / "_telemetry.jsonl", span.record)
    run_dir(tmp_path / "a", 4096)
    run_dir(tmp_path / "b", 128)
    rc, lines = compare_runs.compare(str(tmp_path / "a"),
                                     str(tmp_path / "b"))
    assert rc == 1
    assert any("artifact shrank" in x for x in lines)


# -- bench history ----------------------------------------------------------

def test_bench_history_append_idempotent_and_regression(tmp_path):
    sys.path.insert(0, str(SCRIPTS))
    try:
        import bench_history
    finally:
        sys.path.pop(0)
    hist = str(tmp_path / "BENCH_history.jsonl")
    r1 = tmp_path / "r1.json"
    r2 = tmp_path / "r2.json"
    r1.write_text(json.dumps({"n": 1, "parsed": {
        "metric": "m throughput", "value": 100.0, "unit": "clips/sec",
        "metrics": [{"metric": "overhead", "value": 1.0,
                     "unit": "x wall-clock"}]}}))
    r2.write_text(json.dumps({"n": 2, "parsed": {
        "metric": "m throughput", "value": 50.0, "unit": "clips/sec",
        "metrics": [{"metric": "overhead", "value": 1.5,
                     "unit": "x wall-clock"}]}}))
    assert bench_history.append_rounds(hist, [str(r1), str(r2)]) == 0
    assert len(bench_history.load_history(hist)) == 2
    bench_history.append_rounds(hist, [str(r1)])  # idempotent
    assert len(bench_history.load_history(hist)) == 2
    regressions, lines = bench_history.check_regressions(hist, band=0.2)
    text = "\n".join(lines)
    # throughput halved (down = bad) AND overhead grew (up = bad)
    assert len(regressions) == 2, text
    # CLI: --fail-on-regression turns the flag into exit 1
    p = subprocess.run(
        [sys.executable, str(SCRIPTS / "bench_history.py"), "check",
         "--history", hist, "--fail-on-regression"],
        capture_output=True, text=True)
    assert p.returncode == 1, p.stdout + p.stderr
    assert "REGRESSION" in p.stdout


def test_bench_history_raw_line_and_stdin_roundtrip(tmp_path):
    sys.path.insert(0, str(SCRIPTS))
    try:
        import bench_history
    finally:
        sys.path.pop(0)
    hist = str(tmp_path / "h.jsonl")
    raw = tmp_path / "line.json"
    raw.write_text(json.dumps({"metric": "x", "value": 5, "unit": "u"}))
    bench_history.append_rounds(hist, [str(raw)])
    recs = bench_history.load_history(hist)
    assert recs[0]["round"] == 1  # inferred when the line carries no n
    assert recs[0]["headline"]["value"] == 5


# -- telemetry_report --fail-on-failures ------------------------------------

def test_report_fail_on_failures_gate(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    report = [sys.executable, str(SCRIPTS / "telemetry_report.py"),
              str(out), "--fail-on-failures"]
    p = subprocess.run(report, capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr  # empty journal: green
    tjsonl.append_jsonl(out / "_failures.jsonl", {
        "video": "bad.mp4", "category": "POISON", "attempts": 3,
        "error": "x"})
    p = subprocess.run(report, capture_output=True, text=True)
    assert p.returncode == 1
    # a RESOLVED record lifts the gate (journal last-record-wins contract)
    tjsonl.append_jsonl(out / "_failures.jsonl", {
        "video": "bad.mp4", "category": "RESOLVED"})
    p = subprocess.run(report, capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr


# -- run_id heartbeat hygiene ----------------------------------------------

def test_stale_heartbeats_from_prior_run_are_ignored(tmp_path):
    from video_features_tpu.telemetry.heartbeat import matches_run
    # same id, missing ids -> keep; different id + older than the run ->
    # stale; different id but still ticking (fleet sibling) -> keep
    assert matches_run({"run_id": "a", "time": 1.0}, "a", 100.0)
    assert matches_run({"time": 1.0}, "a", 100.0)
    assert matches_run({"run_id": "b", "time": 1.0}, None, None)
    assert not matches_run({"run_id": "b", "time": 1.0}, "a", 100.0)
    assert matches_run({"run_id": "b", "time": 150.0}, "a", 100.0)

    out = tmp_path / "out"
    out.mkdir()
    tjsonl.write_json_atomic(out / "_run.json", {
        "schema": "vft.run_manifest/1", "run_id": "current",
        "started_time": 1000.0, "tally": {}})
    tjsonl.write_json_atomic(out / "_heartbeat_old-host.json", {
        "schema": "vft.heartbeat/1", "run_id": "previous",
        "host_id": "old-host", "time": 10.0, "interval_s": 30})
    tjsonl.write_json_atomic(out / "_heartbeat_new-host.json", {
        "schema": "vft.heartbeat/1", "run_id": "current",
        "host_id": "new-host", "time": 2000.0, "interval_s": 30,
        "final": True, "videos_done": 1})
    p = subprocess.run(
        [sys.executable, str(SCRIPTS / "telemetry_report.py"), str(out)],
        capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "PRIOR RUN" in p.stdout and "old-host" in p.stdout
    assert "FINISHED" in p.stdout  # the current run's heartbeat renders


# -- recorder roll-up -------------------------------------------------------

def test_recorder_health_rollup_lands_in_manifest(tmp_path):
    from video_features_tpu.telemetry.recorder import TelemetryRecorder
    out = str(tmp_path / "out")
    rec = TelemetryRecorder(out, feature_type="resnet", interval_s=60.0,
                            host_id="p0-test").start()
    try:
        good = np.ones(8, dtype=np.float32)
        bad = good.copy()
        bad[0] = np.nan
        health.digest_features({"feat": good}, "a.mp4", "resnet", out)
        health.digest_features({"feat": bad}, "b.mp4", "raft", out)
    finally:
        rec.close(tally={"done": 2})
    man = json.load(open(os.path.join(out, "_run.json")))
    assert man["run_id"] == rec.run_id
    assert man["health"]["resnet"] == {
        "records": 1, "nonfinite_records": 0, "nan": 0, "inf": 0}
    assert man["health"]["raft"]["nan"] == 1
    assert man["health"]["raft"]["nonfinite_records"] == 1
    # the nonfinite counter series landed in the metrics dump
    names = {s["name"] for s in man["metrics"]["series"]}
    assert "vft_health_nonfinite_total" in names
    hb = json.load(open(os.path.join(
        out, "_heartbeat_p0-test.json")))
    assert hb["run_id"] == rec.run_id
