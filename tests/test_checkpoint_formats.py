"""Checkpoint-FORMAT fidelity: every family's loader parses a file in its
NATIVE on-disk format.

Real pretrained blobs are unavailable in this zero-egress image, but the
*formats* are synthesizable today from the reference's own torch classes
with random weights — a real TorchScript archive for CLIP (the OpenAI CDN
ships JIT archives, reference clip_src/clip.py:128-139), ``module.``-
prefixed DataParallel checkpoints for the flow nets (reference
base_flow_extractor.py:132-134 strips the prefix), torchvision / ig65m hub
``.pth`` layouts for R(2+1)D (reference extract_r21d.py:105-113), the
repo-local ``.pt`` state_dicts for I3D/S3D/PWC, and the torchvggish
release ``.pth`` + PCA ``.npz`` (reference vggish_postprocess.py:22-91).

Chain of evidence: each family's oracle test (test_raft, test_i3d,
test_s3d, test_pwc, test_clip, test_r21d, test_vggish, test_resnet)
already proves in-memory ``state_dict -> flax tree -> forward`` parity
against the reference's own torch source. These tests prove
``native file -> load_torch_state_dict -> flax tree`` equals that
in-memory tree leaf-for-leaf — through the full production path
(store.find_checkpoint filename probing, resolve_params, msgpack cache
round-trip) — which closes the loop file -> forward for every family.
The trickiest parse (the CLIP TorchScript archive, whose architecture is
also INFERRED from the file, clip_src/model.py:399-436) additionally runs
a full file -> forward -> torch-oracle comparison.
"""
import importlib.util
import os
import sys
from pathlib import Path

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.weights import store  # noqa: E402
from video_features_tpu.weights.converters import registry  # noqa: E402
from tests.torch_oracles import (TorchR2Plus1D, TorchVGGish,  # noqa: E402
                                 randomize_bn_stats)

REF_ROOT = Path("/root/reference")


def _load_ref_module(name: str, rel: str):
    path = REF_ROOT / rel
    if not path.exists():
        pytest.skip(f"reference source not available: {path}")
    if str(REF_ROOT) not in sys.path:
        # reference modules import through the 'models.*' package path
        sys.path.insert(0, str(REF_ROOT))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _assert_trees_equal(got, want, key):
    import jax
    gl, gt = jax.tree_util.tree_flatten_with_path(got)
    wl, _ = jax.tree_util.tree_flatten_with_path(want)
    assert len(gl) == len(wl), f"{key}: leaf count differs"
    for (gp, gv), (wp, wv) in zip(gl, wl):
        assert gp == wp, f"{key}: tree paths diverge at {gp} vs {wp}"
        np.testing.assert_array_equal(
            np.asarray(gv), np.asarray(wv),
            err_msg=f"{key}: leaf {jax.tree_util.keystr(gp)}")


def _resolve_native(monkeypatch, tmp_path, model_key, filename, save_fn):
    """Full production load path: drop the native-format file under its
    upstream FILENAME into the weights dir, resolve through
    find_checkpoint's filename probing + the registered converter, verify
    the msgpack cache round-trips, and return the loaded tree."""
    wd = tmp_path / "weights"
    wd.mkdir()
    save_fn(wd / filename)
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(wd))
    # find_checkpoint probes the torch hub cache FIRST for hub filenames;
    # isolate it so a host's real cached checkpoint can't shadow the
    # synthesized oracle file
    monkeypatch.setenv("TORCH_HOME", str(tmp_path / "torch_home"))
    init_fn, convert_fn = registry()[model_key]
    found = store.find_checkpoint(model_key)
    assert found is not None and found.name == filename, \
        f"find_checkpoint missed the native filename {filename!r}: {found}"
    params = store.resolve_params(model_key, init_fn, convert_fn)
    cache = wd / f"{model_key}.msgpack"
    assert cache.exists(), "resolve_params did not write the msgpack cache"
    cached = store.load_msgpack(init_fn(), cache)
    _assert_trees_equal(cached, params, f"{model_key} msgpack round-trip")
    return params


# ---- flow nets: module.-prefixed DataParallel checkpoints ----------------

def test_raft_module_prefixed_ckpt(monkeypatch, tmp_path):
    """raft-sintel.pth as the reference ships it: an OrderedDict whose keys
    carry the nn.DataParallel 'module.' prefix (base_flow_extractor.py:
    132-134)."""
    from video_features_tpu.models import raft as raft_m
    ref_raft = _load_ref_module("ref_raft_fmt", "models/raft/raft_src/raft.py")
    torch.manual_seed(0)
    oracle = ref_raft.RAFT().eval()
    randomize_bn_stats(oracle)
    sd = {f"module.{k}": v for k, v in oracle.state_dict().items()}

    params = _resolve_native(
        monkeypatch, tmp_path, "raft_sintel", "raft-sintel.pth",
        lambda p: torch.save(sd, p))
    want = raft_m.params_from_torch(oracle.state_dict())
    _assert_trees_equal(params, want, "raft_sintel")


def test_pwc_module_prefixed_ckpt(monkeypatch, tmp_path):
    """pwc_net_sintel.pt: module.-prefixed state_dict, same DataParallel
    convention (the reference loads both flow nets through the same
    strip)."""
    from video_features_tpu.models import pwc as pwc_m
    from tests.test_pwc import _load_reference_pwc
    ref = _load_reference_pwc()
    torch.manual_seed(0)
    oracle = ref.PWCNet().eval()
    sd = {f"module.{k}": v for k, v in oracle.state_dict().items()}

    params = _resolve_native(
        monkeypatch, tmp_path, "pwc_sintel", "pwc_net_sintel.pt",
        lambda p: torch.save(sd, p))
    want = pwc_m.params_from_torch(oracle.state_dict())
    _assert_trees_equal(params, want, "pwc_sintel")


# ---- repo-local .pt state_dicts ------------------------------------------

@pytest.mark.parametrize("modality", ["rgb", "flow"])
def test_i3d_repo_local_pt(monkeypatch, tmp_path, modality):
    """i3d_rgb.pt / i3d_flow.pt: plain state_dicts of the reference I3D
    class (models/i3d/checkpoints)."""
    from video_features_tpu.models import i3d as i3d_m
    ref = _load_ref_module("ref_i3d_fmt", "models/i3d/i3d_src/i3d_net.py")
    torch.manual_seed(0)
    oracle = ref.I3D(num_classes=400, modality=modality).eval()
    randomize_bn_stats(oracle)

    params = _resolve_native(
        monkeypatch, tmp_path, f"i3d_{modality}", f"i3d_{modality}.pt",
        lambda p: torch.save(oracle.state_dict(), p))
    want = i3d_m.params_from_torch(oracle.state_dict())
    _assert_trees_equal(params, want, f"i3d_{modality}")


def test_s3d_torchified_ckpt(monkeypatch, tmp_path):
    """S3D_kinetics400_torchified.pt: state_dict of the reference S3D class
    (converted-from-TF release the reference repo carries)."""
    from video_features_tpu.models import s3d as s3d_m
    ref = _load_ref_module("ref_s3d_fmt", "models/s3d/s3d_src/s3d.py")
    torch.manual_seed(0)
    oracle = ref.S3D(num_class=400).eval()
    randomize_bn_stats(oracle)

    params = _resolve_native(
        monkeypatch, tmp_path, "s3d_kinetics400",
        "S3D_kinetics400_torchified.pt",
        lambda p: torch.save(oracle.state_dict(), p))
    want = s3d_m.params_from_torch(oracle.state_dict())
    _assert_trees_equal(params, want, "s3d_kinetics400")


# ---- hub .pth layouts ----------------------------------------------------

@pytest.mark.parametrize("model_key,layers,filename", [
    ("r2plus1d_18_16_kinetics", (2, 2, 2, 2), "r2plus1d_18-91a641e6.pth"),
    # the ig65m hub checkpoints are torchvision-VideoResNet-shaped with the
    # 34-layer block plan (reference extract_r21d.py:105-113 pulls them via
    # torch.hub from moabitcoin/ig65m-pytorch)
    ("r2plus1d_34_32_ig65m_ft_kinetics", (3, 4, 6, 3),
     "r2plus1d_34_clip32_ig65m_from_scratch-449a7af9.pth"),
])
def test_r21d_hub_pth_layouts(monkeypatch, tmp_path, model_key, layers,
                              filename):
    from video_features_tpu.models import r21d as r21d_m
    torch.manual_seed(0)
    num_classes = 400 if layers == (2, 2, 2, 2) else 359
    oracle = TorchR2Plus1D(layers=layers, num_classes=num_classes).eval()
    randomize_bn_stats(oracle)

    params = _resolve_native(
        monkeypatch, tmp_path, model_key, filename,
        lambda p: torch.save(oracle.state_dict(), p))
    want = r21d_m.params_from_torch(oracle.state_dict())
    _assert_trees_equal(params, want, model_key)


# ---- torchvggish release + PCA params ------------------------------------

def test_vggish_release_pth(monkeypatch, tmp_path):
    from video_features_tpu.models import vggish as vggish_m
    torch.manual_seed(0)
    oracle = TorchVGGish().eval()

    params = _resolve_native(
        monkeypatch, tmp_path, "vggish", "vggish-10086976.pth",
        lambda p: torch.save(oracle.state_dict(), p))
    want = vggish_m.params_from_torch(oracle.state_dict())
    _assert_trees_equal(params, want, "vggish")


@pytest.mark.parametrize("kind", ["npz", "pth"])
def test_vggish_pca_formats(monkeypatch, tmp_path, kind):
    """The PCA postprocessor params in both native containers: the
    reference repo's .npz (vggish_postprocess.py:22-32 reads
    'pca_eigen_vectors'/'pca_means') and the torchvggish release .pth (a
    pickled dict of the same arrays)."""
    from video_features_tpu.models.vggish import load_pca_params, postprocess
    rng = np.random.default_rng(0)
    vectors = rng.standard_normal((128, 128)).astype(np.float32)
    means = rng.standard_normal((128,)).astype(np.float32)
    wd = tmp_path / "weights"
    wd.mkdir()
    if kind == "npz":
        path = wd / "vggish_pca_params.npz"
        np.savez(path, pca_eigen_vectors=vectors, pca_means=means)
    else:
        path = wd / "vggish_pca_params-970ea276.pth"
        torch.save({"pca_eigen_vectors": torch.from_numpy(vectors),
                    "pca_means": torch.from_numpy(means)}, path)
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(wd))
    found = store.find_checkpoint("vggish_pca")
    assert found is not None and found.name == path.name
    got_v, got_m = load_pca_params(str(found))
    np.testing.assert_array_equal(got_v, vectors)
    np.testing.assert_array_equal(got_m, means.reshape(-1, 1))
    # and the postprocess consumes them. Contract note: the reference
    # PIPELINE uses the torchvggish Postprocessor (vggish_slim.py:63-92:
    # round, squeeze, float output) — NOT the repo's unused numpy
    # vggish_postprocess.py variant (truncate + uint8 cast); this build
    # matches the one actually executed (test_vggish pins the math).
    emb = rng.standard_normal((3, 128)).astype(np.float32)
    out = postprocess(emb, got_v, got_m)
    assert out.shape == (3, 128)
    assert float(out.min()) >= 0.0 and float(out.max()) <= 255.0


# ---- CLIP: TorchScript archive, architecture inferred from the file ------

def test_clip_torchscript_archive_full_chain(monkeypatch, tmp_path):
    """A real torch.jit archive of the reference CLIP class (the OpenAI CDN
    format; reference clip_src/clip.py:128-139 tries jit.load first), on a
    tiny ViT config. Full chain: archive -> load_torch_state_dict unwrap ->
    config_from_state_dict architecture inference -> params_from_torch ->
    forward, compared against the torch oracle's own forward."""
    from video_features_tpu.models import clip as clip_model
    from video_features_tpu.weights.torch_import import load_torch_state_dict
    ref = _load_ref_module("ref_clip_fmt", "models/clip/clip_src/model.py")
    torch.manual_seed(0)
    oracle = ref.CLIP(embed_dim=32, image_resolution=56, vision_layers=2,
                      vision_width=64, vision_patch_size=14,
                      context_length=12, vocab_size=128,
                      transformer_width=64, transformer_heads=2,
                      transformer_layers=2).eval()
    path = tmp_path / "ViT-tiny.pt"
    try:
        scripted = torch.jit.script(oracle)
    except Exception:
        img = torch.zeros(1, 3, 56, 56)
        toks = torch.zeros(1, 12, dtype=torch.long)
        scripted = torch.jit.trace(oracle, (img, toks))
    scripted.save(str(path))

    sd = load_torch_state_dict(str(path))
    cfg = clip_model.config_from_state_dict(sd)
    assert (cfg.embed_dim, cfg.image_resolution, cfg.vision_layers,
            cfg.vision_patch_size) == (32, 56, 2, 14), cfg
    params = clip_model.params_from_torch(sd)
    model = clip_model.CLIP(cfg)

    rng = np.random.default_rng(1)
    img = rng.normal(size=(2, 56, 56, 3)).astype(np.float32)
    with torch.no_grad():
        want = oracle.encode_image(
            torch.from_numpy(img).permute(0, 3, 1, 2)).numpy()
    got = np.asarray(model.apply({"params": params}, jnp.asarray(img),
                                 method="encode_image"))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)
