"""Native IO layer (atomic .npy writer / O(header) validator) + Prefetcher."""
import pickle

import numpy as np
import pytest

from video_features_tpu import native
from video_features_tpu.utils.io import Prefetcher
from video_features_tpu.utils import sinks

pytestmark = [pytest.mark.quick,
              pytest.mark.skipif(not native.available(),
                                 reason="native toolchain unavailable")]


@pytest.mark.parametrize("arr", [
    np.arange(12, dtype=np.float32).reshape(3, 4),
    np.arange(5, dtype=np.int64),
    np.float64(3.25),                      # 0-d
    np.zeros((2, 0, 3), dtype=np.float32),  # empty
    np.array([[True, False], [False, True]]),
    np.random.default_rng(0).normal(size=(7, 13, 2)).astype(np.float16),
])
def test_write_npy_atomic_roundtrip(tmp_path, arr):
    f = str(tmp_path / "x.npy")
    assert native.write_npy_atomic(f, arr)
    back = np.load(f)
    assert back.dtype == np.asanyarray(arr).dtype
    assert back.shape == np.asanyarray(arr).shape
    np.testing.assert_array_equal(back, np.asanyarray(arr))
    assert native.validate_npy(f) is True
    assert not list(tmp_path.glob("*.tmp.*"))  # no temp litter


def test_write_npy_appends_extension(tmp_path):
    f = str(tmp_path / "noext")
    assert native.write_npy_atomic(f, np.ones(3))
    np.testing.assert_array_equal(np.load(f + ".npy"), np.ones(3))


def test_validate_npy_accepts_numpy_written_files(tmp_path):
    f = str(tmp_path / "np.npy")
    np.save(f, np.arange(10, dtype=np.int32))
    assert native.validate_npy(f) is True


def test_validate_npy_detects_truncation(tmp_path):
    f = str(tmp_path / "t.npy")
    np.save(f, np.arange(1000, dtype=np.float64))
    data = open(f, "rb").read()
    open(f, "wb").write(data[:len(data) // 2])  # simulate a partial write
    assert native.validate_npy(f) is False


def test_validate_npy_rejects_garbage(tmp_path):
    f = str(tmp_path / "g.npy")
    open(f, "wb").write(b"not a numpy file at all")
    assert native.validate_npy(f) is False


def test_object_arrays_fall_back(tmp_path):
    assert not native.write_npy_atomic(
        str(tmp_path / "o.npy"), np.array([{"a": 1}], dtype=object))


def test_is_already_exist_uses_validator(tmp_path):
    """A truncated .npy must be treated as absent (re-extract), a valid one
    as present — through the real sinks entry point."""
    out = tmp_path
    video = "/some/video.mp4"
    good = sinks.make_path(str(out), video, "feat", ".npy")
    np.save(good, np.ones((4, 8)))
    assert sinks.is_already_exist("save_numpy", str(out), video, ["feat"])
    data = open(good, "rb").read()
    open(good, "wb").write(data[:-5])
    assert not sinks.is_already_exist("save_numpy", str(out), video, ["feat"])


def test_prefetcher_matches_direct_iteration():
    items = [np.full((4,), i) for i in range(17)]
    got = list(Prefetcher(items, depth=3))
    assert len(got) == 17
    for a, b in zip(got, items):
        np.testing.assert_array_equal(a, b)


def test_prefetcher_propagates_exceptions():
    def gen():
        yield 1
        raise RuntimeError("decode failed")

    it = iter(Prefetcher(gen()))
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_prefetcher_exception_with_full_queue_and_slow_consumer():
    """The producer's exception must survive a full queue (regression: it
    used to be dropped after a 1 s timeout, hanging the consumer)."""
    import time

    def gen():
        yield 1
        yield 2
        raise RuntimeError("decode failed late")

    got = []
    with pytest.raises(RuntimeError, match="decode failed late"):
        for item in Prefetcher(gen(), depth=1):
            time.sleep(0.3)  # keep the queue full while the producer raises
            got.append(item)
    assert got == [1, 2]


def test_prefetcher_abandoned_consumer_does_not_hang():
    import threading
    started = threading.Event()

    def gen():
        started.set()
        for i in range(10_000):
            yield i

    it = iter(Prefetcher(gen(), depth=1))
    assert next(it) == 0
    it.close()  # generator close triggers the finally/stop path
    assert started.is_set()
