"""Multi-host/multi-worker coordination: deterministic shard ownership and
the concurrent-workers-one-output-dir contract.

The reference's scale-out story was shuffle + skip-if-exists + accepted
last-writer-wins races (reference README.md:70-84, utils/utils.py:164-165);
it shipped no test for it (SURVEY §4 "Multi-node testing: none"). Here both
halves are tested: the hash sharding is a true partition, and two concurrent
CLI workers over one output dir produce valid, loadable features.
"""
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.parallel.mesh import local_shard_of_list

VIDEOS = [f"/data/vid_{i:03d}.mp4" for i in range(57)]


def test_shard_partition_properties():
    n_hosts = 4
    shards = [local_shard_of_list(VIDEOS, host_id=h, num_hosts=n_hosts)
              for h in range(n_hosts)]
    # disjoint and covering: every video owned by exactly one host
    seen = [v for s in shards for v in s]
    assert sorted(seen) == sorted(VIDEOS)
    # deterministic and order-independent (workers may shuffle differently)
    reshuffled = list(reversed(VIDEOS))
    again = local_shard_of_list(reshuffled, host_id=2, num_hosts=n_hosts)
    assert set(again) == set(shards[2])


def test_single_host_gets_everything():
    assert local_shard_of_list(VIDEOS, host_id=0, num_hosts=1) == VIDEOS


def test_two_concurrent_workers_one_output_dir(sample_video, tmp_path):
    """Two CLI workers, same (shuffled) list, same output dir — the
    reference's documented deployment pattern. Both must exit cleanly and
    the surviving outputs must load (atomic writes: no torn .npy)."""
    out = tmp_path / "out"
    repo = Path(__file__).resolve().parent.parent
    cmd = [sys.executable, "main.py", "feature_type=resnet",
           "model_name=resnet18", "device=cpu", "batch_size=8",
           "extraction_fps=2", "allow_random_weights=true",
           "on_extraction=save_numpy", f"output_path={out}",
           f"tmp_path={tmp_path / 'tmp'}", f"video_paths={sample_video}"]
    # isolate the weight cache: both workers would otherwise race-write the
    # user's real ~/.cache msgpack via the non-atomic save_msgpack
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VFT_WEIGHTS_DIR": str(tmp_path / "weights")}
    procs = [subprocess.Popen(cmd, cwd=repo, env=env,
                              stdout=subprocess.PIPE, stderr=subprocess.PIPE)
             for _ in range(2)]
    try:
        for p in procs:
            _, err = p.communicate(timeout=600)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        for p in procs:  # never orphan the sibling on failure/timeout
            if p.poll() is None:
                p.kill()
                p.wait()
    stem = Path(sample_video).stem
    files = sorted((out / "resnet" / "resnet18").glob("*.npy"))
    assert {f.name for f in files} == {f"{stem}_resnet.npy", f"{stem}_fps.npy",
                                       f"{stem}_timestamps_ms.npy"}
    for f in files:
        arr = np.load(f)  # a torn write would raise here
        assert np.isfinite(np.asarray(arr, dtype=np.float64)).all()


def test_video_workers_threaded_pipeline_matches_serial(sample_video,
                                                        tmp_path, monkeypatch):
    """video_workers=2: the host sides of two videos run on concurrent
    threads feeding one device queue (cli.py). Outputs must be file-for-file
    identical to the serial loop."""
    import shutil
    from video_features_tpu.cli import main as cli_main

    second = tmp_path / "v_worker_copy.mp4"
    shutil.copy(sample_video, second)
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "weights"))

    def run(out, workers):
        cli_main([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_fps=2", "allow_random_weights=true",
            f"video_workers={workers}", "on_extraction=save_numpy",
            f"output_path={out}", f"tmp_path={tmp_path / 'tmp'}",
            f"video_paths=[{sample_video},{second}]",
        ])
        return {p.name: np.load(p)
                for p in sorted((out / "resnet" / "resnet18").glob("*.npy"))}

    serial = run(tmp_path / "serial", 1)
    threaded = run(tmp_path / "threaded", 2)
    assert serial.keys() == threaded.keys() and len(serial) == 6
    for name in serial:
        np.testing.assert_array_equal(serial[name], threaded[name],
                                      err_msg=name)


@pytest.mark.slow  # ~35s (subprocess + settle sleeps); worker-pool siblings stay quick
def test_sigterm_graceful_preemption(sample_video, tmp_path):
    """Preemptible-worker contract (cli.py): on SIGTERM the worker finishes
    the in-flight video, drops the rest, prints the run summary, and exits
    143; a restarted worker resumes via the idempotent skip."""
    import shutil
    import signal as _signal
    import time as _time

    # enough videos that plenty of work remains when the signal lands right
    # after the first output file (fine-grained 50ms poll below)
    vids = []
    for i in range(8):
        v = tmp_path / f"v_pre_{i}.mp4"
        shutil.copy(sample_video, v)
        vids.append(str(v))
    out = tmp_path / "out"
    repo = Path(__file__).resolve().parent.parent
    cmd = [sys.executable, "main.py", "feature_type=resnet",
           "model_name=resnet18", "device=cpu", "batch_size=8",
           "extraction_fps=2", "allow_random_weights=true",
           "on_extraction=save_numpy", f"output_path={out}",
           f"tmp_path={tmp_path / 'tmp'}",
           f"video_paths=[{','.join(vids)}]"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VFT_WEIGHTS_DIR": str(tmp_path / "weights")}
    # log to a file, not a PIPE: nobody drains a PIPE while we poll for
    # output files, and a full pipe buffer would deadlock the worker
    log_path = tmp_path / "worker.log"
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(cmd, cwd=repo, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
        try:
            # wait for the first feature file, then preempt
            deadline = _time.time() + 300
            while _time.time() < deadline:
                if list(out.rglob("*_resnet.npy")):
                    break
                if proc.poll() is not None:
                    raise AssertionError(log_path.read_text()[-2000:])
                _time.sleep(0.05)
            else:
                raise AssertionError("no output appeared before deadline: "
                                     + log_path.read_text()[-2000:])
            proc.send_signal(_signal.SIGTERM)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    text = log_path.read_text()
    assert proc.returncode == 143, text[-2000:]
    assert "SIGTERM: finishing in-flight" in text
    assert "failed" in text  # the run summary printed
    done_before = {p.name for p in out.rglob("*_resnet.npy")}
    assert 0 < len(done_before) <= 8
    # every written output is complete & loadable (atomic writes)
    for p in out.rglob("*.npy"):
        np.load(p)
    # restart: remaining videos extract, finished ones skip
    r = subprocess.run(cmd, cwd=repo, env=env, capture_output=True,
                       timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert len(list(out.rglob("*_resnet.npy"))) == 8


def test_video_workers_with_device_resize(sample_video, tmp_path,
                                          monkeypatch):
    """video_workers=2 + resize=device over two different source
    resolutions: the lock-guarded per-resolution runner cache is exercised
    from concurrent threads and outputs must match the serial run."""
    import cv2
    import pytest
    from video_features_tpu.cli import main as cli_main

    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "weights"))
    second = str(tmp_path / "v_small_dr.mp4")
    cap = cv2.VideoCapture(sample_video)
    wtr = cv2.VideoWriter(second, cv2.VideoWriter_fourcc(*"mp4v"), 20,
                          (160, 120))
    if not wtr.isOpened():  # same guard as conftest._synthesize_sample
        pytest.skip("cv2 cannot encode mp4v")
    for _ in range(30):
        ok, frame = cap.read()
        if not ok:
            break
        wtr.write(cv2.resize(frame, (160, 120)))
    wtr.release()
    cap.release()

    def run(out, workers):
        cli_main([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_fps=2", "allow_random_weights=true",
            "resize=device", f"video_workers={workers}",
            "on_extraction=save_numpy", f"output_path={out}",
            f"tmp_path={tmp_path / 'tmp'}",
            f"video_paths=[{sample_video},{second}]",
        ])
        return {p.name: np.load(p)
                for p in sorted((out / "resnet" / "resnet18").glob("*.npy"))}

    serial = run(tmp_path / "serial", 1)
    threaded = run(tmp_path / "threaded", 2)
    assert serial.keys() == threaded.keys() and len(serial) == 6
    for name in serial:
        np.testing.assert_array_equal(serial[name], threaded[name],
                                      err_msg=name)
