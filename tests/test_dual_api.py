"""Dual-API equivalence — the reference's core test mechanism.

The reference computes every feature twice — through the import API
(``extractor.extract(path)``) and through a literal ``main.py`` subprocess
— in BOTH save formats, and asserts pairwise closeness (reference
tests/utils.py:57-120). This file mirrors that mechanism exactly once
(resnet18 on a tiny synthetic clip): import API vs CLI/save_numpy vs
CLI/save_pickle must agree on every output key. Random init is
deterministic (PRNGKey(0) in models/*.init_params), so value equality
holds across processes without real checkpoints.
"""
import os
import pickle
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.quick

REPO = str(Path(__file__).resolve().parent.parent)


def _write_clip(path: str, frames: int = 14) -> str:
    cv2 = pytest.importorskip("cv2")
    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"),
                        16.0, (64, 48))
    if not w.isOpened():
        pytest.skip("cv2 cannot encode mp4v")
    yy, xx = np.mgrid[0:48, 0:64].astype(np.float32)
    for t in range(frames):
        frame = np.stack([
            127 + 120 * np.sin(xx / 9 + t / 5),
            127 + 120 * np.sin(yy / 7 - t / 6),
            127 + 120 * np.sin((xx + yy) / 11 + t / 4),
        ], axis=-1)
        w.write(frame.clip(0, 255).astype(np.uint8))
    w.release()
    return path


def _cli(video: str, sink: str, out: Path, tmp: Path, cache: Path,
         weights: Path) -> None:
    ext = ".npy" if sink == "save_numpy" else ".pkl"
    cmd = [sys.executable, "main.py", "feature_type=resnet",
           "model_name=resnet18", "device=cpu", "batch_size=4",
           "extraction_fps=4", "allow_random_weights=true",
           f"on_extraction={sink}", f"output_path={out}", f"tmp_path={tmp}",
           f"compilation_cache_dir={cache}", f"video_paths={video}"]
    res = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                         timeout=600,
                         env={**os.environ, "JAX_PLATFORMS": "cpu",
                              "VFT_WEIGHTS_DIR": str(weights),
                              "TORCH_HOME": str(weights / "torch_home")})
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    # the CLI isolates per-video errors (tally + exit 0), so rc alone can't
    # prove the extraction ran: require the feature file, with the captured
    # output in the failure message
    feat = out / "resnet" / "resnet18" / f"v_resnet{ext}"
    assert feat.exists(), (
        f"{sink}: no {feat} —\n" + res.stdout[-2000:] + res.stderr[-2000:])


def test_import_api_and_both_cli_sinks_agree(tmp_path, monkeypatch):
    # isolate weight resolution: no real checkpoints/caches, no writes to
    # the user's cache — all three runs must take the seeded random init
    weights = tmp_path / "weights"
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(weights))
    monkeypatch.setenv("TORCH_HOME", str(tmp_path / "torch_home"))
    video = _write_clip(str(tmp_path / "v.mp4"))
    cache = tmp_path / "xla_cache"  # shared: compile once across all runs

    # import API
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.registry import get_extractor_cls
    cfg = load_config("resnet", {
        "model_name": "resnet18", "device": "cpu", "batch_size": 4,
        "extraction_fps": 4, "allow_random_weights": True,
        "on_extraction": "save_numpy",
        "output_path": str(tmp_path / "api_out"),
        "tmp_path": str(tmp_path / "api_tmp"),
        "video_paths": video,
    })
    sanity_check(cfg)
    api = get_extractor_cls("resnet")(cfg).extract(video)

    # CLI subprocesses, one per save format
    _cli(video, "save_numpy", tmp_path / "np_out", tmp_path / "np_tmp",
         cache, weights)
    _cli(video, "save_pickle", tmp_path / "pk_out", tmp_path / "pk_tmp",
         cache, weights)
    np_dir = tmp_path / "np_out" / "resnet" / "resnet18"
    pk_dir = tmp_path / "pk_out" / "resnet" / "resnet18"

    for key in ("resnet", "fps", "timestamps_ms"):
        assert key in api, f"import API output missing {key!r}"
        from_npy = np.load(np_dir / f"v_{key}.npy")
        with open(pk_dir / f"v_{key}.pkl", "rb") as f:
            from_pkl = np.asarray(pickle.load(f))
        # same seed, same math, different processes/sinks: pairwise close
        np.testing.assert_allclose(np.asarray(api[key]), from_npy,
                                   atol=1e-6, rtol=1e-6, err_msg=f"{key}: "
                                   "import API vs CLI save_numpy")
        np.testing.assert_allclose(from_npy, from_pkl, atol=0, rtol=0,
                                   err_msg=f"{key}: save_numpy vs "
                                   "save_pickle")
