"""Golden-artifact parity against the reference's committed test refs.

The reference ships recorded extraction outputs under
``/root/reference/tests/<family>/reference/*.pt`` (format defined by
reference tests/utils.py:36-45): each file stores ``{args, video_path,
video_path_md5, data}`` where ``data`` is ONE output key's array — the
feature array, the ``fps`` scalar, or the ``timestamps_ms`` vector — as
produced by the original CUDA/torch stack on the real sample video. They pin
exactly the windowing / fps-resampling / timestamp semantics this framework
re-derived from source, and they are verifiable with zero model weights.

Two tiers per recorded variant:

  - **shape tier** (always runs): the real extractor pipeline executes with
    the device forward replaced by a :func:`jax.eval_shape`-derived stub —
    all decode, resampling, windowing, timestamp and ragged-batch bookkeeping
    stays live at zero FLOPs. Asserts ``fps`` exactly, ``timestamps_ms``
    allclose, and feature-array shape equality.
  - **value tier** (runs when real checkpoints resolve via
    ``weights.store.find_checkpoint``): full forward, feature values compared
    under a cross-backend tolerance. Groups that fall back to the shape tier
    are counted and reported by ``test_value_tier_coverage_report`` — never
    silently skipped.

The refs' ``args`` were saved as OmegaConf objects; omegaconf is not
installed here, so they are unpickled with stub classes and flattened to
plain dicts (no omegaconf code runs).
"""
from __future__ import annotations

import contextlib
import glob
import hashlib
import os
import pickle
import shutil
import types
import wave
from contextlib import contextmanager
from pathlib import Path
from unittest import mock

import numpy as np
import pytest

torch = pytest.importorskip("torch")

REF_ROOT = "/root/reference/tests"
SAMPLE = "/root/reference/sample/v_GGSY1Qvo990.mp4"

# ---------------------------------------------------------------- ref loading


class _OmegaStub:
    """Placeholder for any pickled omegaconf class; holds raw state."""

    def __init__(self, *a, **k):
        pass

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self._state = state


#: the exact globals the committed refs need (enumerated by recording every
#: find_class over all 29 ref files) — anything else is refused. The refs
#: live under the explicitly-untrusted /root/reference mount, so this
#: unpickler must never resolve an arbitrary global: a malicious .pt would
#: otherwise execute code at test-collection time.
_SAFE_GLOBALS = {
    ("__builtin__", "dict"), ("__builtin__", "list"), ("__builtin__", "long"),
    ("builtins", "dict"), ("builtins", "list"),
    ("_codecs", "encode"),
    ("collections", "OrderedDict"), ("collections", "defaultdict"),
    ("numpy", "dtype"), ("numpy", "ndarray"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("typing", "Any"),
}


class _StubUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module.startswith("omegaconf"):
            return type(name, (_OmegaStub,), {"__module__": module})
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"golden ref requested global {module}.{name}, which is not in "
            "the recorded allowlist — refusing to unpickle content from the "
            "untrusted reference mount")


_stub_pickle = types.ModuleType("golden_stub_pickle")
_stub_pickle.Unpickler = _StubUnpickler
_stub_pickle.load = pickle.load


def _plain(x):
    """omegaconf stub tree -> plain python (DictConfig._content/AnyNode._val)."""
    if isinstance(x, _OmegaStub):
        d = vars(x)
        if "_content" in d:
            return _plain(d["_content"])
        if "_val" in d:
            return _plain(d["_val"])
        return {k: _plain(v) for k, v in d.items()}
    if isinstance(x, dict):
        return {k: _plain(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_plain(v) for v in x]
    return x


def _load_ref(path: str) -> dict:
    d = torch.load(path, map_location="cpu", weights_only=False,
                   pickle_module=_stub_pickle)
    return {
        "args": _plain(d["args"]),
        "video_path": str(d["video_path"]),
        "video_path_md5": d["video_path_md5"],
        "data": np.asarray(d["data"]),
    }


#: output keys a ref filename can end with, longest first so that
#: ``..._timestamps_ms.pt`` is not parsed as key ``ms``
_KNOWN_KEYS = sorted(
    ["timestamps_ms", "fps", "rgb", "flow",
     "r21d", "s3d", "clip", "resnet", "raft", "pwc", "vggish"],
    key=len, reverse=True)


def _split_key(stem: str):
    for key in _KNOWN_KEYS:
        if stem.endswith("_" + key):
            return stem[: -len(key) - 1], key
    raise ValueError(f"Cannot parse output key from ref name {stem!r}")


def _collect_groups():
    """{(family, variant): {key: ref_path}} for every committed ref."""
    groups = {}
    for path in sorted(glob.glob(os.path.join(REF_ROOT, "*", "reference",
                                              "*.pt"))):
        family = Path(path).parent.parent.name
        variant, key = _split_key(Path(path).stem)
        groups.setdefault((family, variant), {})[key] = path
    return groups


GROUPS = _collect_groups()
GROUP_IDS = [f"{fam}-{var}" for fam, var in GROUPS]

# extractor config keys we replay from the recorded args (everything else —
# device/paths/sinks — is environment, not semantics)
_ARG_ALLOWLIST = (
    "stack_size", "step_size", "streams", "flow_type", "extraction_fps",
    "batch_size", "model_name", "side_size", "resize_to_smaller_edge",
    "finetuned_on",
)


def _weight_keys(family: str, args: dict):
    """model keys whose checkpoints enable the value tier for this variant."""
    if family in ("resnet", "r21d"):
        return [args["model_name"]]
    if family == "s3d":
        return ["s3d_kinetics400"]
    if family == "clip":
        return ["clip_" + str(args["model_name"]).replace("/", "-")]
    if family == "raft":
        return ["raft_" + str(args.get("finetuned_on") or "sintel")]
    if family == "pwc":
        return ["pwc_sintel"]
    if family == "vggish":
        return ["vggish"]
    if family == "i3d":
        streams = args.get("streams")
        streams = ["rgb", "flow"] if streams in (None, "null") else [streams]
        keys = [f"i3d_{s}" for s in streams]
        if "flow" in streams:
            flow = args.get("flow_type") or "pwc"  # the reference default
            keys.append("raft_sintel" if flow == "raft" else "pwc_sintel")
        return keys
    raise ValueError(family)


def _value_tier_available(family: str, args: dict) -> bool:
    from video_features_tpu.weights import store
    return all(store.find_checkpoint(k) is not None
               for k in _weight_keys(family, args))


# ------------------------------------------------------------- forward stubs


@contextmanager
def _stub_forwards():
    """Replace DataParallelApply's device execution with eval_shape zeros.

    ``dispatch`` keeps its contract (padded rows, async-shaped output) and
    ``__call__`` keeps its valid-row slicing, so every pipeline — including
    the chained i3d flow->i3d handoff — runs its full host logic while the
    jitted forwards never execute. Shapes come from ``jax.eval_shape`` on the
    real jitted fn with the real params, so a model whose output dim drifted
    would still fail the shape assertions.
    """
    import jax
    from video_features_tpu.parallel import mesh as mesh_mod

    cls = mesh_mod.DataParallelApply
    orig_dispatch, orig_call = cls.dispatch, cls.__call__
    shape_cache = {}

    def _zeros(self, padded):
        key = (id(self), padded.shape, str(padded.dtype))
        if key not in shape_cache:
            out = jax.eval_shape(
                self._fn, self.params,
                jax.ShapeDtypeStruct(padded.shape, padded.dtype))
            shape_cache[key] = (out.shape, out.dtype)
        shape, dtype = shape_cache[key]
        return np.zeros(shape, dtype)

    def dispatch(self, batch_np):
        return _zeros(self, self._pad(batch_np))

    def call(self, batch_np, n_valid=None):
        n = batch_np.shape[0] if n_valid is None else n_valid
        return dispatch(self, batch_np)[:n]

    cls.dispatch, cls.__call__ = dispatch, call
    try:
        yield
    finally:
        cls.dispatch, cls.__call__ = orig_dispatch, orig_call


# ------------------------------------------------------------------ fixtures


def _md5(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.md5(f.read()).hexdigest()


@pytest.fixture(scope="session")
def golden_sample():
    if not GROUPS:
        pytest.skip("reference mount has no committed golden refs")
    if not os.path.exists(SAMPLE):
        pytest.skip("reference sample video absent: golden refs record "
                    "outputs for that exact file")
    recorded = next(iter(GROUPS.values()))
    any_ref = _load_ref(next(iter(recorded.values())))
    if _md5(SAMPLE) != any_ref["video_path_md5"]:
        pytest.skip("sample video md5 differs from the one the refs recorded")
    return SAMPLE


_RESULTS = {}  # (family, variant) -> (out_dict, value_tier: bool)
_TIER_LOG = {}  # group id -> "value" | "shape"


def _extract_group(family: str, variant: str, sample: str, tmp_root: Path):
    key = (family, variant)
    if key in _RESULTS:
        return _RESULTS[key]

    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.registry import get_extractor_cls

    ref_args = _load_ref(next(iter(GROUPS[key].values())))["args"]
    patch = {k: ref_args[k] for k in _ARG_ALLOWLIST if k in ref_args}
    patch.update({
        "video_paths": sample,
        "device": "cpu",
        "allow_random_weights": True,
        "on_extraction": "print",
        "output_path": str(tmp_root / family / variant / "out"),
        "tmp_path": str(tmp_root / family / variant / "tmp"),
    })
    # Golden fps mode rides the VALIDATED `fps_mode` config key (select |
    # reencode, config.sanity_check) — VFT_GOLDEN_FPS_MODE is only this
    # harness's way of injecting it into every golden run's config.
    # reencode decodes fps-resampled variants through the reference's
    # lossy re-encoded intermediate — the committed golden refs were
    # computed from those pixels, so a value-tier run on a host with real
    # weights (+ ffmpeg for byte-exact provenance) should set it
    # (VERDICT r4 missing #2; utils/io.py module docstring)
    golden_fps_mode = os.environ.get("VFT_GOLDEN_FPS_MODE")
    if golden_fps_mode:
        patch["fps_mode"] = golden_fps_mode
    cfg = load_config(family, patch)
    sanity_check(cfg)

    value_tier = _value_tier_available(family, ref_args)
    wav_ctx = contextlib.nullcontext()
    if family == "vggish" and shutil.which("ffmpeg") is None:
        # No binary to rip the real audio track. Instead of skipping the
        # variant, synthesize a wav whose duration is derived from the
        # RECORDED example count via Google's published VGGish framing
        # constants (16 kHz, 25 ms/10 ms STFT frames, 96-frame
        # non-overlapping examples) — NOT from this repo's frontend — and
        # patch the rip. The real host chain (wav read -> mono mix ->
        # resample_poly -> log-mel -> framing) still runs and must land on
        # exactly that count; values can't match synthetic audio, so the
        # variant is pinned to the shape tier.
        n = int(_load_ref(GROUPS[key]["vggish"])["data"].shape[0])
        s16 = 160 * (96 * n + 47) + 400   # mid-window: exactly n examples
        s44 = int(round(s16 * 44100 / 16000))
        rng = np.random.default_rng(0)
        pcm = (rng.uniform(-0.5, 0.5, size=(s44, 2)) * 32767).astype("<i2")
        synth_dir = tmp_root / family / variant
        synth_dir.mkdir(parents=True, exist_ok=True)
        wav = str(synth_dir / "synth_44k.wav")
        with wave.open(wav, "wb") as w:
            w.setnchannels(2)
            w.setsampwidth(2)
            w.setframerate(44100)
            w.writeframes(pcm.tobytes())
        aac = str(synth_dir / "synth.aac")  # rip returns (wav, aac); the
        Path(aac).touch()                   # extractor removes both
        wav_ctx = mock.patch(
            "video_features_tpu.extractors.vggish.extract_wav_from_mp4",
            lambda vp, tmp: (wav, aac))
        value_tier = False

    extractor = get_extractor_cls(family)(cfg)
    with wav_ctx:
        if value_tier:
            out = extractor.extract(sample)
        else:
            with _stub_forwards():
                out = extractor.extract(sample)
    _RESULTS[key] = (out, value_tier)
    return _RESULTS[key]


# --------------------------------------------------------------------- tests


@pytest.mark.parametrize("group", list(GROUPS) if GROUPS else [],
                         ids=GROUP_IDS)
def test_golden_variant(group, golden_sample, tmp_path_factory):
    family, variant = group
    refs = {k: _load_ref(p) for k, p in GROUPS[group].items()}

    out, value_tier = _extract_group(
        family, variant, golden_sample,
        tmp_path_factory.mktemp("golden"))
    _TIER_LOG[f"{family}-{variant}"] = "value" if value_tier else "shape"

    # VFT_REQUIRE_VALUE_TIER=fam1,fam2 (or 'all'): a required family
    # silently falling back to the shape tier is a FAILURE, not a quieter
    # pass — the contract a weights-arrival run needs (VERDICT r4 #7)
    required = {f.strip() for f in
                os.environ.get("VFT_REQUIRE_VALUE_TIER", "").split(",")
                if f.strip()}
    if not value_tier and ("all" in required or family in required):
        from video_features_tpu.weights import store
        ref_args = next(iter(refs.values()))["args"]
        keys = _weight_keys(family, ref_args)
        missing = [k for k in keys if store.find_checkpoint(k) is None]
        why = (f"checkpoints are missing for {missing}" if missing else
               "the variant is pinned to the shape tier for a non-weight "
               "reason (vggish with no ffmpeg to rip real audio)")
        pytest.fail(
            f"{family}/{variant}: VFT_REQUIRE_VALUE_TIER demands value-"
            f"level verification but {why} — the run would have silently "
            "downgraded to the shape tier")

    for key, ref in refs.items():
        want = ref["data"]
        assert key in out, f"extractor output is missing key {key!r}"
        got = np.asarray(out[key])

        if key == "fps":
            # recorded via the same cv2 metadata read — must match exactly
            np.testing.assert_allclose(got, want, rtol=0, atol=1e-9,
                                       err_msg=f"{family}/{variant}: fps")
            continue
        if key == "timestamps_ms":
            assert got.shape == want.shape, (
                f"{family}/{variant}: {got.shape[0]} timestamps vs recorded "
                f"{want.shape[0]} — frame selection/windowing diverged")
            np.testing.assert_allclose(
                got, want, rtol=1e-9, atol=1e-6,
                err_msg=f"{family}/{variant}: timestamps_ms")
            continue

        # feature arrays: shape always; values only with real weights
        assert got.shape == tuple(want.shape), (
            f"{family}/{variant}: feature {key!r} shape {got.shape} vs "
            f"recorded {tuple(want.shape)}")
        if value_tier:
            # vggish: pre-decided wider tolerance. The sample's 44.1 kHz
            # audio goes through scipy resample_poly where the reference
            # used resampy (ops/audio.py header) — ~1e-3 waveform delta
            # compounds through log-mel + the conv stack to ~1e-1 feature
            # scale. All other families keep the cross-backend tolerance.
            atol, rtol = (1e-1, 1e-2) if family == "vggish" else (1e-2, 1e-3)
            np.testing.assert_allclose(
                got.astype(np.float64), want.astype(np.float64),
                atol=atol, rtol=rtol,
                err_msg=f"{family}/{variant}: feature {key!r} values "
                        "(cross-backend tolerance)")

    # internal consistency the refs can't see but the contract implies:
    # frame-wise features carry one row per timestamp; flow families read n
    # frames (n timestamps) and emit n-1 pairwise flows (reference
    # base_flow_extractor.py:77-95)
    if family in ("resnet", "clip") and family in out:
        assert out[family].shape[0] == out["timestamps_ms"].shape[0]
    if family in ("raft", "pwc") and family in out:
        assert out[family].shape[0] == out["timestamps_ms"].shape[0] - 1


def test_value_tier_coverage_report():
    """Explicit accounting of which variants got value-level verification.

    The value tier needs real pretrained checkpoints, which this environment
    cannot fetch (no egress; reference blobs absent per .MISSING_LARGE_BLOBS).
    This test makes that visible instead of letting skips hide it.
    """
    if not _TIER_LOG:
        pytest.skip("no golden variants ran")
    shape_only = sorted(g for g, t in _TIER_LOG.items() if t == "shape")
    value = sorted(g for g, t in _TIER_LOG.items() if t == "value")
    print(f"\ngolden refs: {len(value)} value-verified, "
          f"{len(shape_only)} shape/fps/timestamps-verified (no weights)")
    for g in value:
        print(f"  value: {g}")
    for g in shape_only:
        print(f"  shape: {g}")
    assert _TIER_LOG, "golden harness ran no variants"
