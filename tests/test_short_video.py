"""Videos shorter than one stack window: the drop-partial-tail contract.

The reference drops the trailing partial stack (``form_slices``,
utils/utils.py:59-68) and its i3d loop only fires on full ``stack_size+1``
accumulations — a video shorter than one window therefore produces EMPTY
feature arrays, a warning from the sink, and no crash (the per-video error
isolation never even engages). Pinned here for the clip-stack and i3d
pipelines, which do their own windowing.
"""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def six_frame_video(tmp_path_factory):
    import cv2
    path = str(tmp_path_factory.mktemp("short") / "v_short6.mp4")
    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), 10.0,
                        (64, 64))
    if not w.isOpened():
        pytest.skip("cv2 cannot encode mp4v")
    rng = np.random.default_rng(0)
    base = rng.integers(0, 255, size=(64, 64, 3), dtype=np.uint8)
    for t in range(6):
        w.write(np.roll(base, t, axis=1))
    w.release()
    return path


def _cfg(ft, video, tmp_path, **patch):
    from video_features_tpu.config import load_config, sanity_check
    cfg = load_config(ft, dict({
        "video_paths": video, "device": "cpu",
        "allow_random_weights": True, "on_extraction": "save_numpy",
        "output_path": str(tmp_path / "out"),
        "tmp_path": str(tmp_path / "tmp")}, **patch))
    sanity_check(cfg)
    return cfg


def test_r21d_shorter_than_stack_yields_empty(six_frame_video, tmp_path,
                                              capsys):
    from video_features_tpu.registry import get_extractor_cls
    # default r2plus1d_18_16 stack=16 > 6 frames -> zero windows
    ex = get_extractor_cls("r21d")(_cfg("r21d", six_frame_video, tmp_path))
    feats = ex._extract(six_frame_video)
    assert feats["r21d"].shape[0] == 0
    out = capsys.readouterr().out
    assert "empty" in out.lower()  # the sink's empty-value warning fired


def test_i3d_shorter_than_stack_yields_empty(six_frame_video, tmp_path):
    from video_features_tpu.registry import get_extractor_cls
    ex = get_extractor_cls("i3d")(_cfg(
        "i3d", six_frame_video, tmp_path,
        stack_size=10, step_size=10, streams="rgb"))
    feats = ex.extract(six_frame_video)
    assert feats["rgb"].shape[0] == 0
    assert feats["timestamps_ms"].shape == (0,)
    assert float(feats["fps"]) == 10.0
