"""I420 wire format: device conversion parity vs cv2, ingest-mode feature
consistency on the flagship R(2+1)D path."""
import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.ops import colorspace as cs  # noqa: E402


def test_packed_roundtrip_matches_cv2():
    rng = np.random.default_rng(1)
    frame = rng.integers(0, 256, size=(112, 112, 3), dtype=np.uint8)
    packed = cs.rgb_to_yuv420(frame)
    assert packed.shape == (cs.packed_size(112, 112),)
    want = cv2.cvtColor(packed.reshape(168, 112),
                        cv2.COLOR_YUV2RGB_I420).astype(np.float32)
    got = np.asarray(cs.yuv420_packed_to_rgb(packed[None], 112, 112))[0]
    assert got.shape == (112, 112, 3)
    # same studio-swing BT.601 + nearest chroma upsample as cv2; <1 level
    assert np.abs(got - want).max() < 1.0


def test_odd_dims_rejected():
    with pytest.raises(ValueError):
        cs.packed_size(113, 112)


def test_natural_frame_chroma_loss_is_small(sample_video):
    """On real video (already 4:2:0 at the codec level) the re-subsampled
    chroma loses almost nothing."""
    cap = cv2.VideoCapture(sample_video)
    ok, bgr = cap.read()
    cap.release()
    assert ok
    rgb = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)[:224, :224]
    got = np.asarray(cs.yuv420_packed_to_rgb(
        cs.rgb_to_yuv420(rgb)[None], 224, 224))[0]
    err = np.abs(got - rgb.astype(np.float32))
    assert err.mean() < 2.0, f"mean abs err {err.mean()}"


@pytest.mark.parametrize("family,stack,ingest", [
    ("r21d", 8, "uint8"),
    ("r21d", 8, "yuv420"),
    # ~32s (S3D head needs stack >= 16, so the clips are 2x deeper): the
    # r21d yuv420 case keeps the packed-wire path in the quick tier
    pytest.param("s3d", 16, "yuv420", marks=pytest.mark.slow),
])
def test_ingest_modes_match_float32(sample_video, tmp_path, family, stack,
                                    ingest):
    """Every family's compressed wire formats must reproduce the float32
    path's features (random weights, natural frames): cosine > 0.99."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.registry import get_extractor_cls

    def run(mode, sub):
        cfg = load_config(family, {
            "video_paths": sample_video, "device": "cpu",
            "extraction_fps": 2, "stack_size": stack, "step_size": stack,
            "clip_batch_size": 2, "ingest": mode,
            "allow_random_weights": True,
            "output_path": str(tmp_path / sub / "o"),
            "tmp_path": str(tmp_path / sub / "t"),
        })
        sanity_check(cfg)
        return get_extractor_cls(family)(cfg).extract(sample_video)[family]

    ref = run("float32", "f32")
    got = run(ingest, ingest)
    assert got.shape == ref.shape and ref.shape[0] > 0
    cos = np.sum(ref * got, axis=1) / (
        np.linalg.norm(ref, axis=1) * np.linalg.norm(got, axis=1) + 1e-9)
    assert np.all(cos > 0.99), \
        f"{family} {ingest} features diverged: cos={cos}"


@pytest.mark.parametrize("family", ["resnet", "clip"])
def test_framewise_yuv420_ingest_matches_uint8(sample_video, tmp_path,
                                               family):
    """Frame-wise families: packed-I420 wire reproduces the uint8 (default,
    lossless) path's features on natural frames: cosine > 0.99."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.registry import get_extractor_cls

    def run(mode, sub):
        cfg = load_config(family, {
            "video_paths": sample_video, "device": "cpu",
            "extraction_fps": 1, "batch_size": 8, "ingest": mode,
            "allow_random_weights": True,
            "output_path": str(tmp_path / sub / "o"),
            "tmp_path": str(tmp_path / sub / "t"),
        })
        sanity_check(cfg)
        return get_extractor_cls(family)(cfg).extract(sample_video)[family]

    ref = run("uint8", "u8")
    got = run("yuv420", "yuv")
    assert got.shape == ref.shape and ref.shape[0] > 0
    cos = np.sum(ref * got, axis=1) / (
        np.linalg.norm(ref, axis=1) * np.linalg.norm(got, axis=1) + 1e-9)
    assert np.all(cos > 0.99), \
        f"{family} yuv420 features diverged: cos={cos}"
