"""Ground-truth parity of the in-process fps resampler vs the ffmpeg binary.

`VideoSource` replaces the reference's ``ffmpeg -filter:v fps=N`` re-encode
(reference utils/io.py:14-36) with pure frame selection (`fps_filter_map`).
The rule is pinned two ways:

  - against recorded reality: the golden refs were produced with the real
    binary and fix the output frame counts (tests/test_golden.py);
  - against the binary itself, HERE, whenever ``ffmpeg`` is installed (CI
    installs it; the image this repo usually develops in does not ship it —
    then these tests skip visibly, not silently pass).

For each target fps the sample is re-encoded by the real binary and decoded;
the frame COUNT must equal ``len(fps_filter_map(...))`` and each output
frame must be closest (mean |Δ|, despite x264 loss) to exactly the source
frame the map selects — not its neighbors.
"""
import shutil
import subprocess

import cv2
import numpy as np
import pytest

from video_features_tpu.utils.io import fps_filter_map, get_video_props

pytestmark = pytest.mark.skipif(
    shutil.which("ffmpeg") is None,
    reason="ffmpeg binary not installed (parity vs the real binary runs in "
           "CI; the frame-count rule itself is golden-pinned in "
           "test_golden.py)")


def _decode_all(path: str):
    cap = cv2.VideoCapture(path)
    frames = []
    try:
        while True:
            ok, f = cap.read()
            if not ok:
                break
            frames.append(cv2.cvtColor(f, cv2.COLOR_BGR2RGB))
    finally:
        cap.release()
    return frames


@pytest.mark.parametrize("dst_fps", [1, 3, 25, 19.62])
def test_fps_filter_matches_real_ffmpeg(dst_fps, sample_video, tmp_path):
    out = tmp_path / f"reenc_{dst_fps}.mp4"
    # the reference's exact invocation shape (utils/io.py:27-30)
    cmd = ["ffmpeg", "-hide_banner", "-loglevel", "panic", "-y",
           "-i", str(sample_video), "-filter:v", f"fps=fps={dst_fps}",
           str(out)]
    subprocess.run(cmd, check=True)

    src = _decode_all(str(sample_video))
    got = _decode_all(str(out))
    props = get_video_props(sample_video)
    mapping = fps_filter_map(len(src), props["fps"], float(dst_fps))

    assert len(got) == len(mapping), (
        f"fps={dst_fps}: real ffmpeg emitted {len(got)} frames, "
        f"fps_filter_map predicts {len(mapping)}")

    # content check: each re-encoded frame must be nearest to the predicted
    # source frame; x264 loss is far smaller than one frame of motion.
    # Cast candidates lazily — only ~100 frames are ever compared.
    for k in range(0, len(got), max(len(got) // 20, 1)):  # ~20 spot checks
        g = got[k].astype(np.float32)
        pred = int(mapping[k])
        cands = range(max(pred - 2, 0), min(pred + 3, len(src)))
        diffs = {i: float(np.mean(np.abs(src[i].astype(np.float32) - g)))
                 for i in cands}
        best = min(diffs, key=diffs.get)
        assert best == pred, (
            f"fps={dst_fps}: output frame {k} is closest to source frame "
            f"{best}, map predicts {pred} (diffs {diffs})")
