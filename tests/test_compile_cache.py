"""Fleet-shared persistent XLA compile cache (compile_cache.py): keying,
verify-before-trust at the executable level, and the warmup-then-extract
zero-miss contract (ISSUE 11).

Contracts pinned here:
  - the entry key is invariant under NON_SEMANTIC config churn (output
    paths, worker counts, telemetry/fleet/inject switches — cache.py's
    canonicalization, reused verbatim) and under ``resize=auto`` vs its
    resolution, and CHANGES on semantic keys;
  - a jax/jaxlib/backend version change changes the environment
    fingerprint, which resolves to a DIFFERENT entry directory — the
    miss-on-version-change contract (a stale executable can never be
    offered to a new runtime);
  - verify-before-trust: a sealed file whose bytes rotted, and a file a
    crashed writer never sealed, are both DELETED at attach (clean miss,
    recompile) — never handed to the XLA deserializer;
  - warmup-then-extract zero-miss: after ``vft-warmup`` populates the
    triple, a fresh extraction process reports compile-cache hits > 0
    and misses == 0 in its run manifest.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from video_features_tpu import compile_cache as cc

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def cc_detached():
    """Detach the process-global entry around a test and restore JAX's
    compilation-cache config afterwards, so in-process attach tests
    cannot leak state into the rest of the suite."""
    import jax
    prev = jax.config.jax_compilation_cache_dir
    cc.detach_for_tests()
    yield
    cc.detach_for_tests()
    jax.config.update("jax_compilation_cache_dir", prev)
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache()
    except Exception:
        pass


# -- keying ------------------------------------------------------------------

BASE = {"feature_type": "resnet", "model_name": "resnet18",
        "extraction_fps": 4, "batch_size": 16, "on_extraction": "save_numpy",
        "output_path": "./output", "video_workers": 1, "telemetry": False,
        "compile_cache": True, "compile_cache_dir": None}


@pytest.mark.quick
def test_entry_key_invariant_under_non_semantic_churn(tmp_path):
    _, env_fp = cc.env_fingerprint()
    key = cc.entry_key("resnet", cc.config_fingerprint(BASE), env_fp)
    churned = dict(BASE, output_path=str(tmp_path), video_workers=8,
                   telemetry=True, trace=True, health=True,
                   retry_attempts=5, fleet="queue", fleet_lease_s=5,
                   inject="seed=1;sink.fsync=enospc@n1",
                   compile_cache_dir=str(tmp_path / "cc"),
                   cache=True, cache_dir=str(tmp_path / "fc"))
    assert cc.entry_key("resnet", cc.config_fingerprint(churned),
                        env_fp) == key
    # semantic keys DO key: a different network or frame selection is a
    # different program set
    assert cc.entry_key("resnet", cc.config_fingerprint(
        dict(BASE, model_name="resnet50")), env_fp) != key
    assert cc.entry_key("resnet", cc.config_fingerprint(
        dict(BASE, extraction_fps=2)), env_fp) != key
    # family is its own axis
    assert cc.entry_key("clip", cc.config_fingerprint(BASE),
                        env_fp) != key


@pytest.mark.quick
def test_resolved_overlay_makes_auto_equal_its_resolution():
    # a save-sink run predicts resize=auto -> device: same key as the
    # explicit setting (the feature cache's auto-equivalence, applied
    # pre-construction via the driver-side predictor)
    auto = dict(BASE, resize="auto")
    explicit = dict(BASE, resize="device")
    fp_auto = cc.config_fingerprint(auto, cc.resolved_overlay(auto))
    fp_explicit = cc.config_fingerprint(explicit,
                                        cc.resolved_overlay(explicit))
    assert fp_auto == fp_explicit
    # a print run resolves host — a different program set, different key
    printy = dict(BASE, resize="auto", on_extraction="print")
    assert cc.config_fingerprint(
        printy, cc.resolved_overlay(printy)) != fp_auto


@pytest.mark.quick
def test_env_fingerprint_misses_on_version_change(tmp_path):
    env, fp = cc.env_fingerprint()
    assert env["jax"] and env["backend"] == "cpu"
    assert "cpu_features" in env  # CPU entries are microarch-scoped
    _, fp_jax = cc.env_fingerprint(jax_version="99.0.0")
    _, fp_jaxlib = cc.env_fingerprint(jaxlib_version="99.0.0")
    _, fp_backend = cc.env_fingerprint(backend="tpu", device_kind="v5e")
    assert len({fp, fp_jax, fp_jaxlib, fp_backend}) == 4
    # a changed fingerprint resolves to a DIFFERENT directory: the new
    # runtime starts cold instead of deserializing a stale executable
    cfg = cc.config_fingerprint(BASE)
    dirs = {cc.CompileCacheEntry(str(tmp_path), "resnet", cfg, f).dir
            for f in (fp, fp_jax, fp_jaxlib, fp_backend)}
    assert len(dirs) == 4


# -- verify-before-trust ------------------------------------------------------

def _fake_entry(tmp_path) -> cc.CompileCacheEntry:
    entry = cc.CompileCacheEntry(str(tmp_path / "store"), "resnet",
                                 "c" * 64, "e" * 64)
    os.makedirs(entry.dir, exist_ok=True)
    return entry


@pytest.mark.quick
def test_seal_then_verify_keeps_sealed_files(tmp_path):
    entry = _fake_entry(tmp_path)
    for name in ("jit_a-1111-cache", "jit_b-2222-cache"):
        Path(entry.dir, name).write_bytes(os.urandom(256))
    assert not entry.is_warm()  # unsealed files carry no warm promise
    assert entry.seal() == 2
    assert entry.is_warm()
    assert entry.verify() == {"verified": 2, "dropped": 0}
    assert entry.is_warm()


@pytest.mark.quick
def test_corrupt_sealed_file_dropped_not_served(tmp_path):
    entry = _fake_entry(tmp_path)
    good, bad = "jit_a-1111-cache", "jit_b-2222-cache"
    Path(entry.dir, good).write_bytes(os.urandom(256))
    Path(entry.dir, bad).write_bytes(os.urandom(256))
    entry.seal()
    # bit rot / a torn pre-atomic write: same size, different bytes
    Path(entry.dir, bad).write_bytes(os.urandom(256))
    Path(entry.dir, bad[:-len("-cache")] + "-atime").write_bytes(b"t")
    assert entry.verify() == {"verified": 1, "dropped": 1}
    assert not Path(entry.dir, bad).exists()  # never reaches XLA
    assert not Path(entry.dir,
                    bad[:-len("-cache")] + "-atime").exists()
    assert Path(entry.dir, good).exists()
    # a sealed file is now missing -> the warm promise is off until the
    # recompile re-seals
    assert not entry.is_warm()
    entry.seal()
    assert entry.is_warm()


@pytest.mark.quick
def test_unsealed_file_dropped_at_attach(tmp_path):
    entry = _fake_entry(tmp_path)
    Path(entry.dir, "jit_a-1111-cache").write_bytes(os.urandom(128))
    entry.seal()
    # a writer died mid-run: its file exists but was never sealed —
    # completeness is unprovable, so it is dropped (clean recompile)
    Path(entry.dir, "jit_orphan-9999-cache").write_bytes(os.urandom(128))
    assert entry.verify() == {"verified": 1, "dropped": 1}
    assert not Path(entry.dir, "jit_orphan-9999-cache").exists()


# -- enable/attach semantics --------------------------------------------------

@pytest.mark.quick
def test_resolve_root_semantics(tmp_path, monkeypatch):
    assert cc.resolve_root({"compile_cache": False}) is None
    # auto on the CPU backend without an explicit dir: disabled (tests
    # and casual runs must not grow a store in $HOME)
    assert cc.resolve_root({"compile_cache": "auto"}) is None
    assert cc.resolve_root({"compile_cache": "auto",
                            "compile_cache_dir": str(tmp_path)}) \
        == str(tmp_path)
    monkeypatch.setenv("VFT_COMPILE_CACHE_DIR", str(tmp_path / "envroot"))
    assert cc.resolve_root({"compile_cache": True}) \
        == str(tmp_path / "envroot")
    with pytest.raises(ValueError, match="compile_cache"):
        cc.resolve_root({"compile_cache": "bogus"})


@pytest.mark.quick
def test_attach_is_first_wins_process_global(tmp_path, cc_detached):
    args_a = dict(BASE, compile_cache_dir=str(tmp_path / "store"))
    entry = cc.attach("resnet", args_a)
    assert entry is not None and cc.active() is entry
    assert os.path.isdir(entry.dir)
    # a second attach (another family, another dir) returns the active
    # entry unchanged — JAX holds one cache directory per process
    again = cc.attach("clip", dict(BASE, feature_type="clip",
                                   compile_cache_dir=str(tmp_path / "b")))
    assert again is entry
    info = cc.active_info()
    assert info["family"] == "resnet" and not info["warm_at_attach"]
    cc.detach_for_tests()
    assert cc.active() is None


# -- warmup-then-extract zero-miss (E2E, fresh processes) --------------------

_EXTRACT_WORKER = """\
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
from video_features_tpu.cli import main
main(json.loads(sys.argv[1]))
"""


def _run_manifest_compile_cache(out: Path) -> dict:
    for p in sorted(out.rglob("_run.json")):
        doc = json.loads(p.read_text())
        if doc.get("compile_cache") is not None:
            return doc["compile_cache"]
    return {}


def test_warmup_then_extract_zero_miss(sample_video, tmp_path):
    """vft-warmup populates the triple; a FRESH extraction process over
    the same semantic config must then report hits > 0 and misses == 0 —
    the joining-host promise, proven across real process boundaries."""
    store = tmp_path / "store"
    overrides = {"model_name": "resnet18", "device": "cpu",
                 "allow_random_weights": True, "extraction_total": 6,
                 "batch_size": 8, "compile_cache": True,
                 "compile_cache_dir": str(store),
                 "video_paths": str(sample_video)}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    warm = subprocess.run(
        [sys.executable, "-c", cc._WARMUP_WORKER, "resnet",
         json.dumps(overrides)], capture_output=True, text=True, env=env,
        timeout=300)
    assert warm.returncode == 0, warm.stderr[-2000:]
    result = json.loads([ln for ln in warm.stdout.splitlines()
                         if ln.startswith("VFT_WARMUP_RESULT ")][-1]
                        [len("VFT_WARMUP_RESULT "):])
    assert result["status"] == "ok", result
    assert result["sealed_files"] > 0
    assert not result["warm_before"]

    argv = ["feature_type=resnet", "model_name=resnet18", "device=cpu",
            "allow_random_weights=true", "on_extraction=save_numpy",
            "extraction_total=6", "batch_size=8", "telemetry=true",
            "compile_cache=true", f"compile_cache_dir={store}",
            f"output_path={tmp_path / 'out'}",
            f"tmp_path={tmp_path / 'tmp'}",
            f"video_paths=[{sample_video}]"]
    run = subprocess.run(
        [sys.executable, "-c", _EXTRACT_WORKER, json.dumps(argv)],
        capture_output=True, text=True, env=env, timeout=300)
    assert run.returncode == 0, (run.stdout + run.stderr)[-2000:]
    assert "compile cache: entry" in run.stdout and "warm" in run.stdout
    summary = _run_manifest_compile_cache(tmp_path / "out")
    assert summary.get("misses", 0) == 0, summary
    assert summary.get("hits", 0) > 0, summary
    assert summary.get("warm_at_attach") is True
