"""Multi-family shared-decode extraction (parallel/fanout.py +
extractors/multi.py + the CLI comma-list surface).

Contracts pinned here:
  - the FrameBus union decode pass delivers every subscriber a stream
    bit-identical to its own private VideoSource (frames, timestamps,
    indices, props) across resampled/native/total plans and rgb/bgr
    channel orders;
  - a multi-family CLI run produces BIT-IDENTICAL outputs to the
    corresponding single-family runs (frame-wise + clip-stack + the
    vggish audio family, video_workers 1 and 2), honoring per-family
    dotted overrides;
  - when every family's outputs already exist the video costs zero
    decode (no SharedDecodeSession is even constructed) and the tally
    counts per-family skips;
  - one family's POISON failure journals/quarantines ONLY that family —
    its siblings' outputs and journals stay clean.

The wav rip is monkeypatched (no ffmpeg in CI): the synthesized sample
has no audio track, and the deterministic per-stem tone makes the
single-vs-multi vggish comparison meaningful while exercising the
session's rip-once-share-many path.
"""
import json
import shutil
import threading
import wave
import zlib
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.parallel import fanout
from video_features_tpu.parallel.fanout import FrameBus
from video_features_tpu.utils.io import VideoSource

#: frame-wise + clip-stack + audio, as the shared-decode design carves
#: the world; keep overrides cheap — tier-1 runs on a 1-core CPU host
FAMILY_OVERRIDES = {
    "resnet": ["resnet.model_name=resnet18", "resnet.batch_size=8",
               "resnet.extraction_total=6"],
    "r21d": ["r21d.extraction_fps=1", "r21d.stack_size=10",
             "r21d.step_size=10"],
    "vggish": [],
}


def _fake_rip(video_path, tmp_path):
    """Deterministic per-stem tone standing in for the ffmpeg wav rip:
    same (video -> wav) function for single and multi runs, distinct
    per video so a cross-video mixup in the shared session would show."""
    stem = Path(video_path).stem
    freq = 200.0 + zlib.crc32(stem.encode()) % 500
    t = np.arange(int(16000 * 2.5)) / 16000.0
    tone = (0.4 * np.sin(2 * np.pi * freq * t) * 32767).astype("<i2")
    Path(tmp_path).mkdir(parents=True, exist_ok=True)
    wav = Path(tmp_path) / f"{stem}.wav"
    aac = Path(tmp_path) / f"{stem}.aac"  # the two-step rip's intermediate
    with wave.open(str(wav), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(16000)
        w.writeframes(tone.tobytes())
    aac.write_bytes(b"")
    return str(wav), str(aac)


@pytest.fixture(scope="module", autouse=True)
def _patched_wav_rip():
    # module-scoped (plain monkeypatch is function-scoped): the
    # module-scoped single_runs fixture below rips wavs too
    mp = pytest.MonkeyPatch()
    mp.setattr("video_features_tpu.extractors.vggish."
               "extract_wav_from_mp4", _fake_rip)
    yield
    mp.undo()


# ------------------------------------------------------------------ bus unit

@pytest.mark.quick
def test_bus_bit_identical_to_serial_sources(sample_video):
    """Union decode == N private serial decodes, for resampled / native /
    total plans, rgb / bgr delivery, with and without a transform."""
    def tf(x):
        return x[::4, ::4].astype(np.float32) / 255.0

    specs = {
        "a": dict(fps=3, transform=tf, channel_order="rgb"),
        "b": dict(fps=1, transform=None, channel_order="bgr"),
        "c": dict(total=7, transform=None, channel_order="rgb"),
    }
    bus = FrameBus(sample_video, list(specs), depth=8)
    got, errs = {}, []

    def consume(name, kw):
        try:
            sub = bus.subscribe(name, **kw)
            got[name] = (list(sub.frames()), sub.fps, len(sub))
        except BaseException as e:  # surfaced below, not swallowed
            errs.append((name, e))

    threads = [threading.Thread(target=consume, args=(n, kw))
               for n, kw in specs.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    for name, kw in specs.items():
        src = VideoSource(sample_video, **kw)
        want = list(src.frames())
        frames, fps, n = got[name]
        assert (fps, n) == (src.fps, len(src)), name
        assert len(frames) == len(want), name
        for (xw, tw, iw), (xg, tg, ig) in zip(want, frames):
            assert (tw, iw) == (tg, ig), name
            np.testing.assert_array_equal(xw, xg, err_msg=name)
        ms = bus.shared_ms(name)
        assert ms is not None and ms > 0, (name, ms)


def test_bus_probe_failure_poisons_every_family(tmp_path):
    """A bus over an undecodable input fails each subscriber with the
    worker-protocol-shaped error classify() maps to POISON."""
    from video_features_tpu.utils import faults
    bad = tmp_path / "not_a_video.mp4"
    bad.write_bytes(b"junk")
    bus = FrameBus(str(bad), ["a"], depth=4)
    with pytest.raises(RuntimeError,
                       match="shared decode probe failed") as ei:
        bus.subscribe("a", fps=2)
    assert faults.classify(ei.value) == faults.POISON
    # duplicate/unexpected subscriptions decline -> private-source fallback
    assert bus.subscribe("a") is None
    assert bus.subscribe("b") is None


# ------------------------------------------------------------- CLI E2E

def _base_args(tmp_path, videos):
    return ["device=cpu", "allow_random_weights=true",
            "on_extraction=save_numpy", "retry_attempts=1",
            f"tmp_path={tmp_path / 'tmp'}",
            f"video_paths=[{','.join(videos)}]"]


@pytest.fixture(scope="module")
def corpus(tmp_path_factory, sample_video):
    td = tmp_path_factory.mktemp("multi_corpus")
    vids = []
    for i in range(2):
        dst = td / f"v_mf_{i}.mp4"
        shutil.copy(sample_video, dst)
        vids.append(str(dst))
    return td, vids


@pytest.fixture(scope="module")
def single_runs(corpus, _patched_wav_rip):
    """Reference single-family outputs, computed once for the module."""
    from video_features_tpu.cli import main as cli_main
    td, vids = corpus
    out = td / "single"
    for fam, over in FAMILY_OVERRIDES.items():
        flat = [o.split(".", 1)[1] for o in over]  # strip the fam prefix
        cli_main([f"feature_type={fam}", f"output_path={out}"]
                 + flat + _base_args(td, vids))
    return out


@pytest.mark.parametrize("workers", [
    pytest.param(1, marks=pytest.mark.quick),
    # workers=2 (two concurrent shared-decode sessions) runs in the full
    # CI tier; tier-1's 870s budget keeps the matrix at workers=1 (the
    # per-video fan-out concurrency — 3 family threads — is exercised by
    # every multi test regardless)
    pytest.param(2, marks=pytest.mark.slow)])
def test_multi_cli_bit_identical_to_singles(corpus, single_runs, tmp_path,
                                            workers):
    from video_features_tpu.cli import main as cli_main
    td, vids = corpus
    out = tmp_path / "multi"
    families = ",".join(FAMILY_OVERRIDES)
    overrides = [o for over in FAMILY_OVERRIDES.values() for o in over]
    cli_main([f"feature_type={families}", f"output_path={out}",
              f"video_workers={workers}", "telemetry=true"]
             + overrides + _base_args(td, vids))

    want = sorted(p.relative_to(single_runs)
                  for p in single_runs.rglob("*.npy"))
    got = sorted(p.relative_to(out) for p in out.rglob("*.npy"))
    # resnet [feat, fps, timestamps] x2 videos + r21d x2 + vggish x2
    assert want == got and len(want) == 10
    for rel in want:
        np.testing.assert_array_equal(
            np.load(single_runs / rel), np.load(out / rel),
            err_msg=f"{rel} differs between single-family and "
                    f"shared-decode runs (workers={workers})")

    # per-(video, family) spans carry the shared-decode attribution
    spans = [json.loads(line)
             for line in (out / "_telemetry.jsonl").open()]
    by_fam = {}
    for s in spans:
        by_fam.setdefault(s["feature_type"], []).append(s)
    assert sorted(by_fam) == sorted(FAMILY_OVERRIDES)
    for fam in ("resnet", "r21d"):  # visual families shared the decode
        assert all(s["status"] == "done" and s["decode_shared_ms"] > 0
                   for s in by_fam[fam]), by_fam[fam]
    assert all(s["decode_shared_ms"] is None for s in by_fam["vggish"])


@pytest.mark.quick
def test_multi_all_skipped_runs_zero_decode(corpus, single_runs,
                                            monkeypatch, capsys):
    """Second run over complete outputs: every family skips up front and
    NO shared-decode session (hence no decoder, no wav rip) is built."""
    from video_features_tpu.cli import main as cli_main
    td, vids = corpus
    families = ",".join(FAMILY_OVERRIDES)
    overrides = [o for over in FAMILY_OVERRIDES.values() for o in over]
    # the single-family reference outputs use the same namespacing the
    # multi run expects, so pointing the multi run at them exercises the
    # every-family-already-done path without re-extracting anything
    argv = ([f"feature_type={families}", f"output_path={single_runs}"]
            + overrides + _base_args(td, vids))
    capsys.readouterr()

    def _must_not_construct(*a, **kw):
        raise AssertionError("all families already exist: the shared "
                             "decode session must not be constructed")
    monkeypatch.setattr(fanout, "SharedDecodeSession", _must_not_construct)
    cli_main(argv)
    outtxt = capsys.readouterr().out
    assert f"{len(FAMILY_OVERRIDES) * len(vids)} already done" in outtxt
    for fam in FAMILY_OVERRIDES:  # per-family skip tally in the summary
        assert f"{fam}: 0 extracted, {len(vids)} already done" in outtxt


@pytest.mark.quick
def test_poison_family_is_isolated(corpus, tmp_path):
    """An injected POISON failure in one family's transform journals and
    fails ONLY that family; siblings' outputs + journals stay intact."""
    from video_features_tpu.config import (load_multi_config,
                                           sanity_check_multi)
    from video_features_tpu.extractors.multi import MultiExtractor
    from video_features_tpu.utils.faults import PoisonError

    td, vids = corpus
    out = tmp_path / "iso"
    overrides = {
        "feature_type": "resnet,r21d",
        "device": "cpu", "allow_random_weights": True,
        "on_extraction": "save_numpy", "retry_attempts": 1,
        "output_path": str(out), "tmp_path": str(tmp_path / "t"),
        "video_paths": vids[0],
        "resnet": {"model_name": "resnet18", "batch_size": 8,
                   "extraction_total": 6},
        "r21d": {"extraction_fps": 1, "stack_size": 10, "step_size": 10},
    }
    per = load_multi_config(["resnet", "r21d"], overrides)
    sanity_check_multi(per)
    multi = MultiExtractor(per)

    def poison_transform(frame):
        raise PoisonError("injected: this family chokes on the input")
    multi.extractors["r21d"].host_transform = poison_transform

    failures = []
    statuses = multi.run_video(vids[0], failures=failures)
    assert statuses == {"resnet": "done", "r21d": "error"}
    assert [f["family"] for f in failures] == ["r21d"]

    stem = Path(vids[0]).stem
    assert (out / "resnet" / "resnet18" / f"{stem}_resnet.npy").exists()
    recs = [json.loads(line)
            for line in open(multi.journals["r21d"].path)]
    assert recs and recs[-1]["category"] == "POISON"
    assert not Path(multi.journals["resnet"].path).exists()

    # quarantine on the next run touches only the poisoned family
    statuses2 = multi.run_video(vids[0])
    assert statuses2 == {"resnet": "skipped", "r21d": "quarantined"}
