"""The three scripts/check_*_schema.py CI gates must report torn,
truncated or empty artifact files as findings — never die with a
traceback (a gate that crashes reads as infra flake and gets retried
instead of investigated). Before this suite only trace_report.py's
error path was pinned (tests/test_trace.py)."""
import importlib.util
import json
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parent.parent
SCRIPTS = REPO_ROOT / "scripts"


def _load_script(name):
    spec = importlib.util.spec_from_file_location(name,
                                                  SCRIPTS / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# -- trace gate -------------------------------------------------------------

def test_trace_gate_reports_missing_empty_and_torn_trace(tmp_path):
    gate = _load_script("check_trace_schema")

    missing = tmp_path / "missing"
    missing.mkdir()
    errs = gate.check(missing)
    assert errs and "was not written" in errs[0]

    empty = tmp_path / "empty"
    empty.mkdir()
    (empty / "_trace.json").write_text("")
    errs = gate.check(empty)
    assert errs and "not valid JSON" in errs[0]

    torn = tmp_path / "torn"
    torn.mkdir()
    doc = json.dumps({"traceEvents": [{"ph": "X", "name": "decode",
                                       "ts": 0, "dur": 1, "pid": 1,
                                       "tid": 1}]})
    (torn / "_trace.json").write_text(doc[: len(doc) // 2])
    errs = gate.check(torn)
    assert errs and "not valid JSON" in errs[0]

    hollow = tmp_path / "hollow"
    hollow.mkdir()
    (hollow / "_trace.json").write_text(json.dumps({"traceEvents": []}))
    errs = gate.check(hollow)
    assert errs and "no traceEvents" in errs[0]


def test_trace_gate_reports_torn_heartbeat_not_traceback(tmp_path):
    gate = _load_script("check_trace_schema")
    out = tmp_path / "out"
    out.mkdir()
    # minimal structurally-valid trace so the check reaches the heartbeat
    (out / "_trace.json").write_text(json.dumps({
        "otherData": {"schema": gate.TRACE_SCHEMA},
        "traceEvents": [{"ph": "X", "name": "decode", "ts": 0, "dur": 1,
                         "pid": 1, "tid": 1, "args": {}}]}))
    (out / "_heartbeat_host.json").write_text('{"fanout": {"queue_')
    errs = gate.check(out)  # must return findings, not raise
    assert any("write_json_atomic contract broke" in e for e in errs)


# -- telemetry gate ---------------------------------------------------------

def test_telemetry_gate_reports_torn_schema_file(tmp_path, monkeypatch):
    gate = _load_script("check_telemetry_schema")
    from video_features_tpu.telemetry import schema as tschema
    good = Path(tschema.SPAN_SCHEMA_PATH).read_text()

    for label, payload in (("empty", ""), ("torn", good[: len(good) // 2])):
        broken = tmp_path / f"{label}.schema.json"
        broken.write_text(payload)
        monkeypatch.setattr(tschema, "SPAN_SCHEMA_PATH", str(broken))
        errs = gate.check()
        assert errs and "cannot load" in errs[0], (label, errs)

    monkeypatch.setattr(tschema, "SPAN_SCHEMA_PATH",
                        str(tmp_path / "absent.schema.json"))
    errs = gate.check()
    assert errs and "cannot load" in errs[0]


# -- health gate ------------------------------------------------------------

def test_health_gate_reports_torn_schema_file(tmp_path, monkeypatch):
    gate = _load_script("check_health_schema")
    from video_features_tpu.telemetry import health
    good = Path(health.HEALTH_SCHEMA_PATH).read_text()
    for label, payload in (("empty", ""), ("torn", good[: len(good) // 2])):
        broken = tmp_path / f"{label}.schema.json"
        broken.write_text(payload)
        monkeypatch.setattr(health, "HEALTH_SCHEMA_PATH", str(broken))
        errs = gate.check_static()
        assert errs and "cannot load" in errs[0], (label, errs)


def test_health_jsonl_torn_tail_skipped_not_fatal(tmp_path):
    # the artifact reader every consumer (gate, compare_runs) shares:
    # one good record + a SIGKILL-torn tail -> the good record survives
    import numpy as np
    from video_features_tpu.telemetry import health
    from video_features_tpu.telemetry.jsonl import read_jsonl
    health.digest_features({"feat": np.ones(4, dtype=np.float32)},
                           "v.mp4", "resnet", str(tmp_path))
    path = tmp_path / health.HEALTH_FILENAME
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"schema": "vft.feature_health/1", "video": "torn')
    recs = list(read_jsonl(path))
    assert len(recs) == 1
    assert health.validate_health(recs[0]) == []
    # and compare_runs' loader sees exactly the surviving record
    sys.path.insert(0, str(SCRIPTS))
    try:
        import compare_runs
    finally:
        sys.path.pop(0)
    assert len(compare_runs.load_health(str(tmp_path))) == 1
