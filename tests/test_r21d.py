"""R(2+1)D: Flax-vs-torch parity on transplanted weights, windowing, E2E."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import r21d as r21d_model  # noqa: E402
from tests.torch_oracles import TorchR2Plus1D, randomize_bn_stats  # noqa: E402


def test_flax_matches_torch_oracle():
    torch.manual_seed(0)
    oracle = TorchR2Plus1D(layers=(2, 2, 2, 2)).eval()
    randomize_bn_stats(oracle)
    params = r21d_model.params_from_torch(oracle.state_dict())

    x = np.random.default_rng(0).normal(
        size=(2, 8, 112, 112, 3)).astype(np.float32)
    with torch.no_grad():
        # torch layout (N, C, T, H, W)
        want = oracle(torch.from_numpy(x).permute(0, 4, 1, 2, 3)).numpy()
    model = r21d_model.R2Plus1D("r2plus1d_18_16_kinetics")
    got = np.asarray(model.apply({"params": params["backbone"]}, jnp.asarray(x)))
    assert got.shape == want.shape == (2, 512)
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-4)


def test_r34_variant_converts():
    torch.manual_seed(1)
    oracle = TorchR2Plus1D(layers=(3, 4, 6, 3)).eval()
    randomize_bn_stats(oracle, seed=1)
    params = r21d_model.params_from_torch(oracle.state_dict())
    x = np.random.default_rng(1).normal(
        size=(1, 8, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        want = oracle(torch.from_numpy(x).permute(0, 4, 1, 2, 3)).numpy()
    model = r21d_model.R2Plus1D("r2plus1d_34_8_ig65m_ft_kinetics")
    got = np.asarray(model.apply({"params": params["backbone"]}, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=5e-4)


def test_midplanes_formula():
    # the (2+1)D factorization keeps the 3D-conv parameter count
    assert r21d_model.midplanes(64, 64) == (64 * 64 * 27) // (64 * 9 + 3 * 64)
    assert r21d_model.midplanes(3, 45) == (3 * 45 * 27) // (3 * 9 + 3 * 45)


def test_end_to_end_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.r21d import ExtractR21D

    cfg = load_config("r21d", {
        "video_paths": sample_video, "device": "cpu",
        "extraction_fps": 4, "stack_size": 16, "step_size": 16,
        "clip_batch_size": 2,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractR21D(cfg)
    feats = ex._extract(sample_video)
    # ~18.1s @4fps = 72-73 frames -> 4 complete 16-frame stacks
    assert feats["r21d"].shape == (4, 512)
    # output key contract: only [r21d] (reference extract_r21d.py:57)
    assert ex.output_feat_keys == ["r21d"]
    assert ex._extract(sample_video) is None  # idempotent skip


def test_short_video_yields_empty(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.r21d import ExtractR21D
    cfg = load_config("r21d", {
        "video_paths": sample_video, "device": "cpu",
        "extraction_fps": 1, "stack_size": 64, "step_size": 64,
        "allow_random_weights": True,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    sanity_check(cfg)
    ex = ExtractR21D(cfg)
    feats = ex.extract(sample_video)
    # 18 frames < stack 64: trailing partial stack dropped -> no features
    assert feats["r21d"].shape[0] == 0


def test_streaming_path_matches_buffered(sample_video, tmp_path):
    """step >= stack takes the bounded-memory streaming path; it must
    produce exactly the buffered path's features and window timestamps."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.r21d import ExtractR21D
    from video_features_tpu.utils.io import VideoSource

    cfg = load_config("r21d", {
        "video_paths": sample_video, "device": "cpu",
        "extraction_fps": 4, "stack_size": 8, "step_size": 12,  # gap of 4
        "clip_batch_size": 2, "allow_random_weights": True,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    sanity_check(cfg)
    ex = ExtractR21D(cfg)
    assert ex.step_size >= ex.stack_size

    def make_src():
        return VideoSource(sample_video, batch_size=1,
                           fps=ex.extraction_fps,
                           transform=ex.host_transform,
                           channel_order=ex.frame_channel_order)

    # the streaming window former (disjoint regime, frames dropped as
    # decoded) must produce exactly the windows form_slices prescribes over
    # the materialized sequence — the buffered regime's ground truth
    from video_features_tpu.utils.lists import form_slices
    frames = [f for f, _, _ in make_src().frames()]
    want_windows = form_slices(len(frames), ex.stack_size, ex.step_size)
    got = list(ex._iter_stacks(make_src()))
    assert [w for w, _ in got] == want_windows and len(got) > 0
    for (s, e), stack in got:
        np.testing.assert_array_equal(stack, np.stack(frames[s:e]))
    extracted = ex._extract_grouped(make_src())["r21d"]
    assert extracted.shape[0] == len(want_windows)


def test_show_pred_windows_through_streaming(sample_video, tmp_path, capsys):
    """show_pred flows through the streaming flush with the same (start, end)
    window labels the buffered path printed (reference extract_r21d.py
    prints 'At frames (s, e)' per window)."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.r21d import ExtractR21D

    cfg = load_config("r21d", {
        "video_paths": sample_video, "device": "cpu", "show_pred": True,
        "extraction_fps": 2, "stack_size": 8, "step_size": 8,
        "clip_batch_size": 2, "allow_random_weights": True,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    sanity_check(cfg)
    ex = ExtractR21D(cfg)
    feats = ex.extract(sample_video)
    out = capsys.readouterr().out
    # ~18.1s @2fps = 36-37 frames -> 4 complete 8-frame windows
    assert feats["r21d"].shape[0] == 4
    assert out.count("At frames (") == 4  # no duplicated/spurious windows
    for s in range(0, 32, 8):
        assert f"At frames ({s}, {s + 8})" in out
