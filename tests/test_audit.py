"""The run-invariant auditor (video_features_tpu/audit.py, vft-audit).

Each invariant is exercised on a synthetic output directory built from
the same library primitives the real run uses (append_jsonl,
content_signature, numpy artifacts, queue/done layouts), so the tests
are fast and each violation class is isolated: a consistent dir PASSes,
then one targeted mutation at a time must flip the verdict to FAIL with
the violation named. The end-to-end composition (real CLI chaos runs
ending in an audit) lives in tests/test_chaos.py.
"""
import json
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.audit import audit_run
from video_features_tpu.telemetry.health import digest_array
from video_features_tpu.telemetry.jsonl import append_jsonl

pytestmark = pytest.mark.quick


def _hash(data: bytes) -> str:
    import hashlib
    return hashlib.sha256(data).hexdigest()


def _mk_consistent_run(root: Path) -> Path:
    """A minimal but fully cross-linked output dir: one video, one
    artifact, agreeing health digest + artifact span + queue done marker
    + final heartbeat + an (explained) failure for a second video."""
    root.mkdir(parents=True, exist_ok=True)
    arr = np.arange(16, dtype=np.float32).reshape(4, 4)
    np.save(root / "v0_resnet.npy", arr)
    data = (root / "v0_resnet.npy").read_bytes()
    # health digest of exactly that tensor
    append_jsonl(root / "_health.jsonl",
                 digest_array("resnet", arr, video="v0.mp4",
                              feature_type="resnet"))
    # span record with the artifact event (bytes + sha of what landed)
    append_jsonl(root / "_telemetry.jsonl", {
        "schema": "vft.video_span/1", "video": "v0.mp4", "status": "done",
        "events": [{"kind": "artifact", "key": "resnet",
                    "file": "v0_resnet.npy", "bytes": len(data),
                    "sha256": _hash(data)}],
    })
    # queue: v0 done, v1 errored (journaled below), v2 quarantined+POISON
    q = root / "_queue"
    for d in ("pending", "done", "quarantined", ".staging"):
        (q / d).mkdir(parents=True, exist_ok=True)
    (q / "claimed" / "hostA").mkdir(parents=True, exist_ok=True)
    (q / "done" / "v0-aaaa.json").write_text(json.dumps(
        {"id": "v0-aaaa", "video": "v0.mp4", "status": "done",
         "by": "hostA"}))
    (q / "done" / "v1-bbbb.json").write_text(json.dumps(
        {"id": "v1-bbbb", "video": "v1.mp4", "status": "error",
         "by": "hostA"}))
    (q / "quarantined" / "v2-cccc.json").write_text(json.dumps(
        {"id": "v2-cccc", "video": "v2.mp4", "reclaims": 4}))
    append_jsonl(root / "_failures.jsonl",
                 {"video": "v1.mp4", "category": "FATAL", "attempts": 1,
                  "error": "ValueError: boom"})
    append_jsonl(root / "_failures.jsonl",
                 {"video": "v2.mp4", "category": "POISON", "attempts": 3,
                  "error": "fleet: reclaimed 4x"})
    # hostA exited gracefully: final heartbeat, no claims left
    (root / "_heartbeat_hostA.json").write_text(json.dumps(
        {"host_id": "hostA", "final": True, "time": 0.0,
         "interval_s": 1.0}))
    return root


@pytest.fixture()
def run_dir(tmp_path):
    return _mk_consistent_run(tmp_path / "out")


def _assert_fail(root, needle, **kw):
    ok, violations, _ = audit_run(str(root), **kw)
    assert not ok, f"expected FAIL for {needle!r}"
    assert any(needle in v for v in violations), \
        f"no violation mentioning {needle!r} in {violations}"


def test_consistent_run_passes(run_dir):
    ok, violations, notes = audit_run(str(run_dir), expect_complete=True)
    assert ok, violations


def test_tmp_litter_fails(run_dir):
    (run_dir / "v9_resnet.npy.k3j2.tmp").write_bytes(b"half a write")
    _assert_fail(run_dir, "tmp litter")


def test_corrupt_artifact_fails_health_reverify(run_dir):
    path = run_dir / "v0_resnet.npy"
    data = bytearray(path.read_bytes())
    data[-3] ^= 0xFF  # flip a payload bit
    path.write_bytes(bytes(data))
    _assert_fail(run_dir, "signature mismatch")


def test_artifact_span_sha_mismatch_fails(run_dir):
    # rewrite the artifact with DIFFERENT (still loadable) content and a
    # matching health record, so only the span sha can catch it
    arr = np.zeros((4, 4), np.float32)
    np.save(run_dir / "v0_resnet.npy", arr)
    append_jsonl(run_dir / "_health.jsonl",
                 digest_array("resnet", arr, video="v0.mp4",
                              feature_type="resnet"))
    _assert_fail(run_dir, "sha256")


def test_recorded_artifact_missing_fails(run_dir):
    (run_dir / "v0_resnet.npy").unlink()
    _assert_fail(run_dir, "absent on disk")


def test_midfile_torn_jsonl_fails_tail_torn_passes(run_dir):
    path = run_dir / "_health.jsonl"
    # tail tear: healable, a note not a violation
    with open(path, "ab") as f:
        f.write(b'{"schema": "vft.feature_health/1", "video": "torn')
    ok, violations, notes = audit_run(str(run_dir), expect_complete=True)
    assert ok, violations
    assert any("torn trailing record" in n for n in notes)
    # mid-file tear: impossible under single-write O_APPEND -> violation
    with open(path, "ab") as f:
        f.write(b'\n{"video": "v9.mp4"}\n')
    _assert_fail(run_dir, "corrupt record at line")


def test_done_marker_without_artifact_fails(run_dir):
    q = run_dir / "_queue" / "done"
    (q / "v7-dddd.json").write_text(json.dumps(
        {"id": "v7-dddd", "video": "v7.mp4", "status": "done",
         "by": "hostA"}))
    _assert_fail(run_dir, "has no artifact")


def test_error_marker_without_journal_record_fails(run_dir):
    q = run_dir / "_queue" / "done"
    (q / "v8-eeee.json").write_text(json.dumps(
        {"id": "v8-eeee", "video": "v8.mp4", "status": "error",
         "by": "hostA"}))
    _assert_fail(run_dir, "no failure journal")


def test_quarantined_without_poison_record_fails(run_dir):
    (run_dir / "_queue" / "quarantined" / "v5-ffff.json").write_text(
        json.dumps({"id": "v5-ffff", "video": "v5.mp4", "reclaims": 4}))
    _assert_fail(run_dir, "no POISON record")


def test_orphaned_claim_of_finalized_host_fails(run_dir):
    claim = run_dir / "_queue" / "claimed" / "hostA" / "v3-gggg.json"
    claim.write_text(json.dumps({"id": "v3-gggg", "video": "v3.mp4",
                                 "host_id": "hostA", "deadline": 1.0}))
    _assert_fail(run_dir, "orphaned claim")


def test_claim_of_stale_host_is_recoverable_note(run_dir):
    """A claim whose owner is merely dead-without-final-heartbeat is the
    lease-steal case: recoverable, so a note — unless the run claims to
    be complete."""
    hostb = run_dir / "_queue" / "claimed" / "hostB"
    hostb.mkdir()
    (hostb / "v4-hhhh.json").write_text(json.dumps(
        {"id": "v4-hhhh", "video": "v4.mp4", "host_id": "hostB",
         "deadline": 1.0}))
    ok, violations, notes = audit_run(str(run_dir))  # not expect_complete
    assert ok, violations
    assert any("in-flight claim" in n for n in notes)
    _assert_fail(run_dir, "leftover claim", expect_complete=True)


def test_stranded_staging_fails_when_all_hosts_final(run_dir):
    staging = run_dir / "_queue" / ".staging" / "ab12cd34.v6-iiii.json"
    staging.write_text(json.dumps({"id": "v6-iiii", "video": "v6.mp4"}))
    _assert_fail(run_dir, "stranded in staging", expect_complete=True)
    # same entry for an already-done item: dead weight, only a note
    staging.write_text(json.dumps({"id": "v0-aaaa", "video": "v0.mp4"}))
    ok, violations, notes = audit_run(str(run_dir), expect_complete=True)
    assert ok, violations
    assert any("staging leftover" in n for n in notes)


def test_pending_leftover_fails_only_when_expect_complete(run_dir):
    (run_dir / "_queue" / "pending" / "v6-jjjj.json").write_text(
        json.dumps({"id": "v6-jjjj", "video": "v6.mp4"}))
    ok, violations, _ = audit_run(str(run_dir))
    assert ok, violations
    _assert_fail(run_dir, "pending item", expect_complete=True)


def test_nonfinite_health_record_with_artifact_fails(run_dir):
    arr = np.full((2, 2), np.nan, np.float32)
    np.save(run_dir / "v0_bad.npy", arr)
    append_jsonl(run_dir / "_health.jsonl",
                 digest_array("bad", arr, video="v0.mp4",
                              feature_type="resnet"))
    _assert_fail(run_dir, "non-finite")


def test_cache_reverify(run_dir, tmp_path):
    from video_features_tpu.cache import FeatureCache
    video = tmp_path / "content.bin"
    video.write_bytes(b"cache me")
    store = tmp_path / "cachestore"
    cache = FeatureCache(str(store / "resnet"), "resnet", "cfg", "wts")
    cache.store(str(video), {"resnet": np.ones((3, 3), np.float32)})
    ok, violations, _ = audit_run(str(run_dir), cache_dir=str(store),
                                  expect_complete=True)
    assert ok, violations
    # corrupt the entry in place: re-verification must flag it
    entry = next(store.rglob("*.pkl"))
    data = bytearray(entry.read_bytes())
    data[len(data) // 2] ^= 0xFF
    entry.write_bytes(bytes(data))
    _assert_fail(run_dir, "cache entry", cache_dir=str(store))


def test_cli_verdict_and_exit_codes(run_dir, capsys):
    from video_features_tpu.audit import main
    assert main([str(run_dir), "--expect-complete"]) == 0
    out = capsys.readouterr().out
    assert "AUDIT: PASS" in out
    (run_dir / "junk.tmp").write_bytes(b"x")
    assert main([str(run_dir)]) == 1
    out = capsys.readouterr().out
    assert "AUDIT: FAIL" in out and "tmp litter" in out
