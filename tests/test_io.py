"""VideoSource: batching, overlap, fps resampling, timestamp contract."""
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.utils.io import (VideoSource, fps_filter_map,
                                         get_video_props, read_video_frames)
from video_features_tpu.utils.lists import form_slices

pytestmark = pytest.mark.quick


def test_video_props(sample_video):
    props = get_video_props(sample_video)
    assert props["num_frames"] == 355
    assert props["height"] == 240 and props["width"] == 320
    assert abs(props["fps"] - 19.62) < 0.01


def test_native_fps_iteration(sample_video):
    src = VideoSource(sample_video, batch_size=64)
    total, first_ts = 0, None
    for batch, times, indices in src:
        assert len(batch) == len(times) == len(indices)
        assert len(batch) <= 64
        if first_ts is None:
            first_ts = times[0]
            assert indices[0] == 0
        total += len(batch)
    assert first_ts == 0.0
    assert total == len(src) == 355


def test_timestamps_are_index_over_fps(sample_video):
    src = VideoSource(sample_video, batch_size=16)
    for batch, times, indices in src:
        for t, i in zip(times, indices):
            assert t == pytest.approx(i / src.fps * 1000.0)
        break


def test_overlap_carries_frames(sample_video):
    src = VideoSource(sample_video, batch_size=8, overlap=1)
    batches = list(src)
    # first batch: 8 new; later: 1 carried + 7 new
    assert batches[0][2][0] == 0
    for prev, cur in zip(batches, batches[1:]):
        assert cur[2][0] == prev[2][-1]  # first index of batch = last of prev
    # every frame consumed exactly once beyond the overlap duplicates
    all_idx = [i for _, _, idx in batches for i in idx]
    uniq = sorted(set(all_idx))
    assert uniq == list(range(355))


def test_fps_resampling_count_and_fps(sample_video):
    src = VideoSource(sample_video, batch_size=4, fps=1)
    assert src.fps == 1.0
    n = sum(len(b) for b, _, _ in src)
    # 355 frames @19.62fps = ~18.1s -> 18 or 19 one-fps frames
    assert n == len(src)
    assert 17 <= n <= 19


def test_total_resampling(sample_video):
    src = VideoSource(sample_video, batch_size=4, total=10)
    n = sum(len(b) for b, _, _ in src)
    assert n <= 10
    assert n >= 9


def test_fps_and_total_exclusive(sample_video):
    with pytest.raises(ValueError):
        VideoSource(sample_video, fps=5, total=10)


def test_fps_filter_map_properties():
    # downsample 100 frames 30->10 fps: every 3rd frame (the last of the
    # input frames rounding into each output slot wins, as in ffmpeg's
    # fps filter), monotonic
    m = fps_filter_map(100, 30.0, 10.0)
    assert np.array_equal(m[:-1], 3 * np.arange(len(m) - 1) + 1)
    # the stream ends at EOF pts (num_frames/src_fps): exactly
    # round(100 * 10/30) = 33 output frames, and trailing inputs whose slot
    # lands past that cutoff are dropped (golden-pinned in test_golden.py:
    # the real binary emits 54 frames at fps=3, not 55)
    assert len(m) == 33
    assert m[-1] == 97
    assert np.all(np.diff(m) >= 0)
    # upsample duplicates frames up to the EOF cutoff: round(10 * 2) = 20
    m2 = fps_filter_map(10, 10.0, 20.0)
    assert len(m2) == 20
    assert np.all(np.diff(m2) <= 1)
    # identity
    m3 = fps_filter_map(50, 25.0, 25.0)
    assert np.array_equal(m3, np.arange(50))
    # exact 2x downsample must be temporally uniform (half-away-from-zero
    # rounding; banker's rounding would give jittery [1,2,5,6,9,...])
    m4 = fps_filter_map(20, 30.0, 15.0)
    assert np.array_equal(m4[:-1], 2 * np.arange(len(m4) - 1))


def test_read_video_frames_shape(sample_video):
    frames, fps = read_video_frames(sample_video)
    assert frames.shape == (355, 240, 320, 3)
    assert frames.dtype == np.uint8
    assert abs(fps - 19.62) < 0.01


def test_transform_applied(sample_video):
    src = VideoSource(sample_video, batch_size=2,
                      transform=lambda x: x[:10, :12].astype(np.float32))
    batch, _, _ = next(iter(src))
    assert batch[0].shape == (10, 12, 3)
    assert batch[0].dtype == np.float32
    # the frames() view (used by clip-stack extractors) must apply the
    # transform too — regression for the silently-skipped-resize bug
    frame, _, _ = next(iter(src.frames()))
    assert frame.shape == (10, 12, 3)
    assert frame.dtype == np.float32


def test_form_slices_drops_partial_tail():
    # reference utils/utils.py:59-68 contract
    assert form_slices(100, 15, 15) == [(0, 15), (15, 30), (30, 45), (45, 60),
                                        (60, 75), (75, 90)]
    assert form_slices(10, 4, 2) == [(0, 4), (2, 6), (4, 8), (6, 10)]
    assert form_slices(3, 4, 2) == []


def test_device_resize_matches_pil(rng):
    """ops/preprocess.py device_resize: the PIL-coefficient matmul resize
    must stay within 2 LSB of Pillow for both filters, up- and downscale."""
    from video_features_tpu.ops.preprocess import (device_resize,
                                                   pil_resize,
                                                   pil_resize_matrix)
    for (ih, iw, oh, ow) in ((240, 320, 256, 341), (240, 320, 112, 149),
                             (120, 90, 224, 168)):
        img = rng.integers(0, 255, size=(ih, iw, 3), dtype=np.uint8)
        for interp in ("bilinear", "bicubic"):
            ref = pil_resize(img, (oh, ow), interpolation=interp)
            rmat = pil_resize_matrix(ih, oh, interp)
            cmat = pil_resize_matrix(iw, ow, interp)
            got = np.asarray(device_resize(img[None], rmat, cmat))[0]
            d = np.abs(got - ref.astype(np.float64)).max()
            assert d <= 2.0, (interp, (ih, iw, oh, ow), d)


def test_frame_wise_device_resize_matches_host(sample_video, tmp_path,
                                               monkeypatch):
    """resize=device end to end (resnet): features must match the host-PIL
    path within the 2-LSB input quantization difference."""
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls

    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "weights"))

    def feats(resize):
        args = load_config("resnet", parse_dotlist([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_fps=2", "allow_random_weights=true",
            f"resize={resize}", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}", f"video_paths={sample_video}"]))
        sanity_check(args)
        return get_extractor_cls("resnet")(args).extract(sample_video)

    host = feats("host")
    dev = feats("device")
    np.testing.assert_array_equal(host["timestamps_ms"],
                                  dev["timestamps_ms"])
    a, b = host["resnet"], dev["resnet"]
    assert a.shape == b.shape
    cos = np.sum(a * b, axis=1) / (np.linalg.norm(a, axis=1)
                                   * np.linalg.norm(b, axis=1) + 1e-9)
    assert np.all(cos > 0.999), cos.min()


def test_device_resize_mixed_resolutions(sample_video, tmp_path, monkeypatch):
    """resize=device across videos of different source resolutions: the
    per-resolution runner cache must produce correct shapes for each (and
    features for the re-encoded small video must match its own host-path
    run)."""
    import cv2
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls

    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "weights"))
    # a second video at half resolution, synthesized from the sample
    small = str(tmp_path / "v_small.mp4")
    cap = cv2.VideoCapture(sample_video)
    w = cv2.VideoWriter(small, cv2.VideoWriter_fourcc(*"mp4v"), 20,
                        (160, 120))
    for _ in range(40):
        ok, frame = cap.read()
        if not ok:
            break
        w.write(cv2.resize(frame, (160, 120)))
    w.release()
    cap.release()

    def extractor(resize):
        args = load_config("resnet", parse_dotlist([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_fps=2", "allow_random_weights=true",
            f"resize={resize}", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}",
            f"video_paths=[{sample_video},{small}]"]))
        sanity_check(args)
        return get_extractor_cls("resnet")(args)

    ex = extractor("device")
    big = ex.extract(sample_video)["resnet"]
    sm = ex.extract(small)["resnet"]
    assert big.shape[1] == sm.shape[1] == 512 and len(sm) > 0
    assert len(ex._resize_runners) == 2  # one per source resolution
    # the small video agrees with its own host-path extraction
    sm_host = extractor("host").extract(small)["resnet"]
    cos = np.sum(sm * sm_host, axis=1) / (
        np.linalg.norm(sm, axis=1) * np.linalg.norm(sm_host, axis=1) + 1e-9)
    assert np.all(cos > 0.999), cos.min()


def test_channel_order_bgr_is_flipped_rgb(sample_video):
    """channel_order='bgr' must yield exactly the decoder frames the default
    mode yields, minus the cvtColor — i.e. the same bytes channel-reversed.
    (The deferred-reorder transforms in r21d/s3d/frame-wise device-resize
    rely on this identity.)"""
    rgb_src = VideoSource(sample_video, batch_size=3)
    bgr_src = VideoSource(sample_video, batch_size=3, channel_order="bgr")
    (rgb, _, _) = next(iter(rgb_src))
    (bgr, _, _) = next(iter(bgr_src))
    assert len(rgb) == len(bgr) == 3
    for r, b in zip(rgb, bgr):
        np.testing.assert_array_equal(r, b[:, :, ::-1])


def test_grab_skip_resampling_identical(sample_video):
    """The fps-filter catch-up loop grab()-skips dropped frames (no
    YUV->BGR conversion/copy for the ~95% discarded at low extraction
    fps). Frame SELECTION and bytes must be identical to full decode:
    compare against an index_map-driven full-decode reference."""
    src = VideoSource(sample_video, fps=2.0)
    picked = [(idx, f) for f, _, idx in src.frames()]
    # reference: decode everything, select by the same fps_filter_map
    full = [f for f, _, _ in VideoSource(sample_video).frames()]
    from video_features_tpu.utils.io import fps_filter_map, get_video_props
    props = get_video_props(sample_video)
    mapping = fps_filter_map(props["num_frames"], props["fps"], 2.0)
    assert [i for i, _ in picked] == list(range(len(mapping)))
    assert len(picked) == len(mapping)
    for (out_idx, frame), src_idx in zip(picked, mapping):
        np.testing.assert_array_equal(frame, full[src_idx])


def test_process_video_source_matches_inline(sample_video):
    """video_decode=process: the spawned-worker source yields exactly the
    inline source's frames/timestamps/indices and props, transform applied
    child-side (picklable callables, ops/host_transforms.py)."""
    from video_features_tpu.ops.host_transforms import MinSideResize
    from video_features_tpu.utils.io import ProcessVideoSource
    tf = MinSideResize(128)
    inline = VideoSource(sample_video, fps=2.0, transform=tf)
    proc = ProcessVideoSource(sample_video, fps=2.0, transform=tf)
    assert proc.fps == inline.fps
    assert proc.num_frames == inline.num_frames
    assert (proc.height, proc.width) == (inline.height, inline.width)
    got = list(proc.frames())
    want = list(inline.frames())
    assert len(got) == len(want) > 0
    for (gf, gt, gi), (wf, wt, wi) in zip(got, want):
        assert (gt, gi) == (wt, wi)
        np.testing.assert_array_equal(gf, wf)


def test_process_video_source_error_propagates(tmp_path):
    """A corrupt video fails the PARENT with a per-video error (the chaos
    contract), not a hung queue."""
    import pytest as _pytest
    from video_features_tpu.utils.io import ProcessVideoSource
    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"not a video" * 100)
    with _pytest.raises(RuntimeError, match="decode worker failed"):
        ProcessVideoSource(str(bad), fps=2.0)


def test_process_video_source_killed_worker_raises(sample_video):
    """A worker killed without running its except handler (OOM SIGKILL)
    must fail the video, not hang the parent on an untimed queue get
    (advisor r4). The timed get + liveness check turns it into the same
    per-video RuntimeError as a decode failure."""
    import os
    import signal
    import pytest as _pytest
    from video_features_tpu.utils.io import ProcessVideoSource
    src = ProcessVideoSource(sample_video, fps=2.0, depth=2)
    it = src.frames()
    next(it)  # worker is up and decoding
    os.kill(src._proc.pid, signal.SIGKILL)
    with _pytest.raises(RuntimeError, match="died without a result"):
        for _ in it:  # drain whatever was queued, then hit the dead worker
            pass


# ------------------------------------------------------- fps_mode=reencode


def test_reencode_mode_same_frame_timing(sample_video, tmp_path):
    """reencode (cv2 backend here; ffmpeg absent) must deliver the same
    frame COUNT and timestamps as select-mode — only pixel provenance
    differs (lossy codec). The timing rule is fps_filter_map on both
    paths."""
    from video_features_tpu.utils.io import VideoSource
    sel = VideoSource(sample_video, batch_size=4, fps=2.0)
    ren = VideoSource(sample_video, batch_size=4, fps=2.0,
                      fps_mode="reencode", tmp_path=str(tmp_path))
    sel_items = [(ts, idx) for _, ts, idx in sel.frames()]
    ren_items = [(ts, idx) for _, ts, idx in ren.frames()]
    assert len(sel_items) == len(ren_items) == sel.num_frames
    np.testing.assert_allclose([t for t, _ in sel_items],
                               [t for t, _ in ren_items], rtol=1e-9)
    assert ren.fps == pytest.approx(2.0)


def test_reencode_pixels_are_lossy_but_close(sample_video, tmp_path):
    """The re-encoded stream's pixels must be (a) different from the
    bit-exact select path (it IS a lossy generation) and (b) close to it
    (same underlying frames). Guards against off-by-one frame selection
    masquerading as codec noise."""
    from video_features_tpu.utils.io import VideoSource
    sel = [f for f, _, _ in VideoSource(sample_video, fps=2.0).frames()]
    ren = [f for f, _, _ in VideoSource(
        sample_video, fps=2.0, fps_mode="reencode",
        tmp_path=str(tmp_path)).frames()]
    assert len(sel) == len(ren)
    deltas = [np.abs(a.astype(np.int16) - b.astype(np.int16)).mean()
              for a, b in zip(sel, ren)]
    assert max(deltas) > 0, "reencode delivered bit-identical pixels — " \
        "the lossy intermediate is not actually being decoded"
    # a mis-selected frame pair in this synthetic/real clip differs by
    # far more than codec quantization noise
    assert np.mean(deltas) < 20.0, (
        f"mean |delta| {np.mean(deltas):.1f} u8-steps: frame selection "
        "diverged between the two modes, not just codec noise")


def test_reencode_tmp_file_cleanup(sample_video, tmp_path):
    from video_features_tpu.utils.io import VideoSource
    src = VideoSource(sample_video, fps=2.0, fps_mode="reencode",
                      tmp_path=str(tmp_path))
    tmp_file = Path(src._tmp_file)
    assert tmp_file.exists()
    for _ in src.frames():
        pass
    assert not tmp_file.exists(), "temp file must be removed after decode"
    keep = VideoSource(sample_video, fps=2.0, fps_mode="reencode",
                       tmp_path=str(tmp_path), keep_tmp=True)
    kept = Path(keep._tmp_file)
    for _ in keep.frames():
        pass
    assert kept.exists(), "keep_tmp=True must preserve the temp file"


def test_reencode_total_mode(sample_video, tmp_path):
    """total + reencode: the reference derives fps from total and decodes
    the re-encoded file capped at total frames (utils/io.py:83-89)."""
    from video_features_tpu.utils.io import VideoSource
    src = VideoSource(sample_video, total=9, fps_mode="reencode",
                      tmp_path=str(tmp_path))
    frames = list(src.frames())
    assert len(frames) <= 9
    assert len(frames) >= 8  # round(n*r) may fall one short of total


def test_reencode_second_pass_raises(sample_video, tmp_path):
    """cv2 fails silently on a missing path; a consumed single-pass
    reencode source must raise, not yield an empty stream."""
    from video_features_tpu.utils.io import VideoSource
    src = VideoSource(sample_video, fps=2.0, fps_mode="reencode",
                      tmp_path=str(tmp_path))
    for _ in src.frames():
        pass
    with pytest.raises(RuntimeError, match="single-pass"):
        next(src.frames())


# --------------------------------------------------- intra-video parallel


@pytest.mark.parametrize("workers,fps,overlap", [(2, 2.0, 0), (4, None, 0),
                                                 (3, 3.0, 1)])
def test_parallel_decode_bit_equal_to_serial(sample_video, workers, fps,
                                             overlap):
    """N seek-aligned segment decoders must reproduce the serial stream
    BIT-exactly — frames, timestamps, indices, batching, overlap."""
    from video_features_tpu.ops.host_transforms import ResizeCropTransform
    from video_features_tpu.utils.io import ParallelVideoSource, VideoSource
    kw = dict(batch_size=7, fps=fps, overlap=overlap,
              transform=ResizeCropTransform(80, 64, "bilinear", "uint8"))
    serial = list(VideoSource(sample_video, **kw))
    par = list(ParallelVideoSource(sample_video, decode_workers=workers,
                                   **kw))
    assert len(serial) == len(par)
    for (b1, t1, i1), (b2, t2, i2) in zip(serial, par):
        assert t1 == t2 and i1 == i2
        for f1, f2 in zip(b1, b2):
            np.testing.assert_array_equal(f1, f2)


def test_parallel_decode_corrupt_video_raises(tmp_path):
    from video_features_tpu.utils.io import ParallelVideoSource
    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"junk" * 200)
    with pytest.raises(ValueError):
        ParallelVideoSource(str(bad), fps=2.0, decode_workers=2)


def test_parallel_decode_rejects_reencode(sample_video, tmp_path):
    from video_features_tpu.utils.io import ParallelVideoSource
    with pytest.raises(NotImplementedError, match="fps_mode=select"):
        ParallelVideoSource(sample_video, fps=2.0, decode_workers=2,
                            fps_mode="reencode", tmp_path=str(tmp_path))


def test_parallel_decode_through_extractor(sample_video, tmp_path,
                                           monkeypatch):
    """video_decode=parallel end to end (resnet): features identical to
    the inline decode path — the factory wiring in extractors/base.py."""
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "w"))

    def feats(decode, extra=()):
        args = load_config("resnet", parse_dotlist([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_fps=2", "allow_random_weights=true",
            f"video_decode={decode}", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}",
            f"video_paths={sample_video}", *extra]))
        sanity_check(args)
        return get_extractor_cls("resnet")(args).extract(sample_video)

    inline = feats("inline")
    par = feats("parallel", ("decode_workers=3",))
    np.testing.assert_array_equal(inline["timestamps_ms"],
                                  par["timestamps_ms"])
    np.testing.assert_array_equal(inline["resnet"], par["resnet"])


def test_parallel_decode_lying_metadata_falls_back_to_recount(
        sample_video, monkeypatch, capsys):
    """ADVICE medium: a container whose metadata reports num_frames<=0 in
    native-fps mode must fall back to count_frames_by_decode (like the
    serial resample path) instead of spawning zero workers and silently
    yielding an empty stream."""
    from video_features_tpu.utils import io as io_mod
    real_props = io_mod.get_video_props

    def lying_props(path):
        props = real_props(path)
        props["num_frames"] = 0  # metadata lied; fps stays valid
        return props

    monkeypatch.setattr(io_mod, "get_video_props", lying_props)
    src = io_mod.ParallelVideoSource(sample_video, decode_workers=2,
                                     batch_size=64)
    assert len(src) == 355
    total = sum(len(b) for b, _, _ in src)
    assert total == 355
    assert "counted 355 by decode" in capsys.readouterr().out


def test_parallel_decode_lying_metadata_empty_stream_raises(
        tmp_path, monkeypatch):
    """Same fallback, but a stream with zero decodable frames must fail
    loudly, not emit an empty feature."""
    from video_features_tpu.utils import io as io_mod
    bad = tmp_path / "empty.mp4"
    bad.write_bytes(b"\x00" * 2048)
    monkeypatch.setattr(
        io_mod, "get_video_props",
        lambda path: dict(fps=19.62, num_frames=0, height=240, width=320))
    with pytest.raises(ValueError, match="No decodable frames"):
        io_mod.ParallelVideoSource(str(bad), decode_workers=2)


def test_segment_worker_seek_mismatch_degrades_to_serial(
        sample_video, monkeypatch, capsys):
    """ADVICE low: when CAP_PROP_POS_FRAMES does not land where asked
    (VFR/odd codecs), the segment worker must re-decode serially from
    frame 0 — same bytes, seek benefit lost — instead of silently
    emitting wrong frames."""
    import cv2
    from video_features_tpu.utils import io as io_mod
    real_capture = cv2.VideoCapture

    class _NoSeekCap:
        """Delegates everything but silently ignores frame seeks."""

        def __init__(self, path):
            self._cap = real_capture(path)

        def set(self, prop, val):
            if prop == cv2.CAP_PROP_POS_FRAMES:
                return True  # claims success, does nothing (VFR-style)
            return self._cap.set(prop, val)

        def __getattr__(self, name):
            return getattr(self._cap, name)

    class _ListQ:
        def __init__(self):
            self.items = []

        def put(self, item):
            self.items.append(item)

    # serial reference frames for source indices 100..119 (native fps)
    want = {}
    for f, _, i in io_mod.VideoSource(sample_video).frames():
        if 100 <= i < 120:
            want[i] = f

    monkeypatch.setattr(io_mod.cv2, "VideoCapture", _NoSeekCap)
    q = _ListQ()
    seg = dict(src_indices=np.arange(100, 120, dtype=np.int64),
               out_start=100, fps=19.62, transform=None,
               channel_order="rgb")
    io_mod._segment_decode_worker(q, sample_video, seg)

    assert "seek verification failed" in capsys.readouterr().out
    frames = [p for tag, p in q.items if tag == "frame"]
    assert q.items[-1] == ("done", 20)
    assert [idx for _, _, idx in frames] == list(range(100, 120))
    for x, _, idx in frames:
        np.testing.assert_array_equal(x, want[idx])
