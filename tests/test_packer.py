"""Cross-video clip batching (parallel/packer.py + clip_stack wiring).

The packer's contract: per-video results identical to the per-video-stream
path, any thread interleaving, no deadlock when every worker closes at
once with a part-filled group."""
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from video_features_tpu.parallel.packer import ClipPacker


class FakeRunner:
    """Row-wise 'device' forward: mean over all but the leading axis, with
    a jitter delay so drain/dispatch interleavings actually vary."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.groups = []

    def dispatch(self, group: np.ndarray) -> np.ndarray:
        if self.delay:
            time.sleep(self.delay)
        self.groups.append(group.shape[0])
        return group.reshape(group.shape[0], -1).mean(axis=1, keepdims=True)


def _stack(video: int, idx: int) -> np.ndarray:
    # identifiable content: the fake forward recovers video*1000 + idx
    return np.full((4, 8, 8, 3), float(video * 1000 + idx), np.float32)


def test_single_video_ragged_flush():
    """One video, fewer clips than the batch: the all-closing flush rule
    must dispatch the ragged group instead of deadlocking."""
    runner = FakeRunner()
    p = ClipPacker(runner, batch=8)
    h = p.open_video()
    for i in range(3):
        p.add(h, _stack(0, i))
    rows = p.close_video(h)
    assert rows.shape == (3, 1)
    np.testing.assert_array_equal(rows[:, 0], [0.0, 1.0, 2.0])
    assert runner.groups == [3]  # one ragged dispatch, at close


def test_groups_fill_across_videos():
    """Sequential adds from two videos share one full-size group."""
    runner = FakeRunner()
    p = ClipPacker(runner, batch=4)
    h1, h2 = p.open_video(), p.open_video()
    p.add(h1, _stack(1, 0))
    p.add(h2, _stack(2, 0))
    p.add(h1, _stack(1, 1))
    p.add(h2, _stack(2, 1))  # fills -> dispatches a packed group
    assert runner.groups == [4]
    r1 = p.close_video(h1)
    np.testing.assert_array_equal(r1[:, 0], [1000.0, 1001.0])
    r2 = p.close_video(h2)
    np.testing.assert_array_equal(r2[:, 0], [2000.0, 2001.0])


def test_empty_video():
    p = ClipPacker(FakeRunner(), batch=4)
    h = p.open_video()
    assert p.close_video(h).shape == (0,)


def test_abort_unwedges_closers():
    """Per-video error isolation: a video that dies after open_video must
    not leave the open count elevated — otherwise the all-closing flush
    rule can never fire and every other worker's close_video hangs."""
    runner = FakeRunner()
    p = ClipPacker(runner, batch=8)
    healthy, doomed = p.open_video(), p.open_video()
    p.add(healthy, _stack(1, 0))
    p.add(doomed, _stack(2, 0))
    p.abort_video(doomed)  # what the extractor's except-path calls
    done = []

    def close_healthy():
        done.append(p.close_video(healthy))

    t = threading.Thread(target=close_healthy)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "close_video wedged after a peer aborted"
    np.testing.assert_array_equal(done[0][:, 0], [1000.0])
    # the aborted video's buffered clip was discarded, not computed: the
    # ragged flush carried only the healthy video's single clip
    assert runner.groups == [1]


@pytest.mark.parametrize("batch,workers", [(4, 4), (8, 3)])
def test_concurrent_videos_exact_rows(batch, workers):
    """Many threads, ragged per-video clip counts (including zero), slow
    fake device: every video gets exactly its rows, in clip order."""
    runner = FakeRunner(delay=0.002)
    p = ClipPacker(runner, batch=batch, depth=2)
    rng = np.random.default_rng(0)
    counts = [int(c) for c in rng.integers(0, 6, size=10)]

    def run_video(vid: int) -> np.ndarray:
        h = p.open_video()
        for i in range(counts[vid]):
            p.add(h, _stack(vid, i))
            time.sleep(0.001 * (vid % 3))
        return p.close_video(h)

    with ThreadPoolExecutor(max_workers=workers) as pool:
        results = list(pool.map(run_video, range(len(counts))))
    for vid, rows in enumerate(results):
        assert rows.shape[0] == counts[vid], (vid, rows.shape)
        if counts[vid]:
            np.testing.assert_array_equal(
                rows[:, 0], [vid * 1000 + i for i in range(counts[vid])])
    # conservation: every clip dispatched exactly once
    assert sum(runner.groups) == sum(counts)


class _FailsOnArray:
    """Stand-in for a device buffer whose D2H read surfaces a runtime
    error (what a deferred JAX computation failure looks like at
    np.asarray time)."""

    def __array__(self, dtype=None, copy=None):
        raise RuntimeError("device exploded during D2H")


class PoisonRunner(FakeRunner):
    """FakeRunner whose Nth dispatched group fails lazily at
    materialization — the async-dispatch failure mode."""

    def __init__(self, fail_group: int):
        super().__init__()
        self.fail_group = fail_group

    def dispatch(self, group: np.ndarray) -> np.ndarray:
        gi = len(self.groups)
        out = super().dispatch(group)
        return _FailsOnArray() if gi == self.fail_group else out


def test_device_failure_poisons_only_group_members():
    """A group that dies on device must fail exactly its member videos'
    close_video (with the device error chained) while videos whose clips
    sit in healthy groups complete normally — no hang, no cross-talk."""
    runner = PoisonRunner(fail_group=1)
    p = ClipPacker(runner, batch=2)
    h1, h2, h3 = p.open_video(), p.open_video(), p.open_video()
    p.add(h1, _stack(1, 0))
    p.add(h1, _stack(1, 1))   # group 0 (healthy) dispatches
    p.add(h2, _stack(2, 0))
    p.add(h3, _stack(3, 0))   # group 1 (poisoned) dispatches
    rows = p.close_video(h1)
    np.testing.assert_array_equal(rows[:, 0], [1000.0, 1001.0])
    for doomed in (h2, h3):
        with pytest.raises(RuntimeError, match="failed on device"):
            p.close_video(doomed)


def test_dispatch_failure_propagates_and_poisons_peers():
    """runner.dispatch raising synchronously must surface at the add()
    that filled the group AND poison the group's other members so their
    close_video raises instead of spinning on clips that never ran."""

    class Boom(FakeRunner):
        def dispatch(self, group):
            raise RuntimeError("compile blew up")

    p = ClipPacker(Boom(), batch=2)
    h1, h2 = p.open_video(), p.open_video()
    p.add(h1, _stack(1, 0))
    with pytest.raises(RuntimeError, match="compile blew up"):
        p.add(h2, _stack(2, 0))  # fills the group -> dispatch fails
    p.abort_video(h2)  # what the adder's extractor except-path does
    with pytest.raises(RuntimeError, match="failed on device"):
        p.close_video(h1)


def test_stack_mismatch_poisons_members():
    """np.stack failing inside _dispatch (mismatched clip shapes) has
    already consumed the clips from the buffer, so it must poison the
    members like a device failure — not strand their pending counts."""
    p = ClipPacker(FakeRunner(), batch=2)
    h1, h2 = p.open_video(), p.open_video()
    p.add(h1, _stack(1, 0))
    with pytest.raises(ValueError):  # what np.stack raises for ragged shapes
        p.add(h2, np.zeros((2, 3, 3, 3), np.float32))
    p.abort_video(h2)
    with pytest.raises(RuntimeError, match="failed on device"):
        p.close_video(h1)


def test_add_fails_fast_after_poison():
    """Once a video's group has failed, further add() calls must raise
    immediately instead of decoding + dispatching doomed clips."""
    runner = PoisonRunner(fail_group=0)
    p = ClipPacker(runner, batch=2, depth=1)
    h1, h2 = p.open_video(), p.open_video()
    p.add(h1, _stack(1, 0))
    p.add(h2, _stack(2, 0))   # fills group 0 (poisoned lazily)
    p.add(h1, _stack(1, 1))
    p.add(h2, _stack(2, 1))   # fills group 1 -> inflight(2) > depth(1)
    # forces a drain, materializing poisoned group 0: errors recorded
    with pytest.raises(RuntimeError, match="failed on device"):
        p.add(h1, _stack(1, 2))


def _write_clip(path: str, frames: int, seed: int) -> str:
    cv2 = pytest.importorskip("cv2")
    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"),
                        16.0, (64, 48))
    if not w.isOpened():
        pytest.skip("cv2 cannot encode mp4v")
    yy, xx = np.mgrid[0:48, 0:64].astype(np.float32)
    for t in range(frames):
        frame = np.stack([
            127 + 120 * np.sin(xx / 9 + t / 5 + seed),
            127 + 120 * np.sin(yy / 7 - t / 6 + 2 * seed),
            127 + 120 * np.sin((xx + yy) / 11 + t / 4 + 3 * seed),
        ], axis=-1)
        w.write(frame.clip(0, 255).astype(np.uint8))
    w.release()
    return path


def test_cross_video_survives_corrupt_video(tmp_path):
    """Per-video error isolation under packing, end to end: one unreadable
    video among healthy ones must be reported failed while every healthy
    video still completes (the packer abort path; without it the run
    wedges in close_video)."""
    from video_features_tpu.cli import main

    vids = [_write_clip(str(tmp_path / f"v{i}.mp4"), 40, i) for i in range(2)]
    bad = tmp_path / "broken.mp4"
    bad.write_bytes(b"not a video at all")
    vids.insert(1, str(bad))

    main([
        "feature_type=r21d", "device=cpu", "allow_random_weights=true",
        "on_extraction=save_numpy", f"output_path={tmp_path / 'out'}",
        f"tmp_path={tmp_path / 'tmp'}", "clip_batch_size=8",
        "video_workers=2", "cross_video_batching=true",
        "video_paths=[" + ",".join(vids) + "]",
    ])
    done = sorted(p.name for p in (tmp_path / "out").rglob("*_r21d.npy"))
    assert done == ["v0_r21d.npy", "v1_r21d.npy"], done


@pytest.mark.slow  # ~54s E2E; the unit-level packer tests keep quick coverage
def test_r21d_cross_video_outputs_identical(tmp_path):
    """E2E through the real extractor: cross_video_batching=true over
    several short videos (each well under one clip_batch_size group) must
    write byte-identical features to the unpacked path, independent of
    worker interleaving."""
    from video_features_tpu.cli import main

    vids = [_write_clip(str(tmp_path / f"v{i}.mp4"), 40 + 16 * i, i)
            for i in range(3)]

    def run(out, packed, workers):
        main([
            "feature_type=r21d", "device=cpu", "allow_random_weights=true",
            "on_extraction=save_numpy", f"output_path={tmp_path / out}",
            f"tmp_path={tmp_path / ('tmp_' + out)}", "clip_batch_size=8",
            f"video_workers={workers}",
            f"cross_video_batching={'true' if packed else 'false'}",
            "video_paths=[" + ",".join(vids) + "]",
        ])
        return {
            p.name: np.load(p)
            for p in sorted((tmp_path / out).rglob("*_r21d.npy"))
        }

    plain = run("plain", packed=False, workers=1)
    packed = run("packed", packed=True, workers=2)
    assert set(plain) == set(packed) and len(plain) == 3
    for name in plain:
        assert plain[name].shape == packed[name].shape, name
        np.testing.assert_allclose(packed[name], plain[name],
                                   atol=1e-5, rtol=1e-5, err_msg=name)
