"""Distributed chaos: the failure modes production hits SIMULTANEOUSLY.

The pieces exist as separate tests — two-process ``distributed=true``
(test_distributed.py), cross-video batching (test_packer.py), SIGTERM
preemption + idempotent resume (test_multihost.py), corrupt-input
isolation (test_sinks.py / safe_extract) — but a preempted spot worker in
a real fleet experiences them together. This test composes all four:

  two real processes, distributed=true, cross_video_batching=true,
  one corrupt video in the work list, a mid-run SIGTERM of one worker,
  then a restart round (fresh coordinator, same shared output dir).

Asserts afterwards: every healthy video's features exist exactly once,
disjointly owned (hash sharding), loadable and well-shaped; the corrupt
video produced NO output and is reported failed (not crashed) by exactly
its owner; the restarted round skips already-done work via the idempotent
resume contract. Reference behavior anchor: per-video isolation + resume
in reference models/_base/base_extractor.py:95-127.

Failure-journal contract (utils/faults.py FailureJournal): round 1's
owner quarantines the corrupt video into ``{output}/_failures.jsonl``
(exactly one record, category=POISON, retried ``retry_attempts`` times);
round 2 SKIPS it via the journal ("1 quarantined", "0 failed") instead
of re-failing it, and appends nothing.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.parallel.mesh import local_shard_of_list

TIMEOUT_S = 560
N_HEALTHY = 6


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_WORKER = textwrap.dedent("""
    import sys
    from pathlib import Path
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address={coord!r},
                               num_processes=2, process_id={pid})
    from video_features_tpu.cli import main
    main([
        "feature_type=r21d", "model_name=r2plus1d_18_16_kinetics",
        "device=cpu", "distributed=true", "cross_video_batching=true",
        "clip_batch_size=4", "stack_size=16", "step_size=16",
        "extraction_fps=2", "allow_random_weights=true",
        "on_extraction=save_numpy",
        "output_path={out}", "tmp_path={tmp}",
        "file_with_video_paths={listfile}",
    ])
    print("WORKER_DONE", {pid})
""")


def _spawn(pid, coord, repo, out, tmp, listfile, log_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("VFT_WEIGHTS_DIR", None)
    script = _WORKER.format(repo=repo, coord=coord, pid=pid, out=out,
                            tmp=f"{tmp}_{pid}", listfile=listfile)
    log = open(log_path, "w")
    # logs to files, never PIPEs (un-drained PIPE deadlock, see
    # tests/test_multihost.py)
    return subprocess.Popen([sys.executable, "-c", script], stdout=log,
                            stderr=subprocess.STDOUT, env=env), log


@pytest.mark.slow
def test_chaos_distributed_preempt_corrupt_resume(sample_video, tmp_path):
    repo = str(Path(__file__).resolve().parent.parent)
    videos = []
    for i in range(N_HEALTHY):
        dst = tmp_path / f"v_chaos_{i:03d}.mp4"
        dst.write_bytes(Path(sample_video).read_bytes())
        videos.append(str(dst))
    corrupt = tmp_path / "v_chaos_corrupt.mp4"
    corrupt.write_bytes(b"\x00\x01mp4 junk that cv2 cannot open" * 64)
    videos.append(str(corrupt))
    listfile = tmp_path / "videos.txt"
    listfile.write_text("\n".join(videos) + "\n")

    shards = [set(local_shard_of_list(videos, host_id=i, num_hosts=2))
              for i in range(2)]
    assert shards[0] and shards[1] and not (shards[0] & shards[1])
    victim = 0 if str(corrupt) not in shards[0] else 1
    corrupt_owner = 1 - victim  # keep the corrupt video on the survivor
    feat_dir = tmp_path / "out" / "r21d" / "r2plus1d_18_16_kinetics"

    def victim_outputs():
        return [p for p in feat_dir.glob("*_r21d.npy")
                if str(tmp_path / p.name.replace("_r21d.npy", ".mp4"))
                in shards[victim]]

    # ---- round 1: both workers up; SIGTERM the victim mid-run ----------
    coord = f"127.0.0.1:{_free_port()}"
    procs, logs = zip(*(_spawn(pid, coord, repo, tmp_path / "out",
                               tmp_path / "tmp", listfile,
                               tmp_path / f"r1_worker_{pid}.log")
                        for pid in range(2)))
    try:
        deadline = time.time() + TIMEOUT_S
        while time.time() < deadline:
            if victim_outputs():
                break
            if procs[victim].poll() is not None:
                raise AssertionError(
                    "victim exited before producing output:\n"
                    + (tmp_path / f"r1_worker_{victim}.log").read_text()[-2000:])
            time.sleep(0.1)
        else:
            raise AssertionError("victim produced no output before deadline")
        procs[victim].send_signal(signal.SIGTERM)
        assert procs[victim].wait(timeout=TIMEOUT_S) == 143
        # the survivor finishes its whole shard (incl. failing the corrupt
        # video) and exits cleanly — a dead peer must not take it down
        assert procs[corrupt_owner].wait(timeout=TIMEOUT_S) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()

    r1_victim = (tmp_path / f"r1_worker_{victim}.log").read_text()
    assert "SIGTERM: finishing in-flight" in r1_victim
    r1_surv = (tmp_path / f"r1_worker_{corrupt_owner}.log").read_text()
    assert "1 failed" in r1_surv, r1_surv[-1500:]
    done_r1 = {p.name for p in feat_dir.glob("*_r21d.npy")}
    healthy = {Path(v).stem for v in videos if v != str(corrupt)}
    assert 0 < len(done_r1) < len(healthy)  # work genuinely remains

    # journal contract after round 1: the corrupt video was retried
    # retry_attempts times (config default) by its owner, then journaled
    # exactly once as POISON; no healthy video has a record
    journal_path = feat_dir / "_failures.jsonl"
    assert journal_path.exists(), "terminal failure must be journaled"

    def journal_records():
        return [json.loads(l) for l in journal_path.read_text().splitlines()
                if l.strip()]

    recs = journal_records()
    assert {r["video"] for r in recs} == {str(corrupt)}, recs
    assert len(recs) == 1, recs
    assert recs[0]["category"] == "POISON"
    assert recs[0]["attempts"] == 3  # configs/r21d.yml retry_attempts
    assert recs[0]["host"] and "elapsed_s" in recs[0]
    assert str(corrupt) in shards[corrupt_owner]  # owned by its shard

    # ---- round 2: restart both under a fresh coordinator ---------------
    coord = f"127.0.0.1:{_free_port()}"
    procs, logs = zip(*(_spawn(pid, coord, repo, tmp_path / "out",
                               tmp_path / "tmp2", listfile,
                               tmp_path / f"r2_worker_{pid}.log")
                        for pid in range(2)))
    try:
        for p in procs:
            assert p.wait(timeout=TIMEOUT_S) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()

    # complete: every healthy video has features; corrupt produced nothing
    for v in videos:
        stem = Path(v).stem
        outs = list(feat_dir.glob(f"{stem}_r21d.npy"))
        if v == str(corrupt):
            assert not outs, "corrupt video must not produce features"
        else:
            assert len(outs) == 1, f"missing features for {stem}"
            arr = np.load(outs[0])  # valid: loads, right shape
            assert arr.ndim == 2 and arr.shape[1] == 512

    # round 2: already-done work skipped (resume); the corrupt video is
    # QUARANTINED via the journal by exactly its owner (no re-decode, no
    # new record), nothing else failed
    for pid in range(2):
        text = (tmp_path / f"r2_worker_{pid}.log").read_text()
        assert f"WORKER_DONE {pid}" in text, text[-1500:]
        n_own = len(shards[pid])
        if pid == corrupt_owner:
            assert "is quarantined by" in text, text[-1500:]
            n_skip = len(done_r1 & {Path(v).stem + "_r21d.npy"
                                    for v in shards[pid]})
            assert f"{n_own - 1 - n_skip} extracted, {n_skip} already done, " \
                   f"0 failed, 1 quarantined" in text, text[-1500:]
        else:
            assert "0 failed" in text, text[-1500:]
            assert "quarantined" not in text, text[-1500:]

    # the quarantine skip appended nothing: still exactly one record
    recs = journal_records()
    assert len(recs) == 1 and recs[0]["category"] == "POISON", recs


# ---------------------------------------------------------------------------
# Scheduling chaos (ISSUE 8): the fleet queue promoted from survival to
# scheduling — a killed worker's LEASE is reclaimed and its video finishes
# elsewhere, exactly once, bit-identically.
# ---------------------------------------------------------------------------

_QUEUE_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from video_features_tpu.cli import main
    main([
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "allow_random_weights=true", "on_extraction=save_numpy",
        "extraction_total=6", "batch_size=8", "video_workers=1",
        "telemetry=true", "metrics_interval_s=0.5",
        "fleet=queue", "fleet_lease_s=2",
        "output_path={out}", "tmp_path={tmp}",
        "file_with_video_paths={listfile}",
    ])
    print("QUEUE_WORKER_DONE")
""")


@pytest.mark.slow
def test_chaos_queue_worker_kill_lease_reclaim(sample_video, tmp_path):
    """Two fleet=queue workers share an output dir; the first worker to
    claim a video is SIGKILLed mid-claim (no SIGTERM grace, no final
    heartbeat). The survivor must: notice the dead worker's heartbeat
    going stale, reclaim its expired lease, re-extract the video exactly
    once, and drain the whole queue — with every artifact bit-identical
    to an unkilled single-host run (parallel/queue.py; docs/fleet.md
    failure matrix row 'worker SIGKILLed mid-video')."""
    repo = str(Path(__file__).resolve().parent.parent)
    n_videos = 4
    videos = []
    for i in range(n_videos):
        dst = tmp_path / f"v_fleet_{i:02d}.mp4"
        dst.write_bytes(Path(sample_video).read_bytes())
        videos.append(str(dst))
    listfile = tmp_path / "videos.txt"
    listfile.write_text("\n".join(videos) + "\n")
    out = tmp_path / "out"
    feat_dir = out / "resnet" / "resnet18"
    claimed_root = feat_dir / "_queue" / "claimed"

    def spawn(idx):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        log = open(tmp_path / f"qworker_{idx}.log", "w")
        script = _QUEUE_WORKER.format(
            repo=repo, out=out, tmp=f"{tmp_path}/tmp_{idx}",
            listfile=listfile)
        return subprocess.Popen([sys.executable, "-c", script], stdout=log,
                                stderr=subprocess.STDOUT, env=env), log

    procs, logs = zip(*(spawn(i) for i in range(2)))
    victim = survivor = None
    try:
        # ---- kill the first worker observed holding a claim ------------
        deadline = time.time() + TIMEOUT_S
        claim = None
        while time.time() < deadline:
            claims = list(claimed_root.glob("*/*.json"))
            if claims:
                claim = claims[0]
                break
            if all(p.poll() is not None for p in procs):
                raise AssertionError(
                    "both workers exited before claiming:\n" + "".join(
                        (tmp_path / f"qworker_{i}.log").read_text()[-1000:]
                        for i in range(2)))
            time.sleep(0.01)
        assert claim is not None, "no claim appeared before deadline"
        owner_dir = claim.parent.name  # host id embeds the worker's pid
        victim = next(i for i, p in enumerate(procs)
                      if f"-{p.pid}-" in owner_dir)
        survivor = 1 - victim
        procs[victim].kill()  # SIGKILL: no drain, no final heartbeat
        assert procs[victim].wait(timeout=30) == -signal.SIGKILL
        # ---- the survivor reclaims and drains the fleet ----------------
        assert procs[survivor].wait(timeout=TIMEOUT_S) == 0, \
            (tmp_path / f"qworker_{survivor}.log").read_text()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()

    surv_log = (tmp_path / f"qworker_{survivor}.log").read_text()
    assert "QUEUE_WORKER_DONE" in surv_log, surv_log[-1500:]

    # exactly-once: one done marker per video (O_EXCL first-writer-wins),
    # nothing left pending/claimed, and the killed worker's item carries
    # the reclaim provenance — finished by the survivor after >= 1 steal
    done_dir = feat_dir / "_queue" / "done"
    done = {p.stem: json.loads(p.read_text())
            for p in done_dir.glob("*.json")}
    assert len(done) == n_videos, sorted(done)
    assert not list((feat_dir / "_queue" / "pending").glob("*.json"))
    assert not list(claimed_root.glob("*/*.json"))
    reclaimed = [r for r in done.values() if r["reclaims"] >= 1]
    assert reclaimed, "the killed worker's lease was never reclaimed"
    for rec in reclaimed:
        assert f"-{procs[victim].pid}-" not in rec["by"], \
            "a dead worker cannot complete work"
        assert rec["status"] in ("done", "skipped"), rec
    for rec in done.values():
        assert rec["status"] in ("done", "skipped"), rec

    # bit-identical to an unkilled run: same artifact set, same bytes
    from video_features_tpu.cli import main as cli_main
    ref = tmp_path / "ref"
    cli_main([
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "allow_random_weights=true", "on_extraction=save_numpy",
        "extraction_total=6", "batch_size=8", "video_workers=1",
        f"output_path={ref}", f"tmp_path={tmp_path}/tmp_ref",
        f"file_with_video_paths={listfile}",
    ])
    ref_npy = {p.relative_to(ref): p.read_bytes()
               for p in ref.rglob("*.npy")}
    queue_npy = {p.relative_to(out): p.read_bytes()
                 for p in out.rglob("*.npy")}
    assert set(ref_npy) == set(queue_npy), "artifact sets diverged"
    assert len({rel for rel in ref_npy
                if str(rel).endswith("_resnet.npy")}) == n_videos
    for rel, data in ref_npy.items():
        assert queue_npy[rel] == data, \
            f"{rel}: killed-and-reclaimed run diverged from clean run"


# ---------------------------------------------------------------------------
# Seeded chaos matrix (ISSUE 9): faults as a first-class, replayable input.
# Every seed runs the resnet,clip shared-decode + fleet=queue pipeline with
# a deterministic injection plan (utils/inject.py), must end with
# vft-audit PASS (video_features_tpu/audit.py), and — the faults all being
# survivable — must produce artifacts bit-identical to an uninjected run.
# A failing seed replays exactly: re-run with its recorded plan string.
# ---------------------------------------------------------------------------

#: seed -> plan. Coverage rotates over the decode / sink / cache / queue /
#: heartbeat surfaces; all faults are SURVIVABLE (EIO-class transients,
#: torn writes the atomic sinks hide, skewed leases the steal protocol
#: absorbs, frozen/failing heartbeats) — never ENOSPC-class FATALs, which
#: correctly fail videos (tests/test_inject.py covers those verdicts).
CHAOS_PLANS = {
    0: "seed=0;decode.read=eio@n3",
    1: "seed=1;sink.fsync=eio@n1",
    2: "seed=2;sink.rename=drop@n1",
    3: "seed=3;sink.tmp_write=torn@n1;decode.read=eio@p0.02",
    4: "seed=4;cache.store=eio@n1;cache.lookup=torn@n1",
    5: "seed=5;queue.claim=skew@n1;heartbeat.tick=error@p0.5",
    6: "seed=6;heartbeat.tick=freeze@after1;decode.read=eio@n5",
    7: "seed=7;sink.fsync=eio@n2;queue.claim=eio@n1;"
       "queue.steal_staging=drop@n1",
}

_MATRIX_BASE = [
    "feature_type=resnet,clip", "resnet.model_name=resnet18",
    "device=cpu", "allow_random_weights=true", "on_extraction=save_numpy",
    "extraction_total=4", "batch_size=8", "video_workers=1",
    "telemetry=true", "metrics_interval_s=0.4", "health=true",
    "fleet=queue", "fleet_lease_s=3",
]


@pytest.fixture(scope="module")
def chaos_corpus(sample_video, tmp_path_factory):
    """Shared corpus + ONE clean (uninjected, no-fleet) reference run;
    every seeded chaos run is held to its artifact bytes."""
    td = tmp_path_factory.mktemp("chaos_matrix")
    videos = []
    for i in range(2):
        dst = td / f"v_mx_{i}.mp4"
        dst.write_bytes(Path(sample_video).read_bytes())
        videos.append(str(dst))
    listfile = td / "videos.txt"
    listfile.write_text("\n".join(videos) + "\n")
    from video_features_tpu.cli import main as cli_main
    ref = td / "ref"
    cli_main(["feature_type=resnet,clip", "resnet.model_name=resnet18",
              "device=cpu", "allow_random_weights=true",
              "on_extraction=save_numpy", "extraction_total=4",
              "batch_size=8", "video_workers=1",
              f"output_path={ref}", f"tmp_path={td / 'tmp_ref'}",
              f"file_with_video_paths={listfile}"])
    ref_npy = {p.name: p.read_bytes() for p in ref.rglob("*.npy")}
    assert len(ref_npy) >= 4, sorted(ref_npy)  # 2 videos x >= 2 families
    return td, listfile, ref_npy


def _run_chaos_seed(chaos_corpus, seed: int) -> None:
    from video_features_tpu.audit import audit_run
    from video_features_tpu.cli import main as cli_main
    td, listfile, ref_npy = chaos_corpus
    plan = CHAOS_PLANS[seed]
    out = td / f"seed{seed}"
    cache_dir = td / f"cache{seed}"  # per-seed: a shared store would let
    # later seeds short-circuit decode and starve their own faults
    cli_main(_MATRIX_BASE + [
        f"inject={plan}", "cache=true", f"cache_dir={cache_dir}",
        f"output_path={out}", f"tmp_path={td / f'tmp{seed}'}",
        f"file_with_video_paths={listfile}"])
    ok, violations, _notes = audit_run(
        str(out), cache_dir=str(cache_dir), expect_complete=True)
    assert ok, (f"seed {seed} failed the invariant audit — replay with "
                f"inject={plan!r}:\n  " + "\n  ".join(violations))
    got_npy = {p.name: p.read_bytes() for p in out.rglob("*.npy")}
    assert set(got_npy) == set(ref_npy), \
        f"seed {seed}: artifact set diverged (replay with inject={plan!r})"
    for name, data in ref_npy.items():
        assert got_npy[name] == data, \
            (f"seed {seed}: {name} not bit-identical to the clean run "
             f"(replay with inject={plan!r})")


@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_matrix_smoke(chaos_corpus, seed):
    """Quick-tier (not slow) 2-seed smoke: the decode-fault and
    sink-fsync-fault rows of the matrix, audited + bit-identical."""
    _run_chaos_seed(chaos_corpus, seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 3, 4, 5, 6, 7])
def test_chaos_matrix(chaos_corpus, seed):
    """The full matrix's remaining seeds (with seeds 0-1 riding in the
    quick tier, the slow tier completes the >= 8-seed sweep)."""
    _run_chaos_seed(chaos_corpus, seed)


# ---------------------------------------------------------------------------
# Deterministic worker kill: the scripted SIGKILL of
# test_chaos_queue_worker_kill_lease_reclaim, promoted to an injected,
# seed-replayable fault — VFT_INJECT arms the victim subprocess, which
# SIGKILLs ITSELF at its 2nd video attempt (no external observer races).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Gateway chaos (ISSUE 14): the network front door's failure semantics,
# proven under the same seeded injection plane. Each seed composes the 3
# ingress sites (gateway.read, gateway.spool_submit, spool.respond) with
# a REAL in-process gateway + ServeLoop pair, and must end in vft-audit
# PASS: torn client bodies answer 400 and retry cleanly (content-
# addressed dedup), a lost spool submit is recovered by the deadline
# sweep (terminal expired record, zero decode spans), a lost response
# write is requeued and re-served idempotently.
# ---------------------------------------------------------------------------

GATEWAY_CHAOS_PLANS = {
    30: "seed=30;gateway.read=torn@n1;gateway.spool_submit=enospc@n1;"
        "spool.respond=drop@n1",
    31: "seed=31;gateway.read=stall@n1;gateway.spool_submit=drop@n1",
}


def _http(base, method, path, data=None):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(base + path, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.mark.quick
@pytest.mark.parametrize("seed", sorted(GATEWAY_CHAOS_PLANS))
def test_gateway_chaos_matrix(sample_video, tmp_path, seed):
    import threading

    from video_features_tpu import serve
    from video_features_tpu.audit import audit_run
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.gateway import GatewayServer
    from video_features_tpu.utils import inject

    spool = tmp_path / "spool"
    cfg = load_config("resnet", {
        "model_name": "resnet18", "device": "cpu",
        "allow_random_weights": True, "on_extraction": "save_numpy",
        "extraction_total": 6, "batch_size": 8, "cache": True,
        "cache_dir": str(tmp_path / "cache"), "spool_dir": str(spool),
        "serve_poll_interval_s": 0.05, "metrics_interval_s": 1,
        "output_path": str(tmp_path / "out"),
        "tmp_path": str(tmp_path / "tmp")})
    sanity_check(cfg, require_videos=False)
    plan = inject.arm_for_run(GATEWAY_CHAOS_PLANS[seed])
    loop = gw = t = None
    try:
        loop = serve.ServeLoop(cfg, out_root=str(tmp_path / "out"))
        t = threading.Thread(target=loop.run, daemon=True)
        t.start()
        gw = GatewayServer({"spool_dir": str(spool),
                            "gateway_poll_interval_s": 0.05,
                            "gateway_expire_grace_s": 0.5,
                            "metrics_interval_s": 1}).start()
        base = f"http://127.0.0.1:{gw.port}"
        data = Path(sample_video).read_bytes()

        st, up = _http(base, "POST", "/v1/upload?name=clip.mp4", data)
        if seed == 30:
            # gateway.read=torn@n1: the first body read is cut short —
            # an explicit 400, never a half-ingested inbox file
            assert st == 400 and "torn" in up["error"], up
            assert not list((spool / "inbox").iterdir())
            st, up = _http(base, "POST", "/v1/upload?name=clip.mp4",
                           data)
        # seed 31's stall@n1 just delays this read; either way the
        # (retried) upload lands exactly once, content-addressed
        assert st == 201, up

        if seed == 31:
            # gateway.spool_submit=drop@n1: request A's submit is lost
            # in flight; past deadline+grace the gateway writes the
            # terminal expired record itself — the 202 still resolves
            st, a = _http(base, "POST", "/v1/extract", json.dumps(
                {"video_paths": [up["path"]],
                 "timeout_s": 1.0}).encode())
            assert st == 202
            term = serve.wait_response(str(spool), a["id"],
                                       timeout_s=60)
            assert term["status"] == "deadline_exceeded", term
            assert term["processed"] == 0

        # the surviving request: must end done despite the armed faults
        # (seed 30: first submit raises ENOSPC -> retried next pump
        # pass; first response write dropped -> claim requeued and
        # re-served off the feature cache)
        st, b = _http(base, "POST", "/v1/extract", json.dumps(
            {"video_paths": [up["path"]], "timeout_s": 240}).encode())
        assert st == 202
        resp = serve.wait_response(str(spool), b["id"], timeout_s=240)
        assert resp["status"] == "done", resp
        if seed == 30:
            assert plan.fired.get("gateway.spool_submit") == 1
            assert plan.fired.get("spool.respond") == 1
    finally:
        if gw is not None:
            gw.stop()
        if loop is not None:
            loop.stop()
        if t is not None:
            t.join(timeout=120)
        inject.disarm()
    assert not t.is_alive()
    ok, violations, _notes = audit_run(
        str(tmp_path), cache_dir=str(tmp_path / "cache"),
        expect_complete=True)
    assert ok, (f"gateway seed {seed} failed the audit — replay with "
                f"inject={GATEWAY_CHAOS_PLANS[seed]!r}:\n  "
                + "\n  ".join(violations))


@pytest.mark.slow
def test_chaos_inject_worker_kill_replay(sample_video, tmp_path):
    from video_features_tpu.audit import audit_run
    repo = str(Path(__file__).resolve().parent.parent)
    n_videos = 4
    videos = []
    for i in range(n_videos):
        dst = tmp_path / f"v_ik_{i:02d}.mp4"
        dst.write_bytes(Path(sample_video).read_bytes())
        videos.append(str(dst))
    listfile = tmp_path / "videos.txt"
    listfile.write_text("\n".join(videos) + "\n")
    out = tmp_path / "out"
    feat_dir = out / "resnet" / "resnet18"

    def spawn(idx, inject_env=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("VFT_INJECT", None)
        if inject_env:
            env["VFT_INJECT"] = inject_env
        log = open(tmp_path / f"ikworker_{idx}.log", "w")
        script = _QUEUE_WORKER.format(
            repo=repo, out=out, tmp=f"{tmp_path}/tmp_{idx}",
            listfile=listfile)
        return subprocess.Popen([sys.executable, "-c", script], stdout=log,
                                stderr=subprocess.STDOUT, env=env), log

    # worker 0 is the victim: the injected plan SIGKILLs it at its 2nd
    # per-video attempt — deterministically, every replay
    procs, logs = zip(*(spawn(0, "seed=11;worker.kill=kill@n2"),
                        spawn(1)))
    try:
        assert procs[0].wait(timeout=TIMEOUT_S) == -signal.SIGKILL, \
            "the injected worker.kill must SIGKILL the victim"
        assert procs[1].wait(timeout=TIMEOUT_S) == 0, \
            (tmp_path / "ikworker_1.log").read_text()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
    victim_log = (tmp_path / "ikworker_0.log").read_text()
    assert "INJECT: worker.kill=kill fired" in victim_log

    # the survivor drained the fleet exactly-once; the whole dir passes
    # the invariant audit despite the mid-claim SIGKILL
    done = {p.stem: json.loads(p.read_text())
            for p in (feat_dir / "_queue" / "done").glob("*.json")}
    assert len(done) == n_videos, sorted(done)
    assert all(r["status"] in ("done", "skipped") for r in done.values())
    ok, violations, _ = audit_run(str(out), expect_complete=True)
    assert ok, "\n".join(violations)

    # and bit-identical to an unkilled run
    from video_features_tpu.cli import main as cli_main
    ref = tmp_path / "ref"
    cli_main([
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "allow_random_weights=true", "on_extraction=save_numpy",
        "extraction_total=6", "batch_size=8", "video_workers=1",
        f"output_path={ref}", f"tmp_path={tmp_path}/tmp_ref",
        f"file_with_video_paths={listfile}",
    ])
    ref_npy = {p.name: p.read_bytes() for p in ref.rglob("*.npy")}
    got_npy = {p.name: p.read_bytes() for p in out.rglob("*.npy")}
    assert ref_npy == got_npy, \
        "killed-and-reclaimed run diverged from the clean run"


# ---------------------------------------------------------------------------
# GC chaos (gc.py, this PR's arc): the storage lifecycle plane under the
# same seeded-plan discipline as the gateway matrix. Both seeds build a
# synthetic over-retention tree, arm a plan, sweep, and prove the
# journal-before-unlink contract: a dropped unlink (seed 40 — dying in
# the crash window) or an injected EIO mid-sweep (seed 41, after a
# stall) leaves journaled-but-present remnants that AUDIT as notes, and
# a second, un-faulted sweep converges to the same end state.
# ---------------------------------------------------------------------------

GC_CHAOS_PLANS = {
    40: "seed=40;gc.evict=drop@n1",
    41: "seed=41;gc.sweep=stall@n1;gc.evict=eio@n1",
}


def _gc_litter(tmp_path):
    """An over-retention tree: 3 cold cache entries + 3 expired spool
    responses, every mtime 1000s in the past."""
    root = tmp_path / "out"
    cache = tmp_path / "cache"
    old = time.time() - 1000.0
    for i in range(3):
        p = cache / f"{i:02x}" / f"{i:02x}beef.pkl"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(b"x" * 64)
        os.utime(p, (old, old))
    (root / "done").mkdir(parents=True)
    for i in range(3):
        p = root / "done" / f"rid{i}.json"
        p.write_text(json.dumps({"id": f"rid{i}", "status": "done"}))
        os.utime(p, (old, old))
    return root, cache


@pytest.mark.quick
@pytest.mark.parametrize("seed", sorted(GC_CHAOS_PLANS))
def test_gc_chaos_matrix(tmp_path, seed):
    from video_features_tpu import gc as vgc
    from video_features_tpu.audit import audit_run
    from video_features_tpu.utils import inject

    root, cache = _gc_litter(tmp_path)
    cfg = vgc.GcConfig.from_args({"gc_cache_retention_s": 100,
                                  "gc_spool_retention_s": 100})
    kw = dict(cache_dir=str(cache), compile_dir=str(tmp_path / "cc"))
    plan = inject.arm_for_run(GC_CHAOS_PLANS[seed])
    try:
        result = vgc.sweep(str(root), cfg, **kw)
    finally:
        inject.disarm()

    assert result["planned"] == 6
    assert plan.fired.get("gc.evict") == 1
    if seed == 41:
        assert plan.fired.get("gc.sweep") == 1
        # the injected EIO is a counted error, never a crashed sweep
        assert result["executed"]["cache"]["errors"] == 1
    # exactly one deletion was journaled but never happened; every
    # other one completed despite the armed plan
    journal = list(root.glob("_gc_*.jsonl"))
    assert len(journal) == 1
    remnants = [p for p in (*cache.rglob("*.pkl"),
                            *(root / "done").glob("*.json"))]
    assert len(remnants) == 1, remnants

    # the invariant audit sees the remnant as RECOVERABLE, not a FAIL
    ok, violations, notes = audit_run(str(root))
    assert ok, "\n".join(violations)
    assert any("gc-journaled" in n for n in notes), notes

    # a second, un-faulted sweep converges: the remnant still satisfies
    # its planner, gets re-journaled, and this time the unlink lands
    result2 = vgc.sweep(str(root), cfg, **kw)
    assert result2["planned"] == 1
    assert not list(cache.rglob("*.pkl"))
    assert not list((root / "done").glob("*.json"))
    ok, violations, notes = audit_run(str(root))
    assert ok, "\n".join(violations)
    assert not any("gc-journaled" in n for n in notes), notes
