"""Shared structural contracts across ALL registered extractor families.

Round-3 advisor finding: ``frame_channel_order = 'bgr'`` is an implicit
contract between a class attribute and the family's host transform
(extractors/clip_stack.py) — nothing structurally tied them together. This
suite ties them: for every registered family that streams frames through
``VideoSource``, extraction with the DECLARED channel order must be
bit-identical to forcing RGB delivery and inserting an explicit RGB->
declared-order reorder in front of the same transform. A family that
declares 'bgr' but whose wiring doesn't actually deliver BGR (or vice
versa) fails here; a transform that mis-handles the declared order it
truthfully receives is caught by that family's torch-oracle E2E test.
"""
import numpy as np
import pytest

from video_features_tpu.config import load_config, parse_dotlist, sanity_check
from video_features_tpu.registry import get_extractor_cls

#: families with a frame_channel_order declaration (clip-stack streaming);
#: listed explicitly so a NEW family adding the attribute must add itself
#: here (the test below fails loudly if the lists drift)
CLIP_STACK_FAMILIES = ["r21d", "s3d"]

#: minimum viable stack per family: s3d's 8x temporal downsampling needs
#: >=16 frames to leave >1 temporal position at the head (models/s3d.py)
STACK_SIZE = {"r21d": 10, "s3d": 16}


def _args(family, tmp_path, sample_video):
    stack = STACK_SIZE[family]
    dotlist = [
        f"feature_type={family}", "device=cpu", f"stack_size={stack}",
        f"step_size={stack}", "extraction_fps=2", "allow_random_weights=true",
        f"output_path={tmp_path / 'o'}", f"tmp_path={tmp_path / 't'}",
        f"video_paths={sample_video}",
    ]
    args = load_config(family, parse_dotlist(dotlist))
    sanity_check(args)
    return args


def test_family_list_covers_every_declarer():
    """Any registered family declaring frame_channel_order must be in
    CLIP_STACK_FAMILIES (so the equivalence test below covers it)."""
    from video_features_tpu.registry import _DISPATCH
    declared = []
    for family in _DISPATCH:
        try:
            cls = get_extractor_cls(family)
        except NotImplementedError:
            continue
        if "frame_channel_order" in {
                k for klass in cls.__mro__ for k in vars(klass)}:
            declared.append(family)
    # i3d streams via VideoSource directly (default rgb, no declaration)
    assert sorted(declared) == sorted(CLIP_STACK_FAMILIES), (
        "families declaring frame_channel_order drifted from the shared "
        f"contract test: {declared} vs {CLIP_STACK_FAMILIES}")


@pytest.mark.parametrize("family", CLIP_STACK_FAMILIES)
def test_channel_order_wiring_equivalence(family, sample_video, tmp_path,
                                          monkeypatch):
    """declared-order delivery == rgb delivery + explicit rgb->declared
    reorder into the same transform, end to end through extract()."""
    cls = get_extractor_cls(family)
    declared = cls.frame_channel_order
    args = _args(family, tmp_path, sample_video)

    ext = cls(args)
    native = ext.extract(sample_video)

    monkeypatch.setattr(cls, "frame_channel_order", "rgb")
    ext_rgb = cls(args)
    if declared == "bgr":
        inner = ext_rgb.host_transform
        assert inner is not None, (
            f"{family}: declared 'bgr' but has no host transform to "
            "perform the reorder — the invariant in clip_stack.py is "
            "unsatisfiable")
        ext_rgb.host_transform = lambda f: inner(f[..., ::-1])
    forced = ext_rgb.extract(sample_video)

    assert native.keys() == forced.keys()
    for key in native:
        np.testing.assert_array_equal(
            np.asarray(native[key]), np.asarray(forced[key]),
            err_msg=f"{family}/{key}: frame_channel_order={declared!r} "
                    "delivery is not equivalent to rgb delivery + explicit "
                    "reorder — attribute and transform are out of step")
