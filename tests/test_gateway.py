"""The network front door (gateway.py / `vft-gateway`, ISSUE 14):
multi-tenant admission over real HTTP, end-to-end deadlines, and the
shed-don't-collapse contract.

Three layers of coverage, cheapest first:
  - pure units: tenant-table validation, token-bucket determinism, the
    smooth weighted-fair-share release order;
  - HTTP admission against a BACKENDLESS gateway (ephemeral port, no
    extractor construction): auth 401, rate/in-flight 429 with a
    computed Retry-After, cross-tenant isolation 403, content-addressed
    upload dedup, 503 shed on a dead backend;
  - deadline semantics against a real ``ServeLoop`` with the video step
    stubbed: expiry while queued (cancelled at claim, ZERO video work),
    expiry mid-request between videos (partial results + terminal
    ``expired/`` record, never a ``done/`` response), and clock-skew
    tolerance (deadlines are gateway-duration-relative; a client's
    forged wall clock changes nothing).

The real-extraction E2E twin (upload -> extract -> bit-identical
features -> audit PASS) is scripts/check_gateway_smoke.py (CI quick
gate); the chaos seeds live in tests/test_chaos.py.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from video_features_tpu import gateway, serve
from video_features_tpu.gateway import (GatewayServer, TokenBucket,
                                        load_tenant_table)
from video_features_tpu.telemetry.jsonl import write_json_atomic

pytestmark = pytest.mark.quick

TENANTS_YML = """
tenants:
  alpha:
    key: alpha-k
    rate_rps: 100
    burst: 100
    max_inflight: 2
    priority: high
  beta:
    key: beta-k
    rate_rps: 0.5
    burst: 1
    max_inflight: 2
    priority: low
"""


def _call(base, method, path, data=None, key=None, headers=None):
    req = urllib.request.Request(base + path, data=data, method=method)
    if key:
        req.add_header("X-API-Key", key)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture
def gw(tmp_path):
    ty = tmp_path / "tenants.yml"
    ty.write_text(TENANTS_YML)
    g = GatewayServer({"spool_dir": str(tmp_path / "spool"),
                       "gateway_tenants": str(ty),
                       "gateway_poll_interval_s": 0.05,
                       "gateway_expire_grace_s": 0.5,
                       "metrics_interval_s": 1}).start()
    yield g, f"http://127.0.0.1:{g.port}"
    g.stop()


# -- units -------------------------------------------------------------------

def test_tenant_table_validation(tmp_path):
    p = tmp_path / "tenants.yml"
    p.write_text(TENANTS_YML)
    table = load_tenant_table(str(p))
    assert {t.name for t in table.values()} == {"alpha", "beta"}
    assert table["alpha-k"].priority == "high"
    assert table["beta-k"].max_inflight == 2
    # open mode: no table -> the single implicit keyless tenant
    open_table = load_tenant_table(None)
    assert None in open_table and open_table[None].name == "anon"

    def bad(yml, needle):
        p.write_text(yml)
        with pytest.raises(ValueError, match=needle):
            load_tenant_table(str(p))

    bad("tenants: {}", "at least one tenant")
    # a dashed name would break the {tenant}-{rid} prefix split
    bad("tenants:\n  has-dash:\n    key: k\n", r"\[a-z0-9_\]\+")
    bad("tenants:\n  a:\n    priority: high\n", "needs a string 'key'")
    bad("tenants:\n  a:\n    key: k\n  b:\n    key: k\n", "duplicates")
    bad("tenants:\n  a:\n    key: k\n    priority: urgent\n",
        "priority")
    bad("tenants:\n  a:\n    key: k\n    rate_rps: 0\n", "rate_rps")
    bad("tenants:\n  a:\n    key: k\n    max_inflight: 0\n",
        "max_inflight")


def test_token_bucket_deterministic_retry_after():
    clock = [0.0]
    b = TokenBucket(rate_rps=2.0, burst=3, clock=lambda: clock[0])
    # burst drains, then the refusal names the exact wait for 1 token
    assert [b.try_take()[0] for _ in range(3)] == [True, True, True]
    ok, retry = b.try_take()
    assert not ok and retry == pytest.approx(0.5)
    clock[0] += 0.5  # exactly one token refilled
    assert b.try_take() == (True, 0.0)
    assert not b.try_take()[0]
    clock[0] += 100.0  # refill clamps at burst, never beyond
    assert [b.try_take()[0] for _ in range(4)] == [True, True, True,
                                                  False]


def test_weighted_fair_share_release_order(tmp_path):
    """Smooth WRR over high/normal/low = 4/2/1: with all three classes
    backlogged, any 7 consecutive releases split 4/2/1 and high is
    never starved-out nor allowed to starve low."""
    g = GatewayServer({"spool_dir": str(tmp_path / "spool")})
    t = gateway.Tenant("t", None, **gateway.TENANT_DEFAULTS)
    for klass in ("high", "normal", "low"):
        for i in range(7):
            p = gateway._Pending(f"{klass}{i}", t, ["v"], None)
            p.klass = klass
            g._queues[klass].append(p)
    order = [g._pick_class() for _ in range(7)]
    assert order == ["high", "normal", "high", "low", "high", "normal",
                     "high"]
    # pop what _pick_class scheduled so the next window repeats 4:2:1
    for klass in order:
        g._queues[klass].popleft()
    assert [g._pick_class() for _ in range(7)].count("high") == 4
    g.httpd.server_close()
    g.recorder.close()


# -- HTTP admission (no backend) ----------------------------------------------

def test_admission_auth_rate_inflight_isolation(gw):
    g, base = gw
    body = json.dumps({"video_paths": ["/v.mp4"], "timeout_s": 60}
                      ).encode()
    # 401: unknown/missing key
    assert _call(base, "POST", "/v1/extract", body)[0] == 401
    assert _call(base, "POST", "/v1/extract", body, key="nope")[0] == 401
    # beta: burst 1 -> second immediate request is a rate 429 whose
    # Retry-After is computed from the bucket (0.5 rps -> 2s)
    st1, acc, _ = _call(base, "POST", "/v1/extract", body, key="beta-k")
    st2, rej, h2 = _call(base, "POST", "/v1/extract", body, key="beta-k")
    assert (st1, st2) == (202, 429)
    assert h2["Retry-After"] == "2" and rej["retry_after_s"] == 2
    # alpha: generous rate but max_inflight=2 -> third open request 429
    rids = []
    for _ in range(2):
        st, b, _ = _call(base, "POST", "/v1/extract", body, key="alpha-k")
        assert st == 202
        rids.append(b["id"])
    st, b, h = _call(base, "POST", "/v1/extract", body, key="alpha-k")
    assert st == 429 and "max_inflight" in b["error"]
    assert int(h["Retry-After"]) >= 1
    # tenant identity is minted into the id; isolation holds on poll
    assert all(r.startswith("alpha-") for r in rids)
    st, b, _ = _call(base, "GET", f"/v1/requests/{rids[0]}", key="beta-k")
    assert st == 403
    st, b, _ = _call(base, "GET", f"/v1/requests/{rids[0]}",
                     key="alpha-k")
    assert st == 202 and b["status"] in ("queued", "submitted")
    # healthz needs no auth and reports both planes
    st, b, _ = _call(base, "GET", "/healthz")
    assert st == 200 and b["gateway"]["state"] == "ready"
    assert b["backend"]["state"] == "absent"


def test_upload_content_addressed_idempotent(gw):
    g, base = gw
    import hashlib
    data = b"not really mp4 bytes, but bytes"
    sha = hashlib.sha256(data).hexdigest()
    st1, up1, _ = _call(base, "POST", "/v1/upload?name=clip.mp4", data,
                        key="alpha-k")
    assert st1 == 201 and up1["dedup"] is False and up1["sha256"] == sha
    assert Path(up1["path"]).read_bytes() == data
    # the retry of identical bytes is a HIT, not duplicate work
    st2, up2, _ = _call(base, "POST", "/v1/upload?name=clip.mp4", data,
                        key="alpha-k")
    assert st2 == 200 and up2["dedup"] is True
    assert up2["path"] == up1["path"]
    assert len(list(Path(g.inbox_dir).iterdir())) == 1
    # a checksummed upload whose bytes were corrupted in transit is a
    # client-visible 400, never a silently half-ingested request
    st3, err, _ = _call(base, "POST", "/v1/upload?name=clip.mp4",
                        b"corrupted bytes", key="alpha-k",
                        headers={"X-Content-SHA256": sha})
    assert st3 == 400 and "mismatch" in err["error"]


def test_shed_503_on_dead_backend(gw):
    g, base = gw
    # the only server on the spool wrote a FINAL heartbeat: heartbeat
    # liveness says there is nobody to do the work -> shed, don't queue
    write_json_atomic(Path(g.spool_dir) / "_heartbeat_srv-1.json",
                      {"host_id": "srv-1", "time": time.time(),
                       "interval_s": 1.0, "final": True,
                       "serve": {"state": "exited"}})
    body = json.dumps({"video_paths": ["/v.mp4"]}).encode()
    st, b, h = _call(base, "POST", "/v1/extract", body, key="alpha-k")
    assert st == 503 and "backend_exited" in b["error"]
    assert int(h["Retry-After"]) >= 1
    section = g._gateway_section()
    assert section["tenants"]["alpha"]["shed"] == 1


# -- deadlines (real ServeLoop, stubbed video step) ---------------------------

def _make_loop(tmp_path, sample_video):
    from video_features_tpu.config import load_config, sanity_check
    spool = tmp_path / "spool"
    cfg = load_config("resnet", {
        "model_name": "resnet18", "device": "cpu",
        "allow_random_weights": True, "on_extraction": "save_numpy",
        "extraction_total": 6, "batch_size": 8, "cache": False,
        "spool_dir": str(spool), "serve_poll_interval_s": 0.05,
        "metrics_interval_s": 1,
        "output_path": str(tmp_path / "out"),
        "tmp_path": str(tmp_path / "tmp")})
    sanity_check(cfg, require_videos=False)
    return serve.ServeLoop(cfg, out_root=str(tmp_path / "out")), str(spool)


def _claim(loop, spool, rid):
    src = Path(spool) / "requests" / f"{rid}.json"
    dst = Path(loop.claim_dir) / f"{rid}.json"
    os.rename(src, dst)
    return str(dst)


def test_deadline_expired_while_queued_cancelled_at_claim(
        sample_video, tmp_path):
    """Expiry while queued: the claim-time wasted-work guard cancels the
    request BEFORE any video runs — terminal ``expired/`` record, no
    ``done/`` response, zero extraction calls."""
    loop, spool = _make_loop(tmp_path, sample_video)
    calls = []
    loop._run_one_video = lambda v: calls.append(v) or {"resnet": "done"}
    rid = serve.submit_request(spool, [str(sample_video)],
                              request_id="t1-queuedexp",
                              deadline=time.time() - 0.1)
    loop._process(_claim(loop, spool, rid))
    assert calls == []
    assert serve.read_response(spool, rid) is None  # never a done/
    term = serve.read_terminal(spool, rid)
    assert term["status"] == "deadline_exceeded"
    assert term["expired_at"] == "claim" and term["processed"] == 0
    assert term["tenant"] == "t1"
    assert loop._tallies["deadline_exceeded"] == 1
    # the claim is released, not stranded
    assert not list(Path(loop.claim_dir).glob("*.json"))
    # tenant accounting: an expired request is a violated request
    assert loop._tenants["t1"] == {"requests": 1, "violations": 1,
                                   "rejects": 0}
    loop.recorder.close()


def test_deadline_expires_mid_request_partial_results(
        sample_video, tmp_path):
    """Expiry between videos: whatever finished stays (partial results +
    statuses in the terminal record); the remaining videos never run."""
    loop, spool = _make_loop(tmp_path, sample_video)

    def slow_video(v):
        time.sleep(0.35)
        return {"resnet": "done"}

    loop._run_one_video = slow_video
    vids = [f"/v{i}.mp4" for i in range(4)]
    rid = serve.submit_request(spool, vids, request_id="t1-midexp",
                              deadline=time.time() + 0.5)
    loop._process(_claim(loop, spool, rid))
    term = serve.read_terminal(spool, rid)
    assert term["status"] == "deadline_exceeded"
    assert term["expired_at"] == "mid_request"
    assert 1 <= term["processed"] < len(vids)
    done_vids = set(term["videos"])
    assert done_vids == set(vids[:term["processed"]])
    assert all(v == {"resnet": "done"} for v in term["videos"].values())
    assert serve.read_response(spool, rid) is None
    loop.recorder.close()


def test_deadlines_are_duration_relative_not_client_clock(
        sample_video, tmp_path):
    """Clock-skew tolerance, both halves: (a) the gateway computes the
    deadline from ITS clock + the requested duration — the client's
    wall clock never enters; (b) the server honors the absolute
    deadline even when the request's client-stamped ``time`` field is
    forged hours off (it only skews the reported queue-wait, never
    expiry)."""
    # (a) gateway half
    g = GatewayServer({"spool_dir": str(tmp_path / "gspool"),
                       "gateway_poll_interval_s": 0.05})
    tenant = g.tenants[None]
    before = time.time()
    code, body, _ = g.admit(tenant, ["/v.mp4"], 60.0)
    assert code == 202
    assert before + 59 <= body["deadline"] <= time.time() + 61
    g.httpd.server_close()
    g.recorder.close()

    # (b) server half: forge the client clock 3 hours ahead; a valid
    # 60s deadline from the coordinating (gateway) clock still serves
    loop, spool = _make_loop(tmp_path, sample_video)
    loop._run_one_video = lambda v: {"resnet": "done"}
    rid = serve.submit_request(spool, ["/v.mp4"], request_id="t1-skew",
                              deadline=time.time() + 60)
    req_path = Path(spool) / "requests" / f"{rid}.json"
    req = json.loads(req_path.read_text())
    req["time"] = time.time() + 3 * 3600  # the skewed client clock
    write_json_atomic(req_path, req)
    loop._process(_claim(loop, spool, rid))
    resp = serve.read_response(spool, rid)
    assert resp is not None and resp["status"] == "done"
    assert resp["wait_s"] == 0.0  # clamped, not negative
    assert serve.read_terminal(spool, rid)["status"] == "done"
    loop.recorder.close()


def test_gateway_expires_spooled_request_and_audits_clean(tmp_path):
    """No server ever comes: the gateway's sweep withdraws the spooled
    request at its deadline and writes the terminal record itself —
    every 202 resolves, and the whole tree passes vft-audit."""
    from video_features_tpu.audit import audit_run
    g = GatewayServer({"spool_dir": str(tmp_path / "spool"),
                       "gateway_poll_interval_s": 0.05,
                       "gateway_expire_grace_s": 0.5,
                       "metrics_interval_s": 1}).start()
    base = f"http://127.0.0.1:{g.port}"
    st, acc, _ = _call(base, "POST", "/v1/extract", json.dumps(
        {"video_paths": ["/v.mp4"], "timeout_s": 0.6}).encode())
    assert st == 202
    term = serve.wait_response(str(tmp_path / "spool"), acc["id"],
                               timeout_s=30)
    assert term["status"] == "deadline_exceeded"
    assert term["expired_at"] in ("queued", "spooled")
    # the withdrawn request is gone from requests/
    assert not list((tmp_path / "spool" / "requests").glob("*.json"))
    g.stop()
    ok, violations, _notes = audit_run(str(tmp_path),
                                       expect_complete=True)
    assert ok, "\n".join(violations)
    events = [json.loads(l)["event"]
              for l in Path(g.journal_path).read_text().splitlines()]
    assert "accepted" in events and "expired" in events


# -- audit invariants (crafted violations must FAIL) --------------------------

def _spool_skeleton(root: Path) -> Path:
    spool = root / "spool"
    for d in ("requests", "claimed", "done", "expired", "inbox"):
        (spool / d).mkdir(parents=True)
    return spool


def test_audit_flags_done_and_expired_conflict(tmp_path):
    from video_features_tpu.audit import audit_run
    spool = _spool_skeleton(tmp_path)
    write_json_atomic(spool / "done" / "t1-r1.json",
                      {"id": "t1-r1", "status": "done"})
    write_json_atomic(spool / "expired" / "t1-r1.json",
                      {"id": "t1-r1", "status": "deadline_exceeded",
                       "processed": 0, "videos": {}})
    ok, violations, _ = audit_run(str(tmp_path))
    assert not ok
    assert any("mutually exclusive" in v for v in violations)
    # and a wrong-status expired record is its own violation
    write_json_atomic(spool / "expired" / "t1-r2.json",
                      {"id": "t1-r2", "status": "done"})
    ok, violations, _ = audit_run(str(tmp_path))
    assert any("status=deadline_exceeded" in v for v in violations)


def test_audit_flags_claim_expired_request_with_spans(tmp_path):
    from video_features_tpu.audit import audit_run
    spool = _spool_skeleton(tmp_path)
    write_json_atomic(spool / "expired" / "t1-r1.json",
                      {"id": "t1-r1", "status": "deadline_exceeded",
                       "processed": 0, "videos": {}})
    # a span stamped with the expired request's id = work was burned
    with open(spool / "_telemetry.jsonl", "w") as f:
        f.write(json.dumps({"video": "v.mp4", "status": "done",
                            "request_id": "t1-r1"}) + "\n")
    ok, violations, _ = audit_run(str(tmp_path))
    assert not ok
    assert any("wasted-work guard" in v for v in violations)


def test_audit_flags_orphaned_inbox_and_unreconciled_tenants(tmp_path):
    from video_features_tpu.audit import audit_run
    spool = _spool_skeleton(tmp_path)
    jpath = spool / "_gateway_gw-1.jsonl"
    recs = [
        {"schema": gateway.JOURNAL_SCHEMA, "event": "upload",
         "tenant": "alpha", "path": str(spool / "inbox" / "aa.mp4")},
        {"schema": gateway.JOURNAL_SCHEMA, "event": "accepted",
         "id": "alpha-r1", "tenant": "alpha"},
        {"schema": gateway.JOURNAL_SCHEMA, "event": "accepted",
         "id": "alpha-r2", "tenant": "alpha"},
        {"schema": gateway.JOURNAL_SCHEMA, "event": "rejected",
         "id": "beta-r9", "tenant": "beta", "reason": "rate"},
    ]
    jpath.write_text("".join(json.dumps(r) + "\n" for r in recs))
    (spool / "inbox" / "aa.mp4").write_bytes(b"a")
    (spool / "inbox" / "orphan.mp4").write_bytes(b"o")  # never journaled
    write_json_atomic(spool / "done" / "alpha-r1.json",
                      {"id": "alpha-r1", "status": "done"})
    # alpha-r2 accepted but never terminal; beta-r9 was refused at the
    # door yet somehow reached the spool
    write_json_atomic(spool / "requests" / "beta-r9.json",
                      {"id": "beta-r9", "video_paths": []})
    ok, violations, _ = audit_run(str(tmp_path), expect_complete=True)
    assert not ok
    assert any("orphaned upload" in v and "orphan.mp4" in v
               for v in violations)
    assert any("alpha-r2" in v and "no terminal record" in v
               for v in violations)
    assert any("beta-r9" in v and "refused" in v for v in violations)
    assert any("tenant alpha" in v and "reconcile" in v
               for v in violations)
    # fixing the ledger turns the audit green
    (spool / "inbox" / "orphan.mp4").unlink()
    (spool / "requests" / "beta-r9.json").unlink()
    write_json_atomic(spool / "expired" / "alpha-r2.json",
                      {"id": "alpha-r2", "status": "deadline_exceeded",
                       "processed": 0, "videos": {}})
    ok, violations, _ = audit_run(str(tmp_path), expect_complete=True)
    assert ok, "\n".join(violations)


# -- SIGTERM drain ------------------------------------------------------------

def test_stop_flushes_queued_requests_into_spool(tmp_path):
    """The drain contract: stop accepting, flush accepted-but-unsubmitted
    requests into the spool (their 202 was a promise), final heartbeat."""
    g = GatewayServer({"spool_dir": str(tmp_path / "spool"),
                       # bound 0 releases nothing while running: every
                       # accepted request is still edge-queued at stop
                       "gateway_spool_bound": 1,
                       "gateway_poll_interval_s": 30,
                       "metrics_interval_s": 30})
    tenant = g.tenants[None]
    rids = [g.admit(tenant, ["/v.mp4"], None)[1]["id"] for _ in range(3)]
    g.start()
    g.stop()
    spooled = {p.stem for p
               in (tmp_path / "spool" / "requests").glob("*.json")}
    assert spooled == set(rids)
    hb = json.loads(next((tmp_path / "spool").glob(
        "_heartbeat_gw-*.json")).read_text())
    assert hb["final"] and hb["gateway"]["state"] == "exited"
    events = [json.loads(l)["event"]
              for l in Path(g.journal_path).read_text().splitlines()]
    assert events.count("submitted") == 3 and events[-1] == "drain"
