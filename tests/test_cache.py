"""Content-addressed feature cache (cache.py): keying, verify-before-
trust serving, extractor wiring, and the two-pass CLI contract (ISSUE 7).

Contracts pinned here:
  - the store key changes exactly when a feature VALUE could change:
    input bytes, a semantic config key, or a weights sha — and does NOT
    change for operational knobs (output paths, worker counts,
    telemetry switches) or for a default that resolves to the same
    value an explicit setting names (``resize=auto`` ≡ ``resize=device``
    on a save run);
  - a hit never decodes: the second byte-identical run is served with
    the extractor's decode/forward path provably never entered;
  - serving is verify-before-trust: an entry whose bytes are torn, whose
    schema is stale, or whose tensors fail the quantization-tolerant
    content signature (telemetry/health.py) is deleted and reported as
    a miss — corrupted features are never served;
  - two CLI passes over the same corpus with ``cache=true`` end with
    pass 2 at a 100% hit rate (heartbeat ``cache`` section) and outputs
    bit-identical to pass 1 (the CI smoke's in-suite twin).
"""
import os
import pickle
import shutil
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu import cache as fcache

pytestmark = pytest.mark.quick


# -- identity components ----------------------------------------------------

def test_file_sha256_memoizes_and_tracks_content(tmp_path):
    p = tmp_path / "blob.bin"
    p.write_bytes(b"x" * 4096)
    first = fcache.file_sha256(str(p))
    assert first == fcache.file_sha256(str(p))  # memo path
    # new content (and a new mtime_ns/size key) must re-hash, not re-serve
    p.write_bytes(b"y" * 4097)
    assert fcache.file_sha256(str(p)) != first


def test_content_identity_sha_fast_path_and_plan_fallback(
        sample_video, monkeypatch):
    cid = fcache.content_identity(sample_video)
    assert cid.startswith("sha256:")
    # unreadable bytes (pipe/device sources) fall back to the decode-plan
    # identity: probed props + the exact plan_frame_selection mapping
    monkeypatch.setattr(fcache, "file_sha256",
                        lambda p: (_ for _ in ()).throw(OSError("no bytes")))
    pid = fcache.content_identity(sample_video, fps=4.0)
    assert pid.startswith("plan:")
    # the plan identity is deterministic and fps-sensitive
    assert pid == fcache.content_identity(sample_video, fps=4.0)
    assert pid != fcache.content_identity(sample_video, fps=2.0)


def test_config_fingerprint_operational_keys_do_not_key(tmp_path):
    base = {"feature_type": "resnet", "model_name": "resnet18",
            "extraction_fps": 4, "batch_size": 16,
            "output_path": "./output", "video_workers": 1,
            "telemetry": False, "cache": True, "cache_dir": None}
    fp = fcache.config_fingerprint(base)
    ops = dict(base, output_path=str(tmp_path), video_workers=8,
               telemetry=True, trace=True, retry_attempts=5,
               cache_dir=str(tmp_path / "c"))
    assert fcache.config_fingerprint(ops) == fp
    # batch_size is scheduling, not semantics (same math, wider groups)
    assert fcache.config_fingerprint(dict(base, batch_size=64)) == fp
    # semantic keys DO key
    assert fcache.config_fingerprint(dict(base, extraction_fps=2)) != fp
    assert fcache.config_fingerprint(
        dict(base, model_name="resnet50")) != fp
    # resolved overlays replace the raw key: auto == its resolution
    assert fcache.config_fingerprint(dict(base, resize="auto"),
                                     {"resize": "device"}) \
        == fcache.config_fingerprint(dict(base, resize="device"),
                                     {"resize": "device"})


def test_weights_fingerprint_sha_sensitive_order_insensitive():
    a = {"model_key": "resnet18", "sha256": "a" * 64}
    b = {"model_key": "vggish", "sha256": "b" * 64}
    fp = fcache.weights_fingerprint([a, b])
    assert fp == fcache.weights_fingerprint([b, a])
    assert fp != fcache.weights_fingerprint(
        [dict(a, sha256="c" * 64), b])
    assert fcache.weights_fingerprint(
        [{"model_key": "resnet18", "random": True}]) != \
        fcache.weights_fingerprint([a])
    assert fcache.weights_fingerprint(None) == "none"


# -- store: roundtrip + verify-before-trust ---------------------------------

@pytest.fixture
def store(tmp_path):
    """A FeatureCache over a content file that needs no video decode:
    key_for only reads bytes on the sha256 fast path."""
    content = tmp_path / "input.mp4"
    content.write_bytes(os.urandom(1 << 14))
    fc = fcache.FeatureCache(str(tmp_path / "cache"), "resnet",
                             "cfg" + "0" * 61, "wts" + "0" * 61)
    return fc, str(content)


def _feats(seed=0):
    rng = np.random.default_rng(seed)
    return {"resnet": rng.standard_normal((7, 512)).astype(np.float32),
            "fps": np.float64(4.0),
            "timestamps_ms": (np.arange(7) * 250.0)}


def test_store_lookup_roundtrip_bit_identical(store):
    fc, video = store
    feats = _feats()
    key = fc.store(video, feats)
    assert os.path.exists(fc.entry_path(key))
    got = fc.lookup(video, expected_keys=list(feats))
    assert got is not None and set(got) == set(feats)
    for k in feats:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(feats[k]), err_msg=k)


def test_lookup_misses_on_absent_and_on_key_mismatch(store):
    fc, video = store
    assert fc.lookup(video) is None  # nothing stored yet
    key = fc.store(video, _feats())
    # an entry whose key set doesn't match the extractor's contract is
    # dropped, not partially served
    assert fc.lookup(video, expected_keys=["resnet", "fps"]) is None
    assert not os.path.exists(fc.entry_path(key))


def test_tenant_scope_never_serves_across_tenants(store, tmp_path):
    """``cache_scope=tenant`` isolation (ISSUE 14): the requesting
    tenant (thread-local request id, gateway-minted) salts the entry
    key, so a hit can only be served to the tenant whose extraction
    stored it — while the default ``shared`` scope keeps cross-tenant
    dedup (one entry for everyone, the dominant win at scale)."""
    from video_features_tpu.telemetry.context import use_request
    _fc, video = store
    feats = _feats()
    scoped = fcache.FeatureCache(str(tmp_path / "cache"), "resnet",
                                 "cfg" + "0" * 61, "wts" + "0" * 61,
                                 scope="tenant")
    with use_request("alpha-r1"):
        key_a = scoped.store(video, feats)
        assert scoped.lookup(video) is not None  # own entry: hit
    with use_request("beta-r2"):
        assert scoped.lookup(video) is None      # another tenant: MISS
        assert scoped.key_for(video) != key_a
    with use_request("alpha-r9"):
        assert scoped.lookup(video) is not None  # same tenant, any rid
    # untenanted work keys under its own sentinel, not alpha's
    assert scoped.lookup(video) is None

    shared = fcache.FeatureCache(str(tmp_path / "cache2"), "resnet",
                                 "cfg" + "0" * 61, "wts" + "0" * 61,
                                 scope="shared")
    with use_request("alpha-r1"):
        shared.store(video, feats)
    with use_request("beta-r2"):
        assert shared.lookup(video) is not None  # dedup across tenants


def test_corrupted_tensor_fails_signature_and_is_dropped(store):
    fc, video = store
    key = fc.store(video, _feats())
    path = fc.entry_path(key)
    with open(path, "rb") as f:
        entry = pickle.load(f)
    # bit rot past the quantization lattice, sigs left stale
    entry["feats"]["resnet"] = entry["feats"]["resnet"] + 0.1
    with open(path, "wb") as f:
        pickle.dump(entry, f)
    assert fc.lookup(video, expected_keys=list(_feats())) is None
    assert not os.path.exists(path)  # dropped, so a recompute repopulates


def test_torn_entry_and_stale_schema_are_misses(store):
    fc, video = store
    key = fc.store(video, _feats())
    path = fc.entry_path(key)
    Path(path).write_bytes(b"\x80\x04 torn pickle")
    assert fc.lookup(video) is None and not os.path.exists(path)
    key = fc.store(video, _feats())
    path = fc.entry_path(key)
    with open(path, "rb") as f:
        entry = pickle.load(f)
    entry["schema"] = "vft.feature_cache/0"
    with open(path, "wb") as f:
        pickle.dump(entry, f)
    assert fc.lookup(video) is None and not os.path.exists(path)


def test_different_content_different_key(store, tmp_path):
    fc, video = store
    other = tmp_path / "other.mp4"
    other.write_bytes(os.urandom(1 << 14))
    assert fc.key_for(video) != fc.key_for(str(other))


# -- extractor wiring -------------------------------------------------------

def _resnet_cfg(sample_video, out, cache_dir, **over):
    from video_features_tpu.config import load_config, sanity_check
    cfg = load_config("resnet", {
        "video_paths": sample_video, "device": "cpu", "batch_size": 8,
        "extraction_total": 6, "model_name": "resnet18",
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "cache": True, "cache_dir": str(cache_dir),
        "output_path": str(out / "out"), "tmp_path": str(out / "tmp"),
        **over,
    })
    sanity_check(cfg)
    return cfg


def test_hit_on_byte_identical_rerun_never_decodes(sample_video, tmp_path):
    from video_features_tpu.extractors.resnet import ExtractResNet
    cache_dir = tmp_path / "cache"
    ex1 = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "a", cache_dir))
    feats = ex1._extract(sample_video)
    assert feats is not None
    # fresh extractor, fresh OUTPUT dir (so the filename skip cannot mask
    # the cache path), same cache root: the hit must serve without ever
    # entering decode/forward
    ex2 = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "b", cache_dir))
    def _boom(_):
        raise AssertionError("cache hit must not decode")
    ex2.extract = _boom
    got = ex2._extract(sample_video)
    assert got is not None
    for k in feats:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(feats[k]), err_msg=k)
    # ... and the hit still materialized the sink artifacts in dir b
    stem = Path(sample_video).stem
    assert list((tmp_path / "b" / "out").rglob(f"{stem}_resnet.npy"))


def test_miss_on_semantic_config_change(sample_video, tmp_path):
    from video_features_tpu.extractors.resnet import ExtractResNet
    cache_dir = tmp_path / "cache"
    ex1 = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "a", cache_dir))
    ex1._extract(sample_video)
    # extraction_total=5 selects different frames: must NOT hit total=6's
    # entry (a false hit here would serve wrong-length features)
    ex2 = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "b", cache_dir,
                                    extraction_total=5))
    calls = []
    real = ex2.extract
    ex2.extract = lambda v: calls.append(v) or real(v)
    assert ex2._extract(sample_video) is not None
    assert calls == [sample_video]  # recomputed, not served


def test_miss_on_weights_change(sample_video, tmp_path):
    from video_features_tpu.extractors.resnet import ExtractResNet
    cache_dir = tmp_path / "cache"
    ex1 = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "a", cache_dir))
    ex1._extract(sample_video)
    fc1 = ex1.feature_cache()
    # the same config over a re-converted / fine-tuned checkpoint: the
    # capture carries a different sha, so the key must change
    ex2 = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "b", cache_dir))
    ex2._weights_capture = [{"model_key": "resnet18",
                             "sha256": "f" * 64}]
    fc2 = ex2.feature_cache()
    assert fc2 is not None and fc2.weights_fp != fc1.weights_fp
    assert fc2.key_for(sample_video) != fc1.key_for(sample_video)
    assert fc2.lookup(sample_video, ex2.output_feat_keys) is None


def test_resize_auto_shares_entries_with_resolved_value(
        sample_video, tmp_path):
    from video_features_tpu.extractors.resnet import ExtractResNet
    cache_dir = tmp_path / "cache"
    auto = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "a",
                                     cache_dir, resize="auto"))
    explicit = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "b",
                                         cache_dir, resize="device"))
    host = ExtractResNet(_resnet_cfg(sample_video, tmp_path / "c",
                                     cache_dir, resize="host"))
    assert auto.resize_mode == "device"  # save sink: auto -> device (PR 6)
    fp_auto = auto.feature_cache().config_fp
    assert fp_auto == explicit.feature_cache().config_fp
    assert fp_auto != host.feature_cache().config_fp
    # equivalence is end-to-end: auto's stored entry SERVES the explicit
    # extractor byte-for-byte
    feats = auto._extract(sample_video)
    explicit.extract = lambda v: (_ for _ in ()).throw(
        AssertionError("resize=device must hit resize=auto's entry"))
    got = explicit._extract(sample_video)
    for k in feats:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(feats[k]), err_msg=k)


# -- two-pass CLI contract (the CI smoke's in-suite twin) -------------------

def test_cli_two_pass_all_hits_bit_identical(sample_video, tmp_path):
    import contextlib
    import io as _io
    import json
    from video_features_tpu.cli import main as cli_main

    vids = []
    for i in range(2):
        dst = tmp_path / f"v{i}.mp4"
        shutil.copy(sample_video, dst)
        vids.append(str(dst))
    base = ["feature_type=resnet", "model_name=resnet18", "device=cpu",
            "allow_random_weights=true", "on_extraction=save_numpy",
            "extraction_total=6", "batch_size=8", "telemetry=true",
            "cache=true", f"cache_dir={tmp_path / 'cache'}",
            f"tmp_path={tmp_path / 'tmp'}",
            "video_paths=[" + ",".join(vids) + "]"]
    with contextlib.redirect_stdout(_io.StringIO()):
        cli_main(base + [f"output_path={tmp_path / 'p1'}"])
        cli_main(base + [f"output_path={tmp_path / 'p2'}"])
    p1 = sorted((tmp_path / "p1").rglob("*.npy"))
    p2 = sorted((tmp_path / "p2").rglob("*.npy"))
    assert [p.name for p in p1] == [p.name for p in p2] and len(p1) == 6
    for a, b in zip(p1, p2):
        assert a.read_bytes() == b.read_bytes(), a.name
    # pass 2's final heartbeat: every lookup hit, nothing recomputed
    hbs = list((tmp_path / "p2").rglob("_heartbeat_*.json"))
    assert hbs, "telemetry=true must leave the heartbeat"
    section = json.loads(hbs[0].read_text())["cache"]
    assert section["hits"] == {"resnet": 2}
    assert section["misses"] in ({}, {"resnet": 0})
    assert section["hit_rate"] == 1.0
