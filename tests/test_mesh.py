"""Mesh / data-parallel runner: param casting, sharded padding. Torch-free —
these must run on environments without torch (the TPU production target)."""
import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.models import r21d as r21d_model
from video_features_tpu.parallel.mesh import (DataParallelApply,
                                              cast_floating, get_mesh)


def test_cast_floating_casts_floats_only():
    tree = {"w": np.ones((2, 2), np.float32), "idx": np.arange(3)}
    out = cast_floating(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    # stays integral (jnp.asarray may narrow int64->int32 under x64-disabled)
    assert jnp.issubdtype(out["idx"].dtype, jnp.integer)


def test_bfloat16_precision_casts_params_and_stays_close():
    """precision=bfloat16 must actually run the net in bf16 (flax promotes a
    bf16 activation against f32 params back to f32, so DataParallelApply casts
    the param tree — parallel/mesh.py cast_floating) while staying close to
    the f32 features."""
    model = r21d_model.R2Plus1D("r2plus1d_18_16_kinetics")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4, 32, 32, 3)))["params"]
    casted = cast_floating(params, jnp.bfloat16)
    assert all(x.dtype == jnp.bfloat16
               for x in jax.tree_util.tree_leaves(casted))

    x = np.random.default_rng(0).uniform(size=(2, 4, 32, 32, 3)) \
        .astype(np.float32)
    mesh = get_mesh(n_devices=1)

    def fwd(dtype):
        def f(p, batch):
            return model.apply({"params": p},
                               batch.astype(dtype)).astype(jnp.float32)
        return f

    f32 = DataParallelApply(fwd(jnp.float32), params, mesh=mesh)(x)
    bf16 = DataParallelApply(fwd(jnp.bfloat16), casted, mesh=mesh)(x)
    cos = np.sum(f32 * bf16, axis=1) / (
        np.linalg.norm(f32, axis=1) * np.linalg.norm(bf16, axis=1) + 1e-9)
    assert np.all(cos > 0.99), f"bf16 features diverged: cos={cos}"


def test_data_parallel_over_eight_virtual_devices():
    """The production sharding: batch split over the full 8-device CPU mesh
    (conftest forces xla_force_host_platform_device_count=8), ragged batch
    padded to mesh-divisible size and trimmed after execution."""
    assert len(jax.devices()) == 8, "conftest must force an 8-device mesh"
    mesh = get_mesh()  # all devices
    runner = DataParallelApply(lambda p, b: b * p["scale"] + 1.0,
                               {"scale": np.float32(2.0)}, mesh=mesh)
    assert runner.n_devices == 8
    x = np.arange(5 * 3, dtype=np.float32).reshape(5, 3)  # ragged: 5 % 8 != 0
    out = runner(x)
    assert out.shape == (5, 3)
    np.testing.assert_allclose(out, x * 2.0 + 1.0)
    # fixed_batch caps a power-of-two bucket ladder: ragged host batches
    # trace at the smallest mesh-divisible bucket that holds them (wire
    # bytes bounded at 2x the rows), full batches at fixed_batch itself;
    # the executable count stays logarithmic. The traced shapes prove it.
    traced_shapes = []

    def fn(p, b):
        traced_shapes.append(b.shape)
        return b * p["scale"]

    runner2 = DataParallelApply(fn, {"scale": np.float32(3.0)}, mesh=mesh,
                                fixed_batch=16)
    np.testing.assert_allclose(runner2(x), x * 3.0)        # 5 -> bucket 8
    full = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    np.testing.assert_allclose(runner2(full), full * 3.0)  # 16 -> 16
    mid = np.arange(9 * 3, dtype=np.float32).reshape(9, 3)
    np.testing.assert_allclose(runner2(mid), mid * 3.0)    # 9 -> 16 (cached)
    assert traced_shapes == [(8, 3), (16, 3)], traced_shapes
    assert runner2.padded_batch_size(5) == 8
    assert runner2.bucket_batch_size(5) == 8
    assert runner2.bucket_batch_size(9) == 16
    assert runner2.bucket_batch_size(16) == 16
    assert runner2.bucket_batch_size(2) == 8  # mesh floor: 8 devices
    assert runner2.bucket_batch_size(20) == 24  # > fixed_batch: pad up
    big = np.arange(20 * 3, dtype=np.float32).reshape(20, 3)
    np.testing.assert_allclose(runner2(big), big * 3.0)


def test_feature_stream_matches_sync_path():
    """FeatureStream (async dispatch, the no-show_pred extract path) must
    return exactly what the per-batch synchronous calls return, in submit
    order, including ragged tails and explicit n_valid."""
    mesh = get_mesh()
    runner = DataParallelApply(lambda p, b: b * p["scale"],
                               {"scale": np.float32(2.0)}, mesh=mesh,
                               fixed_batch=8)
    rng = np.random.default_rng(0)
    batches = [rng.normal(size=(n, 3)).astype(np.float32)
               for n in (8, 8, 5)]  # ragged tail
    stream = runner.stream(depth=2)  # depth < #batches: forces mid-loop pops
    for b in batches:
        stream.submit(b)
    got = stream.finish()
    want = [runner(b) for b in batches]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w)
    # a drained stream is reusable and empty
    assert stream.finish() == []
    stream.submit(batches[0], n_valid=4)
    (g,) = stream.finish()
    np.testing.assert_allclose(g, batches[0][:4] * 2.0)


def test_feature_stream_depth_zero_is_synchronous():
    mesh = get_mesh(n_devices=1)
    runner = DataParallelApply(lambda p, b: b + p["one"],
                               {"one": np.float32(1.0)}, mesh=mesh)
    stream = runner.stream(depth=0)
    x = np.zeros((2, 2), np.float32)
    stream.submit(x)
    assert len(stream._inflight) == 0  # materialized immediately
    np.testing.assert_allclose(stream.finish()[0], x + 1.0)


def test_feature_stream_callback_fires_in_order_with_ctx():
    """The show_pred path: depth=0 + callback must fire per submit, in
    order, with valid rows only and the submit's ctx."""
    mesh = get_mesh(n_devices=1)
    runner = DataParallelApply(lambda p, b: b * 2.0, {}, mesh=mesh)
    seen = []
    stream = runner.stream(depth=0,
                           callback=lambda feats, ctx: seen.append(
                               (feats.shape[0], ctx)))
    stream.submit(np.ones((3, 2), np.float32), ctx="a")
    assert seen == [(3, "a")]  # fired before submit returned (synchronous)
    stream.submit(np.ones((2, 2), np.float32), n_valid=1, ctx="b")
    assert seen == [(3, "a"), (1, "b")]
    assert len(stream.finish()) == 2


def test_dispatch_chain_pads_on_device_and_trims():
    """Chained runners (the i3d flow->i3d handoff): dispatch() keeps padded
    rows — callers must slice back to valid rows — and _pad of a device
    array must stay on device (jnp.pad), not round-trip through np.pad."""
    mesh = get_mesh()  # 8 virtual devices
    r1 = DataParallelApply(lambda p, b: b * 2.0, {}, mesh=mesh,
                           fixed_batch=10)
    x = np.arange(10 * 2, dtype=np.float32).reshape(10, 2)
    dev = r1.dispatch(x)
    assert dev.shape[0] == 16  # 10 padded up to the 8-device multiple
    stacked = jnp.stack([dev[:10], dev[:10]])  # lazy on-device slice+stack
    r2 = DataParallelApply(lambda p, b: b.sum(axis=-1), {}, mesh=mesh,
                           fixed_batch=8)
    padded = r2._pad(stacked)
    assert isinstance(padded, jax.Array), "ragged device batch left the device"
    out = r2(stacked, n_valid=2)
    np.testing.assert_allclose(out, np.tile((x * 2.0).sum(-1), (2, 1)))


def test_tensor_parallel_clip_matches_replicated():
    """model_parallel: Megatron-style param sharding over a 2-D (data, model)
    mesh (param_specs_by_rules + TP_RULES_TRANSFORMER) must (a) actually
    shard the attention/MLP weights over 'model' and (b) produce the same
    features as the replicated single-device run — GSPMD inserts the
    collectives from the param layouts alone."""
    from jax.sharding import PartitionSpec as P
    from video_features_tpu.models import clip as clip_m
    from video_features_tpu.parallel.mesh import (TP_RULES_TRANSFORMER,
                                                  param_specs_by_rules)

    cfg = clip_m._cfg(128, 32, 2, 64, 16, 64, 2)  # tiny ViT, heads=1
    model = clip_m.CLIP(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                        method="encode_image")["params"]
    specs = param_specs_by_rules(params, TP_RULES_TRANSFORMER)
    blk = specs["visual"]["transformer"]["resblocks_0"]
    assert blk["attn"]["q_proj"]["kernel"] == P(None, "model")
    assert blk["attn"]["q_proj"]["bias"] == P("model")
    assert blk["attn"]["out_proj"]["kernel"] == P("model", None)
    assert blk["attn"]["out_proj"]["bias"] == P()
    assert blk["mlp_c_fc"]["kernel"] == P(None, "model")
    assert blk["mlp_c_proj"]["kernel"] == P("model", None)
    assert specs["visual"]["conv1"]["kernel"] == P()  # unmatched: replicated

    def fwd(p, x):
        return model.apply({"params": p}, x.astype(jnp.float32),
                           method="encode_image")

    x = np.random.default_rng(0).normal(size=(4, 32, 32, 3)) \
        .astype(np.float32)
    ref = DataParallelApply(fwd, params, mesh=get_mesh(n_devices=1))(x)

    mesh = get_mesh(axis_names=("data", "model"), shape=(4, 2))
    tp = DataParallelApply(fwd, params, mesh=mesh, param_specs=specs)
    # the qkv kernel must really be split over the model axis
    qk = tp.params["visual"]["transformer"]["resblocks_0"]["attn"]["q_proj"]["kernel"]
    shard_shapes = {s.data.shape for s in qk.addressable_shards}
    assert shard_shapes == {(64, 32)}, shard_shapes  # (D, D/2) per device
    np.testing.assert_allclose(tp(x), ref, rtol=2e-5, atol=2e-5)


def test_feature_stream_submit_device_runnerless():
    """submit_device: a runner-less stream accepts already-dispatched device
    arrays (i3d's per-stream queues), bounds retained results, and
    materializes in order with valid-row trimming."""
    from video_features_tpu.parallel.mesh import FeatureStream
    mesh = get_mesh(n_devices=1)
    runner = DataParallelApply(lambda p, b: b * 3.0, {}, mesh=mesh)
    stream = FeatureStream(None, depth=2)
    batches = [np.full((4, 2), i, np.float32) for i in range(5)]
    for i, b in enumerate(batches):
        stream.submit_device(runner.dispatch(b), n_valid=3)
        assert len(stream._inflight) <= 2
    got = stream.finish()
    assert [g.shape for g in got] == [(3, 2)] * 5
    for i, g in enumerate(got):  # submit order preserved
        np.testing.assert_allclose(g, batches[i][:3] * 3.0)
