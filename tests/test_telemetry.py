"""Telemetry subsystem: registry, spans + schema, JSONL crash semantics,
heartbeats, manifest, report tool, and the disabled-path zero-footprint
contract (ISSUE 2 acceptance criteria)."""
import json
import os
import pickle
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu import telemetry
from video_features_tpu.telemetry import jsonl as tjsonl
from video_features_tpu.telemetry import schema as tschema
from video_features_tpu.telemetry import spans as tspans
from video_features_tpu.telemetry.heartbeat import (HeartbeatThread,
                                                    heartbeat_filename)
from video_features_tpu.telemetry.metrics import (MetricsRegistry,
                                                  prometheus_text)
from video_features_tpu.telemetry.recorder import TelemetryRecorder
from video_features_tpu.utils.profiling import StageProfiler, profiler

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parent.parent


# -- metrics registry -------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(2.5)
    assert g.value == 2.5
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 3
    assert snap["sum"] == pytest.approx(5.55)
    assert [b["count"] for b in snap["buckets"]] == [1, 1]
    assert snap["inf_count"] == 1


def test_registry_labels_are_distinct_series_and_kinds_collide():
    reg = MetricsRegistry()
    reg.counter("f_total", category="POISON").inc()
    reg.counter("f_total", category="FATAL").inc(2)
    assert reg.counter("f_total", category="POISON").value == 1
    assert reg.counter("f_total", category="FATAL").value == 2
    with pytest.raises(ValueError):
        reg.gauge("f_total")  # same name, different kind
    dump = reg.to_dict()
    assert len(dump["series"]) == 2


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(500):
            reg.counter("n_total").inc()
            reg.histogram("lat", buckets=(1.0,)).observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert reg.counter("n_total").value == 2000
    assert reg.histogram("lat", buckets=(1.0,)).count == 2000


def test_prometheus_text_roundtrips_through_json():
    reg = MetricsRegistry()
    reg.counter("vft_failures_total", category="POISON").inc(3)
    reg.histogram("vft_stage_seconds", buckets=(0.1, 1.0),
                  stage="decode").observe(0.5)
    dump = json.loads(json.dumps(reg.to_dict()))  # as read from _run.json
    text = prometheus_text(dump)
    assert 'vft_failures_total{category="POISON"} 3.0' in text
    assert 'vft_stage_seconds_bucket{le="+Inf",stage="decode"} 1' in text
    assert 'vft_stage_seconds_count{stage="decode"} 1' in text
    assert "# TYPE vft_stage_seconds histogram" in text


# -- histogram_quantile edges (ISSUE 13 satellite: burn-rate math must
# not return misleading values on sparse windows) ---------------------------

def test_histogram_quantile_empty_snapshot_is_none():
    from video_features_tpu.telemetry.metrics import (Histogram,
                                                      histogram_quantile,
                                                      histogram_quantiles)
    h = Histogram("h", (), buckets=(0.1, 1.0))
    snap = h.snapshot()
    for q in (0.0, 0.5, 0.95, 1.0):
        assert histogram_quantile(snap, q) is None
    assert histogram_quantiles(snap) == {"p50": None, "p95": None,
                                         "p99": None}
    # a count without buckets (torn/foreign snapshot) is also None,
    # never a crash or a fabricated latency
    assert histogram_quantile({"count": 5, "buckets": []}, 0.5) is None
    assert histogram_quantile({}, 0.5) is None


def test_histogram_quantile_single_bucket_interpolates():
    from video_features_tpu.telemetry.metrics import (Histogram,
                                                      histogram_quantile)
    h = Histogram("h", (), buckets=(1.0,))
    for _ in range(4):
        h.observe(0.5)
    snap = h.snapshot()
    # rank interpolates linearly inside the lone [0, 1.0] bucket
    assert histogram_quantile(snap, 0.5) == pytest.approx(0.5)
    assert histogram_quantile(snap, 1.0) == pytest.approx(1.0)
    # q is clamped into [0, 1], and q=0 anchors at the bucket floor
    assert histogram_quantile(snap, -3.0) == pytest.approx(0.0)
    assert histogram_quantile(snap, 7.0) == pytest.approx(1.0)


def test_histogram_quantile_past_last_bucket_clamps():
    from video_features_tpu.telemetry.metrics import (Histogram,
                                                      histogram_quantile)
    h = Histogram("h", (), buckets=(0.1, 1.0))
    # every observation lands in the implicit +Inf bucket
    for _ in range(5):
        h.observe(50.0)
    snap = h.snapshot()
    assert snap["inf_count"] == 5 and snap["count"] == 5
    # the estimate clamps to the largest finite bound — a conservative
    # floor, never a fabricated tail
    assert histogram_quantile(snap, 0.99) == pytest.approx(1.0)
    # mixed: the quantile past the finite mass also clamps
    h2 = Histogram("h2", (), buckets=(0.1, 1.0))
    h2.observe(0.05)
    h2.observe(0.05)
    h2.observe(50.0)
    h2.observe(50.0)
    snap2 = h2.snapshot()
    assert histogram_quantile(snap2, 0.99) == pytest.approx(1.0)
    assert histogram_quantile(snap2, 0.25) == pytest.approx(0.05)


# -- StageProfiler drain (satellite: snapshot/reset race) -------------------

def test_drain_returns_and_clears_atomically():
    p = StageProfiler()
    p.add("decode", 1.0)
    p.add("decode", 0.5, n=2)
    out = p.drain()
    assert out == {"decode": (1.5, 3)}
    assert p.snapshot() == {}
    assert p.drain() == {}


def test_drain_loses_no_updates_under_concurrency():
    p = StageProfiler()
    N, WORKERS = 2000, 4
    drained = []
    stop = threading.Event()

    def flusher():
        while not stop.is_set():
            drained.append(p.drain())
        drained.append(p.drain())

    def producer():
        for _ in range(N):
            p.add("s", 1.0)

    f = threading.Thread(target=flusher)
    producers = [threading.Thread(target=producer) for _ in range(WORKERS)]
    f.start()
    [t.start() for t in producers]
    [t.join() for t in producers]
    stop.set()
    f.join()
    total = sum(d.get("s", (0, 0))[1] for d in drained)
    total += p.snapshot().get("s", (0, 0))[1]
    assert total == N * WORKERS  # snapshot()+reset() could drop some


def test_stage_hook_times_even_when_profiler_disabled():
    p = StageProfiler()
    seen = []
    p.set_hook(lambda name, dt: seen.append((name, dt)))
    assert not p.enabled
    with p.stage("decode"):
        pass
    assert len(seen) == 1 and seen[0][0] == "decode"
    assert p.snapshot() == {}  # aggregate printing stays off
    p.set_hook(None)
    with p.stage("decode"):
        pass
    assert len(seen) == 1


# -- span records vs the checked-in schema ----------------------------------

def test_span_record_validates_against_schema():
    with tspans.VideoSpan("/v/x.mp4", feature_type="i3d",
                          host_id="p0-h") as span:
        span.annotate(status="done", attempts=2, video_fps=25.0,
                      video_frames=100, decode_mode="parallel")
        span.event("ladder", to="process")
        span.observe_stage("decode", 0.25)
        span.observe_stage("decode", 0.25)
        span.observe_stage("forward", 1.0)
    rec = span.record
    assert sorted(rec) == sorted(tspans.SPAN_FIELDS)
    assert tschema.validate_span(rec) == []
    assert rec["stages"]["decode"] == {"s": 0.5, "calls": 2}
    assert rec["ladder_steps"] == ["process"]
    assert json.loads(json.dumps(rec)) == rec  # JSONL-safe


def test_span_unannotated_status_and_schema_rejections():
    with tspans.VideoSpan("v.mp4") as span:
        pass  # an exception path that never annotated
    assert span.record["status"] == "error"
    assert tschema.validate_span(span.record) == []
    bad = dict(span.record)
    bad["extra_key"] = 1
    assert any("extra_key" in e for e in tschema.validate_span(bad))
    bad2 = dict(span.record)
    bad2["status"] = "exploded"
    assert tschema.validate_span(bad2)
    bad3 = dict(span.record)
    del bad3["wall_s"]
    assert any("wall_s" in e for e in tschema.validate_span(bad3))


def test_schema_checker_script_passes():
    p = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" /
                             "check_telemetry_schema.py")],
        capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr


def test_span_thread_propagation_via_use_span():
    results = []
    with tspans.VideoSpan("v.mp4") as span:
        captured = telemetry.current_span()

        def producer():
            # no span on a fresh thread...
            results.append(telemetry.current_span())
            with tspans.use_span(captured):  # ...until re-installed
                results.append(telemetry.current_span())

        t = threading.Thread(target=producer)
        t.start()
        t.join()
        span.annotate(status="done")
    assert results == [None, span]


# -- JSONL crash semantics --------------------------------------------------

def test_jsonl_torn_tail_healing_on_append_and_read(tmp_path):
    path = tmp_path / "t.jsonl"
    tjsonl.append_jsonl(path, {"i": 1})
    # a worker SIGKILLed mid-write leaves a torn, newline-less tail
    with open(path, "ab") as f:
        f.write(b'{"i": 2, "torn')
    tjsonl.append_jsonl(path, {"i": 3})
    recs = list(tjsonl.read_jsonl(path))
    assert [r["i"] for r in recs] == [1, 3]  # torn record skipped, not fatal
    assert list(tjsonl.read_jsonl(tmp_path / "absent.jsonl")) == []


def test_write_json_atomic_leaves_no_partials(tmp_path):
    path = tmp_path / "hb.json"
    tjsonl.write_json_atomic(path, {"a": 1})
    assert json.load(open(path)) == {"a": 1}
    tjsonl.write_json_atomic(path, {"a": 2})
    assert json.load(open(path)) == {"a": 2}
    assert [p.name for p in tmp_path.iterdir()] == ["hb.json"]  # no temps


# -- atomic pickle sink (satellite: write_pickle parity with write_numpy) ---

def test_write_pickle_atomic_success_and_failure(tmp_path):
    from video_features_tpu.utils import sinks
    fpath = str(tmp_path / "v_feat.pkl")
    sinks.write_pickle(fpath, {"x": np.arange(3)})
    np.testing.assert_array_equal(sinks.load_pickle(fpath)["x"],
                                  np.arange(3))

    class Unpicklable:
        def __reduce__(self):
            raise RuntimeError("preempted mid-dump")

    with pytest.raises(RuntimeError):
        sinks.write_pickle(fpath, Unpicklable())
    # the failed write neither tore the existing file nor left temp junk
    np.testing.assert_array_equal(sinks.load_pickle(fpath)["x"],
                                  np.arange(3))
    assert [p.name for p in tmp_path.iterdir()] == ["v_feat.pkl"]


# -- recorder end-to-end ----------------------------------------------------

def test_recorder_files_heartbeat_and_manifest(tmp_path):
    out = str(tmp_path / "out")
    rec = TelemetryRecorder(out, run_config={"feature_type": "resnet"},
                            feature_type="resnet", interval_s=60.0,
                            host_id="p0-test").start()
    try:
        assert telemetry.active() is rec
        with rec.video_span("/v/a.mp4") as s:
            with profiler.stage("decode"):  # flows through the hook
                time.sleep(0.002)
            s.annotate(status="done")
        with rec.video_span("/v/b.mp4") as s:
            s.annotate(status="error", category="POISON",
                       error="ValueError: bad", attempts=3)
            s.event("attempt_failed", attempt=1, category="POISON")
        telemetry.inc("vft_video_retries_total", 2)
    finally:
        rec.close(tally={"done": 1, "error": 1}, wall_s=2.0,
                  failure_tallies={"POISON": 1})
    assert telemetry.active() is None
    assert profiler._hook is None

    spans = list(tjsonl.read_jsonl(os.path.join(out, "_telemetry.jsonl")))
    assert len(spans) == 2
    for r in spans:
        assert tschema.validate_span(r) == []
    assert spans[0]["stages"]["decode"]["calls"] == 1  # hook attribution

    hb = json.load(open(os.path.join(out, heartbeat_filename("p0-test"))))
    assert hb["final"] is True
    assert hb["videos_done"] == 2
    assert hb["last_video"] == "/v/b.mp4"
    assert hb["host_id"] == "p0-test"

    man = json.load(open(os.path.join(out, "_run.json")))
    assert man["schema"] == "vft.run_manifest/1"
    assert man["tally"] == {"done": 1, "error": 1}
    assert man["failure_tallies"] == {"POISON": 1}
    assert man["stage_totals"]["decode"]["calls"] == 1
    assert "jax" in man["versions"]
    assert "platform" in man["topology"]
    assert {"hits", "misses"} <= set(man["compile_cache"])
    names = {s["name"] for s in man["metrics"]["series"]}
    assert "vft_videos_total" in names
    assert "vft_video_retries_total" in names

    # the report tool renders a finished run from artifacts alone
    prom = str(tmp_path / "vft.prom")
    p = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "telemetry_report.py"),
         out, "--prom", prom], capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "FINISHED" in p.stdout
    assert "/v/b.mp4" in p.stdout
    assert "vft_videos_total" in open(prom).read()


def test_heartbeat_thread_ticks_and_stops_fast():
    ticks = []
    hb = HeartbeatThread(lambda: ticks.append(1), interval_s=0.02)
    hb.start()
    deadline = time.monotonic() + 5.0
    while len(ticks) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    t0 = time.monotonic()
    hb.stop()
    assert time.monotonic() - t0 < 1.0  # stop() interrupts the wait
    assert len(ticks) >= 2
    with pytest.raises(ValueError):
        HeartbeatThread(lambda: None, interval_s=0)


# -- disabled path: zero records, zero files, no-op helpers -----------------

def test_disabled_path_writes_nothing(tmp_path):
    assert telemetry.active() is None
    telemetry.inc("vft_anything_total")  # all helpers no-op without a run
    telemetry.annotate(status="done")
    telemetry.event("retry")
    with telemetry.NOOP_SPAN as s:
        s.annotate(status="done")
        s.event("x")
        s.observe_stage("decode", 1.0)
        assert telemetry.current_span() is None  # never installed
    with profiler.stage("decode"):
        pass  # hookless + disabled: the no-op fast path
    assert profiler.snapshot() == {}
    assert list(tmp_path.iterdir()) == []


def test_cli_telemetry_end_to_end(tmp_path, sample_video):
    from video_features_tpu import cli
    out = tmp_path / "out"
    cli.main([
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "batch_size=8", "extraction_fps=1", "allow_random_weights=true",
        "on_extraction=save_numpy", f"output_path={out}",
        f"tmp_path={tmp_path}/tmp", f"video_paths={sample_video}",
        "telemetry=true", "metrics_interval_s=60",
    ])
    run_dir = out / "resnet" / "resnet18"
    spans = list(tjsonl.read_jsonl(run_dir / "_telemetry.jsonl"))
    assert len(spans) == 1
    assert spans[0]["status"] == "done"
    assert tschema.validate_span(spans[0]) == []
    assert "decode" in spans[0]["stages"]  # per-video stage attribution
    assert "forward" in spans[0]["stages"]
    assert spans[0]["video_frames"] is not None  # extractors/base.py hook
    man = json.load(open(run_dir / "_run.json"))
    assert man["tally"]["done"] == 1
    hbs = list(run_dir.glob("_heartbeat_*.json"))
    assert len(hbs) == 1
    assert json.load(open(hbs[0]))["final"] is True
    p = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "telemetry_report.py"),
         str(run_dir)], capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr

    # second run, telemetry off (the default): no telemetry files appear
    out2 = tmp_path / "out2"
    cli.main([
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "batch_size=8", "extraction_fps=1", "allow_random_weights=true",
        "on_extraction=save_numpy", f"output_path={out2}",
        f"tmp_path={tmp_path}/tmp2", f"video_paths={sample_video}",
    ])
    run_dir2 = out2 / "resnet" / "resnet18"
    assert sorted(p.name for p in run_dir2.iterdir()) == sorted(
        p.name for p in run_dir.iterdir()
        if not (p.name.startswith("_heartbeat") or
                p.name in ("_run.json", "_telemetry.jsonl")))
