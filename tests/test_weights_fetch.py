"""Opt-in weight fetch: VFT_FETCH_WEIGHTS gating, SHA-256 refusal, and the
VFT_REQUIRE_VALUE_TIER golden contract.

The reference auto-downloads with digest verification (its CLIP loader
refuses a mismatched SHA-256, reference models/clip/clip_src/clip.py:61-73);
this suite pins the same refusal semantics onto ``store.fetch_checkpoint``
without any network: ``urllib.request.urlopen`` is monkeypatched to serve
canned bytes.
"""
import hashlib
import io
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.quick

from video_features_tpu.weights import store

REPO = str(Path(__file__).resolve().parent.parent)

PAYLOAD = b"synthetic checkpoint bytes" * 64
PAYLOAD_SHA = hashlib.sha256(PAYLOAD).hexdigest()


class _FakeResponse(io.BytesIO):
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@pytest.fixture
def fake_upstream(tmp_path, monkeypatch):
    """A synthetic model key served from a patched urlopen, weights_dir
    redirected to tmp_path. Returns the key."""
    key = "fake_model"
    monkeypatch.setitem(store.HUB_FILENAMES, key, ("fake-model.pt",))
    monkeypatch.setitem(store.WEIGHT_URLS, "fake-model.pt",
                        "https://example.invalid/fake-model.pt")
    monkeypatch.setitem(store.CLIP_SHA256, "fake-model.pt", PAYLOAD_SHA)
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path))

    calls = []

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return _FakeResponse(PAYLOAD)

    import urllib.request
    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    return key, calls


def test_no_fetch_without_flag(fake_upstream, monkeypatch):
    key, calls = fake_upstream
    monkeypatch.delenv("VFT_FETCH_WEIGHTS", raising=False)
    assert store.find_checkpoint(key) is None
    assert calls == [], "fetch ran without VFT_FETCH_WEIGHTS=1"


def test_fetch_verifies_and_caches(fake_upstream, monkeypatch, tmp_path):
    key, calls = fake_upstream
    monkeypatch.setenv("VFT_FETCH_WEIGHTS", "1")
    p = store.find_checkpoint(key)
    assert p is not None and p.read_bytes() == PAYLOAD
    assert len(calls) == 1
    assert not list(tmp_path.glob("*.part")), "temp file left behind"
    # second resolve hits the cached file, no second download
    assert store.find_checkpoint(key) == p
    assert len(calls) == 1


def test_fetch_refuses_digest_mismatch(fake_upstream, monkeypatch, tmp_path):
    key, _ = fake_upstream
    monkeypatch.setitem(store.CLIP_SHA256, "fake-model.pt", "0" * 64)
    monkeypatch.setenv("VFT_FETCH_WEIGHTS", "1")
    with pytest.raises(RuntimeError, match="does not match the published"):
        store.find_checkpoint(key)
    assert not list(tmp_path.iterdir()), (
        "a digest-mismatched download must not leave any file behind")


def test_fetch_prefix_digest(fake_upstream, monkeypatch, tmp_path):
    """torch-hub style name-<8hex>.pth filenames verify against the
    embedded prefix."""
    key, _ = fake_upstream
    fname = f"fake-{PAYLOAD_SHA[:8]}.pth"
    monkeypatch.setitem(store.HUB_FILENAMES, key, (fname,))
    monkeypatch.setitem(store.WEIGHT_URLS, fname,
                        "https://example.invalid/" + fname)
    monkeypatch.setenv("VFT_FETCH_WEIGHTS", "1")
    p = store.find_checkpoint(key)
    assert p is not None and p.name == fname


def test_expected_digest_kinds():
    assert store.expected_digest("ViT-B-32.pt")[0] == "sha256"
    assert store.expected_digest("resnet18-f37072fd.pth") == (
        "sha256-prefix", "f37072fd")
    assert store.expected_digest("raft-sintel.pth") == (None, None)
    assert store.expected_digest("i3d_rgb.pt") == (None, None)


def test_every_hub_filename_has_a_url_or_is_alt():
    """Each model key's PRIMARY upstream filename carries a URL (the
    downloader tries filenames in order); alternates may be cache-only."""
    for key, fnames in store.HUB_FILENAMES.items():
        assert any(f in store.WEIGHT_URLS for f in fnames), (
            f"{key}: no downloadable source filename")


def test_require_value_tier_fails_loudly_without_weights(tmp_path):
    """VFT_REQUIRE_VALUE_TIER=resnet makes the golden resnet variant FAIL
    (not silently shape-tier) when no checkpoints resolve."""
    import os
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               VFT_WEIGHTS_DIR=str(tmp_path),  # guaranteed empty
               VFT_REQUIRE_VALUE_TIER="resnet")
    env.pop("TORCH_HOME", None)
    env["TORCH_HOME"] = str(tmp_path / "th")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_golden.py",
         "-q", "-k", "resnet", "--no-header", "-x"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    joined = proc.stdout + proc.stderr
    if "no committed golden refs" in joined or "sample video absent" in joined:
        pytest.skip("golden refs not mounted")
    if "deselected" in joined and " 0 selected" not in joined \
            and "passed" not in joined and "failed" not in joined:
        # hosts without the reference mount collect no golden resnet
        # cases at all (the parametrization comes from the mounted refs),
        # so the inner run deselects everything before the gate can fire
        pytest.skip("golden refs not mounted: no resnet golden cases "
                    "collected on this host (inner run deselected all)")
    assert proc.returncode != 0, (
        "required family silently downgraded to shape tier:\n" + joined)
    assert "silently downgraded" in joined


def test_ref_blob_refuses_mutable_master(tmp_path, monkeypatch):
    """ADVICE low: pickled checkpoints served from the reference repo's
    git tree must not download from the mutable 'master' ref — require
    an immutable VFT_REF_COMMIT pin or an explicit opt-in."""
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path))
    monkeypatch.setenv("VFT_FETCH_WEIGHTS", "1")
    monkeypatch.delenv("VFT_ALLOW_MUTABLE_REF", raising=False)
    calls = []

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return _FakeResponse(PAYLOAD)

    import urllib.request
    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    with pytest.raises(RuntimeError, match="VFT_REF_COMMIT"):
        store.find_checkpoint("raft_sintel")
    assert calls == [], "refusal must happen BEFORE any network touch"
    assert not list(tmp_path.iterdir())


def test_ref_blob_records_digest_then_verifies(tmp_path, monkeypatch):
    """Trust-on-first-use for the no-published-digest blobs: the first
    (explicitly opted-in) fetch records the SHA-256 into
    ref_digests.json; a later fetch of different bytes is refused."""
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path))
    monkeypatch.setenv("VFT_FETCH_WEIGHTS", "1")
    monkeypatch.setenv("VFT_ALLOW_MUTABLE_REF", "1")
    payload = [PAYLOAD]
    import urllib.request
    monkeypatch.setattr(urllib.request, "urlopen",
                        lambda url, timeout=None: _FakeResponse(payload[0]))

    p = store.find_checkpoint("raft_sintel")
    assert p is not None and p.read_bytes() == PAYLOAD
    assert store.recorded_digest("raft-sintel.pth") == PAYLOAD_SHA

    # swapped upstream bytes on a re-fetch: recorded digest refuses
    p.unlink()
    payload[0] = b"tampered bytes" * 64
    with pytest.raises(RuntimeError, match="recorded digest"):
        store.find_checkpoint("raft_sintel")
    assert not (tmp_path / "raft-sintel.pth").exists()

    # same bytes again: verifies cleanly against the record
    payload[0] = PAYLOAD
    assert store.find_checkpoint("raft_sintel") is not None
