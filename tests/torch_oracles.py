"""Minimal torch reference implementations used only as numerical oracles.

torchvision is not installed in this image and the reference repo does not
contain these architectures in-tree either (it pulls them from
torchvision/torch.hub at runtime — reference models/resnet/extract_resnet.py:46-51,
models/r21d/extract_r21d.py:105-113). These oracles replicate the standard
architectures with state_dict keys identical to the torchvision originals, so
the production torch->Flax converters can be exercised end-to-end and our Flax
forward passes can be compared numerically against torch semantics (conv
padding, BN eval mode, pooling) on random weights.

Test-only code: never imported by the framework.
"""
import torch
import torch.nn as nn

_RESNET_SPECS = {
    "resnet18": ("basic", (2, 2, 2, 2)),
    "resnet34": ("basic", (3, 4, 6, 3)),
    "resnet50": ("bottleneck", (3, 4, 6, 3)),
    "resnet101": ("bottleneck", (3, 4, 23, 3)),
    "resnet152": ("bottleneck", (3, 8, 36, 3)),
}


class _BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class _Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class TorchResNet(nn.Module):
    """torchvision-v1.5-compatible ResNet returning pooled features
    (fc kept as an attribute, applied separately like the reference's
    class_head split at extract_resnet.py:54-56)."""

    def __init__(self, variant="resnet50", num_classes=1000):
        super().__init__()
        kind, layers = _RESNET_SPECS[variant]
        block = _BasicBlock if kind == "basic" else _Bottleneck
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * block.expansion, 1, stride,
                          bias=False),
                nn.BatchNorm2d(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return torch.flatten(self.avgpool(x), 1)


# ---------------------------------------------------------------------------
# R(2+1)D (torchvision VideoResNet layout; state_dict keys identical to
# torchvision's r2plus1d_18 / IG-65M's r2plus1d_34)
# ---------------------------------------------------------------------------

class _Conv2Plus1D(nn.Sequential):
    def __init__(self, in_planes, out_planes, midplanes, stride=1):
        super().__init__(
            nn.Conv3d(in_planes, midplanes, (1, 3, 3), (1, stride, stride),
                      (0, 1, 1), bias=False),
            nn.BatchNorm3d(midplanes),
            nn.ReLU(inplace=True),
            nn.Conv3d(midplanes, out_planes, (3, 1, 1), (stride, 1, 1),
                      (1, 0, 0), bias=False),
        )


class _VideoBasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        mid = (inplanes * planes * 3 * 3 * 3) // (inplanes * 3 * 3 + 3 * planes)
        self.conv1 = nn.Sequential(
            _Conv2Plus1D(inplanes, planes, mid, stride),
            nn.BatchNorm3d(planes), nn.ReLU(inplace=True))
        mid2 = (planes * planes * 3 * 3 * 3) // (planes * 3 * 3 + 3 * planes)
        self.conv2 = nn.Sequential(
            _Conv2Plus1D(planes, planes, mid2), nn.BatchNorm3d(planes))
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.conv2(self.conv1(x))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class TorchR2Plus1D(nn.Module):
    """VideoResNet with R2Plus1dStem, returning pooled 512-d features."""

    def __init__(self, layers=(2, 2, 2, 2), num_classes=400):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv3d(3, 45, (1, 7, 7), (1, 2, 2), (0, 3, 3), bias=False),
            nn.BatchNorm3d(45), nn.ReLU(inplace=True),
            nn.Conv3d(45, 64, (3, 1, 1), (1, 1, 1), (1, 0, 0), bias=False),
            nn.BatchNorm3d(64), nn.ReLU(inplace=True))
        self.inplanes = 64
        self.layer1 = self._make_layer(64, layers[0], 1)
        self.layer2 = self._make_layer(128, layers[1], 2)
        self.layer3 = self._make_layer(256, layers[2], 2)
        self.layer4 = self._make_layer(512, layers[3], 2)
        self.avgpool = nn.AdaptiveAvgPool3d(1)
        self.fc = nn.Linear(512, num_classes)

    def _make_layer(self, planes, blocks, stride):
        downsample = None
        if stride != 1 or self.inplanes != planes:
            downsample = nn.Sequential(
                nn.Conv3d(self.inplanes, planes, 1, (stride, stride, stride),
                          bias=False),
                nn.BatchNorm3d(planes))
        layers = [_VideoBasicBlock(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes
        for _ in range(1, blocks):
            layers.append(_VideoBasicBlock(planes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.stem(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return torch.flatten(self.avgpool(x), 1)


def randomize_bn_stats(model, seed=0):
    """Give every BN layer non-trivial running stats so converter bugs show."""
    g = torch.Generator().manual_seed(seed)
    for m in model.modules():
        if isinstance(m, (nn.BatchNorm1d, nn.BatchNorm2d, nn.BatchNorm3d)):
            m.running_mean.copy_(torch.rand(m.running_mean.shape, generator=g) - 0.5)
            m.running_var.copy_(torch.rand(m.running_var.shape, generator=g) + 0.5)


# ---------------------------------------------------------------------------
# VGGish (harritaylor/torchvggish layout; state_dict keys features.N /
# embeddings.N, identical to the reference's vggish_slim.py VGG)
# ---------------------------------------------------------------------------

class TorchVGGish(nn.Module):
    def __init__(self):
        super().__init__()
        layers, in_ch = [], 1
        for v in [64, "M", 128, "M", 256, 256, "M", 512, 512, "M"]:
            if v == "M":
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers += [nn.Conv2d(in_ch, v, 3, padding=1),
                           nn.ReLU(inplace=True)]
                in_ch = v
        self.features = nn.Sequential(*layers)
        self.embeddings = nn.Sequential(
            nn.Linear(512 * 4 * 6, 4096), nn.ReLU(True),
            nn.Linear(4096, 4096), nn.ReLU(True),
            nn.Linear(4096, 128), nn.ReLU(True))

    def forward(self, x):
        x = self.features(x)
        x = torch.transpose(x, 1, 3)
        x = torch.transpose(x, 1, 2)
        return self.embeddings(x.contiguous().view(x.size(0), -1))
