"""vft-lint: per-rule fire/clean fixtures + engine contracts.

Every rule gets two proofs on a synthetic mini-repo: it FIRES on a
seeded violation and stays QUIET once the violation is fixed the way
the finding message says to fix it. Engine contracts (suppressions,
the unreasoned-suppression meta-warning, baseline grandfathering,
the --json schema) are pinned separately, and the final test pins the
real tree: the landed repository lints clean above the committed
baseline — the acceptance criterion of the pass itself.
"""
from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from video_features_tpu.lint import engine
from video_features_tpu.lint.engine import run_lint

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parents[1]


# -- fixture mini-repo -------------------------------------------------------

def _w(root: Path, rel: str, text: str) -> None:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))


def _wj(root: Path, rel: str, doc) -> None:
    _w(root, rel, json.dumps(doc, indent=1))


def make_repo(tmp_path: Path) -> Path:
    """A minimal, fully-consistent repo the linter passes clean. Tests
    seed one violation each by rewriting a single file."""
    root = tmp_path / "repo"
    pkg = "video_features_tpu"

    _w(root, f"{pkg}/__init__.py", "")
    _w(root, f"{pkg}/utils/__init__.py", "")
    _w(root, f"{pkg}/telemetry/__init__.py", "")
    _w(root, f"{pkg}/parallel/__init__.py", "")

    for fam, extra in (("a", "alpha: 1\n"), ("b", "")):
        _w(root, f"{pkg}/configs/{fam}.yml", f"""\
            feature_type: '{fam}'
            device: 'cpu'
            cache: false
            output_path: './out'
            tmp_path: './tmp'
            {extra}""")

    _w(root, f"{pkg}/config.py", """\
        OPTIONAL_KEYS = frozenset({"alpha"})
        LAUNCH_KEYS = frozenset({"spool_dir"})
        REMOVED_KEYS = frozenset({"device_ids"})


        def sanity_check(args):
            if "device_ids" in args:
                del args["device_ids"]
            assert args.feature_type
            args.get("device")
            args.get("alpha")
            args.get("cache")
            args.get("output_path")
            args.get("tmp_path")
        """)

    _w(root, f"{pkg}/cache.py", """\
        NON_SEMANTIC_KEYS = frozenset({
            "output_path", "tmp_path", "cache", "spool_dir",
        })
        SEMANTIC_KEYS = frozenset({
            "feature_type", "device", "alpha",
        })
        """)

    _w(root, f"{pkg}/utils/inject.py", """\
        SITES = (
            "sink.write",
        )


        def fire(site, **info):
            return None
        """)

    _w(root, f"{pkg}/utils/sinks.py", """\
        from . import inject
        from ..telemetry import telemetry


        def _write_bytes_atomic(fpath, data):
            inject.fire("sink.write", path=str(fpath))
            telemetry.inc("vft_writes_total")
        """)

    _w(root, f"{pkg}/telemetry/telemetry.py", """\
        def inc(name, n=1, **labels):
            pass
        """)

    _w(root, f"{pkg}/telemetry/names.py", """\
        METRICS = {
            "vft_writes_total": "counter",
        }
        """)

    _w(root, "docs/chaos.md", """\
        # chaos

        | Site | Hook |
        |---|---|
        | `sink.write` | sinks |
        """)

    # schema-lockstep contract modules + JSONs (all the checked pairs)
    _w(root, f"{pkg}/telemetry/spans.py", """\
        SCHEMA_VERSION = "vft.video_span/1"
        STATUSES = ("done", "error")
        SPAN_FIELDS = ("schema", "status", "video")
        """)
    _wj(root, f"{pkg}/telemetry/video_span.schema.json", {
        "properties": {"schema": {"enum": ["vft.video_span/1"]},
                       "status": {"enum": ["done", "error"]},
                       "video": {"type": "string"}},
        "required": ["schema", "video"],
        "additionalProperties": False})

    _w(root, f"{pkg}/telemetry/health.py", """\
        SCHEMA_VERSION = "vft.feature_health/1"
        HEALTH_FIELDS = ("schema", "video")
        """)
    _wj(root, f"{pkg}/telemetry/feature_health.schema.json", {
        "properties": {"schema": {"enum": ["vft.feature_health/1"]},
                       "video": {"type": "string"}},
        "required": ["schema"], "additionalProperties": False})

    _w(root, f"{pkg}/telemetry/alerts.py", """\
        SCHEMA_VERSION = "vft.alert/1"
        STATES = ("pending", "firing", "resolved")
        SEVERITIES = ("page", "ticket")
        ALERT_FIELDS = ("schema", "state", "severity")
        """)
    _wj(root, f"{pkg}/telemetry/alert.schema.json", {
        "properties": {"schema": {"enum": ["vft.alert/1"]},
                       "state": {"enum": ["pending", "firing",
                                          "resolved"]},
                       "severity": {"enum": ["page", "ticket"]}},
        "required": ["schema"], "additionalProperties": False})

    _w(root, f"{pkg}/loadgen.py", """\
        SCHEMA_VERSION = "vft.loadgen_event/1"
        SCENARIO_SCHEMA = "vft.scenario/1"
        EVENTS = ("begin", "request", "end")
        VERDICTS = ("PASS", "FAIL")
        LOADGEN_FIELDS = ("schema", "event")
        SCENARIO_FIELDS = ("schema", "verdict")
        """)
    _wj(root, f"{pkg}/telemetry/loadgen_event.schema.json", {
        "properties": {"schema": {"enum": ["vft.loadgen_event/1"]},
                       "event": {"enum": ["begin", "request", "end"]}},
        "required": ["schema"], "additionalProperties": False})
    _wj(root, f"{pkg}/telemetry/scenario.schema.json", {
        "properties": {"schema": {"enum": ["vft.scenario/1"]},
                       "verdict": {"enum": ["PASS", "FAIL"]}},
        "required": ["schema"], "additionalProperties": False})

    _w(root, f"{pkg}/telemetry/parity.py", """\
        SCHEMA_VERSION = "vft.parity/1"
        VERDICT_SCHEMA = "vft.parity_verdict/1"
        SEAMS = ("decode", "head")
        VERDICTS = ("PASS", "FAIL")
        PARITY_FIELDS = ("schema", "seam")
        VERDICT_FIELDS = ("schema", "verdict")
        """)
    _wj(root, f"{pkg}/telemetry/parity.schema.json", {
        "properties": {"schema": {"enum": ["vft.parity/1"]},
                       "seam": {"enum": ["decode", "head"]}},
        "required": ["schema"], "additionalProperties": False})
    _wj(root, f"{pkg}/telemetry/parity_verdict.schema.json", {
        "properties": {"schema": {"enum": ["vft.parity_verdict/1"]},
                       "verdict": {"enum": ["PASS", "FAIL"]}},
        "required": ["schema"], "additionalProperties": False})

    _w(root, f"{pkg}/telemetry/roofline.py", """\
        SCHEMA_VERSION = "vft.roofline/1"
        VERDICTS = ("compute-bound", "host-bound")
        ROOFLINE_FIELDS = ("schema", "device", "families")
        DEVICE_FIELDS = ("platform",)
        FAMILY_FIELDS = ("programs", "verdict")
        CARD_FIELDS = ("flops",)
        """)
    _wj(root, f"{pkg}/telemetry/roofline.schema.json", {
        "properties": {
            "schema": {"enum": ["vft.roofline/1"]},
            "device": {"properties": {"platform": {"type": "string"}},
                       "additionalProperties": False},
            "families": {"additionalProperties": {
                "properties": {
                    "programs": {"items": {
                        "properties": {"flops": {"type": "number"}},
                        "additionalProperties": False}},
                    "verdict": {"enum": ["compute-bound", "host-bound",
                                         None]}},
                "additionalProperties": False}}},
        "required": ["schema"], "additionalProperties": False})

    # threaded modules (VFT007 scope): a correctly-locked mutation
    _w(root, f"{pkg}/serve.py", """\
        import threading

        _OPEN = {}
        _LOCK = threading.Lock()


        def accept(rid):
            with _LOCK:
                _OPEN[rid] = "queued"
        """)
    _w(root, f"{pkg}/gateway.py", "")
    _w(root, f"{pkg}/parallel/queue.py", "")
    _w(root, f"{pkg}/telemetry/heartbeat.py", "")
    return root


def errors_of(findings, rule=None):
    return [f for f in findings if f.tier == engine.ERROR
            and (rule is None or f.rule == rule)]


def warns_of(findings, rule=None):
    return [f for f in findings if f.tier == engine.WARN
            and (rule is None or f.rule == rule)]


@pytest.fixture()
def repo(tmp_path):
    return make_repo(tmp_path)


# -- the clean fixture -------------------------------------------------------

def test_clean_fixture_passes(repo):
    findings, suppressed, _ = run_lint(str(repo))
    assert errors_of(findings) == [], \
        [f.render() for f in errors_of(findings)]
    assert suppressed == []


# -- VFT001 ------------------------------------------------------------------

def test_vft001_unclassified_key_fires_then_classified_is_quiet(repo):
    yml = repo / "video_features_tpu/configs/a.yml"
    yml.write_text(yml.read_text() + "newknob: 3\n")
    findings, _, _ = run_lint(str(repo), ["VFT001"])
    msgs = [f.message for f in errors_of(findings, "VFT001")]
    assert any("'newknob' is unclassified" in m for m in msgs)

    cache = repo / "video_features_tpu/cache.py"
    cache.write_text(cache.read_text().replace(
        '"spool_dir",', '"spool_dir", "newknob",'))
    findings, _, _ = run_lint(str(repo), ["VFT001"])
    assert errors_of(findings, "VFT001") == []


def test_vft001_double_classification_fires(repo):
    cache = repo / "video_features_tpu/cache.py"
    cache.write_text(cache.read_text().replace(
        '"feature_type",', '"feature_type", "cache",'))
    findings, _, _ = run_lint(str(repo), ["VFT001"])
    assert any("BOTH" in f.message
               for f in errors_of(findings, "VFT001"))


def test_vft001_stale_classification_warns(repo):
    cache = repo / "video_features_tpu/cache.py"
    cache.write_text(cache.read_text().replace(
        '"alpha",', '"alpha", "ghost_knob",'))
    findings, _, _ = run_lint(str(repo), ["VFT001"])
    assert any("stale" in f.message
               for f in warns_of(findings, "VFT001"))


# -- VFT002 ------------------------------------------------------------------

def test_vft002_validated_key_in_no_yaml_fires(repo):
    cfg = repo / "video_features_tpu/config.py"
    cfg.write_text(cfg.read_text().replace(
        'args.get("cache")', 'args.get("cache")\n    args.get("ghost")'))
    findings, _, _ = run_lint(str(repo), ["VFT002"])
    msgs = [f.message for f in errors_of(findings, "VFT002")]
    assert any("validated config key 'ghost'" in m for m in msgs)


def test_vft002_partial_yaml_key_needs_optional_declaration(repo):
    # 'alpha' is only in a.yml; removing it from OPTIONAL_KEYS fires
    cfg = repo / "video_features_tpu/config.py"
    cfg.write_text(cfg.read_text().replace(
        'OPTIONAL_KEYS = frozenset({"alpha"})',
        'OPTIONAL_KEYS = frozenset({"unused_decl"})'))
    findings, _, _ = run_lint(str(repo), ["VFT002"])
    msgs = [f.message for f in errors_of(findings, "VFT002")]
    assert any("'alpha' appears in only some family YAMLs" in m
               for m in msgs)
    # and the stale declaration warns
    assert any("'unused_decl'" in f.message
               for f in warns_of(findings, "VFT002"))


def test_vft002_undeclared_code_read_fires_then_yaml_fixes(repo):
    mod = repo / "video_features_tpu/serve.py"
    mod.write_text(mod.read_text() + "\n\ndef poll(args):\n"
                   "    return args.get('spool_poll_s')\n")
    findings, _, _ = run_lint(str(repo), ["VFT002"])
    msgs = [f.message for f in errors_of(findings, "VFT002")]
    assert any("'spool_poll_s' is read here but declared nowhere" in m
               for m in msgs)
    for fam in ("a", "b"):
        yml = repo / f"video_features_tpu/configs/{fam}.yml"
        yml.write_text(yml.read_text() + "spool_poll_s: 0.25\n")
    findings, _, _ = run_lint(str(repo), ["VFT002"])
    assert errors_of(findings, "VFT002") == []


def test_vft002_argparse_namespace_is_not_a_config(repo):
    mod = repo / "video_features_tpu/gateway.py"
    mod.write_text("import argparse\n\n\ndef main(argv):\n"
                   "    ap = argparse.ArgumentParser()\n"
                   "    args = ap.parse_args(argv)\n"
                   "    return args.get('prom'), args.verbose\n")
    findings, _, _ = run_lint(str(repo), ["VFT002"])
    assert errors_of(findings, "VFT002") == []


# -- VFT003 ------------------------------------------------------------------

def test_vft003_unregistered_site_fires(repo):
    mod = repo / "video_features_tpu/utils/sinks.py"
    mod.write_text(mod.read_text().replace(
        'inject.fire("sink.write"', 'inject.fire("sink.typo"'))
    findings, _, _ = run_lint(str(repo), ["VFT003"])
    msgs = [f.message for f in errors_of(findings, "VFT003")]
    assert any("'sink.typo' is fired here but not registered" in m
               for m in msgs)
    # ...and the now-orphaned registered site is dead coverage
    assert any("'sink.write' has no fire()/check() call site" in m
               for m in msgs)


def test_vft003_missing_doc_row_fires(repo):
    doc = repo / "docs/chaos.md"
    doc.write_text("# chaos\n\nno table here\n")
    findings, _, _ = run_lint(str(repo), ["VFT003"])
    assert any("no row in the docs/chaos.md site table" in f.message
               for f in errors_of(findings, "VFT003"))


# -- VFT004 ------------------------------------------------------------------

def test_vft004_raw_write_fires_and_suppression_silences(repo):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    mod.write_text("import json\n\n\ndef flush(path, doc):\n"
                   "    with open(path, 'w') as f:\n"
                   "        json.dump(doc, f)\n")
    findings, _, _ = run_lint(str(repo), ["VFT004"])
    assert len(errors_of(findings, "VFT004")) == 1

    mod.write_text("import json\n\n\ndef flush(path, doc):\n"
                   "    # vft-lint: disable=VFT004 — test fixture reason\n"
                   "    with open(path, 'w') as f:\n"
                   "        json.dump(doc, f)\n")
    findings, suppressed, _ = run_lint(str(repo), ["VFT004"])
    assert errors_of(findings, "VFT004") == []
    assert len(suppressed) == 1


def test_vft004_np_save_to_path_fires_but_buffer_is_fine(repo):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    mod.write_text("import io\nimport numpy as np\n\n\n"
                   "def a(path, v):\n    np.save(path, v)\n\n\n"
                   "def b(v):\n    buf = io.BytesIO()\n"
                   "    np.save(buf, v)\n    return buf.getvalue()\n")
    findings, _, _ = run_lint(str(repo), ["VFT004"])
    errs = errors_of(findings, "VFT004")
    assert len(errs) == 1 and errs[0].line == 6


def test_vft004_read_mode_never_fires(repo):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    mod.write_text("def load(path):\n"
                   "    with open(path) as f:\n        return f.read()\n")
    findings, _, _ = run_lint(str(repo), ["VFT004"])
    assert errors_of(findings, "VFT004") == []


# -- VFT005 ------------------------------------------------------------------

def test_vft005_undeclared_metric_fires_then_registered_quiet(repo):
    mod = repo / "video_features_tpu/utils/sinks.py"
    mod.write_text(mod.read_text().replace(
        'telemetry.inc("vft_writes_total")',
        'telemetry.inc("vft_writes_total")\n'
        '    telemetry.inc("vft_mystery_total")'))
    findings, _, _ = run_lint(str(repo), ["VFT005"])
    assert any("'vft_mystery_total' is not declared" in f.message
               for f in errors_of(findings, "VFT005"))

    names = repo / "video_features_tpu/telemetry/names.py"
    names.write_text(names.read_text().replace(
        '"vft_writes_total": "counter",',
        '"vft_writes_total": "counter",\n'
        '    "vft_mystery_total": "counter",'))
    findings, _, _ = run_lint(str(repo), ["VFT005"])
    assert errors_of(findings, "VFT005") == []


def test_vft005_counter_naming_and_kind_mismatch(repo):
    names = repo / "video_features_tpu/telemetry/names.py"
    names.write_text('METRICS = {\n'
                     '    "vft_writes_total": "gauge",\n'
                     '    "vft_bad_counter": "counter",\n'
                     '}\n')
    findings, _, _ = run_lint(str(repo), ["VFT005"])
    msgs = [f.message for f in errors_of(findings, "VFT005")]
    assert any("'vft_bad_counter' must end in _total" in m for m in msgs)
    # sinks.py uses .inc() on a now-gauge-declared name
    assert any("declared a gauge but used via .inc()" in m for m in msgs)


def test_vft005_unused_registration_warns(repo):
    names = repo / "video_features_tpu/telemetry/names.py"
    names.write_text(names.read_text().replace(
        '"vft_writes_total": "counter",',
        '"vft_writes_total": "counter",\n'
        '    "vft_orphan_total": "counter",'))
    findings, _, _ = run_lint(str(repo), ["VFT005"])
    assert any("'vft_orphan_total' is referenced nowhere" in f.message
               for f in warns_of(findings, "VFT005"))


# -- VFT006 ------------------------------------------------------------------

def test_vft006_missing_schema_property_fires(repo):
    sj = repo / "video_features_tpu/telemetry/video_span.schema.json"
    doc = json.loads(sj.read_text())
    del doc["properties"]["video"]
    doc["required"] = ["schema"]
    sj.write_text(json.dumps(doc))
    findings, _, _ = run_lint(str(repo), ["VFT006"])
    assert any("emitter field 'video' missing from the schema" in f.message
               for f in errors_of(findings, "VFT006"))


def test_vft006_enum_drift_fires(repo):
    al = repo / "video_features_tpu/telemetry/alerts.py"
    al.write_text(al.read_text().replace(
        '("pending", "firing", "resolved")',
        '("pending", "firing", "resolved", "silenced")'))
    findings, _, _ = run_lint(str(repo), ["VFT006"])
    assert any("'state' enum" in f.message
               for f in errors_of(findings, "VFT006"))


def test_vft006_roofline_nested_drift_fires(repo):
    rf = repo / "video_features_tpu/telemetry/roofline.py"
    rf.write_text(rf.read_text().replace(
        'CARD_FIELDS = ("flops",)', 'CARD_FIELDS = ("flops", "bytes")'))
    findings, _, _ = run_lint(str(repo), ["VFT006"])
    assert any("roofline.card" in f.message and "'bytes'" in f.message
               for f in errors_of(findings, "VFT006"))


# -- VFT007 ------------------------------------------------------------------

def test_vft007_unlocked_mutation_warns_locked_is_quiet(repo):
    serve = repo / "video_features_tpu/serve.py"
    serve.write_text(serve.read_text().replace(
        '    with _LOCK:\n        _OPEN[rid] = "queued"',
        '    _OPEN[rid] = "queued"'))
    findings, _, _ = run_lint(str(repo), ["VFT007"])
    ws = warns_of(findings, "VFT007")
    assert len(ws) == 1 and "_OPEN" in ws[0].message

    # the original (locked) fixture is quiet
    repo2 = make_repo(serve.parents[2] / "again")
    findings, _, _ = run_lint(str(repo2), ["VFT007"])
    assert warns_of(findings, "VFT007") == []


# -- engine contracts --------------------------------------------------------

def test_unreasoned_suppression_warns_vft000(repo):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    mod.write_text("def flush(path, doc):\n"
                   "    # vft-lint: disable=VFT004\n"
                   "    with open(path, 'w') as f:\n"
                   "        f.write(doc)\n")
    findings, suppressed, _ = run_lint(str(repo))
    assert len(suppressed) == 1
    assert any(f.rule == "VFT000" and "without a reason" in f.message
               for f in warns_of(findings))


def test_baseline_grandfathers_then_fails_on_new(repo, tmp_path, capsys):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    mod.write_text("def flush(path, doc):\n"
                   "    with open(path, 'w') as f:\n        f.write(doc)\n")
    base = tmp_path / "baseline.json"
    assert engine.main([str(repo), "--write-baseline", str(base)]) == 0
    capsys.readouterr()

    # grandfathered: the old finding no longer gates
    assert engine.main([str(repo), "--baseline", str(base),
                       "--fail-on-new"]) == 0
    capsys.readouterr()
    # without the baseline it still fails outright
    assert engine.main([str(repo)]) == 1
    capsys.readouterr()

    # a NEW violation fails even with the baseline
    mod.write_text(mod.read_text() +
                   "\n\ndef flush2(path, doc):\n"
                   "    with open(path, 'wb') as f:\n        f.write(doc)\n")
    assert engine.main([str(repo), "--baseline", str(base),
                       "--fail-on-new"]) == 1
    out = capsys.readouterr().out
    assert "(baselined)" in out and "1 new" in out


def test_json_output_schema_stable(repo, capsys):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    mod.write_text("def flush(path, doc):\n"
                   "    with open(path, 'w') as f:\n        f.write(doc)\n")
    rc = engine.main([str(repo), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["schema"] == "vft.lint/1"
    assert set(doc["counts"]) == {"errors", "warnings", "suppressed",
                                  "new_errors", "baselined"}
    f = [x for x in doc["findings"] if x["rule"] == "VFT004"][0]
    assert set(f) == {"rule", "tier", "path", "line", "message",
                      "fingerprint", "new"}
    assert f["new"] is True and f["tier"] == "error"


def test_fingerprint_survives_line_shift(repo):
    mod = repo / "video_features_tpu/telemetry/heartbeat.py"
    body = ("def flush(path, doc):\n"
            "    with open(path, 'w') as f:\n        f.write(doc)\n")
    mod.write_text(body)
    f1 = errors_of(run_lint(str(repo), ["VFT004"])[0])[0]
    mod.write_text("# a comment\n# another\n" + body)
    f2 = errors_of(run_lint(str(repo), ["VFT004"])[0])[0]
    assert f1.line != f2.line
    assert f1.fingerprint == f2.fingerprint


# -- the real tree -----------------------------------------------------------

def test_real_tree_lints_clean_above_baseline():
    findings, _suppressed, elapsed = run_lint(str(REPO_ROOT))
    baseline_path = REPO_ROOT / ".vft-lint-baseline.json"
    baseline = engine.load_baseline(str(baseline_path)) \
        if baseline_path.exists() else set()
    new = [f for f in findings if f.tier == engine.ERROR
           and f.fingerprint not in baseline]
    assert new == [], "the landed tree must lint clean: " + \
        "; ".join(f.render() for f in new)
    # the <10s acceptance bound, with slack for loaded CI boxes
    assert elapsed < 30.0


def test_real_tree_suppressions_all_reasoned():
    findings, _, _ = run_lint(str(REPO_ROOT))
    unreasoned = [f for f in findings
                  if f.rule == "VFT000" and "without a reason" in f.message]
    assert unreasoned == [], [f.render() for f in unreasoned]
