"""Pipeline tracing (telemetry/trace.py + scripts/trace_report.py):
recorder event shapes, the zero-cost disabled path, the StageProfiler
trace hook, fan-out backpressure accounting, the span event cap, torn-
file handling, profile_trace --self-time, and the CLI E2E contract
(ISSUE 4 acceptance criteria)."""
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu import telemetry
from video_features_tpu.telemetry import spans as tspans
from video_features_tpu.telemetry import trace
from video_features_tpu.telemetry.recorder import TelemetryRecorder
from video_features_tpu.telemetry.trace import (REQUIRED_C_FIELDS,
                                                REQUIRED_I_FIELDS,
                                                REQUIRED_X_FIELDS,
                                                TraceRecorder)
from video_features_tpu.utils.profiling import profiler

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parent.parent


def _events(doc, ph=None, name=None):
    evs = doc["traceEvents"]
    if ph is not None:
        evs = [e for e in evs if e.get("ph") == ph]
    if name is not None:
        evs = [e for e in evs if e.get("name") == name]
    return evs


# -- recorder unit ----------------------------------------------------------

def test_recorder_event_shapes_and_atomic_file(tmp_path):
    rec = TraceRecorder(str(tmp_path)).start()
    try:
        assert trace.active() is rec
        with trace.span("work", video="v.mp4", attempt=1):
            time.sleep(0.002)
        trace.complete("ext", time.perf_counter() - 0.01, 0.01, family="a")
        trace.instant("marker", reason="x")
        trace.counter("depth", 3)
    finally:
        path = rec.close()
    assert trace.active() is None
    assert path == str(tmp_path / "_trace.json")
    # complete-or-absent: no temp files next to it
    assert sorted(p.name for p in tmp_path.iterdir()) == ["_trace.json"]
    doc = json.load(open(path))

    xs = _events(doc, "X")
    assert {e["name"] for e in xs} == {"work", "ext"}
    for e in xs:
        assert all(k in e for k in REQUIRED_X_FIELDS), e
    work = _events(doc, "X", "work")[0]
    assert work["dur"] >= 2000  # ~2ms in µs
    assert work["args"] == {"video": "v.mp4", "attempt": 1}
    i = _events(doc, "i", "marker")[0]
    assert all(k in i for k in REQUIRED_I_FIELDS)
    c = _events(doc, "C", "depth")[0]
    assert all(k in c for k in REQUIRED_C_FIELDS)
    assert c["args"] == {"value": 3}
    # metadata names the process and this thread
    assert _events(doc, "M", "process_name")
    tnames = [e["args"]["name"] for e in _events(doc, "M", "thread_name")]
    assert threading.current_thread().name in tnames
    other = doc["otherData"]
    assert other["schema"] == "vft.trace/1"
    assert other["dropped_events"] == 0
    # close() is idempotent and the timeline is sorted by ts
    assert rec.close() is None
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)


def test_recorder_per_thread_buffers_and_cap(tmp_path):
    rec = TraceRecorder(str(tmp_path), max_events_per_thread=3).start()
    try:
        def emit(n):
            for i in range(n):
                trace.instant(f"e{i}")

        t = threading.Thread(target=emit, args=(5,), name="vft-test-emit")
        t.start()
        t.join()
        emit(2)  # main thread: under its own cap
    finally:
        rec.close()
    doc = json.load(open(tmp_path / "_trace.json"))
    # the worker thread kept 3 of 5 and dropped 2; main kept both
    assert doc["otherData"]["dropped_events"] == 2
    assert len(_events(doc, "i")) == 5
    tids = {e["args"]["name"]: e["tid"]
            for e in _events(doc, "M", "thread_name")}
    assert "vft-test-emit" in tids


def test_trace_helpers_noop_when_inactive(tmp_path):
    assert trace.active() is None
    cm = trace.span("anything", video="v")
    assert cm is trace.NOOP_TRACE_SPAN  # one shared object, no state
    with cm:
        pass
    trace.instant("x")
    trace.counter("y", 1)
    trace.complete("z", time.perf_counter(), 0.1)
    assert list(tmp_path.iterdir()) == []


def test_stage_trace_hook_emits_and_uninstalls(tmp_path):
    rec = TraceRecorder(str(tmp_path)).start()
    try:
        assert not profiler.enabled
        with profiler.stage("decode"):
            time.sleep(0.001)
    finally:
        rec.close()
    assert profiler._trace_hook is None
    assert profiler.snapshot() == {}  # aggregate printing stayed off
    doc = json.load(open(tmp_path / "_trace.json"))
    decode = _events(doc, "X", "decode")
    assert len(decode) == 1 and decode[0]["dur"] >= 1000
    # hook gone: stages stop emitting
    with profiler.stage("decode"):
        pass


# -- fan-out backpressure accounting ---------------------------------------

def test_fanout_backpressure_counters_and_heartbeat(tmp_path, sample_video):
    """A tiny queue + slow consumer must show up as put-blocked time on
    the bus side and land in the heartbeat's fanout section; the get
    side accumulates starved time while waiting for decode."""
    from video_features_tpu.parallel.fanout import FrameBus

    rec = TelemetryRecorder(str(tmp_path / "out"), feature_type="x",
                            interval_s=60.0, host_id="p0-t").start()
    tracer = TraceRecorder(str(tmp_path / "out")).start()
    try:
        bus = FrameBus(sample_video, ["slow"], depth=2)
        sub = bus.subscribe("slow", total=30)
        frames = []
        for x, ts, idx in sub.frames():
            time.sleep(0.02)  # slow consumer: the 2-deep queue fills
            frames.append(idx)
        assert len(frames) == len(sub)
        assert sub.put_blocked_s > 0  # the decoder waited on us
        assert sub.get_starved_s >= 0
        reg = rec.registry
        assert reg.counter("vft_fanout_put_blocked_ms_total",
                           family="slow").value > 0
        fan = rec.fanout_snapshot()
        assert "slow" in fan["queue_depth"]
        assert fan["put_blocked_ms_total"]["slow"] > 0
        hb = rec.build_heartbeat()
        assert hb["fanout"]["put_blocked_ms_total"]["slow"] > 0
    finally:
        tracer.close()
        rec.close()
    doc = json.load(open(tmp_path / "out" / "_trace.json"))
    names = {e["name"] for e in _events(doc, "X")}
    assert "fanout.decode_pass" in names
    assert "fanout.put_blocked" in names  # >=1ms stalls hit the timeline
    tnames = [e["args"]["name"] for e in _events(doc, "M", "thread_name")]
    assert "vft-fanout-decode" in tnames


# -- span event cap (satellite) ---------------------------------------------

def test_video_span_event_cap(tmp_path):
    with tspans.VideoSpan("v.mp4") as span:
        for i in range(tspans.MAX_SPAN_EVENTS + 40):
            span.event("retry_tick", i=i)
        span.event("ladder", to="inline")  # past the cap
        span.annotate(status="done")
    rec = span.record
    events = rec["events"]
    # first N kept + ONE drop-counter record; nothing unbounded
    assert len(events) == tspans.MAX_SPAN_EVENTS + 1
    assert events[-1]["kind"] == "events_dropped"
    assert events[-1]["count"] == 41
    # ladder_steps stays complete even past the cap
    assert rec["ladder_steps"] == ["inline"]
    from video_features_tpu.telemetry import schema as tschema
    assert tschema.validate_span(rec) == []


# -- trace_report.py --------------------------------------------------------

def _write_trace(path, events, dropped=0):
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"schema": "vft.trace/1", "dropped_events": dropped}}
    path.write_text(json.dumps(doc))
    return path


def _report(args):
    return subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "trace_report.py")]
        + [str(a) for a in args], capture_output=True, text=True)


def test_trace_report_verdict_and_stalls(tmp_path):
    """Synthetic timeline: a decode-heavy video on a fanout bus thread
    must report decode-bound and rank the injected stall."""
    def x(name, ts, dur, tid, args=None):
        e = {"ph": "X", "name": name, "ts": ts, "dur": dur, "pid": 1,
             "tid": tid}
        if args:
            e["args"] = args
        return e

    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "vft-host"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 10,
         "args": {"name": "vft-fanout-decode"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 20,
         "args": {"name": "vft-family-resnet"}},
        x("video_attempt", 0, 100_000, 20, {"video": "a.mp4",
                                            "attempt": 1}),
        x("decode", 0, 80_000, 10),            # bus lane: pure decode
        x("decode", 10_000, 5_000, 20),        # family lane: transform
        x("forward", 20_000, 10_000, 20),
        x("write", 90_000, 2_000, 20),
        x("fanout.get_starved", 40_000, 30_000, 20,
          {"family": "resnet"}),
    ]
    p = _report([_write_trace(tmp_path / "_trace.json", events)])
    assert p.returncode == 0, p.stdout + p.stderr
    assert "verdict: decode-bound" in p.stdout
    assert "fanout.get_starved" in p.stdout
    assert "a.mp4" in p.stdout
    assert "vft-fanout-decode" in p.stdout
    # accepts the run directory too
    assert _report([tmp_path]).returncode == 0


def test_trace_report_merge_host_device(tmp_path):
    """--merge splices a jax.profiler-style device capture with the host
    trace into one file, pids disjoint, both rebased to t=0."""
    host = _write_trace(tmp_path / "_trace.json", [
        {"ph": "X", "name": "decode", "ts": 5_000_000, "dur": 100,
         "pid": 7, "tid": 1},
    ])
    dev_dir = tmp_path / "jaxtrace" / "plugins" / "profile" / "run1"
    dev_dir.mkdir(parents=True)
    (dev_dir / "host.trace.json").write_text(json.dumps({"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "fusion.1", "ts": 9_000_000, "dur": 50,
         "pid": 3, "tid": 2},
    ]}))
    p = _report([host, "--merge", tmp_path / "jaxtrace",
                 "--out", tmp_path / "merged.json"])
    assert p.returncode == 0, p.stdout + p.stderr
    merged = json.load(open(tmp_path / "merged.json"))
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in xs} == {"decode", "fusion.1"}
    assert len({e["pid"] for e in xs}) == 2  # host pid remapped, disjoint
    assert min(e["ts"] for e in xs) == 0  # both rebased


def test_trace_report_merge_wall_clock_anchors(tmp_path):
    """Two vft traces whose recorders started 3 s apart must merge onto
    shared WALL time (ISSUE 10 satellite): each keeps its internal ts
    and shifts by its otherData.start_unix offset against the earliest
    anchor — not both silently pinned to t=0, which misaligns any two
    captures not started together."""
    a_dir, b_dir = tmp_path / "a", tmp_path / "b"
    a_dir.mkdir(), b_dir.mkdir()
    host = a_dir / "_trace.json"
    host.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "decode", "ts": 100.0, "dur": 10.0,
         "pid": 7, "tid": 1}],
        "otherData": {"schema": "vft.trace/1", "start_unix": 1000.0}}))
    other = b_dir / "_trace.json"
    other.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "decode", "ts": 100.0, "dur": 10.0,
         "pid": 7, "tid": 1}],
        "otherData": {"schema": "vft.trace/1", "start_unix": 1003.0}}))
    p = _report([host, "--merge", b_dir,
                 "--out", tmp_path / "merged.json"])
    assert p.returncode == 0, p.stdout + p.stderr
    merged = json.load(open(tmp_path / "merged.json"))
    assert merged["otherData"]["aligned"] is True
    xs = sorted((e["ts"] for e in merged["traceEvents"]
                 if e.get("ph") == "X"))
    # host anchored at the minimum keeps ts=100; the +3 s capture shifts
    assert xs == [100.0, 100.0 + 3e6]
    # anchorless second input (a jax capture): legacy both-to-t=0 path
    (b_dir / "_trace.json").write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "fusion.1", "ts": 9_000_000, "dur": 50,
         "pid": 3, "tid": 2}]}))
    p2 = _report([host, "--merge", b_dir,
                  "--out", tmp_path / "merged2.json"])
    assert p2.returncode == 0, p2.stdout + p2.stderr
    merged2 = json.load(open(tmp_path / "merged2.json"))
    assert merged2["otherData"]["aligned"] is False
    assert min(e["ts"] for e in merged2["traceEvents"]
               if e.get("ph") == "X") == 0


def test_trace_report_truncated_file_clear_error(tmp_path):
    torn = tmp_path / "_trace.json"
    torn.write_text('{"traceEvents": [{"ph": "X", "name": "dec')  # torn
    p = _report([torn])
    assert p.returncode != 0
    err = p.stdout + p.stderr
    assert "not a complete JSON trace" in err
    assert "Traceback" not in err  # a message, not a JSON traceback
    # missing file: same discipline
    p2 = _report([tmp_path / "absent"])
    assert p2.returncode != 0 and "trace=true" in (p2.stdout + p2.stderr)
    # JSON but not a trace
    notrace = tmp_path / "x.json"
    notrace.write_text('{"foo": 1}')
    p3 = _report([notrace])
    assert p3.returncode != 0
    assert "traceEvents" in (p3.stdout + p3.stderr)


# -- profile_trace --self-time (satellite) ----------------------------------

def test_profile_trace_self_time_subtracts_children(tmp_path):
    run = tmp_path / "plugins" / "profile" / "r1"
    run.mkdir(parents=True)
    (run / "h.trace.json").write_text(json.dumps({"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        # while-loop span (100us) containing two fusions (60us + 20us)
        {"ph": "X", "name": "while", "ts": 0, "dur": 100, "pid": 1,
         "tid": 1},
        {"ph": "X", "name": "fusion.a", "ts": 5, "dur": 60, "pid": 1,
         "tid": 1},
        {"ph": "X", "name": "fusion.b", "ts": 70, "dur": 20, "pid": 1,
         "tid": 1},
    ]}))
    script = str(REPO_ROOT / "scripts" / "profile_trace.py")

    def run_tool(*flags):
        p = subprocess.run([sys.executable, script, str(tmp_path)]
                           + list(flags), capture_output=True, text=True)
        assert p.returncode == 0, p.stdout + p.stderr
        rows = {}
        for line in p.stdout.splitlines():
            parts = line.split()
            if len(parts) == 3 and parts[2].startswith(("while", "fusion")):
                rows[parts[2]] = float(parts[0]) * 1e3  # ms -> us
        return rows

    inclusive = run_tool()
    assert inclusive["while"] == pytest.approx(100)
    self_time = run_tool("--self-time")
    assert self_time["while"] == pytest.approx(20)  # 100 - 60 - 20
    assert self_time["fusion.a"] == pytest.approx(60)
    assert sum(self_time.values()) == pytest.approx(100)  # sums to real


# -- CLI E2E ----------------------------------------------------------------

def test_cli_trace_end_to_end(tmp_path, sample_video):
    """trace=true on a real (single-family) run: a valid trace with the
    pipeline spans; trace=false leaves no _trace.json and an identical
    telemetry footprint."""
    from video_features_tpu import cli

    def run(out, extra):
        cli.main([
            "feature_type=resnet", "model_name=resnet18", "device=cpu",
            "batch_size=8", "extraction_total=6",
            "allow_random_weights=true", "on_extraction=save_numpy",
            f"output_path={tmp_path / out}", f"tmp_path={tmp_path}/tmp",
            f"video_paths={sample_video}", "telemetry=true",
            "metrics_interval_s=60"] + extra)
        return tmp_path / out / "resnet" / "resnet18"

    run_dir = run("traced", ["trace=true"])
    doc = json.load(open(run_dir / "_trace.json"))
    xs = _events(doc, "X")
    for e in xs:
        assert all(k in e for k in REQUIRED_X_FIELDS), e
    names = {e["name"] for e in xs}
    assert {"decode", "forward", "write", "video_attempt"} <= names
    att = _events(doc, "X", "video_attempt")[0]
    assert att["args"]["video"] == str(sample_video)
    p = _report([run_dir])
    assert p.returncode == 0, p.stdout + p.stderr
    assert "verdict:" in p.stdout

    run_dir_off = run("plain", [])
    assert not (run_dir_off / "_trace.json").exists()
    # identical telemetry footprint with trace off: same artifact set,
    # same per-video span record shape
    on_files = {p.name for p in run_dir.iterdir()}
    off_files = {p.name for p in run_dir_off.iterdir()}
    assert on_files - off_files == {"_trace.json"}
    from video_features_tpu.telemetry import jsonl as tjsonl
    span_on = list(tjsonl.read_jsonl(run_dir / "_telemetry.jsonl"))[0]
    span_off = list(tjsonl.read_jsonl(run_dir_off / "_telemetry.jsonl"))[0]
    assert sorted(span_on) == sorted(span_off)
    # ...and identical features
    for npy in sorted(run_dir.glob("*.npy")):
        np.testing.assert_array_equal(
            np.load(npy), np.load(run_dir_off / npy.name),
            err_msg=f"{npy.name} differs between trace on/off")
