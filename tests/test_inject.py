"""Deterministic fault-injection plane (utils/inject.py, ISSUE 9).

Covers the plan grammar + launch validation, seeded determinism (same
seed => same firing pattern, the replay contract), the
zero-when-disarmed contract, and each site's behavioral semantics at
the unit level: the sink write legs (no-litter under ENOSPC/torn/drop),
cache store/lookup, the queue claim-skew and steal-staging-drop
windows, and the heartbeat tick error accounting + freeze. The
end-to-end composition (full CLI chaos runs audited by vft-audit) lives
in tests/test_chaos.py; the auditor itself in tests/test_audit.py.
"""
import errno
import json
import os
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.utils import inject

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends injection-off (the module global must
    never leak between tests — exactly the cli.py finally contract)."""
    inject._set_active(None)
    yield
    inject._set_active(None)


# ---------------------------------------------------------------------------
# plan grammar
# ---------------------------------------------------------------------------

def test_parse_plan_round_trip():
    p = inject.parse_plan(
        "seed=7;sink.fsync=enospc@n1;decode.read=eio@p0.05;"
        "heartbeat.tick=freeze@after2;queue.claim=skew@every3")
    assert p.seed == 7
    assert set(p.rules) == {"sink.fsync", "decode.read", "heartbeat.tick",
                            "queue.claim"}
    assert p.rules["sink.fsync"].trigger == "n"
    assert p.rules["decode.read"].value == pytest.approx(0.05)
    assert p.rules["heartbeat.tick"].trigger == "after"
    assert p.rules["queue.claim"].trigger == "every"


def test_parse_plan_gateway_sites(tmp_path):
    """The ISSUE-14 ingress sites parse with their behavioral faults —
    and the grammar still rejects kinds that make no sense there."""
    p = inject.parse_plan(
        "seed=3;gateway.read=torn@n1;gateway.spool_submit=drop@n2;"
        "spool.respond=drop@every2")
    assert set(p.rules) == {"gateway.read", "gateway.spool_submit",
                            "spool.respond"}
    inject.parse_plan("gateway.read=stall@n1")  # the slow-client fault
    inject.parse_plan("gateway.spool_submit=enospc@n1")
    with pytest.raises(ValueError, match="only applies"):
        inject.parse_plan("gateway.read=drop@n1")
    with pytest.raises(ValueError, match="only applies"):
        inject.parse_plan("spool.respond=torn@n1")
    with pytest.raises(ValueError, match="only applies"):
        inject.parse_plan("sink.fsync=stall@n1")


def test_parse_plan_default_trigger_is_first_hit():
    p = inject.parse_plan("seed=1;sink.rename=drop")
    r = p.rules["sink.rename"]
    assert r.should_fire(1) and not r.should_fire(2)


def test_seed_clause_position_does_not_matter():
    a = inject.parse_plan("seed=5;decode.read=eio@p0.4")
    b = inject.parse_plan("decode.read=eio@p0.4;seed=5")
    fa = [a.rules["decode.read"].should_fire(i) for i in range(1, 100)]
    fb = [b.rules["decode.read"].should_fire(i) for i in range(1, 100)]
    assert fa == fb


@pytest.mark.parametrize("bad", [
    "", "   ", "seed=1",                    # no site rules
    "seed=x;sink.fsync=eio",                # bad seed
    "bogus.site=eio",                       # unknown site
    "sink.fsync=bogus",                     # unknown fault
    "sink.fsync=eio@n0",                    # trigger needs n >= 1
    "sink.fsync=eio@p0",                    # p in (0, 1]
    "sink.fsync=eio@p1.5",
    "sink.fsync=eio@sometimes",             # unknown trigger
    "decode.read=torn",                     # torn is sink-only
    "sink.fsync=skew",                      # skew is queue.claim-only
    "no-equals-clause;sink.fsync=eio",
])
def test_parse_plan_rejects(bad):
    with pytest.raises(ValueError):
        inject.parse_plan(bad)


def test_sanity_check_validates_inject_key(tmp_path, sample_video):
    from video_features_tpu.config import load_config, sanity_check
    base = dict(video_paths=[sample_video], output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"), device="cpu")
    ok = load_config("resnet", dict(base, inject="seed=1;sink.fsync=eio@n1"))
    sanity_check(ok)  # parses cleanly
    bad = load_config("resnet", dict(base, inject="sink.fsync=wat"))
    with pytest.raises(ValueError, match="unknown fault"):
        sanity_check(bad)
    notstr = load_config("resnet", dict(base, inject=17))
    with pytest.raises(ValueError, match="plan string"):
        sanity_check(notstr)


# ---------------------------------------------------------------------------
# determinism + the replay contract
# ---------------------------------------------------------------------------

def test_same_seed_same_firing_pattern():
    spec = "seed=3;decode.read=eio@p0.3"
    runs = []
    for _ in range(2):
        plan = inject.parse_plan(spec)
        fired = []
        for i in range(200):
            try:
                fired.append(plan.check("decode.read", {}) is not None)
            except OSError:
                fired.append(True)
        runs.append(fired)
    assert runs[0] == runs[1], "same seed+spec must replay exactly"
    assert any(runs[0]) and not all(runs[0])
    other = inject.parse_plan("seed=4;decode.read=eio@p0.3")
    fired4 = []
    for i in range(200):
        try:
            fired4.append(other.check("decode.read", {}) is not None)
        except OSError:
            fired4.append(True)
    assert fired4 != runs[0], "different seeds must differ"


def test_per_site_streams_are_independent():
    """Adding a rule for one site must not shift another site's draws —
    otherwise narrowing a plan during triage changes the failure."""
    solo = inject.parse_plan("seed=9;decode.read=eio@p0.25")
    both = inject.parse_plan(
        "seed=9;decode.read=eio@p0.25;heartbeat.tick=error@p0.5")
    seq = [solo.rules["decode.read"].should_fire(i) for i in range(1, 300)]
    seq2 = [both.rules["decode.read"].should_fire(i) for i in range(1, 300)]
    assert seq == seq2


def test_fire_disarmed_is_none_and_counts_nothing():
    assert inject.active() is None
    assert inject.fire("decode.read", video="v") is None
    assert inject.fire("worker.kill") is None  # would SIGKILL if armed!


def test_arm_for_run_env_overrides_config(monkeypatch):
    monkeypatch.delenv("VFT_INJECT", raising=False)
    plan = inject.arm_for_run("seed=1;sink.fsync=eio@n1")
    assert plan is not None and plan.seed == 1
    monkeypatch.setenv("VFT_INJECT", "seed=2;decode.read=eio@n1")
    plan = inject.arm_for_run("seed=1;sink.fsync=eio@n1")
    assert plan.seed == 2 and "decode.read" in plan.rules, \
        "VFT_INJECT must override the config key (subprocess workers)"
    monkeypatch.delenv("VFT_INJECT")
    assert inject.arm_for_run(None) is None
    assert inject.active() is None


def test_fired_tally_and_summary():
    plan = inject.parse_plan("seed=1;sink.fsync=eio@n2")
    inject._set_active(plan)
    assert inject.fire("sink.fsync") is None          # hit 1: no fire
    with pytest.raises(OSError):
        inject.fire("sink.fsync")                     # hit 2: fires
    assert inject.fire("sink.fsync") is None          # hit 3: no fire
    assert plan.hits["sink.fsync"] == 3
    assert plan.fired["sink.fsync"] == 1
    assert "sink.fsync:1/3" in plan.summary()


# ---------------------------------------------------------------------------
# sink legs: ENOSPC / torn / drop never litter, never tear
# ---------------------------------------------------------------------------

def _arm(spec):
    plan = inject.parse_plan(spec)
    inject._set_active(plan)
    return plan


def test_sink_fsync_enospc_no_litter_then_clean_retry(tmp_path):
    from video_features_tpu.utils.sinks import _write_bytes_atomic
    _arm("seed=1;sink.fsync=enospc@n1")
    target = tmp_path / "x.bin"
    with pytest.raises(OSError) as ei:
        _write_bytes_atomic(str(target), b"payload")
    assert ei.value.errno == errno.ENOSPC
    assert not target.exists()
    assert list(tmp_path.iterdir()) == [], \
        "an injected ENOSPC at fsync must not leak the .tmp file"
    _write_bytes_atomic(str(target), b"payload")  # hit 2: clean
    assert target.read_bytes() == b"payload"


def test_sink_torn_write_keeps_prior_artifact(tmp_path):
    from video_features_tpu.utils.sinks import _write_bytes_atomic
    target = tmp_path / "x.bin"
    _write_bytes_atomic(str(target), b"generation-1")
    _arm("seed=1;sink.tmp_write=torn@n1")
    with pytest.raises(OSError) as ei:
        _write_bytes_atomic(str(target), b"generation-2-much-longer")
    assert ei.value.errno == errno.EIO
    assert target.read_bytes() == b"generation-1", \
        "a torn replacement write must leave the prior artifact intact"
    assert [p.name for p in tmp_path.iterdir()] == ["x.bin"]


def test_sink_rename_drop_is_retryable_transient(tmp_path):
    from video_features_tpu.utils import faults
    from video_features_tpu.utils.sinks import _write_bytes_atomic
    _arm("seed=1;sink.rename=drop@n1")
    target = tmp_path / "x.bin"
    with pytest.raises(OSError) as ei:
        _write_bytes_atomic(str(target), b"data")
    assert faults.classify(ei.value) == faults.TRANSIENT
    assert list(tmp_path.iterdir()) == []


def test_write_numpy_armed_path_byte_identical(tmp_path):
    """Arming a plan reroutes write_numpy through the Python atomic path
    (so the sink sites cover it); the bytes must equal the native
    writer's — the inject-off-is-identical discipline."""
    from video_features_tpu.utils.sinks import write_numpy
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    off = tmp_path / "off.npy"
    write_numpy(str(off), arr)
    _arm("seed=1;decode.read=eio@n999999")  # armed, but never fires
    on = tmp_path / "on.npy"
    write_numpy(str(on), arr)
    assert off.read_bytes() == on.read_bytes()


# ---------------------------------------------------------------------------
# cache sites: store failures raise, torn lookups drop-and-miss
# ---------------------------------------------------------------------------

def _mini_cache(tmp_path):
    from video_features_tpu.cache import FeatureCache
    video = tmp_path / "v.bin"
    video.write_bytes(b"not really a video, but hashable content")
    return FeatureCache(str(tmp_path / "store"), "resnet", "cfg", "wts"), \
        str(video)


def test_cache_store_fault_raises_and_leaves_no_entry(tmp_path):
    cache, video = _mini_cache(tmp_path)
    _arm("seed=1;cache.store=eio@n1")
    with pytest.raises(OSError):
        cache.store(video, {"resnet": np.ones((2, 4), np.float32)})
    assert cache.lookup(video) is None
    assert not list(Path(cache.root).rglob("*.pkl"))
    cache.store(video, {"resnet": np.ones((2, 4), np.float32)})  # hit 2
    assert cache.lookup(video) is not None


def test_cache_lookup_torn_entry_dropped_never_served(tmp_path):
    cache, video = _mini_cache(tmp_path)
    feats = {"resnet": np.arange(8, dtype=np.float32)}
    cache.store(video, feats)
    entry = cache.entry_path(cache.key_for(video))
    _arm("seed=1;cache.lookup=torn@n1")
    assert cache.lookup(video) is None, \
        "a torn entry must be a miss, never served"
    assert not os.path.exists(entry), "the torn entry must be dropped"
    got = cache.lookup(video)  # hit 2: entry gone -> plain miss
    assert got is None
    cache.store(video, feats)
    got = cache.lookup(video)
    assert got is not None and np.array_equal(got["resnet"],
                                              feats["resnet"])


def test_cache_store_failure_contained_by_extractor(tmp_path, sample_video):
    """A cache-store failure after the sink landed must not fail the
    video: the store is an optimization (extractors/base.py contains
    it), and the artifacts are already durable."""
    from video_features_tpu.cli import main
    out = tmp_path / "out"
    main(["feature_type=resnet", "model_name=resnet18", "device=cpu",
          "allow_random_weights=true", "on_extraction=save_numpy",
          "extraction_total=4", "batch_size=8",
          "cache=true", f"cache_dir={tmp_path / 'cachedir'}",
          "inject=seed=1;cache.store=eio@n1",
          f"output_path={out}", f"tmp_path={tmp_path / 'tmp'}",
          f"video_paths=[{sample_video}]"])
    arts = list(out.rglob("*_resnet.npy"))
    assert len(arts) == 1, "the video must complete despite the store fault"
    journal = list(out.rglob("_failures.jsonl"))
    assert not journal, "a contained store failure must not journal"


# ---------------------------------------------------------------------------
# queue sites: skewed leases get stolen; a dropped steal is swept back
# ---------------------------------------------------------------------------

def _mk_queue(tmp_path, host, clock, lease_s=10.0):
    from video_features_tpu.parallel.queue import WorkQueue
    return WorkQueue(str(tmp_path), host_id=host, run_id=f"r-{host}",
                     lease_s=lease_s, clock=clock)


def _write_heartbeat(tmp_path, host, t, final=False, interval=1.0):
    from video_features_tpu.telemetry.heartbeat import heartbeat_filename
    from video_features_tpu.telemetry.jsonl import write_json_atomic
    write_json_atomic(os.path.join(str(tmp_path), heartbeat_filename(host)),
                      {"host_id": host, "time": t, "interval_s": interval,
                       "final": final})


def test_queue_claim_skew_makes_lease_immediately_stealable(tmp_path):
    now = [1000.0]
    qa = _mk_queue(tmp_path, "hostA", lambda: now[0])
    qb = _mk_queue(tmp_path, "hostB", lambda: now[0])
    qa.seed(["v0.mp4"])
    _write_heartbeat(tmp_path, "hostA", now[0])  # A looks alive
    _write_heartbeat(tmp_path, "hostB", now[0])
    _arm("seed=1;queue.claim=skew@n1")
    rec = qa.claim_next()
    assert rec is not None
    assert float(rec["deadline"]) < now[0], "skew must stamp an " \
        "already-expired deadline"
    inject._set_active(None)
    assert qb.reclaim_expired() == 1, \
        "a skew-expired lease must be stealable despite a live owner"
    stolen = qb.claim_next()
    assert stolen is not None and stolen["reclaims"] == 1
    assert stolen["last_owner"] == "hostA"


def test_queue_steal_staging_drop_recovered_by_sweep(tmp_path):
    now = [1000.0]
    qa = _mk_queue(tmp_path, "hostA", lambda: now[0], lease_s=10.0)
    qb = _mk_queue(tmp_path, "hostB", lambda: now[0], lease_s=10.0)
    qa.seed(["v0.mp4"])
    _write_heartbeat(tmp_path, "hostB", now[0])
    rec = qa.claim_next()
    assert rec is not None
    now[0] += 100.0  # lease long expired; hostA heartbeat silent
    _arm("seed=1;queue.steal_staging=drop@n1")
    assert qb.reclaim_expired() == 0, "the stealer 'died' mid-move"
    inject._set_active(None)
    staging = list(Path(qb.root, ".staging").glob("*.json"))
    assert len(staging) == 1, "the item must sit in .staging, not vanish"
    # age the orphan past STAGING_ORPHAN_LEASES * lease_s ON THE QUEUE'S
    # (injected) clock — the sweep compares its clock to file mtimes
    os.utime(staging[0], (now[0] - 100.0, now[0] - 100.0))
    assert qb.reclaim_expired() == 1, "the staging sweep must recover it"
    got = qb.claim_next()
    assert got is not None and got.get("video") == "v0.mp4"


# ---------------------------------------------------------------------------
# heartbeat site: tick errors counted + surfaced; freeze looks dead
# ---------------------------------------------------------------------------

def test_heartbeat_tick_errors_counted_and_surfaced(tmp_path):
    from video_features_tpu.telemetry.recorder import TelemetryRecorder
    _arm("seed=1;heartbeat.tick=error@n1")
    rec = TelemetryRecorder(str(tmp_path), interval_s=0.03,
                            host_id="tickhost").start()
    try:
        deadline = time.time() + 5.0
        while rec._hb.tick_errors_total < 1 and time.time() < deadline:
            time.sleep(0.01)
        # wait for the NEXT (successful) tick to surface the error
        while time.time() < deadline:
            hb = json.loads(Path(rec.heartbeat_path).read_text())
            if hb.get("tick_errors"):
                break
            time.sleep(0.01)
    finally:
        rec.close()
    assert rec._hb.tick_errors_total == 1
    assert "injected fault at heartbeat.tick" in rec._hb.last_tick_error
    hb = json.loads(Path(rec.heartbeat_path).read_text())
    assert hb["tick_errors"] == 1
    assert "heartbeat.tick" in hb["last_tick_error"]
    series = [s for s in rec.registry.to_dict()["series"]
              if s["name"] == "vft_heartbeat_tick_errors_total"]
    assert series and series[0]["value"] == 1


def test_heartbeat_freeze_skips_ticks_silently():
    from video_features_tpu.telemetry.heartbeat import HeartbeatThread
    ticks = [0]

    def tick():
        ticks[0] += 1

    _arm("seed=1;heartbeat.tick=freeze@after1")
    hb = HeartbeatThread(tick, 0.02)
    hb.start()
    try:
        deadline = time.time() + 5.0
        while hb.frozen_ticks < 3 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        hb.stop()
    assert ticks[0] == 1, "only the pre-freeze tick may run"
    assert hb.frozen_ticks >= 3
    assert hb.tick_errors_total == 0, "freeze is silence, not an error"
