"""Storage lifecycle plane (gc.py): config validation, per-plane and
per-tenant accounting, the planner safety rules, journal-before-unlink
execution + audit reconciliation, monitor caching — plus the PR's
satellites: telemetry ENOSPC degradation latches, stale weights
``.part`` sweeping, and bench-history compaction."""
import errno
import json
import os
import sys
import time
from pathlib import Path

import pytest

from video_features_tpu import gc as vgc
from video_features_tpu import telemetry
from video_features_tpu.audit import audit_run
from video_features_tpu.config import load_config, sanity_check
from video_features_tpu.telemetry import jsonl as tjsonl
from video_features_tpu.telemetry.jsonl import append_jsonl

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parent.parent
NOW = 1_000_000.0


class Clock:
    def __init__(self, t: float = NOW) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def _touch(path: Path, nbytes: int = 16, *, age_s: float = 0.0,
           text: str = None) -> Path:
    path.parent.mkdir(parents=True, exist_ok=True)
    if text is not None:
        path.write_text(text)
    else:
        path.write_bytes(b"x" * nbytes)
    if age_s:
        t = time.time() - age_s
        os.utime(path, (t, t))
    return path


def _cfg(**kw) -> vgc.GcConfig:
    return vgc.GcConfig(**kw)


# -- config surface ---------------------------------------------------------

def test_validate_gc_args_accepts_the_full_surface():
    vgc.validate_gc_args({"gc": True, "gc_quota_gb": 50,
                          "gc_cache_retention_s": 3600,
                          "gc_spool_retention_s": "86400",
                          "gc_interval_s": 60})
    vgc.validate_gc_args({})  # nothing gc-related: nothing to check


@pytest.mark.parametrize("bad", [
    {"gc": "yes"},
    {"gc_quota_gb": 0},
    {"gc_quota_gb": -1},
    {"gc_quota_gb": "plenty"},
    {"gc_cache_retention_s": 0},
    {"gc_inbox_retention_s": "forever"},
    {"gc_interval_s": -5},
])
def test_validate_gc_args_rejects(bad):
    with pytest.raises(ValueError):
        vgc.validate_gc_args(bad)


def test_sanity_check_delegates_gc_validation(tmp_path):
    """A typo'd gc knob on a run config fails at launch, exactly like
    any other key — the CLI and vft-gc validate identically."""
    cfg = load_config("resnet", {
        "video_paths": "x.mp4", "device": "cpu",
        "output_path": str(tmp_path / "out"),
        "tmp_path": str(tmp_path / "tmp"),
        "gc": True, "gc_quota_gb": -3,
    })
    with pytest.raises(ValueError, match="gc_quota_gb"):
        sanity_check(cfg)


def test_config_from_args_resolves_quota_to_bytes():
    cfg = vgc.GcConfig.from_args({"gc_quota_gb": 0.5,
                                  "gc_cache_retention_s": 10})
    assert cfg.quota_bytes == int(0.5e9)
    assert cfg.cache_retention_s == 10.0
    assert cfg.spool_retention_s is None  # unset = account-only
    assert cfg.interval_s == 300.0


# -- usage accounting -------------------------------------------------------

def test_usage_accounts_planes_and_tenants(tmp_path):
    root = tmp_path / "root"
    cache = tmp_path / "cache"
    comp = tmp_path / "compile"
    _touch(root / "done" / "r1.json", 100)
    _touch(root / "expired" / "r2.json", 50)
    _touch(root / "inbox" / "blobA", 300)
    _touch(root / "_incidents" / "b1" / "hb.json", 40)
    _touch(root / "_queue" / "quarantined" / "q.json", 20)
    _touch(root / "_queue" / ".staging" / "s.json", 10)
    _touch(root / "_telemetry.jsonl", 64)
    _touch(cache / "ab" / "abcd.pkl", 500)
    # tenant attribution comes from the gateway admission journal, not
    # from unpickling cache entries (the tenant salt is irreversible)
    gw = root / "_gateway_h1.jsonl"
    append_jsonl(gw, {"event": "upload", "tenant": "acme",
                      "sha256": "aa", "bytes": 300})
    append_jsonl(gw, {"event": "upload", "tenant": "acme",
                      "sha256": "aa", "bytes": 300, "dedup": True})
    append_jsonl(gw, {"event": "accepted", "tenant": "acme", "id": "r1"})

    use = vgc.usage(str(root), cache_dir=str(cache), compile_dir=str(comp))
    p = use["planes"]
    assert p["cache"] == {"files": 1, "bytes": 500}
    assert p["spool"] == {"files": 2, "bytes": 150}
    assert p["inbox"]["bytes"] == 300
    assert p["incidents"]["bytes"] == 40
    assert p["quarantine"]["bytes"] == 20
    assert p["staging"]["bytes"] == 10
    assert p["compile"] == {"files": 0, "bytes": 0}
    # journals: _telemetry.jsonl + the gateway journal itself
    assert p["journals"]["files"] == 2
    t = use["tenants"]["acme"]
    assert t["upload_bytes"] == 300  # the dedup'd re-upload is excluded
    assert t["accepted"] == 1
    assert t["spool_bytes"] == 100  # done/r1.json priced via rid->tenant
    assert use["total_bytes"] == sum(v["bytes"] for v in p.values())


# -- planner safety rules ---------------------------------------------------

def test_plan_cache_lru_coldest_first_under_quota(tmp_path):
    cache = tmp_path / "cache"
    for i, age in enumerate((5000.0, 3000.0, 10.0)):
        _touch(cache / f"{i:02x}" / f"{i:02x}beef.pkl", 100, age_s=age)
    cfg = _cfg()
    # need 150 bytes back: the two coldest go, the hot entry survives
    dels = vgc.plan_cache(str(cache), cfg, time.time(), 150)
    assert [os.path.basename(d.path) for d in dels] == \
        ["00beef.pkl", "01beef.pkl"]
    assert all(d.plane == "cache" for d in dels)
    # no quota pressure, no retention: nothing planned
    assert vgc.plan_cache(str(cache), cfg, time.time(), 0) == []


def test_plan_cache_retention_expiry(tmp_path):
    cache = tmp_path / "cache"
    _touch(cache / "aa" / "aaold.pkl", 10, age_s=5000.0)
    _touch(cache / "bb" / "bbnew.pkl", 10, age_s=10.0)
    dels = vgc.plan_cache(str(cache), _cfg(cache_retention_s=1000.0),
                          time.time(), 0)
    assert [os.path.basename(d.path) for d in dels] == ["aaold.pkl"]
    assert "retention" in dels[0].reason


def test_plan_spool_never_deletes_a_claimable_response(tmp_path):
    root = tmp_path
    _touch(root / "done" / "r1.json", 10, age_s=5000.0)     # claimable!
    _touch(root / "done" / "r2.json", 10, age_s=5000.0)     # expirable
    _touch(root / "expired" / "r3.json", 10, age_s=10.0)    # too young
    _touch(root / "requests" / "r1.json", text=json.dumps({"id": "r1"}))
    dels = vgc.plan_spool(str(root), _cfg(spool_retention_s=1000.0),
                          time.time())
    assert [os.path.basename(d.path) for d in dels] == ["r2.json"]
    # a claimed/ file pins the rid exactly like requests/
    _touch(root / "claimed" / "hostX" / "r2.json",
           text=json.dumps({"id": "r2"}))
    assert vgc.plan_spool(str(root), _cfg(spool_retention_s=1000.0),
                          time.time()) == []


def test_plan_inbox_never_deletes_a_referenced_blob(tmp_path):
    root = tmp_path
    _touch(root / "inbox" / "blobA", 10, age_s=5000.0)  # referenced
    _touch(root / "inbox" / "blobB", 10, age_s=5000.0)  # orphaned
    _touch(root / "requests" / "r1.json", text=json.dumps(
        {"id": "r1", "video_paths": [str(root / "inbox" / "blobA")]}))
    dels = vgc.plan_inbox(str(root), _cfg(inbox_retention_s=1000.0),
                          time.time())
    assert [os.path.basename(d.path) for d in dels] == ["blobB"]


def test_plan_incidents_honors_pinned_marker(tmp_path):
    root = tmp_path
    _touch(root / "_incidents" / "keep" / "hb.json", 10)
    _touch(root / "_incidents" / "keep" / "pinned", 0)
    _touch(root / "_incidents" / "drop" / "hb.json", 10)
    for b in ("keep", "drop"):
        t = time.time() - 5000.0
        os.utime(root / "_incidents" / b, (t, t))
    dels = vgc.plan_incidents(str(root),
                              _cfg(incident_retention_s=1000.0),
                              time.time())
    assert [os.path.basename(d.path) for d in dels] == ["drop"]
    assert dels[0].is_dir


def test_plan_compile_pins_matching_env_fp(tmp_path):
    from video_features_tpu.compile_cache import env_fingerprint
    _env, fp = env_fingerprint()
    comp = tmp_path / "compile"

    def entry(key, env_fp, age_s):
        d = comp / "resnet" / key[:2] / key
        _touch(d / "_entry.json", text=json.dumps({"env_fp": env_fp}))
        _touch(d / "blob.bin", 100)
        t = time.time() - age_s
        os.utime(d, (t, t))

    entry("aa11", fp, 9000.0)        # this host's fingerprint: pinned
    entry("bb22", "ffff", 9000.0)    # foreign + idle: pruned
    entry("cc33", "ffff", 10.0)      # foreign but young: kept
    dels = vgc.plan_compile(str(comp), _cfg(compile_retention_s=1000.0),
                            time.time())
    assert [os.path.basename(d.path) for d in dels] == ["bb22"]
    assert dels[0].is_dir and dels[0].bytes > 0


def test_plan_staging_requires_done_marker(tmp_path):
    root = tmp_path
    _touch(root / "_queue" / ".staging" / "a.json",
           text=json.dumps({"id": "it-1"}))
    _touch(root / "_queue" / ".staging" / "b.json",
           text=json.dumps({"id": "it-2"}))
    for fn in ("a.json", "b.json"):
        p = root / "_queue" / ".staging" / fn
        t = time.time() - 5000.0
        os.utime(p, (t, t))
    _touch(root / "_queue" / "done" / "it-1.json",
           text=json.dumps({"id": "it-1", "status": "done"}))
    dels = vgc.plan_staging(str(root), _cfg(staging_retention_s=1000.0),
                            time.time())
    # it-2 has no done marker: unfinished work belongs to the queue's
    # own sweep, never to GC — only the completed remnant is planned
    assert [os.path.basename(d.path) for d in dels] == ["a.json"]


def test_plan_quarantine_expires_by_age(tmp_path):
    root = tmp_path
    _touch(root / "_queue" / "quarantined" / "old.json", 10, age_s=5000.0)
    _touch(root / "_queue" / "quarantined" / "new.json", 10, age_s=10.0)
    dels = vgc.plan_quarantine(str(root),
                               _cfg(quarantine_retention_s=1000.0),
                               time.time())
    assert [os.path.basename(d.path) for d in dels] == ["old.json"]


def test_plan_quota_pressure_only_touches_cache(tmp_path):
    """Quota overflow is resolved against the recoverable plane only —
    spool/inbox/incident responses are never sacrificed to a byte
    target."""
    root = tmp_path / "root"
    cache = tmp_path / "cache"
    comp = tmp_path / "compile"
    _touch(cache / "aa" / "aadead.pkl", 4000, age_s=100.0)
    _touch(root / "done" / "r1.json", 4000, age_s=100.0)
    _touch(root / "inbox" / "blob", 4000, age_s=100.0)
    cfg = vgc.GcConfig(quota_gb=1e-6)  # 1000 bytes: far over quota
    dels = vgc.plan(str(root), cfg, cache_dir=str(cache),
                    compile_dir=str(comp))
    assert {d.plane for d in dels} == {"cache"}


# -- journaled execution ----------------------------------------------------

def test_execute_journals_before_unlink(tmp_path):
    root = tmp_path
    victim = _touch(root / "done" / "r9.json", 64, age_s=5000.0)
    dels = vgc.plan_spool(str(root), _cfg(spool_retention_s=1000.0),
                          time.time())
    tally = vgc.execute(str(root), dels, host_id="testhost")
    assert not victim.exists()
    assert tally == {"spool": {"deleted": 1, "bytes": 64, "errors": 0}}
    jpath = root / vgc.journal_filename("testhost")
    recs = list(tjsonl.read_jsonl(jpath))
    assert len(recs) == 1
    r = recs[0]
    assert r["schema"] == vgc.GC_JOURNAL_SCHEMA
    assert r["event"] == "evict" and r["plane"] == "spool"
    assert r["path"] == str(victim) and r["bytes"] == 64
    # re-executing the same plan converges silently (FileNotFoundError
    # = a sibling GC or the owner got there first)
    tally2 = vgc.execute(str(root), dels, host_id="testhost")
    assert tally2["spool"]["errors"] == 0


def test_journal_remnant_is_a_recoverable_audit_note(tmp_path):
    """A journaled-but-present path = the GC died in the crash window.
    vft-audit notes it; completing the delete clears the note."""
    root = tmp_path
    victim = _touch(root / "done" / "r1.json", 32, age_s=5000.0)
    d = vgc.Deletion("spool", str(victim), 32, "test remnant")
    append_jsonl(str(root / vgc.journal_filename("h1")),
                 vgc._journal_record(d, str(root), "h1"))
    ok, violations, notes = audit_run(str(root))
    assert ok and not violations
    assert any("gc-journaled" in n for n in notes)
    victim.unlink()
    ok, violations, notes = audit_run(str(root))
    assert ok and not any("gc-journaled" in n for n in notes)


def test_audit_fails_deleted_but_still_referenced(tmp_path):
    """The states the safety rules promise cannot happen: a deleted
    spool response whose request is claimable again, a deleted inbox
    blob a live request references."""
    root = tmp_path
    _touch(root / "requests" / "r1.json", text=json.dumps(
        {"id": "r1", "video_paths": [str(root / "inbox" / "blobZ")]}))
    jp = str(root / vgc.journal_filename("h1"))
    append_jsonl(jp, vgc._journal_record(
        vgc.Deletion("spool", str(root / "done" / "r1.json"), 1, "bad"),
        str(root), "h1"))
    append_jsonl(jp, vgc._journal_record(
        vgc.Deletion("inbox", str(root / "inbox" / "blobZ"), 1, "bad"),
        str(root), "h1"))
    ok, violations, _notes = audit_run(str(root))
    assert not ok
    assert any("claimable" in v for v in violations)
    assert any("still referenced" in v for v in violations)


def test_sweep_dry_run_deletes_nothing(tmp_path):
    root = tmp_path / "root"
    cache = tmp_path / "cache"
    comp = tmp_path / "compile"
    victim = _touch(cache / "aa" / "aa.pkl", 100, age_s=5000.0)
    res = vgc.sweep(str(root), _cfg(cache_retention_s=1000.0),
                    cache_dir=str(cache), compile_dir=str(comp),
                    dry_run=True)
    assert res["planned"] == 1 and res["planned_bytes"] == 100
    assert res["executed"] == {} and res["dry_run"]
    assert victim.exists()
    assert not list(Path(root).glob(vgc.GC_JOURNAL_GLOB))
    lines = "\n".join(vgc.render_report(res))
    assert "dry run" in lines and "== usage ==" in lines


# -- monitor + heartbeat section --------------------------------------------

def test_monitor_caches_walks_on_interval(tmp_path):
    root = tmp_path / "root"
    cache = tmp_path / "cache"
    comp = tmp_path / "compile"
    _touch(cache / "aa" / "aa.pkl", 100)
    clk = Clock()
    mon = vgc.GcMonitor(str(root), vgc.GcConfig(quota_gb=1.0,
                                                interval_s=60.0),
                        cache_dir=str(cache), compile_dir=str(comp),
                        clock=clk)
    sec = mon.section()
    assert sec["used_bytes"] == 100
    assert sec["quota_bytes"] == int(1e9)
    assert sec["planes"]["cache"] == 100
    # inside the interval the cached snapshot is republished — the
    # heartbeat cadence never pays a tree walk
    _touch(cache / "bb" / "bb.pkl", 50)
    assert mon.section()["used_bytes"] == 100
    clk.t += 61.0
    assert mon.section()["used_bytes"] == 150


def test_monitor_attach_publishes_gauges(tmp_path):
    from video_features_tpu.telemetry.recorder import TelemetryRecorder
    root = tmp_path / "root"
    cache = tmp_path / "cache"
    comp = tmp_path / "compile"
    root.mkdir()
    _touch(cache / "aa" / "aa.pkl", 100)
    rec = TelemetryRecorder(str(root))
    mon = vgc.GcMonitor(str(root), vgc.GcConfig(quota_gb=1.0),
                        cache_dir=str(cache), compile_dir=str(comp)
                        ).attach(rec)
    assert rec.extra_sections["gc"] == mon.section
    mon.snapshot()
    assert rec.registry.gauge("vft_gc_used_bytes").value == 100
    assert rec.registry.gauge("vft_gc_quota_bytes").value == int(1e9)
    assert rec.registry.gauge("vft_gc_plane_bytes",
                              plane="cache").value == 100


def test_cli_one_shot_json(tmp_path, capsys):
    root = tmp_path / "root"
    cache = tmp_path / "cache"
    comp = tmp_path / "compile"
    root.mkdir()
    _touch(cache / "aa" / "aa.pkl", 64, age_s=5000.0)
    rc = vgc.main([str(root), "--cache-dir", str(cache),
                   "--compile-dir", str(comp),
                   "--cache-retention-s", "1000", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["planned"] == 1
    assert out["executed"]["cache"]["deleted"] == 1
    assert not (cache / "aa" / "aa.pkl").exists()


def test_cli_rejects_bad_flags(tmp_path):
    with pytest.raises(ValueError, match="gc_quota_gb"):
        vgc.main([str(tmp_path), "--quota-gb", "-1"])


# -- satellite: telemetry writers degrade on ENOSPC -------------------------

def _enospc(*_a, **_k):
    raise OSError(errno.ENOSPC, "No space left on device")


def test_emit_span_enospc_disables_pillar_once(tmp_path, monkeypatch,
                                               capsys):
    from video_features_tpu.telemetry.recorder import TelemetryRecorder
    rec = TelemetryRecorder(str(tmp_path))
    monkeypatch.setattr(tjsonl, "append_jsonl", _enospc)
    rec.emit_span({"status": "done", "wall_s": 1.0})
    rec.emit_span({"status": "done", "wall_s": 1.0})
    assert rec._spans_disabled
    assert rec.registry.counter("vft_telemetry_write_failures_total",
                                pillar="spans").value == 1
    out = capsys.readouterr().out
    assert out.count("span channel disabled") == 1
    # the in-memory pillars keep flowing after the latch
    assert rec.registry.counter("vft_videos_total",
                                status="done").value == 2


def test_history_writer_enospc_disables(tmp_path, monkeypatch, capsys):
    from video_features_tpu.telemetry.history import HistoryWriter
    from video_features_tpu.telemetry.recorder import TelemetryRecorder
    rec = TelemetryRecorder(str(tmp_path))
    telemetry._set_active(rec)
    try:
        hw = HistoryWriter(str(tmp_path), "h1")
        monkeypatch.setattr(tjsonl, "append_jsonl", _enospc)
        hw.observe({"time": 1.0})
        hw.observe({"time": 2.0})
        assert hw._disabled
        assert rec.registry.counter(
            "vft_telemetry_write_failures_total",
            pillar="history").value == 1
        assert capsys.readouterr().out.count(
            "history retention disabled") == 1
    finally:
        telemetry._set_active(None)


def test_trace_close_enospc_never_raises(tmp_path, monkeypatch, capsys):
    from video_features_tpu.telemetry.trace import TraceRecorder
    tr = TraceRecorder(str(tmp_path), host_id="h1")
    monkeypatch.setattr(tjsonl, "write_json_atomic", _enospc)
    assert tr.close() is None  # degraded, not raised into the finally
    assert "failed to write" in capsys.readouterr().out
    assert not list(Path(tmp_path).glob("_trace*"))


# -- satellite: weights .part litter sweep ----------------------------------

def test_sweep_stale_parts(tmp_path):
    from video_features_tpu.weights.store import sweep_stale_parts
    stale = _touch(tmp_path / "resnet50.npz.abc123.part", 10,
                   age_s=7200.0)
    fresh = _touch(tmp_path / "clip.npz.def456.part", 10, age_s=60.0)
    done = _touch(tmp_path / "resnet50.npz", 10, age_s=7200.0)
    assert sweep_stale_parts(tmp_path) == 1
    assert not stale.exists()
    assert fresh.exists()  # a concurrent fetcher may still be streaming
    assert done.exists()   # promoted checkpoints are never litter
    assert sweep_stale_parts(tmp_path) == 0  # idempotent
    assert sweep_stale_parts(tmp_path / "missing") == 0


# -- satellite: bench-history compaction ------------------------------------

def test_bench_history_compaction_tiers(tmp_path):
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    import bench_history as bh
    path = str(tmp_path / "BENCH_history.jsonl")
    now = 2_000_000_000.0
    day = 86400.0
    # 10 recent daily rounds + 30 old 6-hourly rounds (the mid tier
    # keeps one per day) + 2 ancient rounds past the final tier
    ages = [i * day for i in range(10)] \
        + [40 * day + i * day / 4 for i in range(30)] \
        + [800 * day, 900 * day]
    for i, age in enumerate(ages):
        append_jsonl(path, {"schema": bh.SCHEMA_VERSION, "round": i,
                            "source": f"r{i}", "recorded_time": now - age,
                            "headline": {"metric": "m", "value": 1.0},
                            "metrics": []})
    kept = bh.compact_history(path, now=now)
    rows = bh.load_history(path)
    assert kept == len(rows)
    times = [r["recorded_time"] for r in rows]
    # recent tier: everything survives; ancient: dropped entirely
    assert sum(1 for t in times if now - t < 30 * day) == 10
    assert all(now - t <= 730 * day for t in times)
    # mid tier: 30 quarter-day rounds collapse to ~one per day
    mid = [t for t in times if 30 * day <= now - t <= 180 * day]
    assert 7 <= len(mid) <= 9
    # the records keep the bench schema (no leaked "time" shim key)
    assert all("time" not in r for r in rows)
    assert bh.compact_history(path, now=now) == kept  # idempotent
