"""ResNet: Flax-vs-torch parity (random transplanted weights) and E2E shape.

The torch oracle is a minimal torchvision-equivalent ResNet defined in
tests/torch_oracles.py (torchvision itself is not installed here); weight
transplant goes through the production converter
(video_features_tpu.models.resnet.params_from_torch), so this validates both
the architecture and the converter.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import resnet as rn  # noqa: E402
from tests.torch_oracles import TorchResNet  # noqa: E402

pytestmark = pytest.mark.quick


@pytest.mark.parametrize("variant", ["resnet18", "resnet50"])
def test_flax_matches_torch_oracle(variant):
    torch.manual_seed(0)
    oracle = TorchResNet(variant).eval()
    # randomize BN stats too: catches mean/var mapping bugs
    for m in oracle.modules():
        if isinstance(m, torch.nn.BatchNorm2d):
            m.running_mean.uniform_(-0.5, 0.5)
            m.running_var.uniform_(0.5, 1.5)

    params = rn.params_from_torch(oracle.state_dict())
    model = rn.ResNet(variant)

    x = np.random.default_rng(0).normal(size=(2, 224, 224, 3)).astype(np.float32)
    with torch.no_grad():
        want_feats = oracle(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    got = np.asarray(model.apply({"params": params["backbone"]}, jnp.asarray(x)))
    assert got.shape == want_feats.shape == (2, rn.FEATURE_DIMS[variant])
    np.testing.assert_allclose(got, want_feats, atol=2e-4, rtol=2e-4)


def test_classifier_head_matches(rng):
    torch.manual_seed(1)
    oracle = TorchResNet("resnet18").eval()
    params = rn.params_from_torch(oracle.state_dict())
    feats = rng.normal(size=(3, 512)).astype(np.float32)
    with torch.no_grad():
        want = oracle.fc(torch.from_numpy(feats)).numpy()
    got = np.asarray(rn.Classifier().apply({"params": params["head"]},
                                           jnp.asarray(feats)))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_end_to_end_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.resnet import ExtractResNet

    cfg = load_config("resnet", {
        "video_paths": sample_video, "device": "cpu", "batch_size": 16,
        "extraction_fps": 4, "model_name": "resnet18",
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractResNet(cfg)
    feats = ex._extract(sample_video)
    n = feats["resnet"].shape[0]
    assert feats["resnet"].shape == (n, 512)
    assert feats["timestamps_ms"].shape == (n,)
    assert float(feats["fps"]) == 4.0
    assert 70 <= n <= 75  # ~18.1s at 4fps
    # written files exist and a second run skips (idempotent resume)
    assert ex._extract(sample_video) is None
