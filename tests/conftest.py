"""Test configuration: force an 8-device virtual CPU mesh.

Must run before the first `import jax` anywhere in the test process, so the
env vars are set at conftest import time. Multi-chip sharding is validated on
this virtual mesh (no multi-chip TPU hardware in CI); the single real TPU chip
is exercised by bench.py instead.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# Hard-pin the CPU backend: site customizations on some hosts re-point
# jax_platforms at an accelerator plugin after env vars are read, so the env
# var alone is not enough. Tests must never claim the real TPU chip.
jax.config.update("jax_platforms", "cpu")

# full-fp32 conv/matmul accumulation: parity tests compare against torch CPU
jax.config.update("jax_default_matmul_precision", "highest")

REFERENCE_ROOT = "/root/reference"
SAMPLE_VIDEO = os.path.join(REFERENCE_ROOT, "sample", "v_GGSY1Qvo990.mp4")


def _synthesize_sample(path: str) -> str:
    """A stand-in with the reference sample's nominal properties (355 frames,
    19.62 fps, 320x240) so the E2E/CLI tests run on hosts without the
    reference mount (e.g. external CI). Smooth moving gradients: natural-ish
    low-frequency content that codecs and the yuv420 paths handle like real
    video, not noise."""
    import cv2
    w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"),
                        19.62, (320, 240))
    if not w.isOpened():  # degrade to the old skip, not a hard error
        pytest.skip("reference sample absent and cv2 cannot encode mp4v")
    yy, xx = np.mgrid[0:240, 0:320].astype(np.float32)
    for t in range(355):
        frame = np.stack([
            127 + 120 * np.sin(xx / 40 + t / 9),
            127 + 120 * np.sin(yy / 30 - t / 13),
            127 + 120 * np.sin((xx + yy) / 50 + t / 7),
        ], axis=-1)
        w.write(frame.clip(0, 255).astype(np.uint8))
    w.release()
    return path


#: committed copy of the synthesized stand-in (same nominal properties as
#: the reference sample), so the repo is test-self-contained without the
#: mount and without an encode-capable cv2 at test time
VENDORED_SAMPLE = os.path.join(os.path.dirname(__file__), "assets",
                               "v_synth_sample.mp4")


@pytest.fixture(scope="session")
def sample_video(tmp_path_factory):
    # VFT_FORCE_SYNTH_SAMPLE=1 exercises the synthesis path even when the
    # reference mount / vendored clip exists (validates the fallback itself)
    force = os.environ.get("VFT_FORCE_SYNTH_SAMPLE", "") not in ("", "0")
    if force:
        return _synthesize_sample(
            str(tmp_path_factory.mktemp("sample") / "v_synth_sample.mp4"))
    if os.path.exists(SAMPLE_VIDEO):
        return SAMPLE_VIDEO
    if os.path.exists(VENDORED_SAMPLE):
        return VENDORED_SAMPLE
    if os.environ.get("VFT_NO_SYNTH_SAMPLE"):
        pytest.skip("reference sample video not available")
    return _synthesize_sample(
        str(tmp_path_factory.mktemp("sample") / "v_synth_sample.mp4"))


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
