"""Test configuration: force an 8-device virtual CPU mesh.

Must run before the first `import jax` anywhere in the test process, so the
env vars are set at conftest import time. Multi-chip sharding is validated on
this virtual mesh (no multi-chip TPU hardware in CI); the single real TPU chip
is exercised by bench.py instead.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# Hard-pin the CPU backend: site customizations on some hosts re-point
# jax_platforms at an accelerator plugin after env vars are read, so the env
# var alone is not enough. Tests must never claim the real TPU chip.
jax.config.update("jax_platforms", "cpu")

# full-fp32 conv/matmul accumulation: parity tests compare against torch CPU
jax.config.update("jax_default_matmul_precision", "highest")

REFERENCE_ROOT = "/root/reference"
SAMPLE_VIDEO = os.path.join(REFERENCE_ROOT, "sample", "v_GGSY1Qvo990.mp4")


@pytest.fixture(scope="session")
def sample_video():
    if not os.path.exists(SAMPLE_VIDEO):
        pytest.skip("reference sample video not available")
    return SAMPLE_VIDEO


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
