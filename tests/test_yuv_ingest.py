"""Raw-YUV ingest (PR 6): packed-I420 decode->device wire at 1.5 B/px.

Contracts pinned here:

  - ``channel_order='i420'`` delivery is bit-identical between a private
    ``VideoSource`` and a FrameBus shared-decode subscription (packed
    frames ride the union pass like any other order, converted at most
    once per source frame);
  - a shared-decode multi-family CLI run with ``ingest=yuv420`` produces
    BIT-IDENTICAL outputs to the corresponding single-family runs at
    ``video_workers`` 1 and 2 — the raw-I420 frame-wise wire (resnet:
    full-res planes, colorspace+resize fused on device) and the
    host-packed clip-stack wire (r21d: 112px crops packed after the host
    transform) both covered;
  - the raw-I420 device path reproduces the raw-BGR (``ingest=uint8``)
    device-resize path's features on natural frames within the chroma
    subsampling envelope (cosine > 0.999) for resnet AND clip — the
    wire carries half the bytes, the features stay put;
  - odd-dimension sources fall back to the BGR wire instead of failing.
"""
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.parallel.fanout import FrameBus
from video_features_tpu.utils.io import VideoSource


@pytest.mark.quick
def test_bus_i420_bit_identical_to_private_source(sample_video):
    """FrameBus 'i420' subscribers get the exact packed planes a private
    VideoSource would decode, alongside rgb/bgr siblings."""
    specs = {
        "a": dict(fps=2, transform=None, channel_order="i420"),
        "b": dict(fps=1, transform=None, channel_order="bgr"),
        "c": dict(total=5, transform=None, channel_order="rgb"),
    }
    bus = FrameBus(sample_video, list(specs), depth=8)
    got, errs = {}, []

    def consume(name, kw):
        try:
            sub = bus.subscribe(name, **kw)
            got[name] = list(sub.frames())
        except BaseException as e:
            errs.append((name, e))

    threads = [threading.Thread(target=consume, args=(n, kw))
               for n, kw in specs.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    for name, kw in specs.items():
        want = list(VideoSource(sample_video, **kw).frames())
        assert len(got[name]) == len(want), name
        for (xw, tw, iw), (xg, tg, ig) in zip(want, got[name]):
            assert (tw, iw) == (tg, ig), name
            np.testing.assert_array_equal(xw, xg, err_msg=name)
    # the i420 wire really is the compressed one: 1.5 B/px vs 3
    h, w = got["b"][0][0].shape[:2]
    assert got["a"][0][0].shape == (h * 3 // 2, w)


def _cli(args, cwd):
    res = subprocess.run([sys.executable, "main.py"] + args, cwd=cwd,
                         capture_output=True, text=True, timeout=900)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]


REPO = str(Path(__file__).resolve().parent.parent)

#: cheap per-family budgets (1-core CI host); both families speak yuv420 —
#: resnet ships raw full-res I420 (resize=device via the auto default),
#: r21d packs its 112px crops host-side (clip-stack keeps host resize)
OVERRIDES = ["resnet.model_name=resnet18", "resnet.batch_size=8",
             "resnet.extraction_total=6", "r21d.extraction_fps=1",
             "r21d.stack_size=10", "r21d.step_size=10"]


@pytest.mark.parametrize("workers", [
    1,
    # ~43s each: one worker count is enough for the quick tier; the
    # threaded variant still runs in the full (slow-inclusive) suite
    pytest.param(2, marks=pytest.mark.slow),
])
def test_yuv420_shared_decode_bit_identical_to_singles(tmp_path,
                                                       sample_video,
                                                       workers):
    base = ["device=cpu", "allow_random_weights=true", "ingest=yuv420",
            "on_extraction=save_numpy", "retry_attempts=1",
            f"tmp_path={tmp_path / 'tmp'}", f"video_paths={sample_video}",
            ] + OVERRIDES
    for fam in ("resnet", "r21d"):
        single = [f"feature_type={fam}", "video_workers=1",
                  f"output_path={tmp_path / 'single'}"]
        # single-family overrides flatten (fam.key= -> key=)
        single += [o.split(".", 1)[1] for o in OVERRIDES
                   if o.startswith(f"{fam}.")]
        _cli(single + [a for a in base if "." not in a.split("=")[0]], REPO)
    _cli([f"feature_type=resnet,r21d", f"video_workers={workers}",
          f"output_path={tmp_path / 'multi'}"] + base, REPO)

    singles = sorted(p.relative_to(tmp_path / "single")
                     for p in (tmp_path / "single").rglob("*.npy"))
    multis = sorted(p.relative_to(tmp_path / "multi")
                    for p in (tmp_path / "multi").rglob("*.npy"))
    assert singles == multis and singles, (singles, multis)
    for rel in singles:
        np.testing.assert_array_equal(
            np.load(tmp_path / "single" / rel),
            np.load(tmp_path / "multi" / rel), err_msg=str(rel))


@pytest.mark.parametrize("family", ["resnet", "clip"])
def test_raw_i420_wire_matches_bgr_wire(tmp_path, sample_video, family):
    """resize=device (the save-run default): ingest=yuv420's fused
    I420->RGB->resize program reproduces the raw-BGR wire's features
    within the 4:2:0 chroma envelope on natural frames."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.registry import get_extractor_cls

    def run(mode, sub):
        cfg = load_config(family, {
            "video_paths": sample_video, "device": "cpu", "batch_size": 8,
            "extraction_total": 4, "ingest": mode,
            "on_extraction": "save_numpy", "allow_random_weights": True,
            "output_path": str(tmp_path / sub / "o"),
            "tmp_path": str(tmp_path / sub / "t"),
        })
        if family == "resnet":
            cfg.model_name = "resnet18"
        sanity_check(cfg)
        ex = get_extractor_cls(family)(cfg)
        assert ex.resize_mode == "device"  # the flipped default
        return ex.extract(sample_video)[family]

    ref = run("uint8", "u8")
    got = run("yuv420", "yuv")
    assert got.shape == ref.shape and ref.shape[0] > 0
    cos = np.sum(ref * got, axis=1) / (
        np.linalg.norm(ref, axis=1) * np.linalg.norm(got, axis=1) + 1e-9)
    assert np.all(cos > 0.999), f"{family} raw-I420 diverged: cos={cos}"


def test_odd_dimension_source_falls_back_to_bgr(tmp_path, sample_video,
                                                monkeypatch, capsys):
    """An odd-dimension source cannot pack I420; the video ships raw BGR
    instead (same features, wider wire) rather than failing. Odd-width
    mp4s can't be synthesized here (cv2's writer rounds the geometry
    down), so the probe is patched to REPORT odd dims — which exercises
    exactly the decision point the fallback lives on."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.resnet import ExtractResNet
    from video_features_tpu.utils import io as vio

    real_props = vio.get_video_props
    monkeypatch.setattr(
        vio, "get_video_props",
        lambda p: {**real_props(p), "width": real_props(p)["width"] - 1})

    cfg = load_config("resnet", {
        "video_paths": sample_video, "device": "cpu", "batch_size": 4,
        "extraction_total": 4, "model_name": "resnet18",
        "ingest": "yuv420", "on_extraction": "save_numpy",
        "allow_random_weights": True,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    sanity_check(cfg)
    ex = ExtractResNet(cfg)
    assert ex.resize_mode == "device"
    assert ex._wire_order(sample_video) == "bgr"
    assert "odd dimensions" in capsys.readouterr().out
    # the full extract rides the BGR fallback wire (decode still yields
    # the real even-geometry frames; only the wire decision was odd)
    feats = ex.extract(sample_video)["resnet"]
    assert feats.shape[0] == 4 and np.isfinite(feats).all()
