"""Fault-tolerance runtime: taxonomy, backoff, deadline watchdog, decode
degradation ladder, persistent failure journal (utils/faults.py +
sinks.safe_extract).

Tier-1 discipline: the retry tests inject ``sleep``/``clock`` so no real
backoff is ever slept; the watchdog tests use sub-second deadlines.
"""
import json
import threading
import time

import numpy as np
import pytest

from video_features_tpu.utils import faults, sinks
from video_features_tpu.utils.faults import (FailureJournal, FaultContext,
                                             DeadlineExceeded, RetryPolicy)

pytestmark = pytest.mark.quick


# ---------------------------------------------------------------- taxonomy

@pytest.mark.parametrize("exc,want", [
    (DeadlineExceeded("v: deadline"), faults.TRANSIENT),
    (OSError("NFS hiccup"), faults.TRANSIENT),
    (MemoryError(), faults.TRANSIENT),
    (RuntimeError("decode worker for v died without a result (killed?)"),
     faults.TRANSIENT),
    (RuntimeError("spawn failed"), faults.TRANSIENT),
    (ValueError("Cannot determine fps of v.mp4"), faults.POISON),
    (ValueError("No decodable frames in v.mp4"), faults.POISON),
    (RuntimeError("decode worker failed for v: ValueError: bad header"),
     faults.POISON),
    (faults.PoisonError("marked"), faults.POISON),
    (NotImplementedError("on_extraction: bogus"), faults.FATAL),
    (AssertionError("stack_size"), faults.FATAL),
    (TypeError("bad transform"), faults.FATAL),
    (faults.FatalError("marked"), faults.FATAL),
])
def test_classify(exc, want):
    assert faults.classify(exc) == want


def test_classify_unknown_defaults_transient():
    class Weird(Exception):
        pass
    assert faults.classify(Weird("?")) == faults.TRANSIENT


def test_classify_disk_full_errnos_are_fatal():
    """ENOSPC/EDQUOT/EROFS must classify FATAL, not TRANSIENT: retrying
    a full disk burns the whole retry budget plus backoff wall-clock per
    video — one full disk would otherwise become a slow fleet-wide hang
    (ISSUE 9 satellite). A plain EIO stays TRANSIENT (NFS blips clear)."""
    import errno
    for code in ("ENOSPC", "EDQUOT", "EROFS"):
        exc = OSError(getattr(errno, code), f"synthetic {code}")
        assert faults.classify(exc) == faults.FATAL, code
    assert faults.classify(OSError(errno.EIO, "blip")) == faults.TRANSIENT
    assert faults.classify(OSError("errno-less oserror")) == faults.TRANSIENT


def test_classify_forwarded_disk_full_is_fatal():
    """The decode-worker protocol forwards child exceptions as strings
    (utils/io.py, parallel/fanout.py); str(OSError) keeps the strerror,
    and the forwarded form must reach the same FATAL verdict."""
    fwd = RuntimeError("OSError: [Errno 28] No space left on device: 'x'")
    assert faults.classify(fwd) == faults.FATAL
    fwd = RuntimeError("shared decode failed for v.mp4: OSError: "
                       "[Errno 122] Disk quota exceeded")
    assert faults.classify(fwd) == faults.FATAL
    # an injected-EIO forwarded error must NOT harden into FATAL
    fwd = RuntimeError("OSError: [Errno 5] injected EIO at decode.read")
    assert faults.classify(fwd) == faults.TRANSIENT


def test_ladder_order():
    assert faults.demote("parallel") == "process"
    assert faults.demote("process") == "inline"
    assert faults.demote("inline") is None
    assert faults.demote(None) is None


# ----------------------------------------------------------- retry policy

def test_backoff_schedule_doubles_and_caps():
    pol = RetryPolicy(attempts=6, backoff_s=0.5, backoff_cap_s=3.0,
                      jitter=0.0)
    assert [pol.backoff_delay(k) for k in range(1, 6)] == \
        [0.5, 1.0, 2.0, 3.0, 3.0]


def test_backoff_jitter_bounds():
    pol = RetryPolicy(attempts=2, backoff_s=1.0, jitter=0.25)
    delays = [pol.backoff_delay(1) for _ in range(50)]
    assert all(1.0 <= d <= 1.25 for d in delays)
    assert len(set(delays)) > 1  # actually jittered

def test_policy_from_config_and_validation():
    pol = RetryPolicy.from_config({})
    assert pol.attempts == 1 and pol.deadline_s is None
    pol = RetryPolicy.from_config(
        {"retry_attempts": 4, "retry_backoff_s": 0.1,
         "video_deadline_s": 30, "retry_failed": True})
    assert (pol.attempts, pol.backoff_s, pol.deadline_s,
            pol.retry_failed) == (4, 0.1, 30.0, True)
    with pytest.raises(ValueError):
        RetryPolicy(attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(deadline_s=0)


def test_transient_failure_recovers_on_retry(capsys):
    """Injected transient decode failures succeed on retry; the backoff
    schedule is honored (injected sleep — no real waiting) and the
    success path reports the attempt count (journal-free)."""
    sleeps = []
    pol = RetryPolicy(attempts=3, backoff_s=0.5, jitter=0.0,
                      sleep=sleeps.append, clock=lambda: 0.0)
    calls = []

    def flaky(path):
        calls.append(path)
        if len(calls) < 3:
            raise OSError("ffmpeg blip")
        return {"x": 1}

    assert sinks.safe_extract(flaky, "v.mp4", policy=pol) == "done"
    assert len(calls) == 3
    assert sleeps == [0.5, 1.0]
    assert 'Recovered "v.mp4" on attempt 3/3' in capsys.readouterr().out


def test_poison_quarantined_after_exact_attempts(tmp_path):
    """A poison input is retried exactly ``retry_attempts`` times, then
    journaled with category=POISON; a restarted worker skips it without
    calling the extractor; retry_failed=true re-runs it and a success
    lifts the quarantine."""
    journal = FailureJournal(tmp_path)
    pol = RetryPolicy(attempts=3, backoff_s=0.0, jitter=0.0,
                      sleep=lambda s: None, clock=lambda: 0.0)
    calls = []

    def poison(path):
        calls.append(path)
        raise ValueError(f"Cannot determine fps of {path}")

    assert sinks.safe_extract(poison, "bad.mp4", policy=pol,
                              journal=journal) == "error"
    assert len(calls) == 3

    recs = [json.loads(l) for l in open(journal.path) if l.strip()]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["video"] == "bad.mp4"
    assert rec["category"] == faults.POISON
    assert rec["attempts"] == 3
    assert "Cannot determine fps" in rec["error"]
    assert rec["host"]  # hostname recorded for fleet triage
    assert "elapsed_s" in rec

    # restart: known-poison input is skipped, extractor never called
    assert sinks.safe_extract(poison, "bad.mp4", policy=pol,
                              journal=journal) == "quarantined"
    assert len(calls) == 3

    # retry_failed=true: re-runs; success appends RESOLVED (last wins)
    pol_rf = RetryPolicy(attempts=1, retry_failed=True)
    assert sinks.safe_extract(lambda p: {"x": 1}, "bad.mp4", policy=pol_rf,
                              journal=journal) == "done"
    assert journal.poison_record("bad.mp4") is None
    assert sinks.safe_extract(lambda p: {"x": 1}, "bad.mp4", policy=pol,
                              journal=journal) == "done"  # stays lifted


def test_fatal_fails_without_retry(tmp_path):
    journal = FailureJournal(tmp_path)
    pol = RetryPolicy(attempts=5, backoff_s=0.0, sleep=lambda s: None,
                      clock=lambda: 0.0)
    calls = []

    def broken_config(path):
        calls.append(path)
        raise NotImplementedError("resize='bogus'")

    assert sinks.safe_extract(broken_config, "v.mp4", policy=pol,
                              journal=journal) == "error"
    assert len(calls) == 1  # retrying a config error cannot help
    rec = journal.load()["v.mp4"]
    assert rec["category"] == faults.FATAL and rec["attempts"] == 1
    # FATAL terminal records do NOT quarantine on resume (the config may
    # have been fixed between runs)
    assert journal.poison_record("v.mp4") is None


def test_transient_terminal_failure_does_not_quarantine(tmp_path):
    journal = FailureJournal(tmp_path)
    pol = RetryPolicy(attempts=2, backoff_s=0.0, sleep=lambda s: None,
                      clock=lambda: 0.0)
    calls = []

    def down(path):
        calls.append(path)
        raise OSError("mount gone")

    assert sinks.safe_extract(down, "v.mp4", policy=pol,
                              journal=journal) == "error"
    assert journal.load()["v.mp4"]["category"] == faults.TRANSIENT
    # a restarted worker re-attempts it (the environment may be healthy)
    assert sinks.safe_extract(down, "v.mp4", policy=pol,
                              journal=journal) == "error"
    assert len(calls) == 4


def test_default_policy_matches_legacy_single_shot():
    calls = []

    def bad(path):
        calls.append(path)
        raise RuntimeError("decode failed")

    assert sinks.safe_extract(bad, "v.mp4") == "error"
    assert calls == ["v.mp4"]
    assert sinks.safe_extract(lambda p: {"x": 1}, "v.mp4") == "done"
    assert sinks.safe_extract(lambda p: None, "v.mp4") == "skipped"


# ---------------------------------------------------------------- journal

def test_journal_atomic_append_and_corrupt_line_tolerance(tmp_path):
    journal = FailureJournal(tmp_path)
    journal.record("a.mp4", faults.POISON, 3, "bad", 1.0)
    # a torn append from a SIGKILLed worker must not poison the reader
    with open(journal.path, "a") as f:
        f.write('{"video": "torn.mp4", "categ')
    journal2 = FailureJournal(tmp_path)  # fresh reader (restart)
    loaded = journal2.load()
    assert set(loaded) == {"a.mp4"}
    assert journal2.poison_record("a.mp4")["attempts"] == 3
    # appends still line-atomic afterwards
    journal2.record("b.mp4", faults.TRANSIENT, 1, "x", 0.1)
    assert set(FailureJournal(tmp_path).load()) == {"a.mp4", "b.mp4"}


def test_journal_last_record_wins(tmp_path):
    journal = FailureJournal(tmp_path)
    journal.record("v.mp4", faults.TRANSIENT, 1, "first", 0.1)
    journal.record("v.mp4", faults.POISON, 3, "second", 0.2)
    assert journal.load()["v.mp4"]["error"] == "second"
    assert journal.poison_record("v.mp4") is not None
    journal.resolve("v.mp4")
    assert journal.poison_record("v.mp4") is None
    assert journal.tally_by_category() == {}  # RESOLVED not tallied


def test_journal_missing_file_is_empty(tmp_path):
    journal = FailureJournal(tmp_path / "nonexistent")
    assert journal.load() == {}
    assert journal.poison_record("v.mp4") is None


# ------------------------------------------------------- deadline watchdog

def test_deadline_kills_hung_video_and_run_continues(tmp_path):
    """Acceptance: a deliberately hung decode is killed by
    video_deadline_s while the remaining videos in the same run complete
    successfully — the worker thread survives, only the hung video fails,
    and its journal record says so."""
    journal = FailureJournal(tmp_path)
    pol = RetryPolicy(attempts=1, deadline_s=0.2)

    class _HangingSource:
        """Stands in for a decode blocked inside cv2: only the
        watchdog's cancel() can unblock it."""

        def __init__(self):
            self.unblocked = threading.Event()
            self.reason = None

        def cancel(self, reason=""):
            self.reason = reason
            self.unblocked.set()

    def extract(path):
        if path == "hang.mp4":
            src = _HangingSource()
            faults.current_context().register(src)
            assert src.unblocked.wait(timeout=10), "watchdog never fired"
            raise DeadlineExceeded(src.reason)
        return {"ok": np.ones(1)}

    t0 = time.monotonic()
    statuses = [sinks.safe_extract(extract, v, policy=pol, journal=journal)
                for v in ("a.mp4", "hang.mp4", "c.mp4")]
    assert statuses == ["done", "error", "done"]
    assert time.monotonic() - t0 < 5.0  # killed at ~0.2s, not hung
    rec = journal.load()["hang.mp4"]
    assert rec["category"] == faults.TRANSIENT
    assert "deadline" in rec["error"]


def test_deadline_cancels_real_videosource(sample_video):
    """The watchdog's thread-safe cancel() on a live VideoSource makes
    the iterating thread raise DeadlineExceeded instead of yielding a
    silently-truncated stream."""
    from video_features_tpu.utils.io import VideoSource
    src = VideoSource(sample_video, batch_size=4)
    n = 0
    with FaultContext("v", deadline_s=0.15) as ctx:
        ctx.register(src)
        with pytest.raises(DeadlineExceeded):
            for batch, _, _ in src:
                n += len(batch)
                time.sleep(0.01)  # a slow consumer; decode outlives 0.15s
    assert 0 < n < 355  # genuinely interrupted mid-video


def test_register_after_expiry_cancels_immediately():
    cancelled = []

    class _Src:
        def cancel(self, reason=""):
            cancelled.append(reason)

    with FaultContext("v", deadline_s=0.05) as ctx:
        deadline = time.monotonic() + 5
        while not ctx.deadline_expired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ctx.deadline_expired
        ctx.register(_Src())  # constructed after the deadline fired
    assert len(cancelled) == 1


def test_context_is_thread_local_and_restored():
    assert faults.current_context() is None
    with FaultContext("outer") as outer:
        assert faults.current_context() is outer
        with FaultContext("inner") as inner:
            assert faults.current_context() is inner
        assert faults.current_context() is outer
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(faults.current_context()))
        t.start()
        t.join()
        assert seen == [None]  # other threads never see our context
    assert faults.current_context() is None


# -------------------------------------------------- degradation ladder

def test_ladder_process_spawn_failure_degrades_to_inline(
        sample_video, capsys, monkeypatch):
    """A forced ProcessVideoSource spawn failure demotes the retry to
    video_decode=inline via the fault context, and the video succeeds —
    logged loudly (the ladder satellite)."""
    from video_features_tpu.config import Config
    from video_features_tpu.extractors.base import BaseExtractor
    from video_features_tpu.utils import io as io_mod

    class _SpawnBoom:
        def __init__(self, *a, **k):
            raise RuntimeError("spawn failed (injected)")

    monkeypatch.setattr(io_mod, "ProcessVideoSource", _SpawnBoom)

    class _CountingExtractor(BaseExtractor):
        output_feat_keys = ["n"]

        def extract(self, video_path):
            src = self.video_source(video_path, batch_size=64)
            n = sum(len(b) for b, _, _ in src)
            return {"n": np.array([n])}

    args = Config(dict(feature_type="counting", on_extraction="print",
                       tmp_path="tmp", output_path="out", device="cpu",
                       video_decode="process"))
    extractor = _CountingExtractor(args)
    got = {}

    def run(path):
        got["feats"] = extractor.extract(path)
        return got["feats"]

    pol = RetryPolicy(attempts=3, backoff_s=0.0, jitter=0.0,
                      sleep=lambda s: None, clock=lambda: 0.0)
    status = sinks.safe_extract(run, sample_video, policy=pol,
                                decode_mode=extractor.video_decode)
    out = capsys.readouterr().out
    assert status == "done", out
    assert got["feats"]["n"][0] == 355  # the inline retry really decoded
    assert "DECODE LADDER" in out and "video_decode=inline" in out
    assert "Recovered" in out and "attempt 2/3" in out


def test_ladder_disabled_without_decode_mode(monkeypatch, capsys):
    """Library callers that pass no decode_mode get retries but no
    demotion messages (there is nothing to demote)."""
    pol = RetryPolicy(attempts=2, backoff_s=0.0, sleep=lambda s: None,
                      clock=lambda: 0.0)
    calls = []

    def flaky(path):
        calls.append(path)
        if len(calls) < 2:
            raise OSError("blip")
        return {"x": 1}

    assert sinks.safe_extract(flaky, "v.mp4", policy=pol) == "done"
    assert "DECODE LADDER" not in capsys.readouterr().out


# ----------------------------------------------------------- CLI summary

def test_cli_run_quarantines_and_tallies(tmp_path, capsys, monkeypatch):
    """End-to-end through cli.main: run 1 fails a corrupt video after
    retry_attempts tries and journals it; run 2 quarantines it via the
    journal (no re-decode); retry_failed=true re-runs it."""
    from video_features_tpu.cli import main
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "w"))
    bad = tmp_path / "v_corrupt.mp4"
    bad.write_bytes(b"\x00\x01 junk that cv2 cannot open" * 64)
    argv = [
        "feature_type=resnet", "model_name=resnet18", "device=cpu",
        "batch_size=4", "allow_random_weights=true",
        "on_extraction=save_numpy", "retry_attempts=2",
        "retry_backoff_s=0", f"output_path={tmp_path / 'o'}",
        f"tmp_path={tmp_path / 't'}", f"video_paths={bad}",
    ]
    main(argv)
    out1 = capsys.readouterr().out
    assert "1 failed" in out1 and "POISON=1" in out1
    journal_path = tmp_path / "o" / "resnet" / "resnet18" / "_failures.jsonl"
    assert journal_path.exists()
    recs = [json.loads(l) for l in open(journal_path) if l.strip()]
    assert len(recs) == 1 and recs[0]["category"] == faults.POISON
    assert recs[0]["attempts"] == 2

    main(argv)
    out2 = capsys.readouterr().out
    assert "1 quarantined" in out2 and "0 failed" in out2
    # still exactly one record: quarantine skips never append
    assert len([l for l in open(journal_path) if l.strip()]) == 1

    main(argv + ["retry_failed=true"])
    out3 = capsys.readouterr().out
    assert "1 failed" in out3  # re-ran (and failed again: still corrupt)
