"""Output sinks: file naming, idempotent skip, corruption re-extraction."""
import numpy as np
import pytest

from video_features_tpu.utils import sinks

pytestmark = pytest.mark.quick


def test_make_path_contract(tmp_path):
    p = sinks.make_path(str(tmp_path), "/videos/v_abc.mp4", "resnet", ".npy")
    assert p.endswith("v_abc_resnet.npy")


def test_save_and_skip_numpy(tmp_path):
    feats = {"resnet": np.ones((3, 4)), "fps": np.array(25.0),
             "timestamps_ms": np.array([0.0, 40.0, 80.0])}
    keys = list(feats)
    video = "/videos/clip.mp4"
    assert not sinks.is_already_exist("save_numpy", str(tmp_path), video, keys)
    sinks.action_on_extraction(feats, video, str(tmp_path), "save_numpy")
    assert sinks.is_already_exist("save_numpy", str(tmp_path), video, keys)
    loaded = sinks.load_numpy(sinks.make_path(str(tmp_path), video, "resnet", ".npy"))
    np.testing.assert_array_equal(loaded, feats["resnet"])


def test_save_and_skip_pickle(tmp_path):
    feats = {"clip": np.zeros((2, 512))}
    video = "v.mp4"
    sinks.action_on_extraction(feats, video, str(tmp_path), "save_pickle")
    assert sinks.is_already_exist("save_pickle", str(tmp_path), video, ["clip"])


def test_corrupt_file_triggers_reextraction(tmp_path):
    video = "v.mp4"
    keys = ["feat"]
    fpath = sinks.make_path(str(tmp_path), video, "feat", ".npy")
    with open(fpath, "wb") as f:
        f.write(b"not-a-npy")  # partial write from a preempted worker
    assert not sinks.is_already_exist("save_numpy", str(tmp_path), video, keys)


def test_print_sink_never_skips(tmp_path):
    assert not sinks.is_already_exist("print", str(tmp_path), "v.mp4", ["x"])


def test_safe_extract_isolates_errors():
    calls = []

    def bad(path):
        calls.append(path)
        raise RuntimeError("decode failed")

    assert sinks.safe_extract(bad, "v.mp4") == "error"
    assert calls == ["v.mp4"]
    assert sinks.safe_extract(lambda p: {"x": 1}, "v.mp4") == "done"
    assert sinks.safe_extract(lambda p: None, "v.mp4") == "skipped"
