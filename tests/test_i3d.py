"""I3D: parity against the actual reference torch model (imported read-only
from /root/reference as the numerical oracle) + E2E rgb extraction."""
import importlib.util
import os

import numpy as np
from pathlib import Path
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import i3d as i3d_model  # noqa: E402
from tests.torch_oracles import randomize_bn_stats  # noqa: E402

REF_I3D = "/root/reference/models/i3d/i3d_src/i3d_net.py"


def _load_reference_i3d():
    if not os.path.exists(REF_I3D):
        pytest.skip("reference I3D source not available")
    spec = importlib.util.spec_from_file_location("ref_i3d", REF_I3D)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("modality,in_ch", [("rgb", 3), ("flow", 2)])
def test_flax_matches_reference_torch(modality, in_ch):
    ref = _load_reference_i3d()
    torch.manual_seed(0)
    oracle = ref.I3D(num_classes=400, modality=modality).eval()
    randomize_bn_stats(oracle)
    params = i3d_model.params_from_torch(oracle.state_dict())
    model = i3d_model.I3D(num_classes=400)

    # T=18 exercises ceil_mode in BOTH strided 3D maxpools (T: 18 -> 9 ->
    # ceil -> 5 -> ceil -> 3) — the floor-mode result would be a different
    # shape, so a pooling bug cannot hide
    x = np.random.default_rng(1).uniform(
        low=-1, high=1, size=(1, 18, 224, 224, in_ch)).astype(np.float32)
    xt = torch.from_numpy(x).permute(0, 4, 1, 2, 3)
    with torch.no_grad():
        want_feats = oracle(xt, features=True).numpy()
        want_softmax, want_logits = oracle(xt, features=False)
        want_logits = want_logits.numpy()
    got_feats = np.asarray(model.apply({"params": params}, jnp.asarray(x),
                                       features=True))
    got_logits = np.asarray(model.apply({"params": params}, jnp.asarray(x),
                                        features=False))
    assert got_feats.shape == want_feats.shape == (1, 1024)
    np.testing.assert_allclose(got_feats, want_feats, atol=5e-4, rtol=5e-4)
    assert got_logits.shape == want_logits.shape == (1, 400)
    np.testing.assert_allclose(got_logits, want_logits, atol=5e-4, rtol=5e-4)


def test_tf_same_pads_match_reference_formula():
    ref = _load_reference_i3d()
    for kernel, stride in [((7, 7, 7), (2, 2, 2)), ((3, 3, 3), (1, 1, 1)),
                           ((1, 3, 3), (1, 2, 2)), ((2, 2, 2), (2, 2, 2)),
                           ((3, 3, 3), (2, 2, 2)), ((1, 1, 1), (1, 1, 1))]:
        # reference returns (Hlo,Hhi,Wlo,Whi,Tlo,Thi) for ConstantPad3d
        # (last-dim-first); ours is ((Tlo,Thi),(Hlo,Hhi),(Wlo,Whi))
        hlo, hhi, wlo, whi, tlo, thi = ref.get_padding_shape(kernel, stride)
        assert i3d_model.tf_same_pads(kernel, stride) == \
            ((tlo, thi), (hlo, hhi), (wlo, whi))


def test_end_to_end_rgb_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.i3d import ExtractI3D

    cfg = load_config("i3d", {
        "video_paths": sample_video, "device": "cpu", "streams": "rgb",
        "stack_size": 16, "step_size": 16, "extraction_fps": 6,
        "clip_batch_size": 2,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractI3D(cfg)
    feats = ex._extract(sample_video)
    # ~18.1s @6fps = ~109 frames; stacks need 17 frames, step 16 ->
    # stacks complete at frames 17, 33, ..., 97 -> 6 stacks
    assert feats["rgb"].shape == (6, 1024)
    assert feats["timestamps_ms"].shape == (6,)
    assert ex.output_feat_keys == ["rgb", "fps", "timestamps_ms"]


def test_flow_quantize_chain_matches_reference_transforms():
    """The jitted RAFT-side transform tail (crop of the padded field, clamp,
    ToUInt8) + the I3D-side ScaleTo1_1 vs the reference torch Compose
    (extract_i3d.py:53-59). Uses a synthetic flow field so only the transform
    semantics (floor-rule crop, round-half-to-even float quantization) are
    under test — RAFT itself has its own parity test."""
    import importlib.util

    if not os.path.exists("/root/reference/models/transforms.py"):
        pytest.skip("reference transforms source not available")
    spec = importlib.util.spec_from_file_location(
        "ref_transforms", "/root/reference/models/transforms.py")
    ref = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ref)

    rng = np.random.default_rng(7)
    # flow values straddling the clamp boundary, incl. exact +/-20 -> the
    # 255.5 -> 256 round-half-even edge, at an odd padded size (261x349) so
    # the center-crop floor rule is exercised
    flow = rng.uniform(-25, 25, size=(3, 2, 261, 349)).astype(np.float32)
    flow[0, 0, 0, 0] = 20.0
    flow[0, 1, 0, 1] = -20.0

    want = ref.TensorCenterCrop(224)(torch.from_numpy(flow))
    want = ref.Clamp(-20, 20)(want)
    want = ref.ToUInt8()(want)
    want = ref.ScaleTo1_1()(want).numpy()

    # ours: NHWC; crop+clamp+quantize as in _raft_quantized_flow, scale as
    # in _i3d_flow_forward
    x = jnp.asarray(flow.transpose(0, 2, 3, 1))
    hp, wp = x.shape[1], x.shape[2]
    i, j = (hp - 224) // 2, (wp - 224) // 2
    q = jnp.round(128.0 + 255.0 / 40.0 * jnp.clip(x[:, i:i + 224, j:j + 224],
                                                  -20.0, 20.0))
    got = np.asarray(q * (2.0 / 255.0) - 1.0).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.slow  # ~50s; the rgb-only and flow-only E2Es below stay quick
def test_end_to_end_two_stream_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.i3d import ExtractI3D

    cfg = load_config("i3d", {
        "video_paths": sample_video, "device": "cpu",
        "stack_size": 10, "step_size": 10, "extraction_fps": 1,
        "clip_batch_size": 1,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractI3D(cfg)
    feats = ex._extract(sample_video)
    # ~18.1s @1fps = 19 frames; a stack needs 11 frames, step 10 -> one
    # stack completes at frame 11 (next would need frame 21 > 19)
    assert ex.output_feat_keys == ["rgb", "flow", "fps", "timestamps_ms"]
    assert feats["rgb"].shape == (1, 1024)
    assert feats["flow"].shape == (1, 1024)
    assert feats["timestamps_ms"].shape == (1,)
    out_dir = tmp_path / "out" / "i3d"
    assert (out_dir / f"{Path(sample_video).stem}_rgb.npy").exists()
    assert (out_dir / f"{Path(sample_video).stem}_flow.npy").exists()


def test_end_to_end_flow_pwc_extraction(sample_video, tmp_path):
    """The flow_type=pwc composition path (extract_i3d.py:154-155: no
    padder, crop on the unpadded input-resolution field)."""
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.i3d import ExtractI3D

    cfg = load_config("i3d", {
        "video_paths": sample_video, "device": "cpu", "streams": "flow",
        "flow_type": "pwc",
        "stack_size": 10, "step_size": 10, "extraction_fps": 1,
        "clip_batch_size": 1,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractI3D(cfg)
    feats = ex._extract(sample_video)
    assert ex.output_feat_keys == ["flow", "fps", "timestamps_ms"]
    assert feats["flow"].shape == (1, 1024)
    assert (tmp_path / "out" / "i3d" / f"{Path(sample_video).stem}_flow.npy").exists()


@pytest.mark.slow  # ~140s: the slowest quick-tier test by 3x; raft/io device-resize siblings keep the fused-resize path in the quick tier
def test_i3d_device_resize_matches_host(sample_video, tmp_path, monkeypatch):
    """resize=device (both streams: resize fused into rgb-I3D and the
    RAFT pair chain) must match the host-PIL path within the 2-LSB input
    quantization difference."""
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls

    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "weights"))

    def feats(resize):
        args = load_config("i3d", parse_dotlist([
            "feature_type=i3d", "device=cpu", "stack_size=10",
            "step_size=10", "extraction_fps=2", "allow_random_weights=true",
            f"resize={resize}", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}", f"video_paths={sample_video}"]))
        sanity_check(args)
        return get_extractor_cls("i3d")(args).extract(sample_video)

    host = feats("host")
    dev = feats("device")
    np.testing.assert_array_equal(host["timestamps_ms"],
                                  dev["timestamps_ms"])
    for stream in ("rgb", "flow"):
        a, b = host[stream], dev[stream]
        assert a.shape == b.shape and a.shape[1] == 1024
        cos = np.sum(a * b, axis=1) / (np.linalg.norm(a, axis=1)
                                       * np.linalg.norm(b, axis=1) + 1e-9)
        assert np.all(cos > 0.99), (stream, cos.min())


def test_device_flow_multi_stack_chunking(rng):
    """_device_flow fuses k stacks' pair batches into one flow forward
    (round-4 throughput lever); the chunk/reshape/slice algebra must hand
    each stack exactly its own pairs, padded runner rows dropped."""
    from video_features_tpu.extractors.i3d_flow import FlowStream

    class FakeRunner:
        def dispatch(self, pairs):
            # per-pair signature + 3 fake padded rows (dispatch() keeps
            # padding, the caller must slice it off)
            x = jnp.asarray(pairs, jnp.float32)
            return jnp.pad(x.mean(axis=(1, 2, 3, 4)), (0, 3))

    fs = FlowStream.__new__(FlowStream)
    fs.pair_runner = FakeRunner()
    group = rng.integers(0, 255, size=(3, 5, 16, 16, 3)).astype(np.uint8)
    fs.stack_batch = 2  # chunks of 2 + ragged 1
    got = np.asarray(fs._device_flow(group))
    fs.stack_batch = 1  # the round-3 per-stack path
    want = np.asarray(fs._device_flow(group))
    assert got.shape == want.shape == (3, 4)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    pairs0 = np.stack([group[0, :-1], group[0, 1:]], axis=1)
    np.testing.assert_allclose(
        got[0], pairs0.reshape(4, -1).mean(axis=1), rtol=1e-5)


def test_stacks_per_forward_geometry_budget():
    """Auto flow-stack batching: 4 at the 224px flagship geometry, scaled
    down for larger sources so the correlation pyramid fits HBM."""
    from video_features_tpu.extractors.i3d_flow import _stacks_per_forward
    assert _stacks_per_forward(64, 224, 224) == 4
    assert _stacks_per_forward(64, 256, 454) == 1  # 3.8 GB/stack pyramid
    assert _stacks_per_forward(16, 64, 64) == 4    # tiny input: cap wins
