"""PWC-Net: parity against the reference torch model (the CuPy CUDA
correlation is replaced by a pure-torch equivalent oracle; grid_sample is
pinned to align_corners=True = the torch-1.2 behavior of the reference's
dedicated conda env) + E2E extraction."""
import importlib.util
import os
import sys
import types

import numpy as np
from pathlib import Path
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import pwc as pwc_model  # noqa: E402

REF_PWC = "/root/reference/models/pwc/pwc_src/pwc_net.py"


def torch_correlation(tensorFirst, tensorSecond, device=None):
    """Pure-torch twin of the reference CUDA kernel
    (correlation.py:47-115): channel (dy+4)*9+(dx+4) = channel-mean of
    f1 * shift(f2, dy, dx), 4 px zero padding. Keyword names match the
    reference call sites (pwc_net.py:187-193)."""
    f1, f2 = tensorFirst, tensorSecond
    b, c, h, w = f1.shape
    f2p = F.pad(f2, (4, 4, 4, 4))
    outs = []
    for dy in range(-4, 5):
        for dx in range(-4, 5):
            win = f2p[:, :, 4 + dy:4 + dy + h, 4 + dx:4 + dx + w]
            outs.append((f1 * win).mean(dim=1))
    return torch.stack(outs, dim=1)


def _load_reference_pwc():
    if not os.path.exists(REF_PWC):
        pytest.skip("reference PWC source not available")
    # stub the CuPy correlation module the reference imports at module level;
    # restore sys.modules afterwards so other tests can import the reference
    # `models` tree as a real namespace package (stub ModuleTypes have no
    # __path__ and would shadow it)
    corr_mod = types.ModuleType("models.pwc.pwc_src.correlation")
    corr_mod.FunctionCorrelation = torch_correlation
    stub_names = ("models", "models.pwc", "models.pwc.pwc_src",
                  "models.pwc.pwc_src.correlation")
    saved = {name: sys.modules.get(name) for name in stub_names}
    for name in stub_names[:-1]:
        sys.modules.setdefault(name, types.ModuleType(name))
    sys.modules["models.pwc.pwc_src.correlation"] = corr_mod
    try:
        spec = importlib.util.spec_from_file_location("ref_pwc", REF_PWC)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        for name in stub_names:
            if saved[name] is None:
                sys.modules.pop(name, None)
            else:
                sys.modules[name] = saved[name]
    return mod


@pytest.fixture()
def grid_sample_align_corners_true(monkeypatch):
    """The reference runs under torch 1.2, whose grid_sample behaves as
    align_corners=True; modern torch defaults to False. Pin the oracle to
    the reference env's semantics."""
    orig = F.grid_sample

    def pinned(input, grid, mode="bilinear", padding_mode="zeros",
               align_corners=None):
        return orig(input, grid, mode=mode, padding_mode=padding_mode,
                    align_corners=True)

    monkeypatch.setattr(F, "grid_sample", pinned)
    yield


def test_correlation_volume_matches_torch_kernel_semantics():
    rng = np.random.default_rng(0)
    f1 = rng.normal(size=(2, 12, 16, 8)).astype(np.float32)
    f2 = rng.normal(size=(2, 12, 16, 8)).astype(np.float32)
    want = torch_correlation(
        torch.from_numpy(f1).permute(0, 3, 1, 2),
        torch.from_numpy(f2).permute(0, 3, 1, 2)).numpy()
    got = np.asarray(pwc_model.correlation_volume(jnp.asarray(f1),
                                                  jnp.asarray(f2)))
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want,
                               atol=1e-6, rtol=1e-5)


def test_bilinear_warp_matches_reference_backward(
        grid_sample_align_corners_true):
    ref = _load_reference_pwc()
    rng = np.random.default_rng(1)
    feat = rng.normal(size=(2, 10, 14, 6)).astype(np.float32)
    flow = rng.uniform(-3, 3, size=(2, 10, 14, 2)).astype(np.float32)
    want = ref.Backward(
        torch.from_numpy(feat).permute(0, 3, 1, 2),
        torch.from_numpy(flow).permute(0, 3, 1, 2),
        torch.device("cpu")).numpy()
    got = np.asarray(pwc_model.bilinear_warp(jnp.asarray(feat),
                                             jnp.asarray(flow)))
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want,
                               atol=1e-5, rtol=1e-4)


def test_flax_matches_reference_torch(grid_sample_align_corners_true):
    ref = _load_reference_pwc()
    torch.manual_seed(0)
    oracle = ref.PWCNet().eval()
    # give the net non-degenerate weights (default init + eval only)
    params = pwc_model.params_from_torch(oracle.state_dict())
    model = pwc_model.PWCNet()

    rng = np.random.default_rng(2)
    # 96x128 is already /64-divisible on W but not H -> exercises the
    # internal bilinear resize to 128x128 and the per-axis flow rescale
    img1 = rng.uniform(0, 255, size=(1, 96, 128, 3)).astype(np.float32)
    img2 = np.clip(img1 + rng.normal(scale=8, size=img1.shape), 0,
                   255).astype(np.float32)
    with torch.no_grad():
        want = oracle(torch.from_numpy(img1).permute(0, 3, 1, 2),
                      torch.from_numpy(img2).permute(0, 3, 1, 2)).numpy()
    got = np.asarray(model.apply({"params": params}, jnp.asarray(img1),
                                 jnp.asarray(img2)))
    assert got.shape == (1, 96, 128, 2)
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want,
                               atol=5e-4, rtol=5e-4)


def test_end_to_end_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.pwc import ExtractPWC

    cfg = load_config("pwc", {
        "video_paths": sample_video, "device": "cpu",
        "batch_size": 4, "extraction_fps": 1, "side_size": 112,
        "resize_to_smaller_edge": False,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractPWC(cfg)
    feats = ex._extract(sample_video)
    # 355 frames @1fps = round(355/19.62) = 18 frames (ffmpeg EOF rule,
    # golden-pinned) -> 17 pairs; larger-edge resize 112 on 320x240 -> 112x84
    n, c, h, w = feats["pwc"].shape
    assert (c, h, w) == (2, 84, 112)
    assert n == 17 and len(feats["timestamps_ms"]) == 18
    assert (tmp_path / "out" / "pwc" / f"{Path(sample_video).stem}_pwc.npy").exists()


def test_precision_bfloat16_wires_model_dtype(tmp_path, monkeypatch):
    """precision=bfloat16 must reach PWCNet.dtype (wiring only)."""
    import jax.numpy as jnp
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check
    from video_features_tpu.registry import get_extractor_cls
    monkeypatch.setenv("VFT_WEIGHTS_DIR", str(tmp_path / "w"))
    for precision, want in (("float32", jnp.float32),
                            ("bfloat16", jnp.bfloat16)):
        args = load_config("pwc", parse_dotlist([
            "feature_type=pwc", "device=cpu", f"precision={precision}",
            "allow_random_weights=true", f"output_path={tmp_path / 'o'}",
            f"tmp_path={tmp_path / 't'}", "video_paths=x.mp4"]))
        sanity_check(args)
        ex = get_extractor_cls("pwc")(args)
        assert ex.model.dtype == want, precision
