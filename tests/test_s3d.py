"""S3D: parity against the actual reference torch model (imported read-only
from /root/reference as the numerical oracle), resize semantics, E2E."""
import importlib.util
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import s3d as s3d_model  # noqa: E402
from video_features_tpu.ops import preprocess as pp  # noqa: E402
from tests.torch_oracles import randomize_bn_stats  # noqa: E402

REF_S3D = "/root/reference/models/s3d/s3d_src/s3d.py"


def _load_reference_s3d():
    if not os.path.exists(REF_S3D):
        pytest.skip("reference S3D source not available")
    spec = importlib.util.spec_from_file_location("ref_s3d", REF_S3D)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_flax_matches_reference_torch():
    ref = _load_reference_s3d()
    torch.manual_seed(0)
    oracle = ref.S3D(num_class=400).eval()
    randomize_bn_stats(oracle)
    params = s3d_model.params_from_torch(oracle.state_dict())
    model = s3d_model.S3D(num_classes=400)

    # stem/pools stride time by 2 three times: T=24 -> 3 at the head (>=2
    # needed for the size-2 temporal avg pool)
    x = np.random.default_rng(0).uniform(
        size=(1, 24, 96, 96, 3)).astype(np.float32)
    xt = torch.from_numpy(x).permute(0, 4, 1, 2, 3)
    with torch.no_grad():
        want_feats = oracle(xt, features=True).numpy()
        want_logits = oracle(xt, features=False).numpy()
    got_feats = np.asarray(model.apply({"params": params}, jnp.asarray(x),
                                       features=True))
    got_logits = np.asarray(model.apply({"params": params}, jnp.asarray(x),
                                        features=False))
    assert got_feats.shape == want_feats.shape == (1, 1024)
    np.testing.assert_allclose(got_feats, want_feats, atol=5e-4, rtol=5e-4)
    assert got_logits.shape == want_logits.shape == (1, 400)
    np.testing.assert_allclose(got_logits, want_logits, atol=5e-4, rtol=5e-4)


def test_scale_factor_resize_matches_torch():
    # the reference's int-size Resize uses F.interpolate(scale_factor=...)
    # (models/transforms.py:86-96); our host resize must match it exactly
    import torch.nn.functional as F
    rng = np.random.default_rng(0)
    img = rng.uniform(size=(240, 320, 3)).astype(np.float32)
    scale = 224.0 / 240.0
    want = F.interpolate(torch.from_numpy(img).permute(2, 0, 1)[None],
                         scale_factor=scale, mode="bilinear",
                         align_corners=False, recompute_scale_factor=False)
    want = want[0].permute(1, 2, 0).numpy()
    got = pp.bilinear_resize_by_scale(img, scale)
    assert got.shape == want.shape
    # torch computes interpolation weights in float32; ours are float64
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_end_to_end_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.s3d import ExtractS3D

    cfg = load_config("s3d", {
        "video_paths": sample_video, "device": "cpu",
        "stack_size": 24, "step_size": 24, "extraction_fps": 6,
        "clip_batch_size": 2,
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractS3D(cfg)
    feats = ex._extract(sample_video)
    # ~18.1s @6fps = ~109 frames -> 4 full 24-frame stacks
    assert feats["s3d"].shape == (4, 1024)
    assert ex.output_feat_keys == ["s3d"]


def test_default_fps_forced_to_25(tmp_path, sample_video):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.s3d import ExtractS3D
    cfg = load_config("s3d", {
        "video_paths": sample_video, "device": "cpu", "extraction_fps": None,
        "allow_random_weights": True,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    sanity_check(cfg)
    ex = ExtractS3D(cfg)
    assert ex.extraction_fps == 25  # reference extract_s3d.py:29
