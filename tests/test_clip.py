"""CLIP: parity against the reference torch model/tokenizer (imported
read-only from /root/reference as the numerical oracle) + E2E extraction."""
import importlib.util
import os
import sys
import types

import numpy as np
from pathlib import Path
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import clip as clip_model  # noqa: E402
from tests.torch_oracles import randomize_bn_stats  # noqa: E402

REF_CLIP_DIR = "/root/reference/models/clip/clip_src"


def _load_ref(module_file, name):
    path = os.path.join(REF_CLIP_DIR, module_file)
    if not os.path.exists(path):
        pytest.skip("reference CLIP source not available")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _flax_cfg(embed_dim, res, layers, width, patch, twidth, theads, tlayers,
              ctx, vocab):
    return clip_model.CLIPConfig(
        embed_dim=embed_dim, image_resolution=res, vision_layers=layers,
        vision_width=width, vision_patch_size=patch, context_length=ctx,
        vocab_size=vocab, transformer_width=twidth,
        transformer_heads=theads, transformer_layers=tlayers)


def _text_tokens(rng, n, ctx, vocab):
    """Random token rows whose max sits at a controlled 'eot' position."""
    toks = rng.integers(1, vocab - 1, size=(n, ctx)).astype(np.int64)
    for i in range(n):
        eot = rng.integers(2, ctx)
        toks[i, eot] = vocab - 1  # strict max -> argmax picks it
        toks[i, eot + 1:] = 0
    return toks


def test_vit_clip_matches_reference_torch():
    ref = _load_ref("model.py", "ref_clip_model")
    torch.manual_seed(0)
    # tiny ViT-B-shaped model: width 64 (1 head), 2+2 layers, patch 14 on
    # 56px -> 16+1 tokens, vocab 128, ctx 12
    oracle = ref.CLIP(embed_dim=32, image_resolution=56, vision_layers=2,
                      vision_width=64, vision_patch_size=14,
                      context_length=12, vocab_size=128,
                      transformer_width=64, transformer_heads=2,
                      transformer_layers=2).eval()
    cfg = _flax_cfg(32, 56, 2, 64, 14, 64, 2, 2, 12, 128)
    params = clip_model.params_from_torch(oracle.state_dict())
    model = clip_model.CLIP(cfg)

    rng = np.random.default_rng(1)
    img = rng.normal(size=(3, 56, 56, 3)).astype(np.float32)
    toks = _text_tokens(rng, 4, 12, 128)
    with torch.no_grad():
        want_img = oracle.encode_image(
            torch.from_numpy(img).permute(0, 3, 1, 2)).numpy()
        want_txt = oracle.encode_text(torch.from_numpy(toks)).numpy()
    got_img = np.asarray(model.apply({"params": params}, jnp.asarray(img),
                                     method="encode_image"))
    got_txt = np.asarray(model.apply(
        {"params": params}, jnp.asarray(toks.astype(np.int32)),
        method="encode_text"))
    assert got_img.shape == want_img.shape == (3, 32)
    np.testing.assert_allclose(got_img, want_img, atol=2e-5, rtol=1e-4)
    assert got_txt.shape == want_txt.shape == (4, 32)
    np.testing.assert_allclose(got_txt, want_txt, atol=2e-5, rtol=1e-4)


def test_vision_attn_blockwise_matches_dense():
    """vision_attn=blockwise (streaming-softmax attention, block 256 over
    the patch tokens) must reproduce the dense tower bit-for-bit-close; the
    opt-in exists for the 577-token ViT-L/14@336 tower where the dense
    per-layer score tensor dominates activation memory."""
    import jax
    cfg = _flax_cfg(32, 56, 2, 64, 14, 64, 2, 2, 12, 128)
    dense = clip_model.CLIP(cfg)
    blockwise = clip_model.CLIP(cfg, vision_attn="blockwise")
    params = dense.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 56, 56, 3)),
                        jnp.zeros((1, 12), jnp.int32))["params"]
    rng = np.random.default_rng(2)
    img = jnp.asarray(rng.normal(size=(3, 56, 56, 3)).astype(np.float32))
    want = np.asarray(dense.apply({"params": params}, img,
                                  method="encode_image"))
    got = np.asarray(blockwise.apply({"params": params}, img,
                                     method="encode_image"))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-5)
    # blockwise boundary actually exercised: token count above one block
    big = clip_model.VisionTransformer(width=64, layers=1, patch_size=2,
                                       output_dim=16, attn_impl="blockwise")
    small = clip_model.VisionTransformer(width=64, layers=1, patch_size=2,
                                         output_dim=16)
    x = jnp.asarray(rng.normal(size=(2, 48, 48, 3)).astype(np.float32))
    p = small.init(jax.random.PRNGKey(1), x)["params"]  # 577 tokens
    np.testing.assert_allclose(
        np.asarray(big.apply({"params": p}, x)),
        np.asarray(small.apply({"params": p}, x)), atol=2e-5, rtol=1e-5)


def test_modified_resnet_clip_matches_reference_torch():
    ref = _load_ref("model.py", "ref_clip_model")
    torch.manual_seed(2)
    # RN50-shaped but tiny: width 64 -> embed 2048, attnpool grid 64/32=2,
    # uneven stage depths exercise the stride placement
    oracle = ref.CLIP(embed_dim=48, image_resolution=64,
                      vision_layers=(1, 2, 1, 1), vision_width=64,
                      vision_patch_size=None, context_length=10,
                      vocab_size=64, transformer_width=64,
                      transformer_heads=1, transformer_layers=1).eval()
    randomize_bn_stats(oracle)
    cfg = _flax_cfg(48, 64, (1, 2, 1, 1), 64, None, 64, 1, 1, 10, 64)
    params = clip_model.params_from_torch(oracle.state_dict())
    model = clip_model.CLIP(cfg)

    rng = np.random.default_rng(3)
    img = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        want = oracle.encode_image(
            torch.from_numpy(img).permute(0, 3, 1, 2)).numpy()
    got = np.asarray(model.apply({"params": params}, jnp.asarray(img),
                                 method="encode_image"))
    assert got.shape == want.shape == (2, 48)
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=1e-4)


def test_config_from_state_dict_matches_reference_inference():
    ref = _load_ref("model.py", "ref_clip_model")
    torch.manual_seed(4)
    for kwargs in (
        dict(embed_dim=32, image_resolution=56, vision_layers=2,
             vision_width=64, vision_patch_size=14, context_length=12,
             vocab_size=128, transformer_width=64, transformer_heads=2,
             transformer_layers=2),
        dict(embed_dim=48, image_resolution=64, vision_layers=(1, 2, 1, 1),
             vision_width=64, vision_patch_size=None, context_length=10,
             vocab_size=64, transformer_width=64, transformer_heads=1,
             transformer_layers=1),
    ):
        sd = ref.CLIP(**kwargs).state_dict()
        cfg = clip_model.config_from_state_dict(sd)
        assert cfg.embed_dim == kwargs["embed_dim"]
        assert cfg.image_resolution == kwargs["image_resolution"]
        assert tuple(np.atleast_1d(cfg.vision_layers)) == \
            tuple(np.atleast_1d(kwargs["vision_layers"]))
        assert cfg.vision_width == kwargs["vision_width"]
        assert cfg.context_length == kwargs["context_length"]
        assert cfg.vocab_size == kwargs["vocab_size"]
        assert cfg.transformer_width == kwargs["transformer_width"]
        assert cfg.transformer_layers == kwargs["transformer_layers"]


REF_BPE = os.path.join(REF_CLIP_DIR, "bpe_simple_vocab_16e6.txt.gz")


def test_tokenizer_matches_reference():
    if not os.path.exists(REF_BPE):
        pytest.skip("reference BPE vocab not available")
    # the reference tokenizer imports ftfy (not installed here); its
    # basic_clean is an identity for already-clean text, so stub it
    if "ftfy" not in sys.modules:
        ftfy = types.ModuleType("ftfy")
        ftfy.fix_text = lambda t: t
        sys.modules["ftfy"] = ftfy
    ref_tok_mod = _load_ref("simple_tokenizer.py", "ref_simple_tokenizer")
    ref_tok = ref_tok_mod.SimpleTokenizer(REF_BPE)

    from video_features_tpu.utils.tokenizer import ClipTokenizer
    tok = ClipTokenizer(bpe_path=REF_BPE)
    assert len(tok.encoder) == 49408
    assert tok.encoder == ref_tok.encoder

    texts = [
        "a photo of abseiling",
        "a photo of washing dishes",
        "Hello, World!  it's a   test...",
        "hyphenated-words & punctuation?!",
        "numbers 123 and 42nd",
        "café naïve déjà vu",  # non-ASCII bytes
        "I'll we've can't y'all'd've",
        "",
    ]
    for t in texts:
        assert tok.encode(t) == ref_tok.encode(t), t
    ids = tok.encode("a photo of juggling balls")
    assert tok.decode(ids) == ref_tok.decode(ids)

    # fixed-shape tokenize parity incl. sot/eot/padding
    want = np.zeros((len(texts), 77), dtype=np.int32)
    for i, t in enumerate(texts):
        row = [tok.sot_token] + ref_tok.encode(t) + [tok.eot_token]
        want[i, :len(row)] = row
    np.testing.assert_array_equal(tok.tokenize(texts), want)

    with pytest.raises(RuntimeError):
        tok.tokenize(["word " * 100], context_length=16)
    trunc = tok.tokenize(["word " * 100], context_length=16, truncate=True)
    assert trunc.shape == (1, 16) and trunc[0, -1] == tok.eot_token


def test_end_to_end_extraction(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    from video_features_tpu.extractors.clip import ExtractCLIP

    cfg = load_config("clip", {
        "video_paths": sample_video, "device": "cpu", "batch_size": 8,
        "extraction_fps": 2, "on_extraction": "save_numpy",
        "allow_random_weights": True,
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    ex = ExtractCLIP(cfg)
    feats = ex._extract(sample_video)
    # 355 frames @2fps = round(355*2/19.62) = 36 frames (ffmpeg EOF rule,
    # golden-pinned in test_golden.py), ViT-B/32 -> 512-d
    assert feats["clip"].shape == (36, 512)
    assert feats["timestamps_ms"].shape == (36,)
    out_dir = tmp_path / "out" / "clip" / "ViT-B_32"
    assert (out_dir / f"{Path(sample_video).stem}_clip.npy").exists()
