"""The one-view fleet aggregator (fleet_report.py / `vft-fleet`,
ISSUE 10): heartbeat merging over synthetic multi-host dirs, straggler
flagging, wall-clock-aligned trace stitching, single-pass --watch, the
--prom fleet textfile, and request-id retrieval.

Everything here is synthetic-artifact driven — the aggregator's whole
contract is that it reconstructs the fleet from files alone, so the
tests write the files by hand and assert the view. The real-subprocess
end-to-end twin is scripts/check_fleet_report.py (CI quick gate).
"""
import json
import re
import time
from pathlib import Path

import pytest

from video_features_tpu import fleet_report
from video_features_tpu.telemetry.jsonl import write_json_atomic

pytestmark = pytest.mark.quick

NOW = 1_700_000_000.0


def _hb(host_id, t, *, final=False, interval=30.0, run_id="run-a",
        done=3, fleet=None, serve=None, cache=None):
    hb = {"schema": "vft.heartbeat/1", "run_id": run_id,
          "host": "synth", "host_id": host_id, "pid": 1,
          "feature_type": "resnet", "time": t, "started_time": t - 60,
          "uptime_s": 60.0, "interval_s": interval, "final": final,
          "videos": {"done": done}, "videos_done": done,
          "videos_per_s": 0.5, "last_video": "x.mp4"}
    if fleet is not None:
        hb["fleet"] = fleet
    if serve is not None:
        hb["serve"] = serve
    if cache is not None:
        hb["cache"] = cache
    return hb


def _write_hb(dirp: Path, hb: dict) -> Path:
    dirp.mkdir(parents=True, exist_ok=True)
    p = dirp / f"_heartbeat_{hb['host_id']}.json"
    write_json_atomic(p, hb)
    return p


def test_aggregate_classifies_live_stale_final_prior(tmp_path):
    root = tmp_path / "out"
    _write_hb(root, _hb("live-1", NOW - 5))
    _write_hb(root, _hb("stale-1", NOW - 200))          # 200s > 3*30s
    _write_hb(root, _hb("done-1", NOW - 400, final=True))
    # prior run: the dir's manifest names a newer run, and the heartbeat
    # both mismatches its run_id AND predates its started_time
    _write_hb(root, _hb("prior-1", NOW - 500, run_id="old-run"))
    write_json_atomic(root / "_run.json",
                      {"run_id": "run-a", "started_time": NOW - 100})
    agg = fleet_report.aggregate(str(root), now=NOW)
    assert agg["n_hosts"] == {"live": 1, "stalled": 1, "finished": 1,
                              "prior_run": 1, "unreadable": 0}
    text = "\n".join(fleet_report.render(agg))
    assert "live-1: alive" in text
    assert "stale-1: STALLED?" in text
    assert "done-1: FINISHED" in text
    assert "prior-1: PRIOR RUN" in text and "ignored" in text
    # the prior-run host's tallies stay out of the aggregates: only the
    # three current hosts' videos_done are live rows
    by_state = {e["hb"]["host_id"]: e["state"] for e in agg["hosts"]
                if e["hb"] and not e["prior_run"]}
    assert set(by_state) == {"live-1", "stale-1", "done-1"}


def test_straggler_flag_and_queue_counts(tmp_path):
    root = tmp_path / "out"
    q = {"pending": 0, "claimed": 1, "done": 5}
    _write_hb(root, _hb("busy-1", NOW - 2, fleet={
        "mode": "queue", "active_claims": 1, "queue": q,
        "claimed": 4, "done": 3, "stolen": 1, "reclaimed": 0}))
    _write_hb(root, _hb("idle-1", NOW - 2, fleet={
        "mode": "queue", "active_claims": 0, "queue": q,
        "claimed": 2, "done": 2, "stolen": 0, "reclaimed": 0}))
    agg = fleet_report.aggregate(str(root), now=NOW)
    assert agg["stragglers"] == ["busy-1"]
    text = "\n".join(fleet_report.render(agg))
    assert "busy-1" in text and "STRAGGLER" in text
    assert "idle-1" in text
    # queue counts fall back to the freshest heartbeat's fleet section
    assert agg["queue"] == q
    # ... unless the _queue dir itself exists (ground truth wins)
    for d, n in (("pending", 2), ("done", 1)):
        dd = root / "_queue" / d
        dd.mkdir(parents=True)
        for i in range(n):
            (dd / f"it{i}.json").write_text("{}")
    (root / "_queue" / "claimed" / "busy-1").mkdir(parents=True)
    agg = fleet_report.aggregate(str(root), now=NOW)
    assert agg["queue"] == {"pending": 2, "done": 1, "quarantined": 0,
                            "claimed": 0}


def test_serve_slo_and_cache_aggregation(tmp_path):
    root = tmp_path / "spool"
    serve_a = {"state": "ready", "pending": 0, "inflight": 1,
               "requests": {"done": 90}, "active_requests": ["r1"],
               "slo": {"slo_s": 2.0, "requests": 90, "violations": 9,
                       "attainment_pct": 90.0,
                       "queue_wait": {"p50": 0.01, "p95": 0.2,
                                      "p99": 0.4},
                       "service": {"p50": 0.5, "p95": 1.5, "p99": 3.0}}}
    serve_b = {"state": "ready", "pending": 2, "inflight": 0,
               "requests": {"done": 10}, "active_requests": [],
               "slo": {"slo_s": 2.0, "requests": 10, "violations": 1,
                       "attainment_pct": 90.0,
                       "queue_wait": {"p50": 0.01, "p95": 0.1,
                                      "p99": 0.2},
                       "service": {"p50": 0.4, "p95": 1.0, "p99": 2.0}}}
    _write_hb(root, _hb("srv-1", NOW - 2, serve=serve_a,
                        cache={"hits": {"resnet": 10},
                               "misses": {"resnet": 30},
                               "bypasses": {}, "hit_rate": 0.25}))
    _write_hb(root, _hb("srv-2", NOW - 2, serve=serve_b,
                        cache={"hits": {"resnet": 5, "clip": 5},
                               "misses": {"resnet": 0},
                               "bypasses": {"resnet": 2},
                               "hit_rate": 1.0}))
    agg = fleet_report.aggregate(str(root), now=NOW)
    t = agg["serve"]["totals"]
    assert t == {"requests": 100, "violations": 10,
                 "attainment_pct": 90.0}
    assert agg["cache"]["hits"] == 20 and agg["cache"]["misses"] == 30
    assert agg["cache"]["hit_rate"] == 0.4
    text = "\n".join(fleet_report.render(agg))
    assert "attainment=90.0%" in text
    assert "service p50/p95/p99=0.5/1.5/3.0s" in text


def test_tenant_rollup_render_and_prom(tmp_path):
    """Per-tenant observability (ISSUE 14): serve heartbeats carry
    tenant request/violation/reject tallies, the gateway heartbeat
    carries door rejections + sheds; vft-fleet merges them into one
    attainment line per tenant and exports
    vft_tenant_{requests,rejects,slo_violations}_total{tenant}."""
    from video_features_tpu.telemetry.metrics import prometheus_text
    root = tmp_path / "spool"
    serve_a = {"state": "ready", "pending": 0, "inflight": 0,
               "requests": {"done": 30}, "active_requests": [],
               "slo": {"slo_s": 2.0, "requests": 30, "violations": 3,
                       "attainment_pct": 90.0,
                       "queue_wait": {"p50": 0.01, "p95": 0.2,
                                      "p99": 0.4},
                       "service": {"p50": 0.5, "p95": 1.5, "p99": 3.0}},
               "tenants": {"alpha": {"requests": 20, "violations": 1,
                                     "rejects": 0},
                           "beta": {"requests": 10, "violations": 2,
                                    "rejects": 4}}}
    _write_hb(root, _hb("srv-1", NOW - 2, serve=serve_a))
    gw_hb = _hb("gw-1", NOW - 2)
    gw_hb["gateway"] = {"state": "ready", "queued_total": 0,
                        "tenants": {"beta": {"accepted": 10,
                                             "rejected": 5, "shed": 2,
                                             "responded": 10,
                                             "expired": 0,
                                             "inflight": 0}}}
    _write_hb(root, gw_hb)
    agg = fleet_report.aggregate(str(root), now=NOW)
    tenants = agg["serve"]["tenants"]
    assert tenants["alpha"] == {"requests": 20, "violations": 1,
                                "rejects": 0, "attainment_pct": 95.0}
    # beta: serve rejects 4 + gateway door 5 rejected + 2 shed = 11
    assert tenants["beta"]["rejects"] == 11
    assert tenants["beta"]["attainment_pct"] == 80.0
    text = "\n".join(fleet_report.render(agg))
    assert "== tenants ==" in text
    assert re.search(r"alpha\s+requests=20\s+violations=1\s+rejects=0"
                     r"\s+attainment=95.0%", text)
    prom = prometheus_text(fleet_report.build_prom_dump(agg))
    assert 'vft_tenant_requests_total{tenant="alpha"} 20.0' in prom
    assert 'vft_tenant_rejects_total{tenant="beta"} 11.0' in prom
    assert 'vft_tenant_slo_violations_total{tenant="beta"} 2.0' in prom
    assert 'vft_tenant_slo_attainment_pct{tenant="alpha"} 95.0' in prom


def test_stitch_aligns_offset_anchors(tmp_path):
    """Two traces whose recorders started 5 s apart must land on ONE
    wall-clock timeline: the later host's events shift by +5e6 µs, each
    host gets its own pid lane titled with its host_id, and every
    event keeps its per-ph required fields."""
    from video_features_tpu.telemetry.trace import (REQUIRED_X_FIELDS,
                                                    TRACE_SCHEMA)

    def doc(host_id, anchor, ts):
        return {
            "traceEvents": [
                {"ph": "M", "name": "process_name", "pid": 7,
                 "args": {"name": "vft-host synth"}},
                {"ph": "M", "name": "thread_name", "pid": 7, "tid": 1,
                 "args": {"name": "MainThread"}},
                {"ph": "X", "name": "video_attempt", "ts": ts,
                 "dur": 10.0, "pid": 7, "tid": 1, "cat": "host"},
            ],
            "otherData": {"schema": TRACE_SCHEMA, "host": "synth",
                          "host_id": host_id, "pid": 7,
                          "start_unix": anchor},
        }

    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "_trace_host-a.json").write_text(
        json.dumps(doc("host-a", 1000.0, 100.0)))
    (tmp_path / "b" / "_trace_host-b.json").write_text(
        json.dumps(doc("host-b", 1005.0, 100.0)))
    out, merged = fleet_report.stitch(str(tmp_path))
    assert out == str(tmp_path / "_trace_fleet.json")
    other = merged["otherData"]
    assert other["aligned"] is True and other["anchor_unix"] == 1000.0
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    by_host = {}
    names = {e["pid"]: e["args"]["name"]
             for e in merged["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    for e in xs:
        by_host[names[e["pid"]]] = e
        for f in REQUIRED_X_FIELDS:
            assert f in e, f"stitched event lost required field {f}"
    # host-a keeps its own timeline; host-b shifts by the 5 s anchor gap
    assert by_host["host-a"]["ts"] == 100.0
    assert by_host["host-b"]["ts"] == 100.0 + 5e6
    assert by_host["host-a"]["pid"] != by_host["host-b"]["pid"]
    # the stitched OUTPUT file is never re-ingested as an input
    out2, merged2 = fleet_report.stitch(str(tmp_path))
    assert len(merged2["otherData"]["hosts"]) == 2


def test_stitch_unanchored_falls_back(tmp_path):
    (tmp_path / "_trace.json").write_text(json.dumps({
        "traceEvents": [{"ph": "X", "name": "decode", "ts": 1.0,
                         "dur": 2.0, "pid": 1, "tid": 1}],
        "otherData": {"host": "old"}}))
    out, merged = fleet_report.stitch(str(tmp_path))
    other = merged["otherData"]
    assert other["aligned"] is False
    assert other["unanchored"], "anchorless trace not flagged"
    assert [e for e in merged["traceEvents"] if e.get("ph") == "X"]


def test_watch_single_pass_and_prom_parses(tmp_path, capsys):
    root = tmp_path / "out"
    _write_hb(root, _hb("live-1", time.time()))
    # --watch --iterations 1: exactly one pass, then exit 0 (no sleep
    # loop to kill — the scripted/test form of the live view)
    rc = fleet_report.main([str(root), "--watch", "--iterations", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("fleet report:") == 1
    assert "live-1" in out

    prom = tmp_path / "fleet.prom"
    rc = fleet_report.main([str(root), "--prom", str(prom)])
    assert rc == 0
    text = prom.read_text()
    line_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+$')
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        assert line_re.match(line), f"unparseable prom line: {line!r}"
    assert "vft_fleet_hosts{state=\"live\"} 1" in text
    assert 'vft_fleet_videos_done{host_id="live-1"} 3' in text


def test_find_request_across_artifacts(tmp_path):
    root = tmp_path / "out"
    root.mkdir()
    rid = "reqabc123"
    with open(root / "_telemetry.jsonl", "w") as f:
        f.write(json.dumps({"video": "a.mp4", "status": "done",
                            "request_id": rid}) + "\n")
        f.write(json.dumps({"video": "b.mp4", "status": "done",
                            "request_id": "other"}) + "\n")
    with open(root / "_health.jsonl", "w") as f:
        f.write(json.dumps({"video": "a.mp4", "key": "resnet",
                            "sig": "ff" * 32, "request_id": rid}) + "\n")
    (root / "done").mkdir()
    (root / "done" / f"{rid}.json").write_text(json.dumps({"id": rid}))
    (root / "_trace_h1.json").write_text(json.dumps({
        "traceEvents": [
            {"ph": "X", "name": "serve.request", "ts": 0, "dur": 1,
             "pid": 1, "tid": 1, "args": {"id": rid}},
            {"ph": "X", "name": "video_attempt", "ts": 0, "dur": 1,
             "pid": 1, "tid": 1, "args": {"request": rid}}],
        "otherData": {}}))
    hits = fleet_report.find_request(str(root), rid)
    kinds = sorted(h.split()[0] for h in hits)
    assert kinds == ["health", "span", "spool", "trace", "trace"], hits
    # and the CLI form renders them
    assert fleet_report.main([str(root), "--request", rid]) == 0
    assert fleet_report.main([str(root), "--request", "missing"]) == 1


# -- ISSUE 11: compile-cache aggregation + capacity decision plane -----------

def test_compile_cache_aggregation_and_render(tmp_path):
    root = tmp_path / "out"
    hb1 = _hb("warm-1", NOW - 2)
    hb1["compile_cache"] = {"hits": 4, "misses": 0, "entry": "abc123def456",
                            "family": "resnet", "warm_at_attach": True,
                            "verified": 4, "dropped": 0}
    hb2 = _hb("cold-1", NOW - 2)
    hb2["compile_cache"] = {"hits": 0, "misses": 3, "entry": "abc123def456",
                            "family": "resnet", "warm_at_attach": False,
                            "verified": 0, "dropped": 1}
    _write_hb(root, hb1)
    _write_hb(root, hb2)
    agg = fleet_report.aggregate(str(root), now=NOW)
    cc = agg["compile_cache"]
    assert cc["hits"] == 4 and cc["misses"] == 3
    assert cc["warm_hosts"] == 1 and cc["attached_hosts"] == 2
    assert cc["dropped"] == 1
    assert cc["hit_rate"] == round(4 / 7, 4)
    assert cc["entries"] == ["abc123def456"]
    text = "\n".join(fleet_report.render(agg))
    assert "== compile cache ==" in text and "warm_hosts=1/2" in text
    dump = fleet_report.build_prom_dump(agg)
    names = {s["name"] for s in dump["series"]}
    assert "vft_fleet_compile_cache_hits_total" in names
    assert "vft_fleet_compile_cache_warm_hosts" in names


def _agg(live=2, pending=0, claimed=0, idle_s=0.0, uptime_s=100.0,
         fleet_hosts=2, attainment=None, requests=0):
    return {
        "n_hosts": {"live": live, "stalled": 0, "finished": 0,
                    "prior_run": 0, "unreadable": 0},
        "queue": {"pending": pending, "claimed": claimed, "done": 0,
                  "quarantined": 0},
        "capacity_inputs": {"idle_wait_s_total": idle_s,
                            "uptime_s": uptime_s,
                            "fleet_hosts": fleet_hosts},
        "serve": {"hosts": [], "totals": {
            "requests": requests, "violations": 0,
            "attainment_pct": attainment}},
        "hosts": [],
    }


def test_planner_scale_up_on_queue_depth_needs_confirmation():
    """Hysteresis: a single hot observation is pressure, not a
    recommendation; the second consecutive one flips it."""
    p = fleet_report.CapacityPlanner(confirm_ticks=2, cooldown_s=0.0)
    r1 = p.observe(_agg(live=2, pending=10), now=NOW)
    assert r1["pressure"] == "scale_up"
    assert r1["recommendation"] == "hold"
    assert any("confirmation" in x for x in r1["reasons"])
    r2 = p.observe(_agg(live=2, pending=10), now=NOW + 2)
    assert r2["recommendation"] == "scale_up" and r2["changed"]
    assert any("queue depth" in x for x in r2["reasons"])


def test_planner_cooldown_pins_recommendation():
    p = fleet_report.CapacityPlanner(confirm_ticks=1, cooldown_s=300.0)
    r1 = p.observe(_agg(live=2, pending=10), now=NOW)
    assert r1["recommendation"] == "scale_up"
    # queue drains and the fleet idles — but the cooldown pins the
    # verdict (the scale-up may still be landing)
    drained = _agg(live=2, pending=0, idle_s=90.0, uptime_s=100.0)
    r2 = p.observe(drained, now=NOW + 10)
    assert r2["pressure"] == "scale_down"
    assert r2["recommendation"] == "scale_up"
    assert any("cooldown" in x for x in r2["reasons"])
    # past the cooldown the same pressure flips it
    r3 = p.observe(_agg(live=2, pending=0, idle_s=95.0, uptime_s=101.0),
                   now=NOW + 400)
    assert r3["recommendation"] == "scale_down"


def test_planner_scale_down_needs_drained_idle_fleet():
    p = fleet_report.CapacityPlanner(confirm_ticks=1, cooldown_s=0.0)
    # idle share high but work still pending: NOT a scale-down
    r = p.observe(_agg(live=2, pending=3, idle_s=90.0), now=NOW)
    assert r["recommendation"] != "scale_down"
    p2 = fleet_report.CapacityPlanner(confirm_ticks=1, cooldown_s=0.0)
    r = p2.observe(_agg(live=2, pending=0, claimed=0, idle_s=90.0,
                        uptime_s=100.0), now=NOW)
    assert r["recommendation"] == "scale_down"
    assert r["idle_share"] == 0.9
    # a single host never scales itself away
    p3 = fleet_report.CapacityPlanner(confirm_ticks=1, cooldown_s=0.0)
    r = p3.observe(_agg(live=1, fleet_hosts=1, pending=0, idle_s=90.0),
                   now=NOW)
    assert r["recommendation"] == "hold"


def test_planner_slo_attainment_slope():
    """Attainment below target and not recovering is a scale-up; the
    slope is measured across the observation window."""
    p = fleet_report.CapacityPlanner(confirm_ticks=2, cooldown_s=0.0,
                                     slo_target_pct=95.0)
    r1 = p.observe(_agg(live=2, attainment=92.0, requests=100), now=NOW)
    assert r1["pressure"] == "scale_up"
    r2 = p.observe(_agg(live=2, attainment=90.0, requests=120),
                   now=NOW + 60)
    assert r2["attainment_slope_pct_per_min"] == -2.0
    assert r2["recommendation"] == "scale_up"
    # recovering attainment (positive slope) is NOT a scale-up even
    # while still below target — the last action is working
    p2 = fleet_report.CapacityPlanner(confirm_ticks=1, cooldown_s=0.0)
    p2.observe(_agg(live=2, attainment=90.0, requests=100), now=NOW)
    r = p2.observe(_agg(live=2, attainment=93.0, requests=120),
                   now=NOW + 60)
    assert r["pressure"] == "hold"


def test_planner_idle_share_uses_window_delta():
    p = fleet_report.CapacityPlanner(confirm_ticks=1, cooldown_s=0.0)
    p.observe(_agg(live=2, pending=0, idle_s=10.0, uptime_s=100.0),
              now=NOW)
    # over the next window the fleet was idle 45 of 50 host-seconds
    r = p.observe(_agg(live=2, pending=0, idle_s=55.0, uptime_s=150.0),
                  now=NOW + 25)
    assert r["idle_share"] == 0.9
    assert r["recommendation"] == "scale_down"


# -- planner persistence (ISSUE 13 satellite: recommendations must
# survive vft-fleet restarts) ------------------------------------------------

def test_planner_state_survives_restart(tmp_path):
    """Streak, cooldown and the slope baseline persist at the root: a
    relaunched watcher continues the hysteresis instead of resetting
    it (previously the planner lived only across one process's --watch
    passes, fleet_report.py:904)."""
    root = str(tmp_path)
    p1 = fleet_report.CapacityPlanner.for_root(root, confirm_ticks=2,
                                               cooldown_s=300.0)
    r1 = p1.observe(_agg(live=2, pending=10), now=NOW)
    assert r1["recommendation"] == "hold" and r1["streak"] == 1
    assert (tmp_path / fleet_report.CapacityPlanner.STATE_FILENAME).exists()
    # restart: a FRESH planner confirms on its first observation,
    # because the streak persisted
    p2 = fleet_report.CapacityPlanner.for_root(root, confirm_ticks=2,
                                               cooldown_s=300.0)
    r2 = p2.observe(_agg(live=2, pending=10), now=NOW + 2)
    assert r2["recommendation"] == "scale_up" and r2["changed"]
    # restart again: the cooldown pin ALSO survives — the queue drains
    # but the fresh watcher must not flip mid-cooldown
    p3 = fleet_report.CapacityPlanner.for_root(root, confirm_ticks=1,
                                               cooldown_s=300.0)
    r3 = p3.observe(_agg(live=2, pending=0, claimed=0, idle_s=90.0,
                         uptime_s=100.0), now=NOW + 10)
    assert r3["recommendation"] == "scale_up"
    assert any("cooldown" in x for x in r3["reasons"])


def test_planner_seeds_slope_baseline_from_history(tmp_path):
    """With no state file yet, the slope inputs re-point at the
    retained history series (telemetry/history.py): the first
    observation of a brand-new watcher already has a real window."""
    from video_features_tpu.telemetry.history import (SAMPLE_SCHEMA,
                                                      HistoryWriter)
    w = HistoryWriter(tmp_path, "h1")
    w.observe({"schema": SAMPLE_SCHEMA, "time": NOW - 60.0,
               "host_id": "h1", "uptime_s": 100.0,
               "fleet": {"idle_wait_s_total": 10.0},
               "slo": {"requests": 100, "violations": 10}})
    p = fleet_report.CapacityPlanner.for_root(str(tmp_path),
                                              confirm_ticks=1,
                                              cooldown_s=0.0)
    assert p._prev is not None
    assert p._prev["attainment_pct"] == 90.0
    # first observation: attainment recovered 90 -> 93 over the minute,
    # slope is positive -> NOT a scale-up even while below target
    r = p.observe(_agg(live=2, attainment=93.0, requests=120), now=NOW)
    assert r["attainment_slope_pct_per_min"] == pytest.approx(3.0)
    assert r["pressure"] == "hold"
