"""Config system: YAML defaults, dotlist overrides, sanity_check semantics."""
import os

import pytest

from video_features_tpu.config import (Config, load_config, merge,
                                       parse_dotlist, sanity_check)

pytestmark = pytest.mark.quick


def test_dotlist_parsing_types():
    cfg = parse_dotlist([
        "feature_type=resnet", "batch_size=16", "extraction_fps=null",
        "video_paths=[a.mp4,b.mp4]", "show_pred=true", "a.b=1",
    ])
    assert cfg.feature_type == "resnet"
    assert cfg.batch_size == 16
    assert cfg.extraction_fps is None
    assert cfg.video_paths == ["a.mp4", "b.mp4"]
    assert cfg.show_pred is True
    assert cfg.a.b == 1


def test_yaml_defaults_merged_under_cli():
    cfg = load_config("resnet", parse_dotlist(["batch_size=32"]))
    assert cfg.batch_size == 32            # CLI wins
    assert cfg.model_name == "resnet50"    # YAML default survives


def test_all_families_have_configs():
    for ft in ("i3d", "r21d", "s3d", "vggish", "resnet", "raft", "pwc", "clip"):
        cfg = load_config(ft)
        assert cfg.feature_type == ft
        assert "output_path" in cfg and "tmp_path" in cfg


def test_sanity_check_namespaces_output_paths(tmp_path):
    cfg = load_config("resnet", {
        "video_paths": "x.mp4", "device": "cpu",
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    # feature_type/model_name appended (reference utils/utils.py:112-125)
    assert cfg.output_path.endswith(os.path.join("out", "resnet", "resnet50"))
    assert cfg.tmp_path.endswith(os.path.join("tmp", "resnet", "resnet50"))


def test_sanity_check_slash_in_model_name(tmp_path):
    cfg = load_config("clip", {
        "video_paths": "x.mp4", "device": "cpu",
        "output_path": str(tmp_path / "out"), "tmp_path": str(tmp_path / "tmp"),
    })
    sanity_check(cfg)
    assert cfg.output_path.endswith(os.path.join("clip", "ViT-B_32"))


def test_sanity_check_rejects_duplicate_stems(tmp_path):
    cfg = load_config("resnet", {
        "video_paths": ["a/v.mp4", "b/v.mp4"], "device": "cpu",
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    with pytest.raises(AssertionError):
        sanity_check(cfg)


def test_sanity_check_fps_total_exclusive(tmp_path):
    cfg = load_config("resnet", {
        "video_paths": "x.mp4", "device": "cpu", "extraction_fps": 5,
        "extraction_total": 10,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    with pytest.raises(AssertionError):
        sanity_check(cfg)


def test_sanity_check_i3d_stack_size(tmp_path):
    cfg = load_config("i3d", {
        "video_paths": "x.mp4", "device": "cpu", "stack_size": 5,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    with pytest.raises(AssertionError):
        sanity_check(cfg)


def test_merge_deep():
    a = Config({"x": {"y": 1, "z": 2}, "k": 0})
    b = Config({"x": {"y": 5}})
    m = merge(a, b)
    assert m.x.y == 5 and m.x.z == 2 and m.k == 0


def test_compilation_cache_knob(monkeypatch, tmp_path):
    """compilation_cache_dir: 'auto' resolves env var then the home cache;
    null/empty disables (cli.py _enable_compilation_cache)."""
    from video_features_tpu.cli import _enable_compilation_cache

    calls = {}
    import jax
    monkeypatch.setattr(jax.config, "update",
                        lambda k, v: calls.__setitem__(k, v))

    _enable_compilation_cache(dict(compilation_cache_dir=None))
    _enable_compilation_cache(dict(compilation_cache_dir=False))  # yaml 'false'
    assert not calls
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path / "env"))
    _enable_compilation_cache(dict(compilation_cache_dir="auto"))
    assert calls["jax_compilation_cache_dir"] == str(tmp_path / "env")
    _enable_compilation_cache(dict(compilation_cache_dir=str(tmp_path / "x")))
    assert calls["jax_compilation_cache_dir"] == str(tmp_path / "x")


def test_video_workers_auto(tmp_path):
    """video_workers=auto resolves to a bounded thread count in the CLI and
    is forced to 1 under print/show_pred by sanity_check."""
    from video_features_tpu.config import load_config, parse_dotlist, \
        sanity_check

    args = load_config("resnet", parse_dotlist(
        ["feature_type=resnet", "video_workers=auto",
         "video_paths=/root/reference/sample/v_GGSY1Qvo990.mp4"]))
    sanity_check(args)  # on_extraction defaults to print
    assert args.video_workers == 1
    args2 = load_config("resnet", parse_dotlist(
        ["feature_type=resnet", "video_workers=auto",
         "on_extraction=save_numpy", f"output_path={tmp_path / 'o'}",
         f"tmp_path={tmp_path / 't'}",
         "video_paths=/root/reference/sample/v_GGSY1Qvo990.mp4"]))
    sanity_check(args2)
    assert args2.video_workers == "auto"  # resolved at run time in cli.main


REF_CONFIGS = "/root/reference/configs"


@pytest.mark.skipif(not os.path.isdir(REF_CONFIGS),
                    reason="reference configs not mounted")
def test_config_defaults_match_reference():
    """Drop-in compat contract: every key in the reference's per-family
    config exists here with the SAME default (so a plain
    `feature_type=<fam>` run means the same thing in both frameworks).
    Sole exemption: `device` — the reference defaults to 'cuda:0', which
    this framework accepts and maps to 'auto' (config.py:resolve_device)."""
    import yaml

    from video_features_tpu.config import build_cfg_path

    for fam in ("resnet", "r21d", "s3d", "i3d", "clip",
                "vggish", "raft", "pwc"):
        with open(os.path.join(REF_CONFIGS, f"{fam}.yml")) as f:
            ref = yaml.safe_load(f)
        with open(build_cfg_path(fam)) as f:
            ours = yaml.safe_load(f)
        for key, want in ref.items():
            assert key in ours, f"{fam}: reference key {key!r} missing"
            if key == "device":
                continue
            assert ours[key] == want, (
                f"{fam}.{key}: default {ours[key]!r} diverges from the "
                f"reference's {want!r} — a drop-in user would silently get "
                "different behavior")


def test_resize_key_validation(tmp_path):
    base = dict(video_paths="a.mp4", output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"))
    for ok in ("auto", "host", "device", None):
        cfg = load_config("resnet", {**base, "resize": ok})
        sanity_check(cfg)  # must not raise
    cfg = load_config("resnet", {**base, "resize": "gpu"})
    with pytest.raises(ValueError):
        sanity_check(cfg)


def test_corr_lookup_config_promotion(monkeypatch, tmp_path):
    """VERDICT next-round #7: the corr-lookup dispatch is a CONFIG key
    applied at init (models/raft.py configure_corr_lookup); the env vars
    remain highest-precedence overrides for trace-time perf probes."""
    from video_features_tpu.models import raft as rm
    monkeypatch.delenv("VFT_CORR_LOOKUP", raising=False)
    monkeypatch.delenv("VFT_FUSE_CONVC1", raising=False)
    # isolate + auto-restore the process-global dispatch state
    monkeypatch.setitem(rm._CORR_CONFIG, "impl", None)
    monkeypatch.setitem(rm._CORR_CONFIG, "fuse_convc1", None)

    assert rm._corr_impl() == "gather"  # CPU auto default
    assert rm._fuse_convc1() is True

    rm.configure_corr_lookup("onehot", False)  # config keys win over auto
    assert rm._corr_impl() == "onehot"
    assert rm._fuse_convc1() is False

    monkeypatch.setenv("VFT_CORR_LOOKUP", "gather")  # env overrides config
    monkeypatch.setenv("VFT_FUSE_CONVC1", "1")
    assert rm._corr_impl() == "gather"
    assert rm._fuse_convc1() is True

    with pytest.raises(ValueError):
        rm.configure_corr_lookup("bogus")

    base = dict(video_paths="a.mp4", output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"))
    cfg = load_config("raft", {**base, "corr_lookup_impl": "pallas",
                               "fuse_convc1": True})
    sanity_check(cfg)  # valid keys pass launch validation
    with pytest.raises(ValueError):
        sanity_check(load_config("raft", {**base,
                                          "corr_lookup_impl": "bogus"}))
    with pytest.raises(ValueError):
        sanity_check(load_config("raft", {**base, "fuse_convc1": "yes"}))


def test_history_alerts_key_validation(tmp_path):
    """history=/alerts= (ISSUE 13, telemetry/history.py +
    telemetry/alerts.py): booleans validated at launch, and both
    require telemetry=true — samples and rule evaluation ride the
    heartbeat cadence, so enabling them without a recorder would
    silently watch nothing."""
    base = dict(video_paths="a.mp4", output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"))
    cfg = load_config("resnet", {**base, "telemetry": True,
                                 "history": True, "alerts": True})
    sanity_check(cfg)  # must not raise
    for bad in ({"history": "yes"}, {"alerts": "on"}):
        with pytest.raises(ValueError):
            sanity_check(load_config("resnet", {**base,
                                                "telemetry": True, **bad}))
    for flag in ("history", "alerts"):
        with pytest.raises(ValueError, match="telemetry=true"):
            sanity_check(load_config("resnet", {**base, flag: True}))


def test_fleet_key_validation(tmp_path):
    """fleet= scheduling keys (parallel/queue.py): a typo'd mode or a
    queue run missing its prerequisites must fail at launch, before N
    hosts start claiming (ISSUE 8)."""
    base = dict(video_paths="a.mp4", output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"))
    sanity_check(load_config("resnet", {**base, "fleet": "static"}))
    # queue mode needs telemetry (lease renewal) + a file sink
    sanity_check(load_config("resnet", {
        **base, "fleet": "queue", "telemetry": True,
        "on_extraction": "save_numpy"}))
    with pytest.raises(ValueError, match="fleet="):
        sanity_check(load_config("resnet", {**base, "fleet": "dynamic"}))
    with pytest.raises(ValueError, match="telemetry"):
        sanity_check(load_config("resnet", {
            **base, "fleet": "queue", "on_extraction": "save_numpy"}))
    with pytest.raises(ValueError, match="file sink"):
        sanity_check(load_config("resnet", {
            **base, "fleet": "queue", "telemetry": True}))
    with pytest.raises(ValueError, match="fleet_lease_s"):
        sanity_check(load_config("resnet", {**base, "fleet_lease_s": 0}))
    with pytest.raises(ValueError, match="fleet_max_reclaims"):
        sanity_check(load_config("resnet",
                                 {**base, "fleet_max_reclaims": 0}))
    with pytest.raises(ValueError, match="fleet_canary"):
        sanity_check(load_config("resnet",
                                 {**base, "fleet_canary": "yes"}))


def test_serve_slo_key_validation(tmp_path):
    """serve_slo_s (serve.py SLO objective, ISSUE 10): null disables,
    a positive float passes, zero/negative/garbage fail at launch —
    never silently count zero violations against a broken objective."""
    base = dict(video_paths="a.mp4", output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"))
    cfg = load_config("resnet", base)
    assert cfg.serve_slo_s is None  # shipped default: disabled
    sanity_check(cfg)
    sanity_check(load_config("resnet", {**base, "serve_slo_s": 2.5}))
    for bad in (0, -1.0, "fast"):
        with pytest.raises(ValueError, match="serve_slo_s"):
            sanity_check(load_config("resnet",
                                     {**base, "serve_slo_s": bad}))


def test_compile_cache_key_validation(tmp_path):
    """compile_cache= / compile_cache_dir= (compile_cache.py, ISSUE 11):
    'auto'/true/false pass, anything else fails at launch — a typo'd
    switch must not silently compile cold forever."""
    base = dict(video_paths="a.mp4", output_path=str(tmp_path / "o"),
                tmp_path=str(tmp_path / "t"))
    sanity_check(load_config("resnet", {**base, "compile_cache": True}))
    sanity_check(load_config("resnet", {**base, "compile_cache": False}))
    sanity_check(load_config("resnet", {
        **base, "compile_cache": "auto",
        "compile_cache_dir": str(tmp_path / "cc")}))
    with pytest.raises(ValueError, match="compile_cache="):
        sanity_check(load_config("resnet",
                                 {**base, "compile_cache": "always"}))
    with pytest.raises(ValueError, match="compile_cache_dir"):
        sanity_check(load_config("resnet",
                                 {**base, "compile_cache_dir": 7}))
