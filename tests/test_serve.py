"""Warm serving mode (serve.py): spool protocol, readiness/liveness via
heartbeats, admission control, and the no-recompile warm path (ISSUE 7).

The server is driven in-process (a thread around ``serve_main``, bounded
by ``serve_max_requests``) — the same loop `vft-serve` runs, minus the
console script. Contracts pinned here:
  - request/response roundtrip over the filesystem spool: atomic submit,
    per-video statuses, artifact root, wait/latency accounting;
  - warm behavior: request 2 reports ZERO compile-cache misses (params
    resident, executables warm) and — with ``cache=true`` and a
    byte-identical second clip — a feature-cache hit in the final
    heartbeat's ``cache`` section;
  - the heartbeat in the SPOOL dir is the liveness/readiness protocol:
    ``server_state`` reads ready/exited off it, ``absent`` without one;
  - admission control: a backlog past ``serve_max_pending`` gets fast
    explicit ``rejected`` responses, oldest requests kept.
"""
import json
import shutil
import threading
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu import serve

pytestmark = pytest.mark.quick


def _base_args(tmp_path, sample_video, n_copies=2):
    vids = []
    for i in range(n_copies):
        dst = tmp_path / f"clip{i}.mp4"
        shutil.copy(sample_video, dst)
        vids.append(str(dst))
    spool = tmp_path / "spool"
    argv = ["feature_type=resnet", "model_name=resnet18", "device=cpu",
            "allow_random_weights=true", "on_extraction=save_numpy",
            "extraction_total=6", "batch_size=8",
            "cache=true", f"cache_dir={tmp_path / 'cache'}",
            f"spool_dir={spool}", "serve_poll_interval_s=0.05",
            "metrics_interval_s=1",
            f"output_path={tmp_path / 'out'}",
            f"tmp_path={tmp_path / 'tmp'}"]
    return argv, str(spool), vids


def test_serve_roundtrip_warm_and_cache_hit(sample_video, tmp_path):
    argv, spool, vids = _base_args(tmp_path, sample_video)
    assert serve.server_state(spool) == {"state": "absent"}
    t = threading.Thread(
        target=serve.serve_main,
        args=(argv + ["serve_max_requests=2", "health=true",
                      "trace=true"],),
        daemon=True)
    t.start()
    # request 1 pays the cold tax (compile + decode); clip0 lands in the
    # feature cache under its CONTENT hash
    r1 = serve.submit_request(spool, [vids[0]])
    resp1 = serve.wait_response(spool, r1, timeout_s=240)
    assert resp1["status"] == "done", resp1
    assert resp1["videos"][vids[0]] == {"resnet": "done"}
    out_root = Path(resp1["output_path"])
    stem = Path(vids[0]).stem
    assert list(out_root.rglob(f"{stem}_resnet.npy"))
    assert resp1["latency_s"] > 0 and resp1["wait_s"] >= 0
    # readiness is visible in the spool heartbeat while the server lives
    state = serve.server_state(spool)
    assert state["state"] in ("ready", "unknown"), state
    # request 2: clip1 is byte-identical content under a different stem —
    # the warm server must neither recompile (flat compile-cache misses)
    # nor recompute (content-addressed hit)
    r2 = serve.submit_request(spool, [vids[1]])
    resp2 = serve.wait_response(spool, r2, timeout_s=240)
    t.join(timeout=60)
    assert not t.is_alive(), "bounded server failed to exit"
    assert resp2["status"] == "done", resp2
    assert resp2["compile_cache"].get("misses", 0) == 0, \
        "request 2 recompiled: warm-path regression"
    # the two stems' features are bit-identical (same content, one compute)
    a = np.load(next(out_root.rglob(f"{Path(vids[0]).stem}_resnet.npy")))
    b = np.load(next(out_root.rglob(f"{Path(vids[1]).stem}_resnet.npy")))
    np.testing.assert_array_equal(a, b)
    # final heartbeat: liveness protocol reports the exit + the hit
    state = serve.server_state(spool)
    assert state["state"] == "exited"
    hb = json.loads(next(Path(spool).glob("_heartbeat_*.json")).read_text())
    assert hb["cache"]["hits"] == {"resnet": 1}
    assert hb["serve"]["requests"]["done"] == 2
    # SLO block present even with no serve_slo_s set: percentiles off
    # the bounded histograms, violation counting disabled
    slo = hb["serve"]["slo"]
    assert slo["slo_s"] is None and slo["requests"] == 2
    assert slo["violations"] == 0 and slo["attainment_pct"] == 100.0
    assert slo["service"]["p95"] is not None

    # request-scoped correlation end-to-end (ISSUE 10): the id returned
    # by submit_request is findable in the span, health and trace
    # records the request produced — the spool roundtrip IS the join key
    spans = [json.loads(line) for line in
             (Path(spool) / "_telemetry.jsonl").read_text().splitlines()]
    assert {s["request_id"] for s in spans} == {r1, r2}
    health = [json.loads(line) for line in
              next(out_root.rglob("_health.jsonl")).read_text()
              .splitlines()]
    assert {h["request_id"] for h in health} == {r1, r2}
    trace_doc = json.loads(
        next(Path(spool).glob("_trace_*.json")).read_text())
    tagged = {e["args"].get("id") or e["args"].get("request")
              for e in trace_doc["traceEvents"]
              if isinstance(e.get("args"), dict)
              and e["name"] in ("serve.request", "video_attempt")}
    assert {r1, r2} <= tagged
    # ... and vft-fleet --request joins them all from the artifacts
    from video_features_tpu import fleet_report
    hits = fleet_report.find_request(str(tmp_path), r1)
    kinds = {h.split()[0] for h in hits}
    assert {"span", "health", "trace", "spool"} <= kinds, hits


def test_admission_control_rejects_overflow(sample_video, tmp_path):
    from video_features_tpu.config import load_config, sanity_check
    argv, spool, vids = _base_args(tmp_path, sample_video, n_copies=1)
    cfg = load_config("resnet", dict(
        kv.split("=", 1) for kv in argv[1:]) | {"feature_type": "resnet"})
    # booleans/numbers arrive as strings through this shortcut; the keys
    # the loop reads are re-set typed here
    cfg.allow_random_weights = True
    cfg.cache = False
    cfg.serve_max_pending = 2
    sanity_check(cfg, require_videos=False)
    loop = serve.ServeLoop(cfg, out_root=str(tmp_path / "out"))
    rids = [serve.submit_request(spool, [vids[0]]) for _ in range(5)]
    loop._reject_overflow()
    rejected = [r for r in rids
                if (resp := serve.read_response(spool, r)) is not None
                and resp["status"] == "rejected"]
    # newest arrivals beyond max_pending refused; oldest 2 still queued
    assert len(rejected) == 3
    assert set(rejected) == set(rids[2:])
    for resp in (serve.read_response(spool, r) for r in rejected):
        assert "serve_max_pending" in resp["error"]


def test_slo_accounting_percentiles_violations_bounded(sample_video,
                                                       tmp_path):
    """The SLO ledger (ISSUE 10): queue-wait/service split into the
    fixed-bucket histograms, violations counted against serve_slo_s on
    wait+service, attainment % in the serve section — and the recent
    window BOUNDED (the unbounded `_request_latencies` list this
    replaced grew for the life of the server)."""
    from video_features_tpu.config import (load_config, parse_dotlist,
                                           sanity_check)
    argv, spool, vids = _base_args(tmp_path, sample_video, n_copies=1)
    cfg = load_config("resnet", parse_dotlist(argv))
    cfg.cache = False
    cfg.serve_slo_s = 1.0
    sanity_check(cfg, require_videos=False)
    loop = serve.ServeLoop(cfg, out_root=str(tmp_path / "out"))

    # before any request: empty-but-well-formed SLO block
    slo = loop._serve_section()["slo"]
    assert slo == {"slo_s": 1.0, "requests": 0, "violations": 0,
                   "attainment_pct": None,
                   "queue_wait": {"p50": None, "p95": None, "p99": None},
                   "service": {"p50": None, "p95": None, "p99": None}}

    # 90 fast requests + 10 slow: wait+service > 1.0s only for the slow
    for _ in range(90):
        assert not loop._account_request(0.01, 0.1)
    for _ in range(10):
        assert loop._account_request(0.6, 0.9)  # 1.5 > slo_s
    slo = loop._serve_section()["slo"]
    assert slo["requests"] == 100 and slo["violations"] == 10
    assert slo["attainment_pct"] == 90.0
    # percentiles: p50 in the fast band, p95+ in the slow band (bucket
    # upper-bound interpolation, telemetry/metrics.py)
    assert slo["service"]["p50"] <= 0.25
    assert slo["service"]["p95"] >= 0.5
    assert slo["queue_wait"]["p50"] <= 0.025
    # the recent window is a fixed-size deque, not an unbounded list
    assert len(loop._recent) == 32
    assert not hasattr(loop, "_request_latencies")
    # violation counter exported for the prometheus/manifest path
    reg = loop.recorder.registry
    assert reg.counter("vft_serve_slo_violations_total").value == 10
    loop.recorder.close()


def test_telemetry_report_serve_line_and_fail_on_slo(tmp_path):
    """telemetry_report renders the per-host serve/SLO lines off the
    heartbeat and --fail-on-slo turns violations into exit 1 (ISSUE 10
    satellite) — the CI/canary gate on serving latency."""
    import sys
    import time
    from pathlib import Path as _P

    from video_features_tpu.telemetry.jsonl import write_json_atomic
    sys.path.insert(0, str(_P(__file__).resolve().parent.parent
                           / "scripts"))
    import telemetry_report

    def hb(violations):
        return {"host_id": "srv-1", "time": time.time(),
                "interval_s": 30.0, "final": False, "videos_done": 5,
                "serve": {
                    "state": "ready", "pending": 1, "inflight": 2,
                    "requests": {"done": 20, "rejected": 1},
                    "slo": {"slo_s": 2.0, "requests": 20,
                            "violations": violations,
                            "attainment_pct": 100.0 - 5.0 * violations,
                            "queue_wait": {"p50": 0.01, "p95": 0.2,
                                           "p99": 0.3},
                            "service": {"p50": 0.5, "p95": 1.5,
                                        "p99": 1.9}}}}

    out = tmp_path / "spool"
    out.mkdir()
    write_json_atomic(out / "_heartbeat_srv-1.json", hb(3))
    text = "\n".join(telemetry_report.render_heartbeats(
        [str(out / "_heartbeat_srv-1.json")], time.time()))
    assert "serve: ready" in text and "rejected=1" in text
    assert "slo: service p50/p95/p99=0.5/1.5/1.9s" in text
    assert "violations=3" in text and "attainment=85.0%" in text
    assert telemetry_report.main([str(out), "--fail-on-slo"]) == 1
    # zero violations (or no objective): the gate passes
    write_json_atomic(out / "_heartbeat_srv-1.json", hb(0))
    assert telemetry_report.main([str(out), "--fail-on-slo"]) == 0


def test_dead_server_claims_reclaimed(sample_video, tmp_path):
    """A server that crashes mid-request must not strand its spool claims
    (ISSUE 8 satellite): once its heartbeat is stale, a live sibling's
    sweep renames the claims back into requests/ — except claims whose
    response already landed, which are dropped, and claims owned by a
    server whose heartbeat is still fresh, which are left alone. A flat
    legacy claim (no owner dir) is reclaimed unconditionally."""
    import os
    import time

    from video_features_tpu.config import (load_config, parse_dotlist,
                                           sanity_check)
    from video_features_tpu.telemetry.jsonl import write_json_atomic

    argv, spool, vids = _base_args(tmp_path, sample_video, n_copies=1)
    cfg = load_config("resnet", parse_dotlist(argv))
    cfg.cache = False
    cfg.serve_max_requests = 2
    sanity_check(cfg, require_videos=False)
    loop = serve.ServeLoop(cfg, out_root=str(tmp_path / "out"))

    claimed = Path(spool) / "claimed"

    def orphan(rid, owner=None):
        src = Path(spool) / "requests" / f"{rid}.json"
        if owner is None:
            dst = claimed / f"{rid}.json"  # legacy flat claim
        else:
            dst = claimed / owner / f"{rid}.json"
            dst.parent.mkdir(parents=True, exist_ok=True)
        os.rename(src, dst)
        return dst

    now = time.time()
    # dead owner: heartbeat 100s old on a 1s interval
    r_dead = serve.submit_request(spool, [vids[0]], request_id="deadclaim")
    orphan(r_dead, owner="deadhost-1")
    write_json_atomic(Path(spool) / "_heartbeat_deadhost-1.json",
                      {"host_id": "deadhost-1", "time": now - 100.0,
                       "interval_s": 1.0, "final": False})
    # dead owner, but the response already landed: drop, don't re-serve
    r_answered = serve.submit_request(spool, [vids[0]],
                                      request_id="answered")
    orphan(r_answered, owner="deadhost-1")
    write_json_atomic(Path(spool) / "done" / "answered.json",
                      {"schema": serve.RESPONSE_SCHEMA, "id": "answered",
                       "status": "done"})
    # live owner: fresh heartbeat — its claim is its own business
    r_live = serve.submit_request(spool, [vids[0]], request_id="liveclaim")
    live_claim = orphan(r_live, owner="livehost-1")
    write_json_atomic(Path(spool) / "_heartbeat_livehost-1.json",
                      {"host_id": "livehost-1", "time": now,
                       "interval_s": 30.0, "final": False})
    # legacy flat claim: a pre-reclamation server version crashed
    r_legacy = serve.submit_request(spool, [vids[0]], request_id="legacy")
    orphan(r_legacy)

    assert loop._reclaim_orphans() == 2  # deadclaim + legacy
    requeued = {p.stem for p in (Path(spool) / "requests").glob("*.json")}
    assert requeued == {"deadclaim", "legacy"}
    assert live_claim.exists(), "fresh-heartbeat owner's claim was stolen"
    assert not (claimed / "deadhost-1" / "deadclaim.json").exists()
    assert not (claimed / "deadhost-1" / "answered.json").exists()
    assert loop._reclaim_orphans() == 0  # idempotent

    # a running server picks the reclaimed requests up end-to-end
    t = threading.Thread(target=loop.run, daemon=True)
    t.start()
    try:
        resp = serve.wait_response(spool, "deadclaim", timeout_s=240)
        assert resp["status"] == "done", resp
        resp = serve.wait_response(spool, "legacy", timeout_s=240)
        assert resp["status"] == "done", resp
    finally:
        loop.stop()
        t.join(timeout=60)
    assert not t.is_alive()
