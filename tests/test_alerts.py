"""Alerting & flight-recorder plane (ISSUE 13, telemetry/alerts.py +
telemetry/history.py): retained heartbeat series with tiered
downsampling, the declarative rule engine's pending→firing→resolved
state machine (journal-as-state, dedup), every built-in rule on
synthetic observations, incident-bundle completeness, the report-tool
gates, and the CLI E2E acceptance loop — an injected fault run
deterministically fires an alert, captures a schema-valid bundle, and
resolves after recovery; ``alerts=false`` stays byte-identical.
"""
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.telemetry import alerts, history
from video_features_tpu.telemetry.alerts import (ALERT_FIELDS, AlertConfig,
                                                 AlertEngine, AlertRule,
                                                 current_alerts,
                                                 load_alert_schema,
                                                 validate_alert,
                                                 verify_incident)
from video_features_tpu.telemetry.jsonl import read_jsonl, write_json_atomic

pytestmark = pytest.mark.quick

REPO_ROOT = Path(__file__).resolve().parent.parent
SAMPLE = REPO_ROOT / "tests" / "assets" / "v_synth_sample.mp4"

NOW = 1_700_000_000.0


# -- helpers -----------------------------------------------------------------

def _obs(root, now=NOW, hosts=(), queue=None, claims=None,
         claims_tracked=False, hist=None):
    return {"root": str(root), "time": now, "hosts": list(hosts),
            "n_live": sum(1 for e in hosts if e.get("state") == "live"),
            "queue": queue, "claims": claims or {},
            "claims_tracked": claims_tracked, "history": hist or {}}


def _host(host_id, state="live", age=1.0, prior=False, fleet=None):
    hb = {"host_id": host_id, "run_id": "r", "time": NOW - age,
          "interval_s": 2.0, "final": state == "FINISHED"}
    if fleet is not None:
        hb["fleet"] = fleet
    return {"path": f"_heartbeat_{host_id}.json", "dir": ".", "hb": hb,
            "state": state, "age_s": age, "prior_run": prior}


def _samples(host="h1", n=10, dt=30.0, t0=NOW - 9 * 30.0, **series):
    """n history samples ending at NOW; each kwarg is a dotted-path leaf
    given as a list of n values (e.g. slo_requests=[...])."""
    out = []
    for i in range(n):
        s = {"schema": history.SAMPLE_SCHEMA, "time": t0 + i * dt,
             "host_id": host, "run_id": "r", "uptime_s": i * dt,
             "final": False,
             "videos": {"done": i, "skipped": 0, "error": 0,
                        "quarantined": 0}}
        for key, vals in series.items():
            path = key.split("__")
            cur = s
            for part in path[:-1]:
                cur = cur.setdefault(part, {})
            cur[path[-1]] = vals[i]
        out.append(s)
    return out


def _rule_flag(flag):
    """A test rule that fires while ``flag['on']`` is truthy."""
    def ev(obs, cfg):
        if flag.get("on"):
            return [{"scope": "s1", "summary": "synthetic condition",
                     "value": 1.0, "threshold": 1.0}]
        return []
    return ev


# -- schema ------------------------------------------------------------------

def test_alert_schema_pins_emitter_fields():
    sch = load_alert_schema()
    assert set(sch["properties"]) == set(ALERT_FIELDS)
    assert set(sch["required"]) <= set(sch["properties"])
    assert sch["additionalProperties"] is False
    assert sch["properties"]["schema"]["enum"] == [alerts.SCHEMA_VERSION]
    assert sch["properties"]["state"]["enum"] == list(alerts.STATES)
    assert sch["properties"]["severity"]["enum"] == list(alerts.SEVERITIES)


# -- the state machine -------------------------------------------------------

def test_pending_dwell_then_firing_then_resolved(tmp_path):
    flag = {"on": True}
    rule = AlertRule("synthetic", "ticket", "test", _rule_flag(flag),
                     for_s=10.0)
    eng = AlertEngine(tmp_path, rules=(rule,), capture_incidents=False)
    obs = _obs(tmp_path)
    r1 = eng.evaluate(obs=obs, now=NOW)
    assert [r["state"] for r in r1] == ["pending"]
    assert not validate_alert(r1[0])
    # dwell not yet elapsed: no transition, no record
    assert eng.evaluate(obs=obs, now=NOW + 5) == []
    r2 = eng.evaluate(obs=obs, now=NOW + 11)
    assert [r["state"] for r in r2] == ["firing"]
    assert r2[0]["alert_id"] == r1[0]["alert_id"]  # one episode
    assert r2[0]["since"] == r1[0]["since"]
    # steady firing: dedup — nothing emitted
    assert eng.evaluate(obs=obs, now=NOW + 20) == []
    flag["on"] = False
    r3 = eng.evaluate(obs=obs, now=NOW + 30)
    assert [r["state"] for r in r3] == ["resolved"]
    assert r3[0]["alert_id"] == r1[0]["alert_id"]
    assert current_alerts(tmp_path) == []


def test_pending_that_clears_resolves_without_firing(tmp_path):
    flag = {"on": True}
    rule = AlertRule("synthetic", "ticket", "test", _rule_flag(flag),
                     for_s=60.0)
    eng = AlertEngine(tmp_path, rules=(rule,), capture_incidents=False)
    eng.evaluate(obs=_obs(tmp_path), now=NOW)
    flag["on"] = False
    r = eng.evaluate(obs=_obs(tmp_path), now=NOW + 5)
    assert [x["state"] for x in r] == ["resolved"]
    states = [x["state"] for x in read_jsonl(tmp_path / "_alerts.jsonl")]
    assert states == ["pending", "resolved"]  # never fired


def test_journal_is_the_state_across_engine_instances(tmp_path):
    """A cron one-shot (fresh engine) adopts and resolves an episode a
    long-dead evaluator fired — the journal is the state."""
    flag = {"on": True}
    rule = AlertRule("synthetic", "page", "test", _rule_flag(flag))
    e1 = AlertEngine(tmp_path, rules=(rule,), capture_incidents=False)
    fired = e1.evaluate(obs=_obs(tmp_path), now=NOW)
    assert [r["state"] for r in fired] == ["firing"]
    flag["on"] = False
    e2 = AlertEngine(tmp_path, rules=(rule,), capture_incidents=False)
    resolved = e2.evaluate(obs=_obs(tmp_path), now=NOW + 60)
    assert [r["state"] for r in resolved] == ["resolved"]
    assert resolved[0]["alert_id"] == fired[0]["alert_id"]


def test_clear_dwell_holds_firing_in_one_engine(tmp_path):
    flag = {"on": True}
    rule = AlertRule("synthetic", "ticket", "test", _rule_flag(flag),
                     clear_for_s=30.0)
    eng = AlertEngine(tmp_path, rules=(rule,), capture_incidents=False)
    eng.evaluate(obs=_obs(tmp_path), now=NOW)
    flag["on"] = False
    assert eng.evaluate(obs=_obs(tmp_path), now=NOW + 10) == []  # dwell
    flag["on"] = True  # condition back: dwell resets, still firing
    assert eng.evaluate(obs=_obs(tmp_path), now=NOW + 20) == []
    flag["on"] = False
    assert eng.evaluate(obs=_obs(tmp_path), now=NOW + 25) == []
    r = eng.evaluate(obs=_obs(tmp_path), now=NOW + 60)
    assert [x["state"] for x in r] == ["resolved"]


# -- built-in rules ----------------------------------------------------------

def test_slo_burn_fires_only_when_both_windows_burn(tmp_path):
    cfg = AlertConfig(short_window_s=300, long_window_s=3600)
    # 13 samples over 1h: no violations until the last 5 min, where 10
    # of 10 requests violate -> short window burns hard, but the hour
    # window holds 10/130 ≈ 7.7% > 5% budget -> burn_l ≈ 1.5: fires
    n = 13
    req = [10 * i for i in range(n)]
    vio = [0] * (n - 1) + [10]
    hist = {"h1": _samples(n=n, dt=300.0, t0=NOW - (n - 1) * 300.0,
                           slo__requests=req, slo__violations=vio)}
    found = alerts._rule_slo_burn(_obs(tmp_path, hist=hist), cfg)
    assert len(found) == 1 and found[0]["scope"] == "h1"
    assert found[0]["value"] >= cfg.burn_threshold
    # same short burst against a long window that already absorbed it:
    # 10 violations an hour ago, none since -> short window clean
    vio2 = [10] * n
    hist2 = {"h1": _samples(n=n, dt=300.0, t0=NOW - (n - 1) * 300.0,
                            slo__requests=req, slo__violations=vio2)}
    assert alerts._rule_slo_burn(_obs(tmp_path, hist=hist2), cfg) == []


def test_slo_burn_scopes_per_tenant(tmp_path):
    """Per-tenant burn (ISSUE 14): one tenant burning ITS budget pages
    as `{host}/tenant={name}`, while a healthy co-tenant (and a healthy
    host-level aggregate) stays silent."""
    cfg = AlertConfig(short_window_s=300, long_window_s=3600)
    n = 13
    # host aggregate: 260 requests, only the noisy tenant's 10
    # violations — host-level burn over the hour stays under budget
    # while tenant `noisy` burns 10/10 in the short window
    req = [20 * i for i in range(n)]
    vio = [0] * (n - 1) + [10]
    quiet = [10 * i for i in range(n)]
    zeros = [0] * n
    hist = {"h1": _samples(
        n=n, dt=300.0, t0=NOW - (n - 1) * 300.0,
        slo__requests=req, slo__violations=zeros,
        tenants__noisy__requests=[10 * i for i in range(n - 1)] + [130],
        tenants__noisy__violations=vio,
        tenants__calm__requests=quiet,
        tenants__calm__violations=zeros)}
    found = alerts._rule_slo_burn(_obs(tmp_path, hist=hist), cfg)
    assert len(found) == 1, found
    assert found[0]["scope"] == "h1/tenant=noisy"
    assert "tenant noisy" in found[0]["summary"]
    assert found[0]["value"] >= cfg.burn_threshold


def test_slo_burn_quiet_service_never_fires(tmp_path):
    hist = {"h1": _samples(slo__requests=[5 * i for i in range(10)],
                           slo__violations=[0] * 10)}
    assert alerts._rule_slo_burn(_obs(tmp_path, hist=hist),
                                 AlertConfig()) == []


def test_host_stalled_scopes_to_held_leases(tmp_path):
    """With claim tracking, a stalled host alerts only while its leases
    are outstanding — the episode resolves when siblings reclaim them
    (the only resolution path a SIGKILLed host ever gets)."""
    claimed = tmp_path / "_queue" / "claimed" / "dead-1"
    claimed.mkdir(parents=True)
    (claimed / "item.json").write_text("{}")
    obs = _obs(tmp_path, hosts=[_host("dead-1", "STALLED", age=120.0)],
               claims={"dead-1": 1}, claims_tracked=True)
    found = alerts._rule_host_stalled(obs, AlertConfig())
    assert len(found) == 1 and "claim" in found[0]["summary"]
    # leases reclaimed -> condition clear even though still STALLED
    obs2 = _obs(tmp_path, hosts=[_host("dead-1", "STALLED", age=200.0)],
                claims={}, claims_tracked=True)
    assert alerts._rule_host_stalled(obs2, AlertConfig()) == []
    # plain batch host (no claim tracking): staleness alone fires
    obs3 = _obs(tmp_path, hosts=[_host("b1", "STALLED", age=120.0)])
    assert len(alerts._rule_host_stalled(obs3, AlertConfig())) == 1
    # live / finished / prior-run hosts never fire
    for h in (_host("a", "live"), _host("b", "FINISHED"),
              _host("c", "STALLED", prior=True)):
        assert alerts._rule_host_stalled(_obs(tmp_path, hosts=[h]),
                                         AlertConfig()) == []


def test_queue_growth_needs_depth_and_no_drain(tmp_path):
    cfg = AlertConfig()
    grow = {"h1": _samples(
        fleet__queue__pending=[2, 4, 6, 8, 10, 12, 14, 16, 18, 20])}
    obs = _obs(tmp_path, hosts=[_host("h1")],
               queue={"pending": 20}, hist=grow)
    assert len(alerts._rule_queue_growth(obs, cfg)) == 1
    # deep but draining: no alert
    drain = {"h1": _samples(
        fleet__queue__pending=[40, 36, 32, 28, 24, 22, 21, 20, 20, 20])}
    obs = _obs(tmp_path, hosts=[_host("h1")],
               queue={"pending": 20}, hist=drain)
    assert alerts._rule_queue_growth(obs, cfg) == []
    # shallow: no alert regardless of slope
    obs = _obs(tmp_path, hosts=[_host("h1")],
               queue={"pending": 1}, hist=grow)
    assert alerts._rule_queue_growth(obs, cfg) == []


def test_spike_rules_fire_on_windowed_increase(tmp_path):
    cfg = AlertConfig()
    hist = {"h1": _samples(
        fleet__reclaimed=[0, 0, 0, 0, 0, 1, 2, 3, 3, 3],
        fleet__queue__quarantined=[0, 0, 0, 0, 0, 0, 0, 0, 0, 1],
        nonfinite_total=[0, 0, 0, 0, 0, 0, 0, 0, 2, 2])}
    obs = _obs(tmp_path, hist=hist)
    assert len(alerts._rule_reclaim_spike(obs, cfg)) == 1
    assert len(alerts._rule_quarantine_spike(obs, cfg)) == 1
    nf = alerts._rule_nonfinite(obs, cfg)
    assert len(nf) == 1 and "non-finite" in nf[0]["summary"]
    quiet = {"h1": _samples(fleet__reclaimed=[2] * 10,
                            nonfinite_total=[3] * 10)}
    obs = _obs(tmp_path, hist=quiet)
    assert alerts._rule_reclaim_spike(obs, cfg) == []
    assert alerts._rule_nonfinite(obs, cfg) == []


def test_failure_spike_counts_error_and_quarantine(tmp_path):
    hist = {"h1": _samples()}
    for i, s in enumerate(hist["h1"]):
        s["videos"]["error"] = 0 if i < 8 else 1
    assert len(alerts._rule_failure_spike(_obs(tmp_path, hist=hist),
                                          AlertConfig())) == 1
    flat = {"h1": _samples()}
    assert alerts._rule_failure_spike(_obs(tmp_path, hist=flat),
                                     AlertConfig()) == []


def test_cache_collapse_needs_warm_baseline(tmp_path):
    # window = one 30s-sample step, so the cold tail IS the window
    cfg = AlertConfig(cache_min_lookups=10, spike_window_s=40)
    # warm run (~80% cumulative) whose last two steps go fully cold
    hits = [0, 90, 180, 270, 360, 450, 540, 630, 632, 634]
    miss = [0, 10, 20, 30, 40, 50, 60, 70, 108, 146]
    warm_cold = {"h1": _samples(cache__hits=hits, cache__misses=miss)}
    found = alerts._rule_cache_collapse(_obs(tmp_path, hist=warm_cold),
                                        cfg)
    assert len(found) == 1
    # never-warm run: identical cold window, no baseline to defend
    cold = {"h1": _samples(cache__hits=[0] * 10,
                           cache__misses=[20 * i for i in range(10)])}
    assert alerts._rule_cache_collapse(_obs(tmp_path, hist=cold),
                                       cfg) == []


def test_mfu_regression_vs_own_history(tmp_path):
    vals = [0.60, 0.61, 0.59, 0.62, 0.60, 0.61, 0.60, 0.59, 0.61, 0.30]
    hist = {"h1": _samples(mfu__r21d=vals)}
    found = alerts._rule_mfu_regression(_obs(tmp_path, hist=hist),
                                        AlertConfig())
    assert len(found) == 1 and found[0]["scope"] == "h1/r21d"
    steady = {"h1": _samples(mfu__r21d=[0.6] * 10)}
    assert alerts._rule_mfu_regression(_obs(tmp_path, hist=steady),
                                       AlertConfig()) == []


def _verdict_doc(fam="raft", host="vm", bad=(), time_=NOW):
    """A schema-valid _parity_verdict.json document with the seams in
    ``bad`` pushed out of band (telemetry/parity.py certify shape)."""
    from video_features_tpu.telemetry import parity
    seams = {}
    for seam in parity.SEAMS:
        ok = seam not in bad
        band = parity.tolerance_for(fam, seam)
        seams[seam] = {"pairs": 2, "mean_abs": 0.0, "max_rel": 0.0,
                       "max_abs": 0.0 if ok else band["max_abs"] * 5,
                       "cos": 1.0 if ok else 0.5,
                       "tol_max_abs": band["max_abs"],
                       "tol_cos": band["cos"], "why": band["why"],
                       "ok": ok, "note": None}
    first = next((s for s in parity.SEAMS if s in bad), None)
    return {"schema": parity.VERDICT_SCHEMA, "family": fam, "host": host,
            "flip": "dtype=bf16", "ref": {"precision": "float32"},
            "cand": {"precision": "bfloat16"},
            "corpus": [{"video": "v.mp4", "sha256": None}],
            "seams": seams, "first_drift": first,
            "verdict": "FAIL" if first else "PASS", "time": time_}


def test_parity_drift_scopes_per_out_of_band_seam(tmp_path):
    from video_features_tpu.telemetry import parity
    doc = _verdict_doc(bad=("backbone", "head"))
    assert parity.validate_verdict(doc) == []
    obs = dict(_obs(tmp_path), parity=[doc])
    found = alerts._rule_parity_drift(obs, AlertConfig())
    assert [f["scope"] for f in found] == ["vm/family=raft/seam=backbone",
                                          "vm/family=raft/seam=head"]
    for f in found:
        assert f["value"] > f["threshold"]
        assert "dtype=bf16" in f["summary"]
    # a PASS verdict (and a missing parity section) fires nothing
    assert alerts._rule_parity_drift(
        dict(_obs(tmp_path), parity=[_verdict_doc()]), AlertConfig()) == []
    assert alerts._rule_parity_drift(_obs(tmp_path), AlertConfig()) == []


def test_parity_drift_artifact_is_the_state(tmp_path):
    """E2E through observe_root + the engine + the report gates: a FAIL
    verdict on disk fires parity_drift and trips --fail-on-alert; a
    re-certify PASS overwriting it resolves and lifts the gate."""
    from video_features_tpu import fleet_report
    root = tmp_path / "out"
    root.mkdir()
    write_json_atomic(root / "_heartbeat_hostA.json",
                      {"run_id": "r1", "host_id": "hostA",
                       "time": time.time(), "interval_s": 2.0,
                       "final": True})
    write_json_atomic(root / "_parity_verdict.json",
                      _verdict_doc(bad=("transform",), time_=time.time()))
    assert [d["family"] for d in alerts.observe_root(root)["parity"]] == \
        ["raft"]
    AlertEngine(root).evaluate()
    active = current_alerts(root)
    assert [a["rule"] for a in active] == ["parity_drift"]
    assert active[0]["scope"] == "vm/family=raft/seam=transform"
    assert all(validate_alert(r) == []
               for r in read_jsonl(root / alerts.ALERTS_FILENAME))
    assert fleet_report.main([str(root), "--fail-on-alert"]) == 1
    # the verdict artifact IS the episode state: a PASS re-certify ends it
    write_json_atomic(root / "_parity_verdict.json",
                      _verdict_doc(time_=time.time()))
    AlertEngine(root).evaluate()
    assert [a["rule"] for a in current_alerts(root)] == []
    assert fleet_report.main([str(root), "--fail-on-alert"]) == 0


# -- flight recorder ---------------------------------------------------------

def _stale_root(tmp_path):
    root = tmp_path / "out"
    root.mkdir()
    write_json_atomic(root / "_heartbeat_hostA.json",
                      {"run_id": "r1", "host_id": "hostA",
                       "time": NOW - 100, "interval_s": 2.0,
                       "final": False})
    (root / "_failures.jsonl").write_text(
        json.dumps({"video": "v.mp4", "category": "FATAL"}) + "\n")
    (root / "_telemetry.jsonl").write_text(
        json.dumps({"video": "v.mp4", "status": "error"}) + "\n")
    return root


def test_incident_bundle_complete_and_tamper_evident(tmp_path):
    root = _stale_root(tmp_path)
    eng = AlertEngine(root, clock=lambda: NOW)
    fired = [r for r in eng.evaluate(now=NOW) if r["state"] == "firing"]
    assert len(fired) == 1 and fired[0]["rule"] == "host_stalled"
    bundle = root / fired[0]["incident"]
    man = json.loads((bundle / "manifest.json").read_text())
    paths = [a["path"] for a in man["artifacts"]]
    assert "alert.json" in paths
    assert any(p.startswith("heartbeats/") for p in paths)
    assert any("_failures" in p for p in paths)
    assert any("_telemetry" in p for p in paths)
    assert verify_incident(bundle) == []
    # every listed artifact is hashed: tampering is detected
    victim = bundle / paths[-1]
    victim.write_text(victim.read_text() + "x")
    assert any("mismatch" in e for e in verify_incident(bundle))
    # and a missing manifest is a hard violation, not a pass
    (bundle / "manifest.json").unlink()
    assert verify_incident(bundle)


def test_bundle_snapshots_never_reingested(tmp_path):
    """Captured heartbeat/journal copies must not resurrect as live
    artifacts in any collector — a bundle is inert evidence."""
    from video_features_tpu import fleet_report
    root = _stale_root(tmp_path)
    eng = AlertEngine(root, clock=lambda: NOW)
    eng.evaluate(now=NOW)
    entries = fleet_report.collect_heartbeats(str(root), now=NOW)
    assert len(entries) == 1  # the real one, not the bundle copy
    assert history.read_history(str(root)) == {}
    fams = fleet_report.collect_family_throughput(str(root))
    assert sum(f["records"] for f in fams.values()) == 1


def test_capture_failure_degrades_to_alert_without_bundle(tmp_path):
    root = _stale_root(tmp_path)
    blocked = root / alerts.INCIDENTS_DIRNAME
    blocked.write_text("not a directory")  # makedirs will fail
    eng = AlertEngine(root, clock=lambda: NOW)
    fired = [r for r in eng.evaluate(now=NOW) if r["state"] == "firing"]
    assert len(fired) == 1 and fired[0]["incident"] is None


# -- history retention -------------------------------------------------------

def test_sample_from_heartbeat_fields():
    hb = {"time": NOW, "host_id": "h", "run_id": "r", "uptime_s": 9.0,
          "final": False, "videos": {"done": 3, "error": 1},
          "videos_done": 4, "videos_per_s": 0.4,
          "cache": {"hits": {"resnet": 5}, "misses": {"resnet": 2},
                    "bypasses": {}},
          "compile_cache": {"hits": 7, "misses": 0},
          "fleet": {"active_claims": 1, "stolen": 0, "reclaimed": 2,
                    "quarantined": 0, "idle_wait_s_total": 1.5,
                    "queue": {"pending": 4, "claimed": 1, "done": 2,
                              "quarantined": 0}},
          "serve": {"pending": 2,
                    "slo": {"slo_s": 1.0, "requests": 10,
                            "violations": 3},
                    "tenants": {"alpha": {"requests": 7, "violations": 1,
                                          "rejects": 2}}},
          "roofline": {"families": {"r21d": {"mfu": 0.61}}}}
    s = history.sample_from_heartbeat(hb, nonfinite_total=2)
    assert s["schema"] == history.SAMPLE_SCHEMA
    assert s["videos"] == {"done": 3, "skipped": 0, "error": 1,
                           "quarantined": 0}
    assert s["cache"] == {"hits": 5, "misses": 2, "bypasses": 0}
    assert s["compile_cache"] == {"hits": 7, "misses": 0}
    assert s["fleet"]["queue"]["pending"] == 4
    assert s["slo"] == {"slo_s": 1.0, "requests": 10, "violations": 3}
    # per-tenant counters ride along for the tenant-scoped burn windows,
    # plus the derived attainment the scenario curves join against
    # (rejects are door-state, not SLO state: not sampled)
    assert s["tenants"] == {"alpha": {"requests": 7, "violations": 1,
                                      "attainment_pct": 85.71}}
    assert s["mfu"] == {"r21d": 0.61}
    assert s["nonfinite_total"] == 2
    json.dumps(s)  # JSON-safe by construction


def test_downsample_tiers_bound_a_week_of_ticks():
    # a week of 2s ticks = 302400 samples
    t0 = NOW - 7 * 86400.0
    samples = [{"time": t0 + i * 2.0} for i in range(302400)]
    kept = history.downsample(samples, now=NOW)
    # ~300 full-res + 120 + 288 + 336 -> comfortably bounded
    assert len(kept) < 1200
    times = [s["time"] for s in kept]
    assert times == sorted(times)
    # the newest 10 minutes keep full resolution
    recent = [t for t in times if NOW - t <= 600.0]
    assert len(recent) >= 295
    # nothing older than the last tier survives
    assert min(times) >= NOW - 7 * 86400.0 - 1800.0


def test_history_writer_appends_and_compacts(tmp_path):
    w = history.HistoryWriter(tmp_path, "hostX", clock=lambda: NOW)
    old = NOW - 2 * 86400.0  # mid: one per 5 min tier
    for i in range(20):
        w.observe({"schema": history.SAMPLE_SCHEMA, "host_id": "hostX",
                   "time": NOW - 8 * 86400.0 + i})  # past the last tier
    for i in range(10):
        w.observe({"schema": history.SAMPLE_SCHEMA, "host_id": "hostX",
                   "time": NOW - i})
    kept = w.compact()
    assert kept == 10  # week-old samples dropped, fresh kept whole
    assert len(history.read_history(str(tmp_path))["hostX"]) == 10
    assert old  # silence lint


def test_window_delta_partial_window_and_reset_guard():
    samples = [{"time": NOW - 60 + i * 10, "videos": {"error": i}}
               for i in range(7)]
    # full window
    d = history.window_delta(samples, "videos.error", NOW, 30.0)
    assert d is not None and d[0] == 3 and abs(d[1] - 30.0) < 1e-6
    # window wider than the series: the oldest sample is the baseline
    d = history.window_delta(samples, "videos.error", NOW, 9999.0)
    assert d is not None and d[0] == 6
    # counter reset (a new run reused the dir): a negative delta is
    # None, never a spike — and gauges opt in to signed deltas
    reset = samples + [{"time": NOW + 10, "videos": {"error": 0}}]
    assert history.window_delta(reset, "videos.error", NOW + 10,
                                30.0) is None
    d = history.window_delta(reset, "videos.error", NOW + 10, 30.0,
                             allow_negative=True)
    assert d is not None and d[0] < 0
    # fewer than two samples with the field: no window
    assert history.window_delta(samples[:1], "videos.error", NOW,
                                30.0) is None
    assert history.window_delta(samples, "videos.nope", NOW, 30.0) is None


# -- rendering / prom / gates ------------------------------------------------

def test_render_and_prom_series(tmp_path):
    root = _stale_root(tmp_path)
    AlertEngine(root, clock=lambda: NOW).evaluate(now=NOW)
    active = current_alerts(root)
    lines = alerts.render_alerts(active)
    assert lines and "1 firing" in lines[0]
    assert any("host_stalled(hostA)" in ln for ln in lines)
    series = alerts.alerts_prom_series(active)
    assert len(series) == 1
    assert series[0]["name"] == "ALERTS"
    assert series[0]["labels"]["alertname"] == "host_stalled"
    assert series[0]["labels"]["alertstate"] == "firing"
    from video_features_tpu.telemetry.metrics import prometheus_text
    text = prometheus_text({"series": series})
    assert 'ALERTS{alertname="host_stalled"' in text


def test_fleet_report_renders_and_gates_on_alerts(tmp_path, capsys):
    from video_features_tpu import fleet_report
    root = _stale_root(tmp_path)
    AlertEngine(root, clock=lambda: NOW).evaluate(now=NOW)
    agg = fleet_report.aggregate(str(root))
    assert [a["rule"] for a in agg["alerts"]] == ["host_stalled"]
    assert any("== alerts ==" in ln for ln in fleet_report.render(agg))
    dump = fleet_report.build_prom_dump(agg)
    assert any(s["name"] == "ALERTS" for s in dump["series"])
    assert fleet_report.main([str(root), "--fail-on-alert"]) == 1
    capsys.readouterr()
    # resolve (fresh heartbeat), re-evaluate: the gate lifts
    write_json_atomic(root / "_heartbeat_hostA.json",
                      {"run_id": "r1", "host_id": "hostA",
                       "time": time.time(), "interval_s": 2.0,
                       "final": False})
    AlertEngine(root).evaluate()
    assert fleet_report.main([str(root), "--fail-on-alert"]) == 0


def test_telemetry_report_fail_on_alert_excludes_prior_run(tmp_path,
                                                           capsys):
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    import telemetry_report
    root = _stale_root(tmp_path)
    AlertEngine(root, clock=lambda: NOW).evaluate(now=NOW)
    assert telemetry_report.main([str(root), "--fail-on-alert"]) == 1
    out = capsys.readouterr()
    assert "host_stalled" in out.out + out.err
    # a NEWER run in the same dir: the stale firing record is that
    # prior run's business — excluded, gate lifts
    write_json_atomic(root / "_run.json",
                      {"run_id": "r2", "started_time": NOW + 50})
    assert telemetry_report.main([str(root), "--fail-on-alert"]) == 0


# -- CLI E2E: the acceptance loop --------------------------------------------

def _run_cli(argv):
    from video_features_tpu.cli import main as cli_main
    cli_main(argv)


def _base_argv(out, tmp, extra=()):
    return ["feature_type=resnet", "allow_random_weights=true",
            "on_extraction=save_numpy", f"output_path={out}",
            f"tmp_path={tmp}", "extraction_fps=2", "batch_size=16",
            f"video_paths=[{SAMPLE}]"] + list(extra)


def test_cli_inject_fires_bundles_and_resolves(tmp_path):
    """ISSUE 13 acceptance: an injected fault run deterministically
    raises a firing alert, writes a schema-valid ``_alerts.jsonl``
    record and a complete incident bundle, and the alert resolves
    after recovery (a later one-shot evaluation)."""
    out = tmp_path / "out"
    _run_cli(_base_argv(out, tmp_path / "tmp", [
        "telemetry=true", "alerts=true", "history=true",
        "metrics_interval_s=0.3", "retry_attempts=1",
        "inject=seed=0;sink.fsync=enospc@n1"]))
    root = out / "resnet" / "resnet50"
    recs = list(read_jsonl(root / "_alerts.jsonl"))
    assert recs, "no alert records"
    for r in recs:
        assert validate_alert(r) == []
    firing = [r for r in recs if r["state"] == "firing"
              and r["rule"] == "failure_spike"]
    assert len(firing) == 1
    assert firing[0]["run_id"] is not None
    bundle = root / firing[0]["incident"]
    assert verify_incident(bundle) == []
    paths = [a["path"] for a in json.loads(
        (bundle / "manifest.json").read_text())["artifacts"]]
    assert any("_failures" in p for p in paths)  # the journal evidence
    assert any(p.startswith("heartbeats/") for p in paths)
    # retained history exists and carries the failure counter
    series = history.read_history(str(root))
    assert len(series) == 1
    (host, samples), = series.items()
    assert samples[-1]["videos"]["error"] == 1
    # recovery: the failure ages out of a (shrunken) window -> resolved
    time.sleep(0.3)
    assert alerts.main([str(root), "--window", "0.05"]) == 0
    final = {(r["rule"], r["scope"]): r
             for r in read_jsonl(root / "_alerts.jsonl")}
    assert final[("failure_spike", host)]["state"] == "resolved"
    assert current_alerts(root) == []
    # and the resolved record still points at the bundle
    assert final[("failure_spike", host)]["incident"] == \
        firing[0]["incident"]


def test_alerts_off_is_byte_identical_and_footprint_free(tmp_path):
    """``alerts=false`` (the default) must leave features AND the
    telemetry artifact set byte-identical to pre-alerting behavior: no
    journal, no history, no incidents, no heartbeat section."""
    out_off = tmp_path / "off"
    out_on = tmp_path / "on"
    _run_cli(_base_argv(out_off, tmp_path / "t1",
                        ["telemetry=true", "metrics_interval_s=60"]))
    _run_cli(_base_argv(out_on, tmp_path / "t2",
                        ["telemetry=true", "metrics_interval_s=60",
                         "alerts=true", "history=true"]))
    root_off = out_off / "resnet" / "resnet50"
    root_on = out_on / "resnet" / "resnet50"
    a = np.load(root_off / "v_synth_sample_resnet.npy")
    b = np.load(root_on / "v_synth_sample_resnet.npy")
    assert a.tobytes() == b.tobytes()
    # the off run has zero alerting footprint
    assert not (root_off / "_alerts.jsonl").exists()
    assert not (root_off / alerts.INCIDENTS_DIRNAME).exists()
    assert list(root_off.glob("_history_*.jsonl")) == []
    hb_off, = root_off.glob("_heartbeat_*.json")
    assert "alerts" not in json.loads(hb_off.read_text())
    # the on run retained history and published the heartbeat section
    assert list(root_on.glob("_history_*.jsonl"))
    hb_on, = root_on.glob("_heartbeat_*.json")
    assert "alerts" in json.loads(hb_on.read_text())
