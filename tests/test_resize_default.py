"""The resize=auto defaults flip (PR 6): device resize is the DEFAULT for
file-sink runs, with automatic host fallback wherever the device path
cannot serve.

Fallback matrix pinned here (extractors/base.py _resolve_resize_mode):

  - ``on_extraction=save_numpy``/``save_pickle``  -> device
  - ``on_extraction=print``                       -> host (interactive /
    parity path; the golden suite runs through it unchanged)
  - ``show_pred=true``                            -> host (prediction
    overlays need host-side frames)
  - family without a fused device resize
    (flow family, ``side_size=null``)             -> host
  - explicit ``resize=host`` / ``resize=device``  -> honored as before
  - bogus values                                  -> loud failure at
    launch (sanity_check) and at init

Plus the behavioral guarantees the flip rides on: the auto default's
output is BIT-IDENTICAL to an explicit ``resize=device`` run, and the
per-source-resolution runner cache still compiles one executable per
geometry under the default (mixed-resolution corpus).
"""
import numpy as np
import pytest

from video_features_tpu.config import Config, load_config, sanity_check
from video_features_tpu.extractors.base import BaseExtractor

SAMPLE_KW = dict(video_paths="x.mp4", output_path="o", tmp_path="t")


def _base(feature_type="resnet", **over):
    args = Config(dict(feature_type=feature_type, device="cpu",
                       **SAMPLE_KW, **over))
    return BaseExtractor(args), args


@pytest.mark.quick
@pytest.mark.parametrize("over,capable,want", [
    (dict(on_extraction="save_numpy"), True, "device"),
    (dict(on_extraction="save_pickle"), True, "device"),
    (dict(on_extraction="print"), True, "host"),
    (dict(on_extraction="save_numpy", show_pred=True), True, "host"),
    (dict(on_extraction="save_numpy"), False, "host"),  # no device resize
    (dict(on_extraction="save_numpy", resize="host"), True, "host"),
    (dict(on_extraction="print", resize="device"), True, "device"),
    (dict(on_extraction="print", resize=None), True, "host"),  # null=auto
])
def test_auto_resolution_matrix(over, capable, want):
    ex, args = _base(**over)
    assert ex._resolve_resize_mode(args, device_capable=capable) == want


@pytest.mark.quick
def test_bogus_resize_fails_at_init_and_at_launch():
    ex, args = _base(on_extraction="save_numpy", resize="gpu")
    with pytest.raises(NotImplementedError):
        ex._resolve_resize_mode(args)
    cfg = load_config("resnet", {"resize": "gpu", **SAMPLE_KW})
    with pytest.raises(ValueError):
        sanity_check(cfg)


@pytest.mark.quick
def test_flow_family_without_side_size_falls_back_to_host(tmp_path,
                                                          sample_video):
    """A flow family with no resize in its pipeline at all must resolve
    the auto default to host (there is nothing to move on-device)."""
    from video_features_tpu.extractors.pwc import ExtractPWC
    cfg = load_config("pwc", {
        "video_paths": sample_video, "device": "cpu",
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / "o"), "tmp_path": str(tmp_path / "t"),
    })
    sanity_check(cfg)
    assert cfg.get("side_size") is None
    ex = ExtractPWC(cfg)
    assert ex.resize_mode == "host"


def _resnet(tmp_path, sample_video, sub, **over):
    from video_features_tpu.extractors.resnet import ExtractResNet
    cfg = load_config("resnet", {
        "video_paths": sample_video, "device": "cpu", "batch_size": 8,
        "extraction_total": 6, "model_name": "resnet18",
        "on_extraction": "save_numpy", "allow_random_weights": True,
        "output_path": str(tmp_path / sub / "o"),
        "tmp_path": str(tmp_path / sub / "t"), **over,
    })
    sanity_check(cfg)
    return ExtractResNet(cfg)


def test_default_is_bit_identical_to_explicit_device(tmp_path, sample_video):
    """The flipped default must be the SAME pipeline as resize=device —
    not a third numeric path."""
    auto = _resnet(tmp_path, sample_video, "auto")
    assert auto.resize_mode == "device"
    explicit = _resnet(tmp_path, sample_video, "dev", resize="device")
    fa = auto.extract(sample_video)
    fd = explicit.extract(sample_video)
    np.testing.assert_array_equal(fa["resnet"], fd["resnet"])
    np.testing.assert_array_equal(fa["timestamps_ms"], fd["timestamps_ms"])


def test_mixed_resolutions_under_default(tmp_path, sample_video):
    """Two source geometries through ONE extractor under the auto default:
    one cached executable per resolution, finite features for both."""
    import cv2
    small = str(tmp_path / "small_res.mp4")
    cap = cv2.VideoCapture(sample_video)
    w = cv2.VideoWriter(small, cv2.VideoWriter_fourcc(*"mp4v"), 10.0,
                        (160, 120))
    for _ in range(12):
        ok, frame = cap.read()
        assert ok
        w.write(cv2.resize(frame, (160, 120)))
    cap.release()
    w.release()

    ex = _resnet(tmp_path, sample_video, "mixed", extraction_total=4)
    assert ex.resize_mode == "device"
    f1 = ex.extract(sample_video)["resnet"]
    f2 = ex.extract(small)["resnet"]
    assert np.isfinite(f1).all() and np.isfinite(f2).all()
    assert len(ex._resize_runners) == 2  # one executable per geometry
