"""Benchmark: R(2+1)D-18 clip throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "clips/sec/chip", "vs_baseline": N}

The reference publishes no throughput numbers (BASELINE.md), so the baseline
here is measured: the same R(2+1)D-18 architecture run in torch (the
reference's engine) on this host's CPU, batch=1 serial slices exactly like
reference models/r21d/extract_r21d.py:84-88. ``vs_baseline`` is
ours/theirs on identical clip shapes (16 frames, 112x112).

Our number is the steady-state jitted forward in the maximum-throughput
ingest mode (``ingest=yuv420``: packed I420 uint8 clips, 1.5 bytes/pixel,
colorspace conversion fused on device — ops/colorspace.py), bfloat16 params
+ activations, B=64 clips per step.

Measurement notes, learned the hard way on tunneled dev chips:
  - completion is fenced with a D2H read of the last output (`settle`,
    parallel/mesh.py) — `block_until_ready` has been observed to ack before
    execution finishes, yielding physically impossible rates (it measured
    dispatch/wire throughput, not compute);
  - input batches are staged on device before the timed loop: host-fed
    dispatch through the tunnel pays a per-call RTT that can exceed the
    batch's compute time 10x, measuring the tunnel rather than the chip.
    In deployment the pipeline streams H2D asynchronously under compute
    (FeatureStream), so the device-resident number is the representative
    steady state;
  - best of TRIALS guards against transient tenancy stalls on both sides
    of the ratio.
The resulting number is stable (+/-2% across trials) and physically
consistent: ~1,000 clips/s = ~66 ms per 64-clip batch = ~39 effective
TFLOPS, a credible fraction of v5e bf16 peak for small 3D convs.
"""
import json
import time

import numpy as np

CLIP = (16, 112, 112, 3)  # stack, H, W, C
BATCH = 64  # measured sweet spot on v5e: ~15% over B=16, B=128 flat, B=256 regresses
WARMUP = 5
ITERS = 30
TRIALS = 3  # report the best trial: tenancy stalls on shared dev chips are transient


def bench_ours() -> float:
    import jax
    import jax.numpy as jnp
    if jax.default_backend() != "cpu":
        # persistent compile cache (safe off-CPU — see cli.py): repeat bench
        # runs skip the ~40 s XLA compile and measure steady state sooner
        from video_features_tpu.cli import _enable_compilation_cache
        _enable_compilation_cache({"device": "auto"})
    from video_features_tpu.models.r21d import R2Plus1D, R21D_MEAN, R21D_STD

    from video_features_tpu.extractors.r21d import _device_forward_yuv420
    from video_features_tpu.ops.colorspace import packed_size
    from video_features_tpu.parallel.mesh import cast_floating

    model = R2Plus1D("r2plus1d_18_16_kinetics")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4, 112, 112, 3)))["params"]
    # bf16 params + bf16 activations: with f32 params flax would promote every
    # conv back to f32, halving MXU throughput (parallel/mesh.py cast_floating)
    params = cast_floating(params, jnp.bfloat16)

    @jax.jit
    def forward(p, packed_u8):
        return _device_forward_yuv420(model, jnp.bfloat16, p, packed_u8)

    rng = np.random.default_rng(0)
    wire = (BATCH, CLIP[0], packed_size(CLIP[1], CLIP[2]))
    batches = [jax.device_put(rng.integers(0, 255, size=wire, dtype=np.uint8))
               for _ in range(2)]
    from video_features_tpu.parallel.mesh import settle
    settle(forward(params, batches[0]))  # compile
    for _ in range(WARMUP):
        settle(forward(params, batches[1]))
    best = 0.0
    for _ in range(TRIALS):  # best-of: shared dev chips stall transiently
        t0 = time.perf_counter()
        for i in range(ITERS):
            out = forward(params, batches[i % 2])
        settle(out)
        dt = time.perf_counter() - t0
        best = max(best, BATCH * ITERS / dt)
    return best


def bench_torch_reference() -> float:
    """Reference-style serial batch=1 torch forward on this host's CPU."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))
    import torch
    from torch_oracles import TorchR2Plus1D

    model = TorchR2Plus1D(layers=(2, 2, 2, 2)).eval()
    x = torch.randn(1, 3, *CLIP[:3])
    best = 0.0
    with torch.no_grad():
        model(x)  # warmup
        n = 3
        for _ in range(TRIALS):  # same best-of selection as bench_ours
            t0 = time.perf_counter()
            for _ in range(n):
                model(x)
            best = max(best, n / (time.perf_counter() - t0))
    return best


def main() -> None:
    ours = bench_ours()
    try:
        theirs = bench_torch_reference()
        ratio = ours / theirs
    except Exception:
        theirs, ratio = None, None
    import jax
    platform = jax.devices()[0].platform
    print(json.dumps({
        "metric": f"r2plus1d_18 16f@112px clip throughput ({platform}, bf16)",
        "value": round(ours, 2),
        "unit": "clips/sec/chip",
        "vs_baseline": round(ratio, 2) if ratio is not None else None,
    }))


if __name__ == "__main__":
    main()
