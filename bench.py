"""Benchmark: both north-star configs on the available accelerator.

Prints ONE JSON line. Top-level fields carry the R(2+1)D-18 headline (the
shape the driver has recorded since round 1); a ``metrics`` array carries
both north-star configs (BASELINE.md: "clips/sec/chip for R(2+1)D and
I3D-RGB+Flow"):

  {"metric": "...r2plus1d_18...", "value": N, "unit": "clips/sec/chip",
   "vs_baseline": N, "metrics": [{r21d...}, {i3d rgb+flow...}]}

The reference publishes no throughput numbers (BASELINE.md), so baselines are
measured: the same architectures run in torch (the reference's engine) on
this host's CPU exactly like the reference's serial per-slice loops.
``vs_baseline`` is ours/theirs on identical work units.

R(2+1)D config: steady-state jitted forward, maximum-throughput ingest
(``ingest=yuv420``: packed I420 uint8 clips, 1.5 bytes/pixel, colorspace
fused on device — ops/colorspace.py), bfloat16, B=128 clips per step.

I3D config: the full reference work unit (extract_i3d.py:140-169) — 64+1 RGB
frames at 224px -> RAFT flow on 64 consecutive pairs (20 GRU iterations
each) -> ToUInt8 quantize -> I3D-RGB + I3D-Flow forwards, all on device.

Measurement notes, learned the hard way on tunneled dev chips:
  - completion is fenced with a D2H read of the last output (`settle`,
    parallel/mesh.py) — `block_until_ready` has been observed to ack before
    execution finishes, yielding physically impossible rates (it measured
    dispatch/wire throughput, not compute);
  - input batches are staged on device before the timed loop: host-fed
    dispatch through the tunnel pays a per-call RTT that can exceed the
    batch's compute time 10x, measuring the tunnel rather than the chip.
    In deployment the pipeline streams H2D asynchronously under compute
    (FeatureStream), so the device-resident number is the representative
    steady state;
  - best of TRIALS guards against transient tenancy stalls on both sides of
    the ratio; torch trials additionally run an adaptive iteration count
    (>= MIN_TRIAL_SECONDS wall each) so the CPU side is not a 3-sample
    coin flip.
"""
import json
import time

import numpy as np

CLIP = (16, 112, 112, 3)  # stack, H, W, C
# measured sweet spot on v5e for the current yuv420+bf16 program (round-2
# sweep): 64 -> 972, 96 -> 1144, 128 -> 1471, 192 -> 1136 (tiling dip),
# 256 -> 1429 clips/s. The round-1 "B=128 flat" note predates this program.
BATCH = 128
I3D_STACK = 64      # the reference's default stack (BASELINE.json flagship)
I3D_SIDE = 224
WARMUP = 5
ITERS = 30
TRIALS = 3  # report the best trial: tenancy stalls on shared dev chips are transient
MIN_TRIAL_SECONDS = 1.5  # torch baselines: floor per timed trial


def _enable_cache_off_cpu() -> None:
    import jax
    if jax.default_backend() != "cpu":
        # persistent compile cache (safe off-CPU — see cli.py): repeat bench
        # runs skip the multi-minute XLA compiles and measure steady state
        from video_features_tpu.cli import _enable_compilation_cache
        _enable_compilation_cache({"device": "auto"})


def bench_ours() -> float:
    import jax
    import jax.numpy as jnp
    _enable_cache_off_cpu()
    from video_features_tpu.models.r21d import R2Plus1D

    from video_features_tpu.extractors.r21d import _device_forward_yuv420
    from video_features_tpu.ops.colorspace import packed_size
    from video_features_tpu.parallel.mesh import cast_floating, settle

    model = R2Plus1D("r2plus1d_18_16_kinetics")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4, 112, 112, 3)))["params"]
    # bf16 params + bf16 activations: with f32 params flax would promote every
    # conv back to f32, halving MXU throughput (parallel/mesh.py cast_floating)
    params = cast_floating(params, jnp.bfloat16)

    @jax.jit
    def forward(p, packed_u8):
        return _device_forward_yuv420(model, jnp.bfloat16, p, packed_u8)

    rng = np.random.default_rng(0)
    wire = (BATCH, CLIP[0], packed_size(CLIP[1], CLIP[2]))
    batches = [jax.device_put(rng.integers(0, 255, size=wire, dtype=np.uint8))
               for _ in range(2)]
    settle(forward(params, batches[0]))  # compile
    for _ in range(WARMUP):
        settle(forward(params, batches[1]))
    best = 0.0
    for _ in range(TRIALS):  # best-of: shared dev chips stall transiently
        t0 = time.perf_counter()
        for i in range(ITERS):
            out = forward(params, batches[i % 2])
        settle(out)
        dt = time.perf_counter() - t0
        best = max(best, BATCH * ITERS / dt)
    return best


def bench_torch_reference() -> float:
    """Reference-style serial batch=1 torch forward on this host's CPU."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))
    import torch
    from torch_oracles import TorchR2Plus1D

    model = TorchR2Plus1D(layers=(2, 2, 2, 2)).eval()
    x = torch.randn(1, 3, *CLIP[:3])
    best = 0.0
    with torch.no_grad():
        model(x)  # warmup
        for _ in range(TRIALS):  # same best-of selection as bench_ours
            n = 0
            t0 = time.perf_counter()
            # adaptive count: at least MIN_TRIAL_SECONDS of wall per trial
            while True:
                model(x)
                n += 1
                dt = time.perf_counter() - t0
                if dt >= MIN_TRIAL_SECONDS and n >= 3:
                    break
            best = max(best, n / dt)
    return best


def bench_i3d_ours(stack: int = I3D_STACK, iters: int = 10,
                   warmup: int = 3, raft_bf16: bool = False) -> float:
    """I3D RGB+Flow(RAFT) stacks/sec, the full on-device two-stream chain.

    ``raft_bf16`` runs the flow model in its plumbed bfloat16 mode
    (models/raft.py RAFT.dtype: conv stacks bf16, pyramid/lookup/coords
    f32) — the extractor's ``precision=bfloat16`` configuration. Flow
    drift is ~0.1 px, under the flow stream's ToUInt8 quantization step
    (~0.16), so it is a legitimate production mode for this chain;
    measured +7.5% stacks/s on v5e (the GRU/encoder convs go MXU-native,
    the selection-bound lookup is unchanged)."""
    import jax
    import jax.numpy as jnp
    _enable_cache_off_cpu()
    from video_features_tpu.extractors.i3d import _i3d_forward
    from video_features_tpu.extractors.i3d_flow import _raft_quantized_flow
    from video_features_tpu.models import i3d as i3d_m, raft as raft_m
    from video_features_tpu.parallel.mesh import cast_floating, settle

    model = i3d_m.I3D(num_classes=400)
    raft_dtype = jnp.bfloat16 if raft_bf16 else jnp.float32
    raft = raft_m.RAFT(iters=raft_m.ITERS, dtype=raft_dtype)
    i3d_rgb = cast_floating(i3d_m.init_params("rgb"), jnp.bfloat16)
    i3d_flow = cast_floating(i3d_m.init_params("flow"), jnp.bfloat16)
    raft_p = cast_floating(raft_m.init_params(), raft_dtype)

    @jax.jit
    def step(rp, pr, pf, stack_u8):
        # stack_u8: (stack+1, H, W, 3) uint8 — the extractor's own device
        # functions composed exactly like ExtractI3D.run_on_a_stack
        pairs = jnp.stack([stack_u8[:-1], stack_u8[1:]], axis=1)
        quant = _raft_quantized_flow(raft, I3D_SIDE, rp, pairs)
        rgb_feat = _i3d_forward(model, jnp.bfloat16, True, pr,
                                stack_u8[:-1][None].astype(jnp.float32))
        flow_feat = _i3d_forward(model, jnp.bfloat16, True, pf, quant[None])
        return rgb_feat, flow_feat

    rng = np.random.default_rng(0)
    stacks = [jax.device_put(
        rng.integers(0, 255, size=(stack + 1, I3D_SIDE, I3D_SIDE, 3),
                     dtype=np.uint8)) for _ in range(2)]
    settle(step(raft_p, i3d_rgb, i3d_flow, stacks[0]))  # compile
    for _ in range(warmup):
        settle(step(raft_p, i3d_rgb, i3d_flow, stacks[1]))
    best = 0.0
    for _ in range(TRIALS):  # best-of: transient tenancy stalls
        t0 = time.perf_counter()
        for i in range(iters):
            out = step(raft_p, i3d_rgb, i3d_flow, stacks[i % 2])
        settle(out)
        best = max(best, iters / (time.perf_counter() - t0))
    return best


def bench_pipeline(n_copies: int = 8) -> dict:
    """Sustained REAL-pipeline throughput: decode -> transform -> device ->
    sink, through the actual CLI driver, on ``n_copies`` of the vendored
    sample video — the deliverable number next to the device-only steady
    state (which assumes decode keeps up). Uses the headline device config
    (yuv420 ingest, bf16, clip_batch_size=128) with cross-video batching,
    so short videos can actually fill the B=128 groups the device number
    is measured at. On a few-core host this is decode-bound — that gap IS
    the measurement."""
    import shutil
    import tempfile
    from pathlib import Path

    sample = Path(__file__).parent / "tests" / "assets" / "v_synth_sample.mp4"
    if not sample.exists():
        sample = Path("/root/reference/sample/v_GGSY1Qvo990.mp4")
    if not sample.exists():
        raise FileNotFoundError("no sample video for the pipeline bench")
    import contextlib
    import sys as _sys
    from video_features_tpu.cli import main as cli_main
    with tempfile.TemporaryDirectory(prefix="vft_bench_pipe_") as td:
        vids = []
        for i in range(n_copies):
            dst = Path(td) / f"sample_copy{i}.mp4"
            shutil.copy(sample, dst)
            vids.append(str(dst))
        t0 = time.perf_counter()
        # the CLI prints its tally to stdout; bench.py's stdout contract is
        # ONE JSON line (the driver parses it), so route it to stderr
        with contextlib.redirect_stdout(_sys.stderr):
            cli_main([
                "feature_type=r21d", "precision=bfloat16", "ingest=yuv420",
                "clip_batch_size=128", "cross_video_batching=true",
                "video_workers=auto", "allow_random_weights=true",
                "on_extraction=save_numpy", f"output_path={td}/out",
                f"tmp_path={td}/tmp",
                "video_paths=[" + ",".join(vids) + "]",
            ])
        wall = time.perf_counter() - t0
        outputs = list(Path(td, "out").rglob("*_r21d.npy"))
        clips = sum(np.load(p).shape[0] for p in outputs)
    if len(outputs) < n_copies:
        # cli_main tallies per-video failures and returns normally; a bench
        # over identical healthy copies must complete ALL of them — anything
        # less would publish an inflated videos/s (n_copies / wall) for work
        # that partly failed. Route it to the caller's warning path instead.
        raise RuntimeError(
            f"pipeline bench: only {len(outputs)}/{n_copies} videos "
            "produced features — failed runs must not publish throughput")
    return {"videos_per_s": n_copies / wall, "clips_per_s": clips / wall,
            "clips": clips, "wall_s": wall}


def bench_i3d_torch(stack: int = I3D_STACK) -> float:
    """The full reference-shaped stack unit in torch on this host's CPU:
    RAFT flow on the frame pairs PLUS both I3D tower forwards (all classes
    imported read-only from /root/reference). Same best-of-TRIALS /
    adaptive >= MIN_TRIAL_SECONDS rigor as bench_torch_reference, applied
    to every term. Absent the reference source, return nan (no baseline)."""
    import importlib.util
    import sys
    from pathlib import Path
    import torch

    ref_root = Path("/root/reference")
    ref_raft = ref_root / "models/raft/raft_src/raft.py"
    ref_i3d = ref_root / "models/i3d/i3d_src/i3d_net.py"
    if not (ref_raft.exists() and ref_i3d.exists()):
        return float("nan")
    # reference raft.py imports via the 'models.raft.raft_src' package path,
    # so the reference ROOT goes on sys.path (same as tests/test_raft.py)
    if str(ref_root) not in sys.path:
        sys.path.insert(0, str(ref_root))

    def _load(name, path):
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    raft = _load("ref_raft", ref_raft).RAFT().eval()  # no args (raft.py:54)
    i3d_net = _load("ref_i3d", ref_i3d)
    towers = {s: i3d_net.I3D(num_classes=400, modality=s).eval()
              for s in ("rgb", "flow")}

    def timed(fn) -> float:
        """Best-of-TRIALS seconds/call; each trial repeats fn until the
        adaptive wall floor so short calls are not a 3-sample coin flip
        (heavy calls exceed the floor in one repeat, which is fine — their
        single-sample noise is proportionally small)."""
        best = float("inf")
        with torch.no_grad():
            for _ in range(TRIALS):
                n = 0
                t0 = time.perf_counter()
                while True:
                    fn()
                    n += 1
                    dt = time.perf_counter() - t0
                    if dt >= MIN_TRIAL_SECONDS:
                        break
                best = min(best, dt / n)
        return best

    pairs = 4  # timed pair-batch; flow cost scales linearly to the stack
    x = torch.randint(0, 255, (pairs, 3, I3D_SIDE, I3D_SIDE),
                      dtype=torch.float32)
    with torch.no_grad():
        raft(x[:1], x[:1], iters=2)  # warmup
    t_flow = timed(lambda: raft(x, x, iters=20,
                                test_mode=True)) * (stack / pairs)
    rgb_in = torch.randn(1, 3, stack, I3D_SIDE, I3D_SIDE)
    flow_in = torch.randn(1, 2, stack, I3D_SIDE, I3D_SIDE)
    t_rgb = timed(lambda: towers["rgb"](rgb_in))
    t_flow_tower = timed(lambda: towers["flow"](flow_in))
    return 1.0 / (t_flow + t_rgb + t_flow_tower)


def main() -> None:
    import jax
    platform = jax.devices()[0].platform

    ours = bench_ours()
    try:
        theirs = bench_torch_reference()
        r21d_ratio = ours / theirs
    except Exception:
        r21d_ratio = None

    # never lose the already-measured r21d headline to an I3D-side failure
    # (the RAFT scan's cold compile and shared-chip tenancy faults are the
    # two realistic ways bench_i3d_ours can die)
    try:
        i3d = bench_i3d_ours()
    except Exception as e:
        print(f"WARNING: i3d bench failed: {type(e).__name__}: {e}",
              file=__import__("sys").stderr)
        i3d = None
    try:
        i3d_bf = bench_i3d_ours(raft_bf16=True) if i3d is not None else None
    except Exception as e:
        print(f"WARNING: i3d bf16-raft bench failed: "
              f"{type(e).__name__}: {e}", file=__import__("sys").stderr)
        i3d_bf = None
    i3d_torch = None
    if i3d is not None:
        try:
            i3d_torch = bench_i3d_torch()
        except Exception:
            i3d_torch = None

    r21d_entry = {
        "metric": f"r2plus1d_18 16f@112px clip throughput ({platform}, bf16)",
        "value": round(ours, 2),
        "unit": "clips/sec/chip",
        "vs_baseline": round(r21d_ratio, 2) if r21d_ratio is not None else None,
    }
    metrics = [r21d_entry]
    # the bf16-raft row is the precision=bfloat16 flow-stream mode: flow
    # drift ~0.1 px stays under the ToUInt8 quantization step, so it is
    # the fast production configuration of the same work unit
    for label, value in (("bf16 i3d / f32 raft", i3d),
                         ("bf16 i3d + bf16 raft", i3d_bf)):
        if value is None:
            continue
        ratio = (value / i3d_torch
                 if i3d_torch and i3d_torch == i3d_torch else None)
        metrics.append({
            "metric": f"i3d rgb+flow(raft) {I3D_STACK}f@{I3D_SIDE}px stack "
                      f"throughput ({platform}, {label})",
            "value": round(value, 3),
            "unit": "stacks/sec/chip",
            "vs_baseline": round(ratio, 2) if ratio is not None else None,
        })
    # sustained real-pipeline number (decode -> device -> sink): the
    # deliverable throughput next to the device-only steady state;
    # wall-clock includes the one-time compile when the persistent cache
    # is cold, so cache warmth (the two device benches above) matters
    try:
        pipe = bench_pipeline()
        metrics.append({
            "metric": "r2plus1d_18 sustained pipeline decode->device->sink "
                      "(8x sample video, yuv420+bf16, cross-video B=128; "
                      f"{pipe['videos_per_s']:.2f} videos/s)",
            "value": round(pipe["clips_per_s"], 2),
            "unit": "clips/sec",
            "vs_baseline": None,
        })
    except Exception as e:
        print(f"WARNING: pipeline bench failed: {type(e).__name__}: {e}",
              file=__import__("sys").stderr)

    # one JSON line: headline fields stay the r21d config (driver contract
    # since round 1); "metrics" carries the north-star configs + pipeline
    print(json.dumps({**r21d_entry, "metrics": metrics}))


if __name__ == "__main__":
    main()
